"""memrec (harp_tpu/utils/memrec, PR 19) — the device-memory ledger,
eighth telemetry spine.

Evidence layers, all on the 8-worker CPU sim:

1. ledger mechanics: staged/output enter the live set, freed/donated
   leave it, restored is a zero-delta, and every row's live/peak
   re-derives EXACTLY from the event stream (check_jsonl invariant 17);
2. the donation twin of HL303: a ``flightrec.track(...,
   donate_argnums=…)`` dispatch claims the NEWEST live buffer whose
   byte size matches the donated arg — metadata only, nothing is
   materialized — and an unmatched size claims nothing;
3. the VMEM gate: an over-budget Pallas tile is REFUSED before
   dispatch with a MemoryError naming the predicted bytes (the
   2026-08-01 silicon OOM as a pre-silicon check), regardless of
   telemetry state; the registry declarations sit inside the same
   PRESIZE_BAND harplint HL205 enforces;
4. THE chaos drill (ISSUE 19 acceptance): staging + donation +
   checkpoint restore + an injected over-VMEM config in ONE traced run
   yield (a) the pre-dispatch refusal and (b) ONE export where the
   watermark re-derives exactly, donated buffers have left the live
   set, and the steptrace timeline carries memory marks — with a
   healthy control alongside;
5. the PR-3 contract: with telemetry off the ledger stays EMPTY and
   traced programs/results are bit-identical; with memrec ARMED the
   flagship flight budget (1 dispatch / 1 stacked readback / 0 steady
   compiles / 0 H2D) passes UNCHANGED.
"""

import io
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.health import sentinel
from harp_tpu.utils import flightrec, memrec, steptrace, telemetry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import check_jsonl  # noqa: E402


def _export_rows():
    """The ledger's stamped export (rows + closing summary) as dicts."""
    buf = io.StringIO()
    memrec.export_jsonl(buf)
    return [json.loads(ln) for ln in buf.getvalue().splitlines()]


# ---------------------------------------------------------------------------
# vocabulary sync (the invariant-11/13/14/16 pin pattern)
# ---------------------------------------------------------------------------

def test_vocab_sync_with_check_jsonl():
    """The frozen invariant-17 vocabularies must mirror the module's —
    drift fails tier-1 before it can corrupt committed evidence."""
    assert check_jsonl.KNOWN_MEMORY_EVS == memrec.EVS
    assert check_jsonl.KNOWN_MEMORY_EVENTS == memrec.BUFFER_EVENTS
    # the memory spine threads onto the superstep timeline (PR 18)...
    assert "memory" in steptrace.SOURCES
    assert "memory" in check_jsonl.KNOWN_STEPTRACE_SOURCES
    # ...and into the health sentinel (PR 14)
    assert "memory_pressure" in sentinel.DETECTORS
    assert "memory_pressure" in check_jsonl.KNOWN_HEALTH_DETECTORS


# ---------------------------------------------------------------------------
# ledger mechanics
# ---------------------------------------------------------------------------

def test_lifecycle_replays_exactly(tmp_path):
    """stage → dispatch(donate) → output → restore → free → vmem pass →
    executable: the export re-derives clean through BOTH replays (the
    module's summarize_rows and check_jsonl invariant 17)."""
    def step(state, batch):
        return (state * 0.5 + batch.sum()).sum()

    tracked = flightrec.track(jax.jit(step), "memtest.step",
                              donate_argnums=(0,))
    state = jnp.zeros((8, 8), jnp.float32)    # 256 B
    batch = jnp.ones((4,), jnp.float32)
    with telemetry.scope(True):
        memrec.on_staged(int(state.nbytes), "memtest.state")
        tracked(state, batch)
        memrec.on_restored(4096, "ckpt:step_1")
        memrec.note_freed(nbytes=4)           # the scalar output
        memrec.require_vmem_fit("memtest.kernel", 1 << 20,
                                budget=14 << 20)
        memrec.note_executable("memtest.step", {
            "argument_bytes": 272, "output_bytes": 4,
            "temp_bytes": 0, "generated_code_bytes": 0})
        rows = _export_rows()
        s = memrec.summarize_rows(rows)
    assert s["errors"] == []
    assert s["staged_bytes"] == 256 and s["donated_bytes"] == 256
    assert s["freed_bytes"] == 4 and s["live_hbm_bytes"] == 0
    # the staged buffer is donated at dispatch BEFORE the 4 B output
    # lands, so the watermark is the staged buffer alone
    assert s["peak_hbm_bytes"] == 256
    assert s["vmem_checks"] == 1 and s["vmem_refusals"] == 0
    assert s["executables"] == 1 and s["exec_hbm_bytes"] == 276
    p = tmp_path / "mem.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert check_jsonl.check_file(str(p)) == []


def test_restored_is_zero_delta():
    with telemetry.scope(True):
        memrec.on_staged(1000, "x")
        before = (memrec.ledger.live_bytes, memrec.ledger.peak_bytes)
        memrec.on_restored(1 << 30, "ckpt:step_9")
        assert (memrec.ledger.live_bytes,
                memrec.ledger.peak_bytes) == before
        row = memrec.ledger._rows[-1]
        assert row["event"] == "restored" and row["buf"] == 0


def test_donation_claims_newest_exact_byte_match():
    """The ledger claims the NEWEST live buffer with the donated arg's
    exact byte size (LIFO matches the depth-2 pipeline's staging order);
    an unmatched size claims nothing — never a wrong buffer."""
    with telemetry.scope(True):
        memrec.ledger.staged(100, "a")
        memrec.ledger.staged(200, "b")
        memrec.ledger.staged(100, "c")      # newest 100-byte buffer
        memrec.ledger.dispatch("memtest.d1", [100])
        drow = [r for r in memrec.ledger._rows
                if r["ev"] == "dispatch"][-1]
        assert drow["donated"] == [3] and drow["donated_bytes"] == 100
        assert sorted(memrec.ledger._live) == [1, 2]
        memrec.ledger.dispatch("memtest.d2", [999])   # no such buffer
        drow = [r for r in memrec.ledger._rows
                if r["ev"] == "dispatch"][-1]
        assert drow["donated"] == [] and drow["donated_bytes"] == 0
        assert memrec.summarize_rows(_export_rows())["errors"] == []


def test_superstep_window_peak_marks():
    """An armed superstep threads its window HBM peak onto the timeline
    as a ``memory`` mark; a memory-inactive run keeps its pre-PR-19
    mark counts bit-identical (note_superstep no-ops on an empty
    ledger)."""
    with telemetry.scope(True):
        with steptrace.run("mem.quiet"):
            with steptrace.superstep("mem.quiet", 0):
                pass
        quiet = [r for r in steptrace.tracer.rows()
                 if r["ev"] == "mark" and r["source"] == "memory"]
    assert quiet == []
    with telemetry.scope(True):
        with steptrace.run("mem.active"):
            with steptrace.superstep("mem.active", 0):
                memrec.on_staged(4096, "mem.active.x")
        marks = [r for r in steptrace.tracer.rows()
                 if r["ev"] == "mark" and r["source"] == "memory"]
    assert len(marks) == 1
    assert marks[0]["name"] == "superstep_peak"
    assert marks[0]["peak_hbm_bytes"] >= 4096
    assert marks[0]["live_hbm_bytes"] == 4096


# ---------------------------------------------------------------------------
# the VMEM gate (the 2026-08-01 OOM as a pre-silicon check)
# ---------------------------------------------------------------------------

def test_require_vmem_fit_refuses_and_records():
    predicted, budget = 20 << 20, 14 << 20
    with telemetry.scope(True):
        with pytest.raises(MemoryError) as ei:
            memrec.require_vmem_fit("memtest.kernel", predicted,
                                    budget=budget)
        msg = str(ei.value)
        assert str(predicted) in msg
        assert "refused before dispatch" in msg
        assert memrec.ledger.vmem_checks == 1
        assert memrec.ledger.vmem_refusals == 1
        row = memrec.ledger._rows[-1]
        assert row["ev"] == "vmem_check" and row["refused"] is True
        assert row["predicted_bytes"] == predicted


def test_require_vmem_fit_is_a_safety_gate_not_a_collector():
    """The refusal fires with telemetry OFF too (it guards silicon, not
    evidence) — but records nothing."""
    memrec.reset()
    assert not telemetry.enabled()
    with pytest.raises(MemoryError, match="refused before dispatch"):
        memrec.require_vmem_fit("memtest.kernel", 20 << 20,
                                budget=14 << 20)
    assert memrec.ledger._rows == []


def test_kmeans_int8_over_vmem_tile_refused_before_dispatch():
    """An explicit 8000-row tile at d=1024 prices over the 14 MB budget
    — the kernel entry point must raise the memrec MemoryError (naming
    the predicted bytes) BEFORE building any Pallas launch."""
    from harp_tpu.ops.kmeans_kernel import (_VMEM_BUDGET_INT8,
                                            kmeans_partials_int8,
                                            vmem_bytes_int8)

    n, d, k = 8000, 1024, 100
    kp = 128
    predicted = vmem_bytes_int8(n, d, kp)
    assert predicted > _VMEM_BUDGET_INT8       # the premise of the test
    pts_q = np.zeros((n, d), np.int8)
    c_q = np.zeros((k, d), np.int8)
    c_scale = np.ones(k, np.float32)
    c2 = np.zeros(k, np.float32)
    col_scale = np.ones(d, np.float32)
    with pytest.raises(MemoryError) as ei:
        kmeans_partials_int8(pts_q, c_q, c_scale, c2, col_scale,
                             tile_rows=n)
    assert str(predicted) in str(ei.value)
    assert "refused before dispatch" in str(ei.value)


def test_presize_tiles_fit_their_own_budget():
    """perfmodel.presize must only ever hand out tiles its own byte
    model prices under the budget — the graded 1M×300 shape reproduces
    the OOM-calibrated 8000-row tile."""
    from harp_tpu.ops.kmeans_kernel import (_VMEM_BUDGET_INT8,
                                            vmem_bytes_int8)
    from harp_tpu.perfmodel import presize

    r = presize("kmeans.partials_int8", n=1_000_000, d=300, k=100)
    assert r["tile"] == 8000
    assert vmem_bytes_int8(r["tile"], 300, 128) <= _VMEM_BUDGET_INT8


def test_hl205_registry_declarations_inside_band():
    """Satellite 2: every registry ``vmem_bytes`` declaration sits
    inside PRESIZE_BAND of the kernel's own byte model (the lint
    cross-check is clean on the real registry), and a stale declaration
    fires HL205."""
    from harp_tpu.analysis import mosaic_audit
    from harp_tpu.ops.kernel_registry import KERNEL_WORK

    assert mosaic_audit.check_work_declarations() == []
    models = mosaic_audit._declared_vmem_models()
    assert models  # the cross-check has teeth: >= 1 kernel participates
    for name, model in models.items():
        declared = KERNEL_WORK[name]["vmem_bytes"]
        assert model <= declared <= model * memrec.PRESIZE_BAND
        assert declared <= memrec.VMEM_CEILING


def test_hl205_fires_on_stale_declaration(monkeypatch):
    from harp_tpu.analysis import mosaic_audit
    from harp_tpu.ops import kernel_registry

    name = "kmeans.partials_int8"
    work = dict(kernel_registry.KERNEL_WORK[name])
    work["vmem_bytes"] = work["vmem_bytes"] * 4   # stale: way over band
    monkeypatch.setitem(kernel_registry.KERNEL_WORK, name, work)
    v = mosaic_audit.check_work_declarations()
    assert any(x.rule == "HL205" and name in x.path
               and "stale" in x.message for x in v)


# ---------------------------------------------------------------------------
# health: memory_pressure
# ---------------------------------------------------------------------------

def test_memory_pressure_fires_on_low_headroom():
    """A run whose peak leaves <10% headroom warns exactly once (the
    latch), carrying peak/capacity/headroom on the finding."""
    with telemetry.scope(True):
        memrec.set_hbm_capacity(1000)
        memrec.on_staged(950, "big")
        memrec.on_staged(10, "bigger")      # latch: no second finding
        rows = [r for r in sentinel.monitor.findings()
                if r["detector"] == "memory_pressure"]
        assert len(rows) == 1
        assert rows[0]["severity"] == "warn"
        assert rows[0]["peak_hbm_bytes"] >= 950
        assert rows[0]["hbm_bytes"] == 1000
        assert rows[0]["headroom_frac"] < sentinel.HEADROOM_WARN_FRAC


def test_memory_pressure_drift_against_baseline():
    with telemetry.scope(True):
        # plenty of headroom but 2x the committed baseline peak: drift
        sentinel.monitor.observe_memory("kmeans", 2_000_000,
                                        16 << 30,
                                        baseline_peak=1_000_000)
        rows = [r for r in sentinel.monitor.findings()
                if r["detector"] == "memory_pressure"]
        assert len(rows) == 1
        assert rows[0]["peak_drift_frac"] == 1.0
    # healthy: high headroom, no baseline — no finding
    with telemetry.scope(True):
        sentinel.monitor.observe_memory("kmeans", 1_000_000, 16 << 30)
        assert [r for r in sentinel.monitor.findings()
                if r["detector"] == "memory_pressure"] == []


# ---------------------------------------------------------------------------
# serve AOT cache sidecar (satellite 1)
# ---------------------------------------------------------------------------

def test_serve_cache_persists_memory_sidecar(tmp_path):
    """compile_and_store writes the memory_analysis() footprint beside
    the pickle; a warm load records the SAME footprint as a
    source='cache' executable row without touching the backend."""
    from harp_tpu.serve.cache import ExecutableCache

    cache = ExecutableCache(str(tmp_path), fingerprint="memtest")
    jitted = jax.jit(lambda x: x + 1.0)
    args = (jnp.zeros((8, 8), jnp.float32),)
    with telemetry.scope(True):
        cache.get_or_compile("memtest.prog", jitted, args)
        assert cache.misses == 1
        compile_rows = [r for r in memrec.ledger._rows
                        if r["ev"] == "executable"]
        assert len(compile_rows) == 1
        assert compile_rows[0]["source"] == "compile"
        assert compile_rows[0]["exec_hbm_bytes"] > 0
    sidecars = [f for f in os.listdir(tmp_path)
                if f.endswith(".mem.json")]
    assert len(sidecars) == 1
    fp = cache.footprint("memtest.prog", args)
    assert fp is not None
    assert fp["argument_bytes"] == 256 and fp["output_bytes"] == 256
    with telemetry.scope(True):
        cache.load("memtest.prog", args)
        assert cache.hits == 1
        rows = [r for r in memrec.ledger._rows
                if r["ev"] == "executable"]
        assert len(rows) == 1 and rows[0]["source"] == "cache"
        assert rows[0]["exec_hbm_bytes"] \
            == compile_rows[0]["exec_hbm_bytes"]


# ---------------------------------------------------------------------------
# the PR-3 contract: zero-cost off, budgets unchanged armed
# ---------------------------------------------------------------------------

def test_zero_cost_with_telemetry_off(mesh):
    """With telemetry off the ledger stays EMPTY through a full driver
    run — and the fit is bit-identical to the armed run (the ledger
    observes, never participates)."""
    from harp_tpu.models import kmeans

    pts = np.random.default_rng(0).normal(size=(256, 8)) \
        .astype(np.float32)
    memrec.reset()
    c_off, inertia_off = kmeans.fit(pts, k=4, iters=3, mesh=mesh, seed=0)
    assert memrec.ledger._rows == []
    assert memrec.snapshot() == {"peak_hbm_bytes": 0, "staged_bytes": 0,
                                 "donated_bytes": 0, "events": 0}
    with telemetry.scope(True):
        c_on, inertia_on = kmeans.fit(pts, k=4, iters=3, mesh=mesh,
                                      seed=0)
        assert memrec.ledger._rows != []    # staged H2D entered the set
    np.testing.assert_array_equal(np.asarray(c_off), np.asarray(c_on))
    assert inertia_off == inertia_on


def test_tracked_program_jaxpr_identical_on_off():
    """The dispatch hooks read shape/dtype metadata only: tracing a
    tracked-with-donation callable yields the IDENTICAL jaxpr with the
    ledger armed or off."""
    tracked = flightrec.track(jax.jit(lambda x: x * 2.0),
                              "memtest.jaxpr", donate_argnums=(0,))
    x = jnp.arange(8.0)
    memrec.reset()
    off = str(jax.make_jaxpr(lambda a: tracked(a))(x))
    with telemetry.scope(True):
        on = str(jax.make_jaxpr(lambda a: tracked(a))(x))
    assert on == off


def test_flagship_budget_pins_unchanged_with_memrec_armed(mesh):
    """The PR-3/PR-17 flagship budget — 1 dispatch, 1 stacked readback,
    0 steady compiles, 0 H2D — must hold bit-for-bit with the memory
    ledger armed: memrec adds rows, never flight traffic."""
    import harp_tpu.models.mfsgd as MF

    cfg = MF.MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                         entry_cap=32)
    with telemetry.scope():
        m = MF.MFSGD(64, 48, cfg, mesh, seed=3)
        u, i, v = MF.synthetic_ratings(64, 48, 600, rank=4, seed=3)
        m.set_ratings(u, i, v)
        m.train_epoch()       # warmup
        m.compile_epochs(3)
        m.train_epochs(3)     # steady (stacked-readback ops compiled)
        assert telemetry.enabled()          # memrec IS armed here
        with flightrec.budget(compiles=0, dispatches=1, readbacks=1,
                              h2d_bytes=0,
                              tag="mfsgd.train_epochs.memrec") as b:
            m.train_epochs(3)
        assert b.spent()["dispatches"] == 1
        assert b.spent()["readbacks"] == 1


# ---------------------------------------------------------------------------
# THE chaos drill (ISSUE 19 acceptance)
# ---------------------------------------------------------------------------

def test_memory_chaos_drill_one_reconciled_export(mesh, tmp_path):
    """Staging + donation + checkpoint restore + an injected over-VMEM
    Pallas config in ONE traced run: the refusal names the predicted
    bytes pre-dispatch, and the single export is invariant-17 clean —
    watermark re-derived exactly, donated buffers out of the live set,
    memory marks on the superstep timeline."""
    from harp_tpu.ops.kmeans_kernel import (kmeans_partials_int8,
                                            vmem_bytes_int8)
    from harp_tpu.utils.checkpoint import CheckpointManager

    def step(state, batch):
        return (state + batch.mean(0, keepdims=True)).sum()

    tracked = flightrec.track(jax.jit(step), "memdrill.step",
                              donate_argnums=(0,))
    x = np.random.default_rng(1).normal(size=(64, 8)).astype(np.float32)
    cm = CheckpointManager(str(tmp_path / "ckpt"))
    p = tmp_path / "drill.jsonl"
    predicted = vmem_bytes_int8(8000, 1024, 128)
    with telemetry.scope(True):
        with steptrace.run("mem.drill"):
            with steptrace.superstep("mem.drill", 0):
                x_dev = mesh.shard_array(x)        # staged (H2D)
                tracked(x_dev, jnp.asarray(x))     # donated + output
                cm.save(1, {"w": x})
                cm.restore(1)                      # restored, zero-delta
            with steptrace.superstep("mem.drill", 1):
                with pytest.raises(MemoryError) as ei:
                    kmeans_partials_int8(
                        np.zeros((8000, 1024), np.int8),
                        np.zeros((100, 1024), np.int8),
                        np.ones(100, np.float32),
                        np.zeros(100, np.float32),
                        np.ones(1024, np.float32), tile_rows=8000)
        telemetry.export(str(p))
    # (a) the refusal named the predicted footprint, before any launch
    assert str(predicted) in str(ei.value)
    assert "refused before dispatch" in str(ei.value)
    # (b) ONE reconciled export: the whole-file sweep (invariants 16+17)
    assert check_jsonl.check_file(str(p)) == []
    rows = telemetry.load_rows(str(p))
    s = memrec.summarize_rows(rows["memory"])
    assert s["errors"] == []
    assert s["staged_bytes"] >= x.nbytes
    assert s["donated_bytes"] == x.nbytes          # left the live set
    assert s["vmem_refusals"] == 1
    events = {(r.get("event"), r.get("label"))
              for r in rows["memory"] if r.get("ev") == "buffer"}
    assert ("restored", "ckpt:step_1") in events
    # the timeline carries the memory spine
    mem_marks = [r for r in rows["steptrace"]
                 if r.get("ev") == "mark" and r.get("source") == "memory"]
    assert len(mem_marks) >= 1
    assert all(m["peak_hbm_bytes"] > 0 for m in mem_marks)
    # healthy control: the same staging/dispatch with a FITTING config
    q = tmp_path / "control.jsonl"
    with telemetry.scope(True):
        with steptrace.run("mem.control"):
            with steptrace.superstep("mem.control", 0):
                x_dev = mesh.shard_array(x)
                tracked(x_dev, jnp.asarray(x))
                memrec.require_vmem_fit(
                    "kmeans.partials_int8",
                    vmem_bytes_int8(128, 256, 128), budget=14 << 20)
        telemetry.export(str(q))
    assert check_jsonl.check_file(str(q)) == []
    s = memrec.summarize_rows(telemetry.load_rows(str(q))["memory"])
    assert s["errors"] == [] and s["vmem_refusals"] == 0


# ---------------------------------------------------------------------------
# report + bench surfaces
# ---------------------------------------------------------------------------

def test_report_renders_memory_section():
    from harp_tpu import report

    with telemetry.scope(True):
        memrec.on_staged(1 << 20, "x")
        memrec.require_vmem_fit("memtest.kernel", 1 << 20,
                                budget=14 << 20)
        info = memrec.live_summary()
        row = report.build_row({}, {}, memory_info=info)
        assert row["memory"]["peak_hbm_bytes"] == 1 << 20
        text = report.render(row)
    assert "memory (device ledger): peak" in text
    # live_summary never bumps the seq — a later export stays clean
    with telemetry.scope(True):
        memrec.on_staged(64, "x")
        memrec.live_summary()
        memrec.live_summary()
        assert memrec.summarize_rows(_export_rows())["errors"] == []


def test_bench_delta_counters():
    with telemetry.scope(True):
        memrec.on_staged(100, "a")
        base = memrec.snapshot()
        memrec.on_staged(50, "b")
        memrec.note_freed(nbytes=100)
        d = memrec.delta_since(base)
        assert d["staged_bytes"] == 50
        assert d["events"] == 2
        assert d["peak_hbm_bytes"] == 150
        assert 0.0 <= d["headroom_frac"] <= 1.0
