"""`python -m harp_tpu report` — the merged run report (golden fixture)."""

import json

import pytest

import harp_tpu.__main__ as cli

# A deterministic fixture run: one epoch span with a nested ingest span,
# one comm tag with two sites, three metrics rows.
FIXTURE_SPANS = [
    {"kind": "span", "span": "epoch", "path": "epoch", "t0": 0.0,
     "dur": 2.0, "depth": 0},
    {"kind": "span", "span": "ingest", "path": "epoch/ingest", "t0": 0.25,
     "dur": 0.5, "depth": 1},
]
FIXTURE_COMMS = [
    {"kind": "comm", "tag": "kmeans.fit", "executions": 10,
     "site": "kmeans.py:322", "verb": "allreduce", "axis": "workers",
     "combiner": "add", "wire_dtype": None, "payload_bytes": 120_400,
     "calls_per_trace": 1, "leaves": 3},
    {"kind": "comm", "tag": "kmeans.fit", "executions": 10,
     "site": "kmeans.py:318", "verb": "push", "axis": "workers",
     "combiner": "add", "wire_dtype": None, "payload_bytes": 1_024,
     "calls_per_trace": 1, "leaves": 2},
]
FIXTURE_METRICS = [{"t": 0.1, "step": 0, "loss": 2.0},
                   {"t": 0.2, "step": 1, "loss": 1.0}]


@pytest.fixture
def fixture_run(tmp_path):
    tele = tmp_path / "run.jsonl"
    with open(tele, "w") as fh:
        for row in FIXTURE_SPANS + FIXTURE_COMMS:
            fh.write(json.dumps(row) + "\n")
    metrics = tmp_path / "metrics.jsonl"
    with open(metrics, "w") as fh:
        for row in FIXTURE_METRICS:
            fh.write(json.dumps(row) + "\n")
    return str(tele), str(metrics)


GOLDEN = """\
== harp-tpu run report ==
comm volume (per-shard wire bytes): 1.16 MiB
  by verb: allreduce            1.15 MiB
  by verb: push                 10.00 KiB
  tag kmeans.fit: 10 execution(s) × 118.58 KiB/exec = 1.16 MiB
    allreduce            kmeans.py:322            117.58 KiB/exec × 1 call(s) axis=workers op=add
    push                 kmeans.py:318            1.00 KiB/exec × 1 call(s) axis=workers op=add
spans (host phases):
  epoch                    2.0000 s
    ingest                   0.5000 s
metrics: 2 row(s)
  last: {"t": 0.2, "step": 1, "loss": 1.0}"""


def test_report_golden(fixture_run, capsys):
    tele, metrics = fixture_run
    rc = cli.main(["report", "--telemetry", tele, "--metrics", metrics])
    assert rc == 0
    out = capsys.readouterr().out
    human, machine = out.rsplit("\n", 2)[0], out.strip().splitlines()[-1]
    assert human == GOLDEN, f"---got---\n{human}\n---want---\n{GOLDEN}"
    rec = json.loads(machine)
    assert rec["config"] == "report"
    assert rec["comm_total_bytes"] == (120_400 + 1_024) * 10
    assert rec["comm_verbs"] == {"allreduce": 1_204_000, "push": 10_240}
    assert rec["comm_tags"]["kmeans.fit"]["executions"] == 10
    assert rec["spans"]["epoch"]["total_s"] == 2.0
    assert rec["metrics_rows"] == 2
    assert rec["metrics_last"]["loss"] == 1.0
    # provenance stamped (the benchmark_json path)
    for field in ("backend", "date", "commit"):
        assert field in rec


def test_report_json_only(fixture_run, capsys):
    tele, _ = fixture_run
    rc = cli.main(["report", "--telemetry", tele, "--json-only"])
    assert rc == 0
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    assert len(lines) == 1
    assert json.loads(lines[0])["comm_total_bytes"] == 1_214_240


FIXTURE_COMPILES = [
    {"kind": "compile", "event": "backend_compile", "count": 1,
     "dur": 0.14, "total_s": 0.14, "span": "epoch"},
    {"kind": "compile", "event": "backend_compile", "count": 2,
     "dur": 0.06, "total_s": 0.2, "span": "epoch"},
]
FIXTURE_TRANSFERS = [
    {"kind": "transfer", "op": "h2d", "site": "kmeans.py:300",
     "span": "epoch/ingest", "bytes": 25_600_000, "calls": 1},
    {"kind": "transfer", "op": "readback", "site": "kmeans.py:340",
     "span": "epoch", "bytes": 4, "calls": 1},
    {"kind": "transfer", "op": "dispatch", "site": "kmeans.fit",
     "span": "epoch", "bytes": 0, "calls": 1},
]


def test_report_roundtrips_flight_sections(tmp_path, capsys):
    """Satellite: a synthetic run carrying compile + transfer + ledger +
    span records round-trips through the CLI — the merged human report
    AND the one-line JSON both surface the new sections."""
    tele = tmp_path / "run.jsonl"
    with open(tele, "w") as fh:
        for row in (FIXTURE_SPANS + FIXTURE_COMMS + FIXTURE_COMPILES
                    + FIXTURE_TRANSFERS):
            fh.write(json.dumps(row) + "\n")
    rc = cli.main(["report", "--telemetry", str(tele)])
    assert rc == 0
    out = capsys.readouterr().out
    human, machine = out.rsplit("\n", 2)[0], out.strip().splitlines()[-1]
    # human report: both new sections, with span attribution
    assert "compiles (XLA backend): 2 in 0.200 s" in human
    assert "transfers (host<->device): H2D 24.41 MiB in 1 call(s); " \
           "D2H 4 B over 1 readback(s); 1 dispatch(es)" in human
    assert "h2d       kmeans.py:300" in human
    # pre-flight sections still render alongside
    assert "comm volume" in human and "spans (host phases):" in human
    # machine row: the same numbers, merged into the one JSON line
    rec = json.loads(machine)
    assert rec["compile"]["count"] == 2
    assert rec["compile"]["total_s"] == 0.2
    assert rec["compile"]["by_span"]["epoch"]["count"] == 2
    assert rec["transfer"]["h2d_bytes"] == 25_600_000
    assert rec["transfer"]["readbacks"] == 1
    assert rec["transfer"]["dispatches"] == 1
    assert len(rec["transfer"]["sites"]) == 3
    assert rec["comm_total_bytes"] == 1_214_240  # comm section unaffected


def test_report_without_flight_rows_keeps_old_shape(fixture_run, capsys):
    """Pre-flight-recorder exports keep their exact old report shape: no
    compile/transfer keys appear when the run recorded none."""
    tele, _ = fixture_run
    rc = cli.main(["report", "--telemetry", tele, "--json-only"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    assert "compile" not in rec and "transfer" not in rec


def test_report_listed_as_app(capsys):
    assert cli.main(["--list"]) == 0
    assert "report" in capsys.readouterr().out


def test_report_from_live_export(tmp_path, mesh, capsys):
    """End-to-end: enable telemetry, run a real collective, export, then
    report from the file — the HARP_TELEMETRY_OUT workflow."""
    import numpy as np

    import harp_tpu.utils.telemetry as T
    from harp_tpu.parallel import collective as C

    path = str(tmp_path / "live.jsonl")
    with T.scope():
        with T.span("phase"):
            op = C.host_op(mesh, C.allgather)
            with T.ledger.run("g", steps=5):
                op(np.ones((8, 128), np.float32))
        T.export(path)
    rc = cli.main(["report", "--telemetry", path])
    assert rc == 0
    out = capsys.readouterr().out
    rec = json.loads(out.strip().splitlines()[-1])
    per = 128 * 4  # one shard: [1, 128] f32
    assert rec["comm_verbs"] == {"allgather": per * 5}
    assert "phase" in rec["spans"]
