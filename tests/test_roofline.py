"""Roofline annotation math (utils/roofline.py)."""

import numpy as np

from harp_tpu.utils import roofline as R


def test_kmeans_annotation_math():
    # 1M×300 k=100 at 400 iter/s: flops = 4ndk·rate
    r = R.annotate("kmeans", {"n": 1_000_000, "d": 300, "k": 100,
                              "iters_per_sec": 400.0, "quantize": None})
    want_tflops = 4 * 1e6 * 300 * 100 * 400 / 1e12
    np.testing.assert_allclose(r["achieved_tflops"], round(want_tflops, 3))
    assert 0 < r["pct_peak_flops"] < 100
    # default-precision f32 matmuls run as single bf16 MXU passes, so the
    # compute wall is the bf16 peak (proven on silicon: kmeans_stream
    # measured 131 TF/s > the 49.25 TF/s f32 peak, 2026-07-31)
    assert r["roofline_peak"] == "bf16_flops"
    assert r["bound"] in ("compute", "memory")


def test_int8_uses_int8_peak_and_smaller_bytes():
    base = {"n": 1_000_000, "d": 300, "k": 100, "iters_per_sec": 400.0}
    f32 = R.annotate("kmeans", {**base, "quantize": None})
    i8 = R.annotate("kmeans_int8", {**base, "quantize": "int8"})
    assert i8["roofline_peak"] == "int8_ops"
    assert i8["pct_peak_flops"] < f32["pct_peak_flops"]  # higher peak
    assert i8["achieved_gbs"] < f32["achieved_gbs"]      # 1-byte points


def test_mesh_aggregate_metrics_divided_per_chip():
    # whole-mesh rates (kmeans iters/s, mlp samples/s) must be divided by
    # num_workers before the single-chip peak comparison — an 8-chip run
    # must not report 8x the per-chip utilization
    base = {"n": 1_000_000, "d": 300, "k": 100, "iters_per_sec": 400.0,
            "quantize": None}
    one = R.annotate("kmeans", {**base, "num_workers": 1})
    eight = R.annotate("kmeans", {**base, "num_workers": 8})
    np.testing.assert_allclose(eight["pct_peak_flops"] * 8,
                               one["pct_peak_flops"], rtol=1e-2)  # 2-dp rounding


def test_unmodeled_config_passes_through():
    r = {"trees_per_sec": 7.0}
    assert R.annotate("rf", r) == r
    assert R.annotate("rf", r) is not r  # copy, not alias


def test_missing_metric_passes_through():
    assert "pct_peak_flops" not in R.annotate("kmeans", {"n": 1})


def test_memory_vs_compute_bound_classification():
    # flops:bytes = 4ndk/(4nd+4n) = dk/(d+1) ≈ k for large d.  Machine
    # balance at the bf16 peak is 197 TF / 819 GB/s ≈ 240 flop/byte, so
    # tiny d·k (ratio 1.6) is memory-bound and the graded k=1000 shape
    # (ratio ≈ 997) is compute-bound.
    lo_k = R.annotate("kmeans", {"n": 1 << 20, "d": 4, "k": 2,
                                 "iters_per_sec": 100.0, "quantize": None})
    hi_k = R.annotate("kmeans", {"n": 1 << 20, "d": 300, "k": 1000,
                                 "iters_per_sec": 100.0, "quantize": None})
    assert lo_k["bound"] == "memory"
    assert hi_k["bound"] == "compute"


def test_measure_all_smoke_record_carries_roofline(mesh):
    # end-to-end: the measure_all pipeline annotates modeled configs
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "measure_all", os.path.join(os.path.dirname(__file__), "..",
                                    "scripts", "measure_all.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    recs = list(mod.run_all(smoke=True, only=["kmeans"]))
    assert len(recs) == 1 and "pct_peak_flops" in recs[0], recs


def test_variant_configs_share_their_family_model():
    """EVERY mfsgd/lda config the sweep runs must be annotated with its
    family's minimum-byte floor — a variant missing from WORK_MODELS
    records an in-window row with no roofline fields, silently thinning
    the very analysis the sprint exists to produce (round 5).  Derived
    from SPRINT_ORDER so the NEXT variant added to the sweep is guarded
    too, not just the six that existed when this was written."""
    import importlib.util
    import os

    from harp_tpu.utils import roofline as R

    spec = importlib.util.spec_from_file_location(
        "measure_all_rr", os.path.join(os.path.dirname(__file__), "..",
                                       "scripts", "measure_all.py"))
    ma = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ma)
    for cfg in ma.SPRINT_ORDER:
        for fam in ("mfsgd", "lda"):
            if cfg == fam or cfg.startswith(fam + "_"):
                assert R.WORK_MODELS.get(cfg) is R.WORK_MODELS[fam], cfg
