"""Superstep skew profiler (utils/skew.py) — per-worker load attribution.

Evidence layers, all on the 8-worker CPU sim:

1. numpy-golden skew stats for a deliberately imbalanced LDA corpus and
   an imbalanced MF-SGD rating matrix — the ingest records match the
   partitioners' ownership rule (``id // own``), and the execution
   counters folded into the stacked readbacks match them;
2. the flagship flight budgets are UNCHANGED with skew collection
   enabled (1 dispatch / 1 stacked readback per run, 0 post-warmup
   compiles) — the counters ride the EXISTING readback;
3. the imbalance model (max/mean → wasted chip-seconds, roofline
   composition) and ``suggest_rebalance`` → ``schedule.apply_rebalance``
   bridge;
4. export rows satisfy scripts/check_jsonl.py invariant 5, and the
   report CLI grows a ``skew`` section whose per-worker counts sum to
   the global total (the acceptance walkthrough);
5. ``op_breakdown(per_device=True)`` splits a synthetic multichip trace
   per device id with the default call unchanged.
"""

import gzip
import json
import os
import sys

import numpy as np
import pytest

from harp_tpu.utils import flightrec, skew, telemetry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import check_jsonl  # noqa: E402

needs_compile_events = pytest.mark.skipif(
    not flightrec.COMPILE_EVENTS_AVAILABLE,
    reason="this jax lacks the monitoring hook")


def _skewed_lda_corpus(seed=0):
    """64 docs, 48 vocab: docs 0-7 (worker 0's range at 8 workers) carry
    40 tokens each, the rest 4 — worker 0 holds ~4.7x the mean load."""
    rng = np.random.default_rng(seed)
    d_ids = np.concatenate([np.repeat(np.arange(8), 40),
                            np.repeat(np.arange(8, 64), 4)]).astype(np.int32)
    w_ids = rng.integers(0, 48, len(d_ids)).astype(np.int32)
    return d_ids, w_ids


# ---------------------------------------------------------------------------
# numpy-golden skew stats (ingest + execution)
# ---------------------------------------------------------------------------

def test_lda_skew_golden_imbalanced_corpus(mesh):
    """Ingest record == bincount by the partitioner's ownership rule
    (doc // d_own), and the execution counter folded into the stacked
    readback reproduces it exactly (every token touched once/sweep)."""
    import harp_tpu.models.lda as L

    cfg = L.LDAConfig(n_topics=8, algo="dense", d_tile=16, w_tile=16,
                      entry_cap=64)
    d_ids, w_ids = _skewed_lda_corpus()
    with telemetry.scope():
        model = L.LDA(64, 48, cfg, mesh, seed=0)
        model.set_tokens(d_ids, w_ids)
        expect = np.bincount(d_ids // model.d_own, minlength=8)
        ing = skew.ledger.summary()["lda.partition"]
        np.testing.assert_allclose(ing["work"], expect)
        assert ing["total"] == len(d_ids)
        assert 0.0 <= ing["padding_frac"] <= 1.0
        assert ing["source"] == "ingest"

        model.sample_epoch()
        ex = skew.ledger.summary()["lda.epochs"]
        np.testing.assert_allclose(ex["work"], expect)
        assert ex["total"] == len(d_ids) == model.n_tokens
        assert ex["source"] == "execution"
        golden_ratio = expect.max() / expect.mean()
        assert ex["max_mean_ratio"] == pytest.approx(golden_ratio, rel=1e-3)
        assert ex["wasted_frac"] == pytest.approx(
            1.0 - expect.mean() / expect.max(), rel=1e-3)
        assert ex["wall_s"] > 0 and ex["wasted_chip_s"] > 0


def test_mfsgd_skew_golden_imbalanced_ratings(mesh):
    """Same golden for MF-SGD: 70% of the ratings land on worker 0's
    user range; ingest and execution agree with numpy's bincount."""
    import harp_tpu.models.mfsgd as MF

    cfg = MF.MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                         entry_cap=32)
    rng = np.random.default_rng(1)
    u = np.concatenate([rng.integers(0, 8, 700),
                        rng.integers(8, 64, 300)]).astype(np.int32)
    i = rng.integers(0, 48, 1000).astype(np.int32)
    v = rng.normal(size=1000).astype(np.float32)
    with telemetry.scope():
        m = MF.MFSGD(64, 48, cfg, mesh, seed=0)
        m.set_ratings(u, i, v)
        expect = np.bincount(u // m.u_own, minlength=8)
        ing = skew.ledger.summary()["mfsgd.partition"]
        np.testing.assert_allclose(ing["work"], expect)
        assert ing["total"] == 1000

        m.train_epoch()
        ex = skew.ledger.summary()["mfsgd.epochs"]
        np.testing.assert_allclose(ex["work"], expect)
        assert ex["unit"] == "ratings"
        assert ex["max_mean_ratio"] == pytest.approx(
            expect.max() / expect.mean(), rel=1e-3)

        # train_epochs (the multi-epoch program) records the same vector
        m.train_epochs(2)
        ex2 = skew.ledger.summary()["mfsgd.epochs"]
        np.testing.assert_allclose(ex2["work"], expect)


def test_kmeans_fit_records_balanced_execution_skew(mesh):
    """kmeans shards evenly by construction — its record pins the
    balanced baseline (ratio 1.0, zero predicted waste)."""
    import harp_tpu.models.kmeans as KM

    pts = np.random.default_rng(0).normal(size=(256, 8)).astype(np.float32)
    with telemetry.scope():
        KM.fit(pts, k=4, iters=2, mesh=mesh, seed=0)
        s = skew.ledger.summary()["kmeans.fit"]
        np.testing.assert_allclose(s["work"], [32.0] * 8)
        assert s["total"] == 256
        assert s["max_mean_ratio"] == pytest.approx(1.0)
        assert s["wasted_frac"] == pytest.approx(0.0)


# ---------------------------------------------------------------------------
# flagship budgets UNCHANGED with skew collection enabled (satellite pin)
# ---------------------------------------------------------------------------

@needs_compile_events
def test_lda_flagship_budget_unchanged_with_skew_enabled(mesh):
    """The acceptance pin: with skew collection on (it rides the
    HARP_TELEMETRY switch), the lda flagship budget from
    tests/test_flightrec.py holds UNCHANGED — 1 dispatch + 1 stacked
    readback per sample_epochs run, 0 post-warmup compiles — because the
    per-worker counter rides the EXISTING readback."""
    import harp_tpu.models.lda as L

    cfg = L.LDAConfig(n_topics=8, algo="dense", d_tile=16, w_tile=16,
                      entry_cap=64)
    d_ids, w_ids = _skewed_lda_corpus()
    with telemetry.scope():
        lda = L.LDA(64, 48, cfg, mesh, seed=0)
        lda.set_tokens(d_ids, w_ids)
        lda.sample_epoch()  # warmup: the single-epoch compile
        lda.compile_epochs(2)
        keys_bytes = mesh.num_workers * 2 * 4
        for rerun in range(2):
            with flightrec.budget(compiles=0, dispatches=1, readbacks=1,
                                  h2d_bytes=keys_bytes,
                                  tag=f"lda.skew#{rerun}") as b:
                lda.sample_epochs(2)
            assert b.spent()["dispatches"] == 1
            assert b.spent()["readbacks"] == 1
        # and the counter it carried sums to the global token total
        ex = skew.ledger.summary()["lda.epochs"]
        assert sum(ex["work"]) == ex["total"] == lda.n_tokens


# ---------------------------------------------------------------------------
# the imbalance model + the scheduler bridge
# ---------------------------------------------------------------------------

def test_imbalance_model_and_roofline_composition():
    with telemetry.scope():
        skew.record_execution("p", [10, 2, 2, 2], unit="u", wall_s=2.0)
        s = skew.ledger.summary()["p"]
        assert s["max_mean_ratio"] == pytest.approx(2.5)  # 10 / 4
        assert s["wasted_frac"] == pytest.approx(0.6)     # 1 - 4/10
        # 4 chips idle 60% of a 2 s superstep
        assert s["wasted_chip_s"] == pytest.approx(4.8)
        # roofline composition: lda's work model at 1e9 tok/s/chip &
        # K=100 achieves 1.4e12/197e12 = 0.7107% of bf16 peak; skew
        # predicts 60% of that lost to the barrier
        pct = skew.wasted_pct_of_peak(
            "lda", {"n_topics": 100, "tokens_per_sec_per_chip": 1e9}, "p")
        assert pct == pytest.approx(0.7107 * 0.6, abs=1e-3)
        # unknown phase / config without a work model → None, not garbage
        assert skew.wasted_pct_of_peak("lda", {}, "nope") is None
        assert skew.wasted_pct_of_peak("no_model", {}, "p") is None


def test_suggest_rebalance_fractional_plan():
    with telemetry.scope():
        skew.record_execution("p", [10, 2, 2, 2], unit="u")
        plan = skew.suggest_rebalance("p")
        assert plan["ratio_before"] == pytest.approx(2.5)
        assert plan["ratio_after"] == pytest.approx(1.0)
        assert all(m["from"] == 0 for m in plan["moves"])
        assert sum(m["work"] for m in plan["moves"]) == pytest.approx(6.0)
        np.testing.assert_allclose(plan["work_after"], [4.0] * 4)
        assert skew.suggest_rebalance("unknown") is None


def test_suggest_rebalance_units_applies_through_schedule(mesh):
    """The scheduler bridge: record per-worker loads WITH movable units
    (files), get a whole-unit greedy plan, replay it on the
    fileformat-shaped splits via schedule.apply_rebalance."""
    from harp_tpu import schedule

    with telemetry.scope():
        skew.record_partition(
            "files", [10, 1, 0, 1], unit="bytes",
            units=[[("a", 6), ("b", 4)], [("c", 1)], [], [("d", 1)]])
        plan = skew.suggest_rebalance("files")
        assert plan["ratio_after"] < plan["ratio_before"]
        assert all("id" in m for m in plan["moves"])
        new = schedule.apply_rebalance([["a", "b"], ["c"], [], ["d"]],
                                       plan)
        # greedy LPT on measured sizes: a→w0, b→w1, c→w2, d→w3
        assert sorted(map(sorted, new)) == [["a"], ["b"], ["c"], ["d"]]

        # a fractional plan must refuse to shuffle items
        skew.record_execution("frac", [4, 0], unit="u")
        with pytest.raises(ValueError, match="fractional"):
            schedule.apply_rebalance([["x"], []],
                                     skew.suggest_rebalance("frac"))


def test_record_host_stamps_per_process_columns():
    with telemetry.scope():
        skew.record_host("sweep", 0, 1.0, n_workers=4)
        skew.record_host("sweep", 2, 3.0, n_workers=4)
        s = skew.ledger.summary()["sweep"]
        assert s["source"] == "host" and s["unit"] == "seconds"
        np.testing.assert_allclose(s["work"], [1.0, 0.0, 3.0, 0.0])


def test_skew_zero_cost_when_disabled():
    with telemetry.scope(False):
        skew.record_execution("p", [1, 2], unit="u")
        skew.record_partition("q", [1, 2], unit="rows")
        skew.record_host("r", 0, 1.0)
        assert skew.ledger.summary() == {}


# ---------------------------------------------------------------------------
# export / checker / report round trips (acceptance walkthrough)
# ---------------------------------------------------------------------------

def test_skew_export_rows_pass_check_jsonl(mesh, tmp_path):
    with telemetry.scope():
        skew.record_execution("p", [3, 1], unit="u", wall_s=0.5)
        skew.record_partition("q", [4, 4], unit="rows", padded_total=10)
        p = tmp_path / "skew.jsonl"
        telemetry.export(str(p))
    rows = telemetry.load_rows(str(p))
    assert len(rows["skew"]) == 2
    for r in rows["skew"]:
        for f in ("backend", "date", "commit"):
            assert f in r, (f, r)
        assert sum(r["work"]) == pytest.approx(r["total"])
    assert check_jsonl.check_file(str(p)) == []


def test_lda_run_report_shows_skew_section_end_to_end(mesh, tmp_path,
                                                      capsys):
    """THE acceptance criterion: a telemetry-enabled lda run on the
    8-worker sim with a skewed corpus → ``python -m harp_tpu report``
    prints a skew section whose per-worker counts sum to the global
    token total, with a max/mean ratio and predicted wasted chip-s."""
    import harp_tpu.__main__ as cli
    import harp_tpu.models.lda as L

    cfg = L.LDAConfig(n_topics=8, algo="dense", d_tile=16, w_tile=16,
                      entry_cap=64)
    d_ids, w_ids = _skewed_lda_corpus()
    path = str(tmp_path / "run.jsonl")
    with telemetry.scope():
        model = L.LDA(64, 48, cfg, mesh, seed=0)
        model.set_tokens(d_ids, w_ids)
        model.sample_epochs(2)
        telemetry.export(path)
    rc = cli.main(["report", "--telemetry", path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "skew (per-worker load" in out
    assert "lda.epochs" in out and "max/mean" in out
    rec = json.loads(out.strip().splitlines()[-1])
    sk = rec["skew"]["lda.epochs"]
    assert sum(sk["work"]) == pytest.approx(sk["total"])
    assert sk["total"] == model.n_tokens
    assert sk["max_mean_ratio"] > 1.5  # the corpus IS skewed
    assert sk["wasted_chip_s"] > 0
    # the ingest-side record travels too, with its padding fraction
    assert 0.0 <= rec["skew"]["lda.partition"]["padding_frac"] <= 1.0


def test_live_report_and_render_skew(mesh):
    from harp_tpu import report

    with telemetry.scope():
        skew.record_execution("phase.x", [8, 2, 2, 2, 2, 2, 2, 2],
                              unit="items", wall_s=1.0)
        row, spans = report.live_report()
    assert row["skew"]["phase.x"]["max_mean_ratio"] == pytest.approx(
        8 / 2.75, rel=1e-3)
    text = report.render(row, spans)
    assert "skew (per-worker load" in text
    assert "w0" in text and "#" in text  # the per-worker histogram


# ---------------------------------------------------------------------------
# scaling sweep / projection carry-through (satellite)
# ---------------------------------------------------------------------------

def _load_script(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_scaling_sweep_skew_columns_prefer_execution_phase():
    ss = _load_script("scaling_sweep")
    with telemetry.scope():
        skew.record_partition("x.partition", [9, 1], unit="tokens")
        skew.record_execution("x.epochs", [9, 1], unit="tokens",
                              wall_s=1.0)
        cols = ss.skew_columns()
    assert cols["skew_phase"] == "x.epochs"
    assert cols["skew_max_mean"] == pytest.approx(1.8)
    assert cols["skew_work"] == [9.0, 1.0]
    with telemetry.scope():
        assert ss.skew_columns() == {"skew_max_mean": None}  # nothing yet


def test_project_scaling_measured_skew_picks_highest_worker_count(
        tmp_path):
    ps = _load_script("project_scaling")
    p = tmp_path / "SCALING_local.jsonl"
    rows = [
        {"app": "lda", "n_workers": 4, "skew_max_mean": 1.5},
        {"app": "lda", "n_workers": 8, "skew_max_mean": 1.2},
        {"app": "mfsgd", "n_workers": 8, "skew_max_mean": None},
        {"app": "kmeans", "n_workers": 8},
        "not json at all",
    ]
    p.write_text("".join(
        (r if isinstance(r, str) else json.dumps(r)) + "\n" for r in rows))
    out = ps.measured_skew(str(p))
    assert out == {"lda": 1.2}


# ---------------------------------------------------------------------------
# op_breakdown per-device split (small-fix satellite)
# ---------------------------------------------------------------------------

def test_op_breakdown_per_device_ids(tmp_path):
    """Synthetic multichip trace dump: per_device=True splits totals by
    the device ordinal from the process metadata; the default call keeps
    its old aggregated shape and numbers."""
    from harp_tpu.utils.profiling import op_breakdown

    d = tmp_path / "plugins" / "profile" / "0001"
    d.mkdir(parents=True)
    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0 (chip 0)"}},
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/device:TPU:1 (chip 1)"}},
        {"ph": "X", "pid": 1, "tid": 0, "ts": 0, "dur": 100,
         "name": "fusion.1"},
        {"ph": "X", "pid": 2, "tid": 0, "ts": 0, "dur": 300,
         "name": "fusion.1"},
        {"ph": "X", "pid": 2, "tid": 0, "ts": 400, "dur": 50,
         "name": "copy.2"},
        # host track: filtered out once device tracks exist
        {"ph": "X", "pid": 7, "tid": 0, "ts": 0, "dur": 999,
         "name": "host_thing"},
    ]
    with gzip.open(d / "x.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)

    agg = dict(op_breakdown(str(tmp_path)))
    assert agg["fusion.1"] == pytest.approx(400e-6)
    assert agg["copy.2"] == pytest.approx(50e-6)
    assert "host_thing" not in agg

    per = {(n, dev): t
           for n, dev, t in op_breakdown(str(tmp_path), per_device=True)}
    assert per[("fusion.1", 0)] == pytest.approx(100e-6)
    assert per[("fusion.1", 1)] == pytest.approx(300e-6)
    assert per[("copy.2", 1)] == pytest.approx(50e-6)
