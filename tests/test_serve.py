"""harp serve — micro-batcher, AOT executable cache, engines, server.

The acceptance gates of the serving subsystem, all on the 8-sim-worker
CPU mesh (no relay):

- shape-ladder bucketing is minimal (padding bounded), ragged tails pad
  to their rung, oversized requests span batches and reassemble;
- the steady-state loop holds ``compiles=0, dispatches=1, readbacks=1``
  per batch for kmeans-assign AND mfsgd-topk (the budget pin);
- a warm restart against a populated executable cache performs ZERO XLA
  compiles before serving its first request (CompileWatch-proven);
- engine outputs match numpy references;
- the stdio JSONL protocol round-trips end-to-end, checkpoint included.
"""

import io
import json

import numpy as np
import pytest

from harp_tpu.serve.batcher import MicroBatcher, ShapeLadder
from harp_tpu.serve.engines import ENGINES
from harp_tpu.serve.server import Server
from harp_tpu.utils import flightrec, telemetry


# ---------------------------------------------------------------------------
# ShapeLadder / MicroBatcher (pure host, no jax)
# ---------------------------------------------------------------------------

def test_ladder_bucket_is_minimal_rung():
    lad = ShapeLadder((1, 8, 64, 512))
    assert lad.bucket(1) == 1
    assert lad.bucket(2) == 8
    assert lad.bucket(8) == 8
    assert lad.bucket(9) == 64
    assert lad.bucket(512) == 512
    with pytest.raises(ValueError):
        lad.bucket(513)
    with pytest.raises(ValueError):
        lad.bucket(0)


def test_ladder_padding_fraction_bounded():
    # minimality bound: (rung - n)/rung < 1 - prev_rung/rung for every n
    lad = ShapeLadder((1, 8, 64, 512))
    rungs = (0,) + lad.rungs
    for n in range(1, 513):
        s = lad.bucket(n)
        prev = max(r for r in rungs if r < s)
        assert (s - n) / s < 1 - prev / s + 1e-12


def test_batcher_coalesces_and_pads_ragged_tail():
    mb = MicroBatcher((1, 8, 32))
    for i in range(5):
        mb.put(i, 9)  # 45 rows queued
    batches = list(mb.batches())
    assert [b.rung for b in batches] == [32, 32]
    assert [b.rows for b in batches] == [32, 13]
    assert batches[1].padding_frac == pytest.approx((32 - 13) / 32)
    # every row of every request landed exactly once, in order
    seen = {i: 0 for i in range(5)}
    for b in batches:
        for req, lo, hi in b.requests:
            assert hi > lo
            assert lo == seen[req]  # contiguous, in-order slices
            seen[req] = hi
    assert all(v == 9 for v in seen.values())
    assert mb.padding_frac() == pytest.approx((64 - 45) / 64)


def test_batcher_single_request_takes_smallest_rung():
    mb = MicroBatcher((1, 8, 64))
    mb.put("a", 1)
    (b,) = list(mb.batches())
    assert b.rung == 1 and b.rows == 1 and b.padding_frac == 0.0


def test_batcher_request_larger_than_max_rung_spans_batches():
    mb = MicroBatcher((1, 8, 32))
    mb.put("big", 70)
    batches = list(mb.batches())
    assert [b.rung for b in batches] == [32, 32, 8]
    assert [b.rows for b in batches] == [32, 32, 6]
    slices = [(lo, hi) for b in batches for _, lo, hi in b.requests]
    assert slices == [(0, 32), (32, 64), (64, 70)]


# ---------------------------------------------------------------------------
# flightrec.SteadyState (the serving-loop guard)
# ---------------------------------------------------------------------------

def test_steady_state_raises_on_violation(mesh):
    with telemetry.scope(True):
        steady = flightrec.SteadyState(compiles=0, dispatches=0,
                                       readbacks=1, tag="t")
        with pytest.raises(flightrec.BudgetExceeded, match="dispatches"):
            with steady.batch():
                flightrec.transfers.record_dispatch("site")
        assert steady.violations == 1


def test_steady_state_warn_mode_counts_and_continues(mesh):
    with telemetry.scope(True):
        steady = flightrec.SteadyState(dispatches=0, action="warn",
                                       tag="t")
        with pytest.warns(RuntimeWarning, match="steady-state budget"):
            with steady.batch():
                flightrec.transfers.record_dispatch("site")
        with steady.batch():
            pass
        s = steady.summary()
        assert s["batches"] == 2 and s["violations"] == 1


def test_steady_state_noop_when_disabled(mesh):
    steady = flightrec.SteadyState(dispatches=0)
    with telemetry.scope(False):
        with steady.batch():
            pass
    assert steady.batches == 0


# ---------------------------------------------------------------------------
# Engines vs numpy references
# ---------------------------------------------------------------------------

def _server(app, state, mesh, tmp_path, ladder=(1, 8, 64), **opts):
    srv = Server(app, state=state, mesh=mesh, ladder=ladder,
                 cache_dir=str(tmp_path / f"aot_{app}"),
                 engine_opts=opts or None)
    srv.startup()
    return srv


def test_kmeans_assign_matches_numpy(mesh, tmp_path):
    rng = np.random.default_rng(0)
    state = ENGINES["kmeans"].synthetic_state(rng, k=16, d=32)
    srv = _server("kmeans", state, mesh, tmp_path)
    x = rng.normal(size=(11, 32)).astype(np.float32)
    (resp,) = srv.process([{"id": 7, "x": x.tolist()}])
    ref = np.argmin(((x[:, None, :] - state["centroids"][None]) ** 2
                     ).sum(-1), axis=1)
    assert resp["id"] == 7 and resp["result"] == ref.tolist()


def test_mfsgd_topk_matches_numpy(mesh, tmp_path):
    rng = np.random.default_rng(1)
    # n_items deliberately NOT divisible by 8 workers: the padded shard
    # must never leak a phantom item into the top-k
    state = ENGINES["mfsgd"].synthetic_state(rng, n_users=64, n_items=50,
                                             rank=8)
    srv = _server("mfsgd", state, mesh, tmp_path, topk=5)
    users = [0, 13, 49, 63]
    (resp,) = srv.process([{"id": 1, "users": users}])
    W, H = state["W"], state["H"]
    for row, u in zip(resp["result"], users):
        scores = W[u] @ H.T
        ref = np.argsort(-scores)[:5]
        assert row["items"] == ref.tolist()
        np.testing.assert_allclose(row["scores"], scores[ref], rtol=1e-4)


def test_lda_infer_recovers_dominant_topic(mesh, tmp_path):
    # peaked synthetic phi: topic t owns vocab band t — a doc drawn from
    # one band must fold in to that topic
    V, K = 64, 4
    Nwk = np.full((V, K), 0.1, np.float32)
    band = V // K
    for t in range(K):
        Nwk[t * band:(t + 1) * band, t] = 100.0
    srv = _server("lda", {"Nwk": Nwk}, mesh, tmp_path)
    x = np.zeros((2, V), np.float32)
    x[0, 2 * band:3 * band] = 5.0   # topic 2 words
    x[1, 0:band] = 3.0              # topic 0 words
    (resp,) = srv.process([{"id": 0, "x": x.tolist()}])
    thetas = np.asarray([r["theta"] for r in resp["result"]])
    np.testing.assert_allclose(thetas.sum(1), 1.0, atol=1e-3)
    assert thetas[0].argmax() == 2 and thetas[1].argmax() == 0


def test_mlp_rf_svm_predict_roundtrip(mesh, tmp_path):
    rng = np.random.default_rng(2)
    for app in ("mlp", "rf", "svm"):
        state = ENGINES[app].synthetic_state(rng)
        srv = _server(app, state, mesh, tmp_path, ladder=(1, 8))
        req = srv.engine.synthetic_request(rng, 5)
        (resp,) = srv.process([{"id": app, **req}])
        assert resp["id"] == app and len(resp["result"]) == 5
    # svm label is the sign of the score
    assert all(r["label"] == (1 if r["score"] >= 0 else -1)
               for r in resp["result"])


def test_engine_rejects_bad_state_and_bad_rows(mesh, tmp_path):
    rng = np.random.default_rng(3)
    with pytest.raises(KeyError, match="centroids"):
        ENGINES["kmeans"]({"wrong": 1}, mesh)
    state = ENGINES["kmeans"].synthetic_state(rng, k=4, d=8)
    srv = _server("kmeans", state, mesh, tmp_path, ladder=(1, 8))
    resp = srv.process([
        {"id": 0, "x": [[0.0] * 8]},          # fine
        {"id": 1, "x": [[0.0] * 5]},          # wrong width
        {"id": 2},                            # missing key
    ])
    assert "result" in resp[0]
    assert "error" in resp[1] and "error" in resp[2]


def test_oversized_request_reassembles_across_batches(mesh, tmp_path):
    rng = np.random.default_rng(4)
    state = ENGINES["kmeans"].synthetic_state(rng, k=8, d=16)
    srv = _server("kmeans", state, mesh, tmp_path, ladder=(1, 8, 32))
    x = rng.normal(size=(70, 16)).astype(np.float32)
    (resp,) = srv.process([{"id": 0, "x": x.tolist()}])
    ref = np.argmin((((x[:, None, :] - state["centroids"][None]) ** 2)
                     ).sum(-1), axis=1)
    assert resp["result"] == ref.tolist()
    assert [r for r, _, _ in srv.last_batch_times] == [32, 32, 8]


# ---------------------------------------------------------------------------
# THE budget pin: steady state at compiles=0, dispatches=1, readbacks=1
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("app", ["kmeans", "mfsgd"])
def test_steady_state_budget_pin(app, mesh, tmp_path):
    rng = np.random.default_rng(5)
    state = ENGINES[app].synthetic_state(rng)
    with telemetry.scope(True):
        srv = _server(app, state, mesh, tmp_path, ladder=(1, 8, 64))
        # warm every rung once (first dispatch may e.g. transfer consts)
        srv.process([srv.engine.synthetic_request(rng, n)
                     for n in (1, 8, 64)])
        srv.steady.reset()
        base = flightrec.snapshot()
        reqs = [srv.engine.synthetic_request(rng, 3) for _ in range(12)]
        srv.process(reqs)  # 36 rows → batches of 8-rung/64-rung shapes
        spent = flightrec.delta_since(base)
        n_batches = srv.steady.batches
        assert n_batches >= 1
        # EXACT accounting, not just under-budget: one dispatch and one
        # stacked readback per batch, zero compiles in steady state
        assert spent["compiles"] == 0
        assert spent["dispatches"] == n_batches
        assert spent["readbacks"] == n_batches
        assert srv.steady.violations == 0


def test_budget_violation_is_loud_in_raise_mode(mesh, tmp_path):
    rng = np.random.default_rng(6)
    state = ENGINES["kmeans"].synthetic_state(rng, k=4, d=8)
    with telemetry.scope(True):
        srv = _server("kmeans", state, mesh, tmp_path, ladder=(1, 8))
        # sabotage: an extra tracked dispatch inside the batch scope must
        # trip the dispatches=1 budget (the per-epoch-dispatch trap)
        real_exec = srv._exec[1]

        def noisy(*args):
            flightrec.transfers.record_dispatch("extra")
            return real_exec(*args)

        srv._exec[1] = noisy
        with pytest.raises(flightrec.BudgetExceeded, match="dispatches"):
            srv.process([srv.engine.synthetic_request(rng, 1)])


# ---------------------------------------------------------------------------
# Continuous plane: scheduler policy, in-flight admission, exact budgets
# ---------------------------------------------------------------------------

def test_continuous_scheduler_policy_on_injected_clock():
    """The two knobs on a deterministic timeline: never hold work while
    idle, accumulate while in flight, flush at the deadline, fill-aware
    rung choice (full smaller rungs from a deep backlog, pad up only at
    >= half fill — the rule that turned the first sustained sweep's
    0.81x regression into the 1.78x win)."""
    from harp_tpu.serve.batcher import ContinuousScheduler

    s = ContinuousScheduler((1, 8, 64), max_queue_delay_s=0.010)
    assert not s.ready(0.0, idle=True)          # nothing queued
    s.put("a", 1, 0.0)
    assert s.ready(0.0, idle=True)              # idle never holds work
    assert not s.ready(0.0, idle=False)         # in flight: accumulate
    assert not s.ready(0.009, idle=False)       # deadline not reached
    assert s.ready(0.010, idle=False)           # max-queue-delay flush
    assert s.next_deadline() == pytest.approx(0.010)
    s.put("b", 63, 0.001)
    assert s.ready(0.001, idle=False)           # 64 rows = max rung
    b = s.next_batch(0.001)
    assert b.rung == 64 and b.rows == 64        # full max-rung batch
    assert len(s) == 0

    # fill-aware rung choice: 100-row backlog on a (1, 8, 64, 512)
    # ladder must NOT cover at 512 (80% padding) — it takes a full 64
    s2 = ContinuousScheduler((1, 8, 64, 512))
    s2.put("big", 100, 0.0)
    b1 = s2.next_batch(0.0)
    assert (b1.rung, b1.rows) == (64, 64)
    b2 = s2.next_batch(0.0)                     # 36 left: 64-rung >= half
    assert (b2.rung, b2.rows) == (64, 36)
    assert s2.padding_frac() == pytest.approx(28 / 128)
    # 5 queued rows: >= half of rung 8, pad up rather than 5x rung-1
    s2.put("c", 5, 0.0)
    b3 = s2.next_batch(0.0)
    assert (b3.rung, b3.rows) == (8, 5)
    # greedy policy covers everything at the minimal rung (PR 6 rule)
    g = ContinuousScheduler((1, 8, 64, 512), rung_policy="greedy")
    g.put("big", 100, 0.0)
    assert g.ready(0.0, idle=False)             # greedy never waits
    bg = g.next_batch(0.0)
    assert (bg.rung, bg.rows) == (512, 100)


def test_continuous_admission_while_in_flight_and_order(mesh, tmp_path):
    """Seeded arrival trace through the runner on a fake clock: requests
    from two interleaved connections are admitted WHILE batches are in
    flight, every response matches numpy, and each connection's
    responses come back in its admission order."""
    rng = np.random.default_rng(30)
    state = ENGINES["kmeans"].synthetic_state(rng, k=8, d=16)
    srv = _server("kmeans", state, mesh, tmp_path, ladder=(1, 8, 32))
    runner = srv.make_runner(max_queue_delay_s=0.005,
                             clock=lambda: 0.0)
    ref_x = {}
    arrivals = rng.exponential(0.001, size=20).cumsum()
    order = []
    out = []
    for i, t in enumerate(arrivals):
        conn = "A" if i % 3 else "B"
        key = (conn, i)
        x = rng.normal(size=(1 + i % 4, 16)).astype(np.float32)
        ref_x[key] = x
        order.append(key)
        assert runner.submit(key, {"id": i, "x": x.tolist()},
                             now=float(t)) == []
        out.extend(runner.step(float(t)))  # admission mid-pipeline
    out.extend(runner.drain(float(arrivals[-1])))
    assert runner.pending() == 0
    got = {k: r for k, r in out}
    assert len(got) == 20
    cent = state["centroids"]
    for key, x in ref_x.items():
        ref = np.argmin(((x[:, None, :] - cent[None]) ** 2).sum(-1), 1)
        assert got[key]["result"] == ref.tolist()
    for conn in ("A", "B"):
        keys = [k for k, _ in out if k[0] == conn]
        assert keys == [k for k in order if k[0] == conn]  # FIFO per conn


def test_continuous_oversized_request_spans_in_flight(mesh, tmp_path):
    """An oversized request spans several batches while OTHER requests
    are admitted mid-flight; reassembly is exact and ordered."""
    rng = np.random.default_rng(31)
    state = ENGINES["kmeans"].synthetic_state(rng, k=8, d=16)
    srv = _server("kmeans", state, mesh, tmp_path, ladder=(1, 8, 32))
    runner = srv.make_runner(clock=lambda: 0.0)
    big = rng.normal(size=(70, 16)).astype(np.float32)
    runner.submit("big", {"id": "big", "x": big.tolist()}, now=0.0)
    out = list(runner.step(0.0))        # dispatch rows 0..31
    small = rng.normal(size=(2, 16)).astype(np.float32)
    runner.submit("small", {"id": "small", "x": small.tolist()},
                  now=0.0)              # admitted while big is in flight
    out += runner.drain(0.0)
    keys = [k for k, _ in out]
    assert keys == ["big", "small"]     # big's tail still beats small
    got = {k: r for k, r in out}
    cent = state["centroids"]
    for key, x in (("big", big), ("small", small)):
        ref = np.argmin(((x[:, None, :] - cent[None]) ** 2).sum(-1), 1)
        assert got[key]["result"] == ref.tolist()
    assert runner.dispatched >= 3       # 32 + 32 + ragged tail


@pytest.mark.parametrize("app", ["kmeans", "mfsgd"])
def test_continuous_steady_state_budget_pin(app, mesh, tmp_path):
    """THE continuous budget pin: windows stay under (compiles=0,
    dispatches<=1, readbacks<=1) and the run totals are EXACT — one
    dispatch and one readback per dispatched batch, zero compiles."""
    rng = np.random.default_rng(32)
    state = ENGINES[app].synthetic_state(rng)
    with telemetry.scope(True):
        srv = _server(app, state, mesh, tmp_path, ladder=(1, 8, 64))
        srv.process([srv.engine.synthetic_request(rng, n)
                     for n in (1, 8, 64)])      # warm every rung
        srv.steady.reset()
        base = flightrec.snapshot()
        runner = srv.make_runner(clock=lambda: 0.0)
        for i in range(12):
            runner.submit(i, srv.engine.synthetic_request(rng, 3),
                          now=0.0)
            runner.step(0.0)
        runner.drain(0.0)
        spent = flightrec.delta_since(base)
        n_batches = runner.dispatched
        assert n_batches >= 2
        assert spent["compiles"] == 0
        assert spent["dispatches"] == n_batches
        assert spent["readbacks"] == n_batches
        assert srv.steady.violations == 0
        assert runner.verify_exact() == spent


def test_continuous_sabotaged_overlap_raises(mesh, tmp_path):
    """A window that dispatches twice (broken overlap bookkeeping) must
    trip the per-window budget loudly, and verify_exact must catch a
    readback that bypassed the tracked path."""
    rng = np.random.default_rng(33)
    state = ENGINES["kmeans"].synthetic_state(rng, k=4, d=8)
    with telemetry.scope(True):
        srv = _server("kmeans", state, mesh, tmp_path, ladder=(1, 8))
        runner = srv.make_runner(clock=lambda: 0.0)
        real_exec = srv._exec[1]

        def noisy(*args):
            flightrec.transfers.record_dispatch("extra")
            return real_exec(*args)

        srv._exec[1] = noisy
        runner.submit(0, srv.engine.synthetic_request(rng, 1), now=0.0)
        with pytest.raises(flightrec.BudgetExceeded, match="dispatches"):
            runner.step(0.0)
        srv._exec[1] = real_exec

        # under-spending is as wrong as over-spending: a batch whose
        # readback bypassed flightrec.readback leaves totals short
        srv.steady.reset()
        runner2 = srv.make_runner(clock=lambda: 0.0)
        runner2.submit(1, srv.engine.synthetic_request(rng, 1), now=0.0)
        runner2.step(0.0)                     # dispatch
        batch, out_dev = runner2._in_flight.popleft()
        np.asarray(out_dev)                   # untracked readback
        runner2._complete(batch, np.asarray(out_dev), 0.0)
        with pytest.raises(flightrec.BudgetExceeded, match="readbacks"):
            runner2.verify_exact()


def test_sustained_ab_row_is_coherent(mesh):
    """The in-process sustained A/B at smoke shape: same seeded trace
    through both planes, offered >= achieved > 0, exact steady totals,
    queue evidence present.  (The >= 1.3x acceptance ratio is graded on
    the committed full-shape row, not asserted at smoke shapes.)"""
    from harp_tpu.serve.bench import benchmark_sustained

    res = benchmark_sustained(app="kmeans", n_requests=96,
                              rows_per_request=1, burst_admit=8,
                              ladder=(1, 8, 32),
                              state_shape={"k": 8, "d": 16})
    assert res["mode"] == "sustained"
    assert res["offered_qps"] >= res["achieved_qps"] > 0
    assert res["burst_qps"] > 0
    assert res["qps_ratio_vs_burst"] == pytest.approx(
        res["achieved_qps"] / res["burst_qps"], rel=1e-3)
    assert res["steady_compiles"] == 0
    assert res["steady_dispatches"] == res["batches"]
    assert res["steady_readbacks"] == res["batches"]
    assert res["budget_violations"] == 0
    assert res["p50_ms"] <= res["p95_ms"] <= res["p99_ms"]
    for k in ("qdepth_p50", "qdepth_p95", "qdepth_p99"):
        assert res[k] >= 0


# ---------------------------------------------------------------------------
# Fault plane: shedding, deadlines, retry-with-restage, isolation (PR 10)
# ---------------------------------------------------------------------------

def _kmeans_server(mesh, tmp_path, seed=40, k=4, d=8, ladder=(1, 8),
                   budget_action="raise"):
    rng = np.random.default_rng(seed)
    state = ENGINES["kmeans"].synthetic_state(rng, k=k, d=d)
    srv = Server("kmeans", state=state, mesh=mesh, ladder=ladder,
                 cache_dir=str(tmp_path / "aot"),
                 budget_action=budget_action)
    srv.startup()
    return srv, state, rng


def _assign_ref(state, x):
    return np.argmin(((x[:, None, :] - state["centroids"][None]) ** 2
                      ).sum(-1), 1).tolist()


def test_runner_sheds_on_admission_queue_full(mesh, tmp_path):
    """Bounded admission: a request that would overflow the queue gets a
    STRUCTURED shed response at submit — and admission reopens once the
    queue drains."""
    srv, state, rng = _kmeans_server(mesh, tmp_path)
    runner = srv.make_runner(max_queue_rows=4, rung_policy="greedy")
    xa = rng.normal(size=(3, 8)).astype(np.float32)
    assert runner.submit("a", {"id": "a", "x": xa.tolist()}, now=0.0) == []
    ((key, resp),) = runner.submit(
        "b", {"id": "b", "x": rng.normal(size=(3, 8)).tolist()}, now=0.0)
    assert key == "b" and resp["shed"] is True
    assert resp["reason"] == "queue_full"
    assert "shed" in resp["error"] and resp["id"] == "b"
    assert runner.shed == 1
    got = dict(runner.drain(now=0.0))
    assert got["a"]["result"] == _assign_ref(state, xa)
    # queue drained: the next request is admitted, not shed
    assert runner.submit(
        "c", {"id": "c", "x": xa.tolist()}, now=1.0) == []
    assert dict(runner.drain(now=1.0))["c"]["result"] == \
        _assign_ref(state, xa)


def test_runner_deadline_sheds_queued_and_counts_late(mesh, tmp_path):
    """Per-request deadlines: a request still queued past its deadline
    is shed with a structured error (never dispatched, never unbounded
    latency); one that completes late is served but counted."""
    srv, state, rng = _kmeans_server(mesh, tmp_path)
    runner = srv.make_runner(deadline_s=0.05, rung_policy="greedy")
    xa = rng.normal(size=(2, 8)).astype(np.float32)
    runner.submit("a", {"id": "a", "x": xa.tolist()}, now=0.0)
    ((key, resp),) = runner.step(now=0.2)  # expired before any dispatch
    assert key == "a" and resp["shed"] is True
    assert resp["reason"] == "deadline"
    assert runner.shed == 1 and runner.pending() == 0

    # late COMPLETION: dispatched in time, read back after the deadline
    runner.submit("b", {"id": "b", "x": xa.tolist()}, now=1.0)
    assert runner.step(now=1.0) == []  # dispatch window
    got = dict(runner.step(now=2.0))   # readback, 1 s late
    assert got["b"]["result"] == _assign_ref(state, xa)
    assert runner.deadline_misses == 1
    assert runner.shed == 1  # the late serve was NOT shed


def test_runner_retries_transient_fault_with_fresh_stage(mesh, tmp_path):
    """Retry-with-restage: an injected transient dispatch fault retries
    the batch through a FRESHLY staged buffer (the donated one is never
    re-dispatched — the serve.retry_restage protocol drive proves that
    under the HL303 audit at lint time); every response still comes back
    correct and the steady-state totals stay EXACT (failed attempts are
    never counted as dispatches)."""
    from harp_tpu.utils.fault import FaultInjector

    with telemetry.scope(True):
        srv, state, rng = _kmeans_server(mesh, tmp_path)
        runner = srv.make_runner(max_retries=2, rung_policy="greedy")
        inj = FaultInjector(seed=0, fail={"dispatch": (2,)})
        xs = {f"r{i}": rng.normal(size=(1, 8)).astype(np.float32)
              for i in range(4)}
        got = {}
        with inj.arm():
            for key, x in xs.items():
                runner.submit(key, {"id": key, "x": x.tolist()})
                got.update(runner.step())
            got.update(runner.drain())
        assert inj.injected["dispatch"] == 1
        assert runner.fault_retries == 1
        assert runner.engine_failures == 0
        for key, x in xs.items():
            assert got[key]["result"] == _assign_ref(state, x)
        spent = runner.verify_exact()  # exact despite the fault
        assert spent["dispatches"] == runner.dispatched
        assert srv.steady.violations == 0


def test_runner_hard_failure_isolates_batch(mesh, tmp_path):
    """Retries exhausted: the batch's requests get structured errors and
    the runner KEEPS SERVING — one crashing batch is not a dead server."""
    from harp_tpu.utils.fault import FaultInjector

    srv, state, rng = _kmeans_server(mesh, tmp_path)
    runner = srv.make_runner(max_retries=1, rung_policy="greedy")
    inj = FaultInjector(fail={"dispatch": (2, 3)})  # batch 2, both tries
    xa = rng.normal(size=(1, 8)).astype(np.float32)
    xc = rng.normal(size=(1, 8)).astype(np.float32)
    got = {}
    with inj.arm():
        runner.submit("a", {"id": "a", "x": xa.tolist()})
        got.update(runner.step())
        runner.submit("b", {"id": "b", "x": xa.tolist()})
        got.update(runner.step())  # fails, retries, hard-fails
        runner.submit("c", {"id": "c", "x": xc.tolist()})
        got.update(runner.step())
        got.update(runner.drain())
    assert "engine failure after 1 retries" in got["b"]["error"]
    assert "shed" not in got["b"]  # a hard failure is not a shed
    assert runner.engine_failures == 1 and runner.failed == 1
    assert runner.fault_retries == 1
    assert got["a"]["result"] == _assign_ref(state, xa)
    assert got["c"]["result"] == _assign_ref(state, xc)
    assert runner.pending() == 0  # nothing leaked


def test_runner_hard_failure_discards_spanning_tail(mesh, tmp_path):
    """An oversized request whose middle batch hard-fails must not leave
    tail segments queued (they would dispatch into an already-errored
    request); later requests still serve."""
    from harp_tpu.utils.fault import FaultInjector

    srv, state, rng = _kmeans_server(mesh, tmp_path, ladder=(1, 4))
    runner = srv.make_runner(max_retries=0, rung_policy="greedy")
    big = rng.normal(size=(10, 8)).astype(np.float32)  # spans 3 batches
    xc = rng.normal(size=(1, 8)).astype(np.float32)
    got = {}
    with FaultInjector(fail={"dispatch": (2,)}).arm():
        runner.submit("big", {"id": "big", "x": big.tolist()})
        got.update(runner.step())  # batch 1 of the span: ok
        got.update(runner.step())  # batch 2: hard fail (max_retries=0)
        runner.submit("c", {"id": "c", "x": xc.tolist()})
        got.update(runner.drain())
    assert "engine failure" in got["big"]["error"]
    assert got["c"]["result"] == _assign_ref(state, xc)
    assert runner.pending() == 0 and len(runner.sched) == 0


def test_sustained_degraded_row_under_faults(mesh):
    """The acceptance bench: sustained CPU-sim load with seeded ~1%
    transient dispatch faults + a deadline + a bounded queue.  The
    server stays up, every request comes back as served / structured
    shed / hard-fail (the invariant-9 ledger), clean batches still
    compile nothing, and the row passes the extended checker."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import check_jsonl

    from harp_tpu.serve.bench import benchmark_sustained

    res = benchmark_sustained(
        app="kmeans", n_requests=96, rows_per_request=1, burst_admit=8,
        ladder=(1, 8, 32), state_shape={"k": 8, "d": 16},
        fault_rate=0.01, fault_seed=34,  # seed 34: first draw (0.004)
        deadline_ms=10_000.0, max_queue_rows=4096, max_retries=3)  # fires
    assert res["offered_requests"] == 96
    assert (res["served_requests"] + res["shed_requests"]
            + res["failed_requests"]) == 96
    assert res["faults_injected"] >= 1  # chaos actually ran
    assert res["fault_retries"] >= 1    # and the retry path absorbed it
    assert 0.0 <= res["shed_frac"] <= 1.0
    assert 0.0 <= res["deadline_miss_frac"] <= 1.0
    assert res["steady_compiles"] == 0  # clean batches never recompile
    # PR 14: a retry-with-restage stages twice in its batch window, and
    # the sustained bench's "one staging per window" warn budget counts
    # exactly those windows — the drift IS the committed restage
    # evidence (it also lands in the budget-drift health row), so under
    # injected faults violations > 0 is the CORRECT reading
    assert 1 <= res["budget_violations"] <= res["fault_retries"]
    assert res["health_budget_drift"] == res["budget_violations"]
    assert res["health_findings"] >= 1
    # the committed-row contract: a stamped copy passes invariants 7 + 9
    row = {**res, "backend": "cpu", "date": "2026-08-04", "commit": "x"}
    assert check_jsonl._check_serve_row("t", 1, row) == []
    # and a forged unbalanced ledger fails invariant 9
    bad = dict(row, served_requests=row["served_requests"] - 1)
    assert any("must come back as exactly one" in e
               for e in check_jsonl._check_serve_row("t", 1, bad))


# ---------------------------------------------------------------------------
# TCP transport: real socket, concurrent connections, ordered responses
# ---------------------------------------------------------------------------

def _tcp_client(port, lines, n_responses):
    import socket

    s = socket.create_connection(("127.0.0.1", port), timeout=60)
    f = s.makefile("rw")
    for line in lines:
        f.write(line + "\n")
    f.flush()
    got = [json.loads(f.readline()) for _ in range(n_responses)]
    f.write(json.dumps({"cmd": "quit"}) + "\n")
    f.flush()
    s.close()
    return got


def test_tcp_front_end_routes_and_orders_per_connection(mesh, tmp_path):
    """Two concurrent clients over a real socket: each gets exactly its
    own responses, in its own send order, with correct numerics."""
    import threading

    from harp_tpu.serve.transport import TCPFrontEnd

    rng = np.random.default_rng(34)
    state = ENGINES["kmeans"].synthetic_state(rng, k=8, d=16)
    srv = Server("kmeans", state=state, mesh=mesh, ladder=(1, 8, 32),
                 cache_dir=str(tmp_path / "aot"), budget_action="warn")
    srv.startup()
    fe = TCPFrontEnd(srv, port=0,
                     max_queue_delay_s=0.002).start_in_thread()
    try:
        xs = {nm: [rng.normal(size=(1 + i % 3, 16)).astype(np.float32)
                   for i in range(12)] for nm in ("A", "B")}
        results = {}

        def run(nm):
            lines = [json.dumps({"id": f"{nm}-{i}", "x": x.tolist()})
                     for i, x in enumerate(xs[nm])]
            results[nm] = _tcp_client(fe.port, lines, len(lines))

        ts = [threading.Thread(target=run, args=(nm,)) for nm in xs]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
        cent = state["centroids"]
        for nm, batches in xs.items():
            assert [r["id"] for r in results[nm]] == \
                [f"{nm}-{i}" for i in range(12)]
            for r, x in zip(results[nm], batches):
                ref = np.argmin(((x[:, None, :] - cent[None]) ** 2
                                 ).sum(-1), 1)
                assert r["result"] == ref.tolist()
    finally:
        fe.shutdown()
        fe.join(60)


def test_tcp_front_end_stats_errors_and_shutdown(mesh, tmp_path):
    """Control plane over TCP: stats carries the continuous counters,
    bad JSON answers an error without killing the connection, and
    shutdown drains in-flight work before the socket closes."""
    import socket

    from harp_tpu.serve.transport import TCPFrontEnd

    rng = np.random.default_rng(35)
    state = ENGINES["kmeans"].synthetic_state(rng, k=4, d=8)
    srv = Server("kmeans", state=state, mesh=mesh, ladder=(1, 8),
                 cache_dir=str(tmp_path / "aot"), budget_action="warn")
    srv.startup()
    fe = TCPFrontEnd(srv, port=0).start_in_thread()
    s = socket.create_connection(("127.0.0.1", fe.port), timeout=60)
    f = s.makefile("rw")
    f.write("this is not json\n")
    f.write(json.dumps({"cmd": "stats"}) + "\n")
    f.flush()
    first = json.loads(f.readline())
    second = json.loads(f.readline())
    assert first["error"] == "unparseable JSON"
    assert second["kind"] == "serve_stats"
    assert second["continuous"]["mode"] == "continuous"
    x = rng.normal(size=(3, 8)).astype(np.float32)
    f.write(json.dumps({"id": "last", "x": x.tolist()}) + "\n")
    f.write(json.dumps({"cmd": "shutdown"}) + "\n")
    f.flush()
    resp = json.loads(f.readline())  # drained before close
    ref = np.argmin(((x[:, None, :] - state["centroids"][None]) ** 2
                     ).sum(-1), 1)
    assert resp["id"] == "last" and resp["result"] == ref.tolist()
    fe.join(60)
    s.close()


def test_tcp_client_disconnect_mid_flight_cleanup(mesh, tmp_path):
    """A client that slams its socket shut with responses outstanding
    costs exactly its own work: the dispatcher finishes the in-flight
    batches, the orphaned responses are dropped, the admitted work
    drains fully (nothing leaks in the assembler), and a concurrent
    connection is untouched."""
    import socket
    import threading
    import time as _time

    from harp_tpu.serve.transport import TCPFrontEnd

    rng = np.random.default_rng(36)
    state = ENGINES["kmeans"].synthetic_state(rng, k=4, d=8)
    srv = Server("kmeans", state=state, mesh=mesh, ladder=(1, 8),
                 cache_dir=str(tmp_path / "aot"), budget_action="warn")
    srv.startup()
    fe = TCPFrontEnd(srv, port=0,
                     max_queue_delay_s=0.002).start_in_thread()
    try:
        # rude client: 6 requests, then the socket slams shut unread
        rude = socket.create_connection(("127.0.0.1", fe.port),
                                        timeout=60)
        payload = b"".join(
            json.dumps({"id": f"rude-{i}",
                        "x": rng.normal(size=(2, 8)).tolist()}
                       ).encode() + b"\n" for i in range(6))
        rude.sendall(payload)
        rude.close()  # mid-flight: nothing was read back

        # polite client on its own connection: full round trip
        xs = [rng.normal(size=(1 + i % 3, 8)).astype(np.float32)
              for i in range(8)]
        lines = [json.dumps({"id": f"ok-{i}", "x": x.tolist()})
                 for i, x in enumerate(xs)]
        got = _tcp_client(fe.port, lines, len(lines))
        assert [r["id"] for r in got] == [f"ok-{i}" for i in range(8)]
        cent = state["centroids"]
        for r, x in zip(got, xs):
            ref = np.argmin(((x[:, None, :] - cent[None]) ** 2).sum(-1),
                            1)
            assert r["result"] == ref.tolist()

        # every admitted request (rude ones included) fully drained —
        # the orphans were SERVED then dropped at delivery, not leaked
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline and (
                fe.runner.completed < 14 or fe.runner.pending()):
            _time.sleep(0.01)
        assert fe.runner.completed == 14
        assert fe.runner.pending() == 0

        # and the server still answers its control plane
        s = socket.create_connection(("127.0.0.1", fe.port), timeout=60)
        f = s.makefile("rw")
        f.write(json.dumps({"cmd": "stats"}) + "\n")
        f.flush()
        stats = json.loads(f.readline())
        assert stats["kind"] == "serve_stats"
        assert stats["continuous"]["completed"] == 14
        f.write(json.dumps({"cmd": "quit"}) + "\n")
        f.flush()
        s.close()
    finally:
        fe.shutdown()
        fe.join(60)
    assert threading.active_count() < 50  # no runaway leaked threads


# ---------------------------------------------------------------------------
# AOT executable cache: warm restart compiles NOTHING
# ---------------------------------------------------------------------------

def test_warm_restart_performs_zero_compiles(mesh, tmp_path):
    import jax

    rng = np.random.default_rng(7)
    state = ENGINES["kmeans"].synthetic_state(rng, k=8, d=16)
    cache_dir = str(tmp_path / "aot")
    ladder = (1, 8)
    req = {"id": 0, "x": rng.normal(size=(3, 16)).astype(
        np.float32).tolist()}
    with telemetry.scope(True):
        srv = Server("kmeans", state=state, mesh=mesh, ladder=ladder,
                     cache_dir=cache_dir)
        cold = srv.startup()
        assert cold["cache_misses"] == len(ladder)
        assert cold["compiles"] >= len(ladder)
        (ref,) = srv.process([req])

    # fresh process stand-in: drop jax's in-memory caches so any compile
    # on the second startup would be OBSERVED by CompileWatch, then
    # prove there isn't one
    jax.clear_caches()
    with telemetry.scope(True):
        srv2 = Server("kmeans", state=state, mesh=mesh, ladder=ladder,
                      cache_dir=cache_dir)
        warm = srv2.startup()
        assert warm["cache_hits"] == len(ladder)
        assert warm["cache_misses"] == 0
        assert warm["compiles"] == 0  # THE acceptance criterion
        (resp,) = srv2.process([req])
        assert resp["result"] == ref["result"]
        # and the first responses stayed compile-free too
        assert flightrec.compile_watch.count == 0


def test_corrupt_cache_entry_falls_back_to_compile(mesh, tmp_path):
    import os

    rng = np.random.default_rng(8)
    state = ENGINES["kmeans"].synthetic_state(rng, k=4, d=8)
    cache_dir = str(tmp_path / "aot")
    srv = Server("kmeans", state=state, mesh=mesh, ladder=(1,),
                 cache_dir=cache_dir)
    srv.startup()
    (entry,) = [f for f in os.listdir(cache_dir) if f.endswith(".pkl")]
    with open(os.path.join(cache_dir, entry), "wb") as fh:
        fh.write(b"not a pickle")
    srv2 = Server("kmeans", state=state, mesh=mesh, ladder=(1,),
                  cache_dir=cache_dir)
    with pytest.warns(RuntimeWarning, match="unreadable"):
        info = srv2.startup()
    assert info["cache_misses"] == 1  # recompiled, didn't crash
    (resp,) = srv2.process([{"id": 0, "x": [[0.0] * 8]}])
    assert "result" in resp


def test_cache_key_changes_with_fingerprint(mesh, tmp_path):
    from harp_tpu.serve.cache import ExecutableCache

    rng = np.random.default_rng(9)
    eng = ENGINES["kmeans"](
        ENGINES["kmeans"].synthetic_state(rng, k=4, d=8), mesh)
    a = ExecutableCache(str(tmp_path / "c"), fingerprint="aaaa")
    b = ExecutableCache(str(tmp_path / "c"), fingerprint="bbbb")
    args = eng.trace_args(1)
    assert a._key("kmeans", args) != b._key("kmeans", args)
    # and with the rung: shapes participate
    assert a._key("kmeans", args) != a._key("kmeans", eng.trace_args(8))


def test_cache_misses_when_engine_options_change(mesh, tmp_path):
    """Options baked into the program as constants (mfsgd topk, lda
    em_iters/alpha) shape NO input aval — a restart with different flags
    must miss, never serve the other option's executable."""
    rng = np.random.default_rng(21)
    state = ENGINES["mfsgd"].synthetic_state(rng, n_users=64, n_items=48,
                                             rank=8)
    cache_dir = str(tmp_path / "aot")
    req = {"id": 0, "users": [1, 2, 3]}
    srv5 = Server("mfsgd", state=state, mesh=mesh, ladder=(4,),
                  cache_dir=cache_dir, engine_opts={"topk": 5})
    srv5.startup()
    (r5,) = srv5.process([req])
    assert all(len(row["items"]) == 5 for row in r5["result"])

    srv7 = Server("mfsgd", state=state, mesh=mesh, ladder=(4,),
                  cache_dir=cache_dir, engine_opts={"topk": 7})
    info = srv7.startup()
    assert info["cache_hits"] == 0 and info["cache_misses"] == 1
    (r7,) = srv7.process([req])
    assert all(len(row["items"]) == 7 for row in r7["result"])

    # same options again: hit (the tag keys, it doesn't disable caching)
    srv5b = Server("mfsgd", state=state, mesh=mesh, ladder=(4,),
                   cache_dir=cache_dir, engine_opts={"topk": 5})
    assert srv5b.startup()["cache_hits"] == 1

    # lda's constants tag too (em_iters is the fori_loop trip count)
    lda_state = ENGINES["lda"].synthetic_state(rng, vocab_size=32,
                                               n_topics=4)
    tags = {ENGINES["lda"](lda_state, mesh, em_iters=k).cache_tag()
            for k in (4, 8)}
    assert len(tags) == 2


def test_cache_load_survives_arbitrary_deserialize_errors(
        mesh, tmp_path, monkeypatch):
    """'The cache can lose, never lie' covers exception types the key
    didn't anticipate (e.g. jaxlib XlaRuntimeError) — any bad entry must
    degrade to a fresh compile, not crash startup."""
    from jax.experimental import serialize_executable

    rng = np.random.default_rng(22)
    state = ENGINES["kmeans"].synthetic_state(rng, k=4, d=8)
    cache_dir = str(tmp_path / "aot")
    Server("kmeans", state=state, mesh=mesh, ladder=(1,),
           cache_dir=cache_dir).startup()

    def boom(*a, **k):
        raise RuntimeError("xla runtime rejected the payload")

    monkeypatch.setattr(serialize_executable, "deserialize_and_load",
                        boom)
    srv2 = Server("kmeans", state=state, mesh=mesh, ladder=(1,),
                  cache_dir=cache_dir)
    with pytest.warns(RuntimeWarning, match="unreadable"):
        info = srv2.startup()
    assert info["cache_misses"] == 1
    monkeypatch.undo()
    (resp,) = srv2.process([{"id": 0, "x": [[0.0] * 8]}])
    assert "result" in resp


# ---------------------------------------------------------------------------
# stdio protocol + CLI end-to-end
# ---------------------------------------------------------------------------

def test_stdio_roundtrip_with_stats_and_quit(mesh, tmp_path):
    rng = np.random.default_rng(10)
    state = ENGINES["kmeans"].synthetic_state(rng, k=8, d=16)
    srv = _server("kmeans", state, mesh, tmp_path, ladder=(1, 8))
    x = rng.normal(size=(2, 16)).astype(np.float32)
    stdin = io.StringIO("\n".join([
        json.dumps({"id": "a", "x": x.tolist()}),
        "this is not json",
        json.dumps({"cmd": "stats"}),
        json.dumps({"id": "b", "x": x[:1].tolist()}),
        json.dumps({"cmd": "quit"}),
    ]) + "\n")
    out = io.StringIO()
    served = srv.serve_stdio(stdin, out)
    lines = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert served == 2
    assert lines[0]["id"] == "a" and len(lines[0]["result"]) == 2
    assert lines[1]["error"] == "unparseable JSON"
    assert lines[2]["kind"] == "serve_stats"
    assert lines[3]["id"] == "b" and len(lines[3]["result"]) == 1


def test_burst_reader_sees_past_text_layer_buffering():
    """Queued lines a TextIOWrapper would have buffered internally (where
    select on the fd can't see them) must land in the CURRENT burst, and
    a partial trailing line must carry over to the next one."""
    import os

    from harp_tpu.serve.server import _BurstReader

    r, w = os.pipe()
    stdin = os.fdopen(r, "r")  # the buffered text wrapper main() gets
    try:
        os.write(w, b'{"id": 1}\n{"id": 2}\n{"id": 3}\n{"id": 4')
        reader = _BurstReader(stdin)
        burst = reader.read_burst()
        assert [json.loads(ln)["id"] for ln in burst] == [1, 2, 3]
        os.write(w, b'}\n')  # the partial line completes
        assert [json.loads(ln)["id"]
                for ln in reader.read_burst()] == [4]
        os.close(w)
        assert reader.read_burst() == []  # EOF
    finally:
        stdin.close()


def test_cli_serves_from_checkpoint_end_to_end(mesh, tmp_path,
                                               monkeypatch, capsys):
    """THE acceptance walkthrough: train-ish state → CheckpointManager →
    ``python -m harp_tpu serve kmeans --ckpt ...`` → JSONL in, JSONL out
    (restore_latest picks the newest step)."""
    import sys

    import harp_tpu.__main__ as cli
    from harp_tpu.utils.checkpoint import CheckpointManager

    rng = np.random.default_rng(11)
    stale = {"centroids": rng.normal(size=(4, 8)).astype(np.float32)}
    fresh = {"centroids": rng.normal(size=(4, 8)).astype(np.float32)}
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(1, stale)
    mgr.save(5, fresh)  # the newest step must win

    x = rng.normal(size=(3, 8)).astype(np.float32)
    monkeypatch.setattr(sys, "stdin", io.StringIO(
        json.dumps({"id": 0, "x": x.tolist()}) + "\n"
        + json.dumps({"cmd": "quit"}) + "\n"))
    rc = cli.main(["serve", "kmeans", "--ckpt", str(tmp_path / "ckpt"),
                   "--ladder", "1,8"])
    assert rc == 0
    out = capsys.readouterr().out
    (resp,) = [json.loads(ln) for ln in out.splitlines()]
    ref = np.argmin(((x[:, None, :] - fresh["centroids"][None]) ** 2
                     ).sum(-1), axis=1)
    assert resp["result"] == ref.tolist()


def test_cli_bench_emits_valid_serve_row(mesh, capsys):
    import os
    import sys

    import harp_tpu.__main__ as cli

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import check_jsonl

    rc = cli.main(["serve", "kmeans", "--bench", "--requests", "24",
                   "--rows-per-request", "2", "--ladder", "1,8"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    row = json.loads(line)
    assert row["config"] == "serve_kmeans" and row["kind"] == "serve"
    assert row["qps"] > 0 and row["steady_compiles"] == 0
    assert row["p50_ms"] <= row["p95_ms"] <= row["p99_ms"]


def test_serve_bench_mfsgd_row(mesh):
    from harp_tpu.serve.bench import benchmark

    res = benchmark(app="mfsgd", n_requests=24, rows_per_request=2,
                    burst=8, ladder=(1, 8),
                    state_shape={"n_users": 64, "n_items": 48,
                                 "rank": 8}, topk=4)
    assert res["kind"] == "serve" and res["app"] == "mfsgd"
    assert res["steady_compiles"] == 0 and res["budget_violations"] == 0
    assert res["p50_ms"] <= res["p95_ms"] <= res["p99_ms"]
    assert res["cache_misses"] == 2 and res["cache_hits"] == 0


def test_server_requires_state_or_ckpt(mesh):
    with pytest.raises(ValueError, match="state= or ckpt="):
        Server("kmeans")
