"""Native loader: correctness vs numpy, threads, fallback, errors."""

import numpy as np
import pytest

from harp_tpu.native import load_csv, load_triples, load_native


@pytest.fixture(scope="module")
def native_lib():
    lib = load_native()
    if lib is None:
        pytest.skip("no g++ and no prebuilt .so")
    return lib


def test_load_csv_matches_numpy(native_lib, tmp_path):
    rng = np.random.default_rng(0)
    a = rng.normal(size=(1000, 7)).astype(np.float32)
    p = tmp_path / "d.csv"
    np.savetxt(p, a, delimiter=",", fmt="%.6g")
    out = load_csv(str(p), n_threads=4)
    ref = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_load_csv_single_thread_same(native_lib, tmp_path):
    rng = np.random.default_rng(1)
    a = rng.normal(size=(257, 3)).astype(np.float32)
    p = tmp_path / "d.csv"
    np.savetxt(p, a, delimiter=",", fmt="%.7g")
    np.testing.assert_array_equal(load_csv(str(p), 1), load_csv(str(p), 8))


def test_load_triples(native_lib, tmp_path):
    rng = np.random.default_rng(2)
    u = rng.integers(0, 1000, 5000).astype(np.int32)
    i = rng.integers(0, 500, 5000).astype(np.int32)
    v = rng.normal(size=5000).astype(np.float32)
    p = tmp_path / "t.txt"
    with open(p, "w") as f:
        for uu, ii, vv in zip(u, i, v):
            f.write(f"{uu} {ii} {vv:.6g}\n")
    u2, i2, v2 = load_triples(str(p), n_threads=4)
    np.testing.assert_array_equal(u2, u)
    np.testing.assert_array_equal(i2, i)
    np.testing.assert_allclose(v2, v, rtol=1e-5)


def test_missing_file_raises(native_lib):
    with pytest.raises(OSError, match="native loader"):
        load_csv("/nonexistent/file.csv")


def test_trailing_newline_and_blank_lines(native_lib, tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("1,2\n\n3,4\n\n\n5,6\n")
    out = load_csv(str(p), 4)
    np.testing.assert_array_equal(out, [[1, 2], [3, 4], [5, 6]])


def test_header_row_does_not_hang(native_lib, tmp_path):
    p = tmp_path / "h.csv"
    p.write_text("x,y,z\n1,2,3\n4,5,6\n")
    out = load_csv(str(p), 2)  # header parses as zeros, must not hang
    assert out.shape == (3, 3)
    np.testing.assert_array_equal(out[1:], [[1, 2, 3], [4, 5, 6]])


def test_huge_integer_digits(native_lib, tmp_path):
    p = tmp_path / "big.csv"
    p.write_text("12345678901234567890123456,1\n")
    out = load_csv(str(p), 1)
    np.testing.assert_allclose(out[0, 0], 1.2345679e25, rtol=1e-6)


def test_fallback_whitespace_equivalent(tmp_path):
    from harp_tpu.native.datasource import _loadtxt_any_sep
    p = tmp_path / "ws.txt"
    p.write_text("1 2 3\n4,5,6\n")
    np.testing.assert_array_equal(_loadtxt_any_sep(str(p)),
                                  [[1, 2, 3], [4, 5, 6]])
