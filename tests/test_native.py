"""Native loader: correctness vs numpy, threads, fallback, errors."""

import numpy as np
import pytest

from harp_tpu.native import (
    csr_to_ell,
    load_csv,
    load_libsvm,
    load_native,
    load_triples,
)


@pytest.fixture(scope="module")
def native_lib():
    lib = load_native()
    if lib is None:
        pytest.skip("no g++ and no prebuilt .so")
    return lib


def test_load_csv_matches_numpy(native_lib, tmp_path):
    rng = np.random.default_rng(0)
    a = rng.normal(size=(1000, 7)).astype(np.float32)
    p = tmp_path / "d.csv"
    np.savetxt(p, a, delimiter=",", fmt="%.6g")
    out = load_csv(str(p), n_threads=4)
    ref = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_load_csv_single_thread_same(native_lib, tmp_path):
    rng = np.random.default_rng(1)
    a = rng.normal(size=(257, 3)).astype(np.float32)
    p = tmp_path / "d.csv"
    np.savetxt(p, a, delimiter=",", fmt="%.7g")
    np.testing.assert_array_equal(load_csv(str(p), 1), load_csv(str(p), 8))


def test_load_triples(native_lib, tmp_path):
    rng = np.random.default_rng(2)
    u = rng.integers(0, 1000, 5000).astype(np.int32)
    i = rng.integers(0, 500, 5000).astype(np.int32)
    v = rng.normal(size=5000).astype(np.float32)
    p = tmp_path / "t.txt"
    with open(p, "w") as f:
        for uu, ii, vv in zip(u, i, v):
            f.write(f"{uu} {ii} {vv:.6g}\n")
    u2, i2, v2 = load_triples(str(p), n_threads=4)
    np.testing.assert_array_equal(u2, u)
    np.testing.assert_array_equal(i2, i)
    np.testing.assert_allclose(v2, v, rtol=1e-5)


def test_missing_file_raises(native_lib):
    with pytest.raises(OSError, match="native loader"):
        load_csv("/nonexistent/file.csv")


def test_trailing_newline_and_blank_lines(native_lib, tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("1,2\n\n3,4\n\n\n5,6\n")
    out = load_csv(str(p), 4)
    np.testing.assert_array_equal(out, [[1, 2], [3, 4], [5, 6]])


def test_header_row_does_not_hang(native_lib, tmp_path):
    p = tmp_path / "h.csv"
    p.write_text("x,y,z\n1,2,3\n4,5,6\n")
    out = load_csv(str(p), 2)  # header parses as zeros, must not hang
    assert out.shape == (3, 3)
    np.testing.assert_array_equal(out[1:], [[1, 2, 3], [4, 5, 6]])


def test_huge_integer_digits(native_lib, tmp_path):
    p = tmp_path / "big.csv"
    p.write_text("12345678901234567890123456,1\n")
    out = load_csv(str(p), 1)
    np.testing.assert_allclose(out[0, 0], 1.2345679e25, rtol=1e-6)


def test_fallback_whitespace_equivalent(tmp_path):
    from harp_tpu.native.datasource import _loadtxt_any_sep
    p = tmp_path / "ws.txt"
    p.write_text("1 2 3\n4,5,6\n")
    np.testing.assert_array_equal(_loadtxt_any_sep(str(p)),
                                  [[1, 2, 3], [4, 5, 6]])


LIBSVM_SAMPLE = """\
1 1:0.5 3:1.25 7:-2.0
-1 2:3.0
# a full-line comment
1 1:1e-3 7:4.5  # trailing comment
-1 5:0.0
"""


def test_comment_lines_match_fallback(native_lib, tmp_path, monkeypatch):
    """'#' comments (numpy.loadtxt's default) are honored identically by
    the native CSV and triples parsers — a comment header must not become
    a phantom (0, 0, 0.0) row."""
    import harp_tpu.native.datasource as ds

    p = tmp_path / "c.txt"
    p.write_text("# user item rating\n5 3 4.0\n  # indented comment\n"
                 "1 2 0.5  # trailing\n\n")
    native = load_triples(str(p))
    np.testing.assert_array_equal(native[0], [5, 1])
    np.testing.assert_allclose(native[2], [4.0, 0.5])
    monkeypatch.setattr(ds, "load_native", lambda: None)
    fallback = ds.load_triples(str(p))
    for a, b in zip(native, fallback):
        np.testing.assert_allclose(a, b)

    p2 = tmp_path / "c.csv"
    p2.write_text("# header\n1.0,2.0\n3.0,4.0 # note\n")
    out = load_csv(str(p2))
    np.testing.assert_allclose(out, [[1.0, 2.0], [3.0, 4.0]])


def test_empty_shard_fallback_returns_empty(tmp_path, monkeypatch):
    import harp_tpu.native.datasource as ds

    p = tmp_path / "empty.txt"
    p.write_text("")
    monkeypatch.setattr(ds, "load_native", lambda: None)
    u, i, v = ds.load_triples(str(p))
    assert len(u) == len(i) == len(v) == 0


def test_load_libsvm_native(native_lib, tmp_path):
    p = tmp_path / "d.svm"
    p.write_text(LIBSVM_SAMPLE)
    labels, indptr, indices, values, nf = load_libsvm(str(p), n_threads=4)
    np.testing.assert_array_equal(labels, [1, -1, 1, -1])
    np.testing.assert_array_equal(indptr, [0, 3, 4, 6, 7])
    np.testing.assert_array_equal(indices, [0, 2, 6, 1, 0, 6, 4])  # 1-based → 0
    np.testing.assert_allclose(values, [0.5, 1.25, -2.0, 3.0, 1e-3, 4.5, 0.0])
    assert nf == 7


def test_load_libsvm_fallback_parity(native_lib, tmp_path, monkeypatch):
    """Python fallback parses identically to the C++ path."""
    import harp_tpu.native.datasource as ds

    p = tmp_path / "d.svm"
    p.write_text(LIBSVM_SAMPLE)
    native = load_libsvm(str(p))
    monkeypatch.setattr(ds, "load_native", lambda: None)
    fallback = ds.load_libsvm(str(p))
    for a, b in zip(native, fallback):
        np.testing.assert_allclose(a, b)


def test_load_libsvm_malformed_trailing_colon(native_lib, tmp_path, monkeypatch):
    """'3:' with no value must not swallow the next line's label (and the
    fallback must agree on malformed input, not crash)."""
    import harp_tpu.native.datasource as ds

    p = tmp_path / "bad.svm"
    p.write_text("1 3:\n5 1:2.0\nheader junk:line\n-1 abc:1 2:7.0\n"
                 "3:1.5\n1 foo#bar 2:9.0\n1x 2:4.0\n")
    native = load_libsvm(str(p))
    labels, indptr, indices, values, nf = native
    # header label → 0; '3:1.5' is a label-only line (label token's
    # trailing garbage dropped whole); '#' comments out the rest of a line
    # even mid-token; '1x' label parses its numeric prefix
    np.testing.assert_array_equal(labels, [1, 5, 0, -1, 3, 1, 1])
    np.testing.assert_array_equal(indptr, [0, 0, 1, 1, 2, 2, 2, 3])
    np.testing.assert_allclose(values, [2.0, 7.0, 4.0])
    monkeypatch.setattr(ds, "load_native", lambda: None)
    fallback = ds.load_libsvm(str(p))
    for a, b in zip(native, fallback):
        np.testing.assert_allclose(a, b)


def test_load_libsvm_zero_based(native_lib, tmp_path):
    p = tmp_path / "d.svm"
    p.write_text("1 0:2.0 3:4.0\n")
    _, _, indices, _, nf = load_libsvm(str(p), zero_based=True)
    np.testing.assert_array_equal(indices, [0, 3])
    assert nf == 4


def test_csr_to_ell_roundtrip():
    indptr = np.array([0, 2, 2, 5])
    indices = np.array([4, 1, 0, 2, 3])
    values = np.array([1.0, 2.0, 3.0, 4.0, 5.0], np.float32)
    ids, vals, mask = csr_to_ell(indptr, indices, values)
    assert ids.shape == (3, 3)
    np.testing.assert_array_equal(mask.sum(1), [2, 0, 3])
    np.testing.assert_allclose(vals[0, :2], [1.0, 2.0])
    np.testing.assert_array_equal(ids[2], [0, 2, 3])
    # truncation at fixed width
    ids2, vals2, mask2 = csr_to_ell(indptr, indices, values, width=2)
    assert mask2.sum() == 4  # row 2 lost one entry


# ---------------------------------------------------------------------------
# Streaming CSV reader (CSVStream / CSVPoints) — beyond-RAM ingest.
# ---------------------------------------------------------------------------


def _write_csv(path, pts, blanks=False):
    with open(path, "w") as f:
        f.write("# header\n")
        for i, row in enumerate(pts):
            f.write(",".join(f"{v:.7e}" for v in row) + "\n")
            if blanks and i % 97 == 0:
                f.write("\n")


def test_csv_stream_blocks_concatenate_to_full_matrix(native_lib, tmp_path):
    from harp_tpu.native.datasource import CSVStream

    pts = np.random.default_rng(0).normal(size=(3001, 5)).astype(np.float32)
    p = str(tmp_path / "s.csv")
    _write_csv(p, pts, blanks=True)
    with CSVStream(p, chunk_rows=450) as st:
        assert st.cols == 5
        blocks = list(st)
    assert all(b.shape[0] <= 450 for b in blocks)
    np.testing.assert_allclose(np.concatenate(blocks, 0), pts, rtol=2e-6)


def test_csv_stream_python_fallback_equivalent(tmp_path, monkeypatch):
    import harp_tpu.native.build as B
    from harp_tpu.native.datasource import CSVStream

    monkeypatch.setattr(B, "_LIB", None)
    monkeypatch.setattr(B, "_TRIED", True)  # force the fallback
    pts = np.random.default_rng(1).normal(size=(800, 4)).astype(np.float32)
    p = str(tmp_path / "f.csv")
    _write_csv(p, pts)
    with CSVStream(p, chunk_rows=123) as st:
        got = np.concatenate(list(st), 0)
    np.testing.assert_allclose(got, pts, rtol=2e-6)


def test_csv_points_sequential_contract(native_lib, tmp_path):
    from harp_tpu.native.datasource import CSVPoints

    pts = np.random.default_rng(2).normal(size=(1200, 3)).astype(np.float32)
    p = str(tmp_path / "p.csv")
    _write_csv(p, pts)
    cp = CSVPoints(p, chunk_rows=256)
    assert cp.shape == (1200, 3) and len(cp) == 1200
    np.testing.assert_allclose(cp[0:300], pts[:300], rtol=2e-6)
    np.testing.assert_allclose(cp[300:900], pts[300:900], rtol=2e-6)
    np.testing.assert_allclose(cp[0:50], pts[:50], rtol=2e-6)  # restart
    with pytest.raises(ValueError, match="sequential"):
        cp[500:600]  # non-contiguous mid-stream
    idx = np.arange(0, 1200, 37)
    np.testing.assert_allclose(cp[idx], pts[idx], rtol=2e-6)  # gather pass
    with pytest.raises(IndexError):
        cp[np.array([5, 1200])]
    cp.close()


def test_csv_points_feeds_fit_streaming(native_lib, mesh, tmp_path):
    from harp_tpu.models import kmeans as K
    from harp_tpu.models import kmeans_stream as KS
    from harp_tpu.native.datasource import CSVPoints

    rng = np.random.default_rng(3)
    pts = (rng.normal(size=(2000, 6))
           + rng.integers(0, 3, size=(2000, 1)) * 8).astype(np.float32)
    p = str(tmp_path / "k.csv")
    _write_csv(p, pts)
    with CSVPoints(p, chunk_rows=700) as cp:
        c0, i0 = K.fit(pts, k=6, iters=5, mesh=mesh, seed=2)
        c1, i1 = KS.fit_streaming(cp, k=6, iters=5, chunk_points=700,
                                  mesh=mesh, seed=2)
    assert abs(i0 - i1) < 1e-3 * abs(i0) + 1.0
    assert np.allclose(c0, c1, rtol=1e-3, atol=1e-3)


def _write_parquet(path, pts):
    import pyarrow as pa
    import pyarrow.parquet as pq

    table = pa.table({f"f{i}": pts[:, i] for i in range(pts.shape[1])})
    # several row groups so streaming actually crosses group boundaries
    pq.write_table(table, path, row_group_size=max(1, len(pts) // 4))


def test_parquet_points_sequential_contract(tmp_path):
    """ParquetPoints honors the exact CSVPoints contract (same shared
    SequentialPoints engine): metadata shape, contiguous ascending
    slices with epoch restarts, sorted gathers, loud rejections."""
    from harp_tpu.native.datasource import ParquetPoints

    pts = np.random.default_rng(4).normal(size=(1200, 3)).astype(np.float32)
    p = str(tmp_path / "p.parquet")
    _write_parquet(p, pts)
    pp = ParquetPoints(p, chunk_rows=256)
    assert pp.shape == (1200, 3) and len(pp) == 1200
    np.testing.assert_allclose(pp[0:300], pts[:300], rtol=1e-6)
    np.testing.assert_allclose(pp[300:900], pts[300:900], rtol=1e-6)
    np.testing.assert_allclose(pp[0:50], pts[:50], rtol=1e-6)  # restart
    with pytest.raises(ValueError, match="sequential"):
        pp[500:600]
    idx = np.arange(0, 1200, 37)
    np.testing.assert_allclose(pp[idx], pts[idx], rtol=1e-6)
    with pytest.raises(IndexError):
        pp[np.array([5, 1200])]
    pp.close()


def test_parquet_points_rejects_non_numeric_columns(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from harp_tpu.native.datasource import ParquetPoints

    p = str(tmp_path / "bad.parquet")
    pq.write_table(pa.table({"x": [1.0, 2.0], "name": ["a", "b"]}), p)
    with pytest.raises(ValueError, match="non-numeric"):
        ParquetPoints(p)


def test_parquet_points_feeds_fit_streaming(mesh, tmp_path):
    from harp_tpu.models import kmeans as K
    from harp_tpu.models import kmeans_stream as KS
    from harp_tpu.native.datasource import ParquetPoints

    rng = np.random.default_rng(5)
    pts = (rng.normal(size=(2000, 6))
           + rng.integers(0, 3, size=(2000, 1)) * 8).astype(np.float32)
    p = str(tmp_path / "k.parquet")
    _write_parquet(p, pts)
    with ParquetPoints(p, chunk_rows=700) as pp:
        c0, i0 = K.fit(pts, k=6, iters=5, mesh=mesh, seed=2)
        c1, i1 = KS.fit_streaming(pp, k=6, iters=5, chunk_points=700,
                                  mesh=mesh, seed=2)
    assert abs(i0 - i1) < 1e-3 * abs(i0) + 1.0
    assert np.allclose(c0, c1, rtol=1e-3, atol=1e-3)


def test_file_splits_mixes_parquet_with_csv_and_npy(native_lib, tmp_path):
    """A directory mixing all three formats streams as one dataset —
    Harp's MultiFileInputFormat never cared what a split was encoded as."""
    from harp_tpu.native.datasource import FileSplits

    rng = np.random.default_rng(6)
    parts = [rng.normal(size=(n, 4)).astype(np.float32)
             for n in (300, 200, 250)]
    p_csv = str(tmp_path / "a.csv")
    _write_csv(p_csv, parts[0])
    p_pq = str(tmp_path / "b.parquet")
    _write_parquet(p_pq, parts[1])
    p_npy = str(tmp_path / "c.npy")
    np.save(p_npy, parts[2])
    fs = FileSplits(sorted([p_csv, p_pq, p_npy]), n_workers=1,
                    local_workers=[0], chunk_rows=128)
    assert fs.rows(0) == 750 and fs.cols == 4
    got = []
    while True:
        blk = fs.next_block(0, 128)
        if blk.shape[0] == 0:
            break
        got.append(blk)
    got = np.concatenate(got, 0)
    # multi_file_splits may reorder files (size-balanced); compare as sets
    # of rows via a stable sort on the first column
    exp = np.concatenate(parts, 0)
    np.testing.assert_allclose(np.sort(got, axis=0), np.sort(exp, axis=0),
                               rtol=2e-6, atol=1e-6)
    fs.close()


def test_load_csv_and_triples_accept_parquet(tmp_path):
    """The materializing front doors (stats/kmeans dense input, the
    mfsgd/lda triples input) take parquet splits too — and the glob
    loader's column validation reads parquet METADATA, not binary bytes
    through the text scanner."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from harp_tpu.native.datasource import (load_csv, load_triples,
                                            load_triples_glob)

    pts = np.random.default_rng(7).normal(size=(50, 4)).astype(np.float32)
    p_dense = str(tmp_path / "d.parquet")
    _write_parquet(p_dense, pts)
    np.testing.assert_allclose(load_csv(p_dense), pts, rtol=1e-6)

    u = np.arange(30, dtype=np.int64)
    i = (u * 7) % 11
    v = np.linspace(0, 1, 30)
    p_tri = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"u": u, "i": i, "r": v}), p_tri)
    gu, gi, gv = load_triples(p_tri)
    np.testing.assert_array_equal(gu, u.astype(np.int32))
    np.testing.assert_array_equal(gi, i.astype(np.int32))
    np.testing.assert_allclose(gv, v.astype(np.float32), rtol=1e-6)

    # two-column parquet: v reads 0.0 and has_value_column is False
    p2 = str(tmp_path / "m1.parquet")
    pq.write_table(pa.table({"u": u, "i": i}), p2)
    gu2, gi2, gv2, has_v = load_triples_glob(p2)
    assert not has_v and (gv2 == 0).all() and len(gu2) == 30
    # mixed text + parquet glob agrees on columns -> concatenates
    p_txt = str(tmp_path / "m2.txt")
    with open(p_txt, "w") as f:
        for a, b in zip(u, i):
            f.write(f"{a} {b}\n")
    gu3, _, _, _ = load_triples_glob(str(tmp_path / "m*"))
    assert len(gu3) == 60
    # a glob MIXING 2- and 3-column files still fails loudly, parquet
    # metadata participating in the same check as the text scan
    with pytest.raises(ValueError, match="disagree"):
        load_triples_glob(str(tmp_path / "[tm]*"))


def test_sequential_points_random_slice_partitions(native_lib, tmp_path):
    """Property: ANY ascending contiguous partition of [0, n) — with
    arbitrary chunk_rows, block-boundary-crossing slices, and occasional
    restarts — reads back exactly the underlying rows (the shared
    SequentialPoints pending-buffer bookkeeping, exercised through both
    the CSV and parquet subclasses)."""
    pytest.importorskip("hypothesis")  # optional in some images
    from hypothesis import given, settings, strategies as st

    from harp_tpu.native.datasource import CSVPoints, ParquetPoints

    n = 700
    pts = np.random.default_rng(9).normal(size=(n, 3)).astype(np.float32)
    p_csv = str(tmp_path / "prop.csv")
    _write_csv(p_csv, pts)
    p_pq = str(tmp_path / "prop.parquet")
    _write_parquet(p_pq, pts)
    sources = [CSVPoints(p_csv, chunk_rows=97),
               ParquetPoints(p_pq, chunk_rows=97)]

    @settings(max_examples=20, deadline=None)
    @given(st.lists(st.integers(1, 200), min_size=1, max_size=12),
           st.integers(0, 3))
    def check(widths, restart_at):
        for src in sources:
            lo = 0
            for j, w in enumerate(widths):
                if j == restart_at and j > 0:
                    lo = 0  # epoch restart mid-pattern
                hi = min(lo + w, n)
                np.testing.assert_allclose(src[lo:hi], pts[lo:hi],
                                           rtol=2e-6, atol=1e-6)
                lo = hi
                if lo >= n:
                    break

    check()
    for src in sources:
        src.close()


def test_gzip_text_inputs_parse_identically(native_lib, tmp_path):
    """.gz text splits (the routine HDFS encoding) parse through the
    Python path with identical results to the plain file on every text
    front door: dense, triples, libsvm, and the streaming reader."""
    import gzip

    from harp_tpu.native.datasource import (CSVPoints, load_csv,
                                            load_libsvm, load_triples)

    pts = np.random.default_rng(8).normal(size=(500, 4)).astype(np.float32)
    p = str(tmp_path / "a.csv")
    _write_csv(p, pts)
    pz = p + ".gz"
    with open(p, "rb") as fin, gzip.open(pz, "wb") as fout:
        fout.write(fin.read())
    np.testing.assert_allclose(load_csv(pz), load_csv(p), rtol=1e-6)

    t = str(tmp_path / "t.txt")
    with open(t, "w") as f:
        for j in range(100):
            f.write(f"{j} {j % 7} {j * 0.5}\n")
    tz = t + ".gz"
    with open(t, "rb") as fin, gzip.open(tz, "wb") as fout:
        fout.write(fin.read())
    for a, b in zip(load_triples(tz), load_triples(t)):
        np.testing.assert_allclose(a, b)

    sv = str(tmp_path / "s.libsvm")
    with open(sv, "w") as f:
        f.write("1.0 1:0.5 3:2.0\n-1.0 2:1.5\n")
    svz = sv + ".gz"
    with open(sv, "rb") as fin, gzip.open(svz, "wb") as fout:
        fout.write(fin.read())
    for a, b in zip(load_libsvm(svz), load_libsvm(sv)):
        np.testing.assert_allclose(a, b)

    with CSVPoints(pz, chunk_rows=128) as cp:
        assert cp.shape == (500, 4)
        np.testing.assert_allclose(cp[0:500], pts, rtol=2e-6)


def test_csv_stream_exact_chunk_newline_split(native_lib, tmp_path):
    # a block landing with EXACTLY chunk_rows newlines plus a partial
    # trailing line must carry the partial bytes, not drop/corrupt them
    pts = np.arange(21, dtype=np.float32).reshape(7, 3)
    p = str(tmp_path / "e.csv")
    with open(p, "w") as f:
        for row in pts:
            f.write(",".join(str(v) for v in row) + "\n")
    from harp_tpu.native.datasource import CSVStream

    for chunk in (1, 2, 3, 7):
        with CSVStream(p, chunk_rows=chunk) as st:
            got = np.concatenate(list(st), 0)
        np.testing.assert_allclose(got, pts, err_msg=f"chunk={chunk}")


def test_csv_stream_comment_prefix_and_blank_runs(native_lib, tmp_path):
    # chunk_rows=1 with a leading comment line: the first block parses to
    # zero rows and must NOT read as EOF; same for long blank runs
    pts = np.random.default_rng(4).normal(size=(20, 2)).astype(np.float32)
    p = str(tmp_path / "c.csv")
    with open(p, "w") as f:
        f.write("# header\n# more\n")
        for i, row in enumerate(pts):
            f.write(" ".join(f"{v:.7e}" for v in row) + "\n")
            if i == 9:
                f.write("\n" * 5)  # blank run longer than chunk_rows
    from harp_tpu.native.datasource import CSVStream

    for chunk in (1, 4):
        with CSVStream(p, chunk_rows=chunk) as st:
            got = np.concatenate(list(st), 0)
        np.testing.assert_allclose(got, pts, rtol=2e-6,
                                   err_msg=f"chunk={chunk}")


def test_csv_points_rejects_negative_indices(native_lib, tmp_path):
    from harp_tpu.native.datasource import CSVPoints

    p = str(tmp_path / "n.csv")
    _write_csv(p, np.ones((10, 2), np.float32))
    with CSVPoints(p) as cp:
        with pytest.raises(IndexError, match="negative"):
            cp[np.array([-1])]


def test_csv_stream_fallback_pads_ragged_rows_like_native(native_lib,
                                                          tmp_path,
                                                          monkeypatch):
    # short rows zero-pad, extra columns are ignored — on BOTH paths
    p = str(tmp_path / "r.csv")
    with open(p, "w") as f:
        f.write("1,2,3\n4,5\n6,7,8,9\n")
    from harp_tpu.native.datasource import CSVStream

    with CSVStream(p, chunk_rows=10) as st:
        nat = np.concatenate(list(st), 0)
    import harp_tpu.native.build as B

    monkeypatch.setattr(B, "_LIB", None)
    monkeypatch.setattr(B, "_TRIED", True)
    with CSVStream(p, chunk_rows=10) as st:
        py = np.concatenate(list(st), 0)
    expect = np.array([[1, 2, 3], [4, 5, 0], [6, 7, 8]], np.float32)
    np.testing.assert_allclose(nat, expect)
    np.testing.assert_allclose(py, expect)


def test_csv_points_rejects_negative_slice_bounds(native_lib, tmp_path):
    from harp_tpu.native.datasource import CSVPoints

    p = str(tmp_path / "ns.csv")
    _write_csv(p, np.ones((10, 2), np.float32))
    with CSVPoints(p) as cp:
        with pytest.raises(IndexError, match="negative"):
            cp[-5:]
        with pytest.raises(IndexError, match="negative"):
            cp[0:-1]


def test_csv_count_stream_matches_whole_file_count(native_lib, tmp_path):
    # the bounded-memory count pass must agree with the dense loader
    import ctypes

    pts = np.random.default_rng(5).normal(size=(777, 4)).astype(np.float32)
    p = str(tmp_path / "cnt.csv")
    _write_csv(p, pts, blanks=True)  # blanks + header comment
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = native_lib.harp_csv_count_stream(p.encode(), ctypes.byref(rows),
                                          ctypes.byref(cols))
    assert rc == 0 and (rows.value, cols.value) == (777, 4)


def test_csv_stream_fallback_cols_past_comment_prefix(tmp_path, monkeypatch):
    import harp_tpu.native.build as B
    from harp_tpu.native.datasource import CSVStream

    monkeypatch.setattr(B, "_LIB", None)
    monkeypatch.setattr(B, "_TRIED", True)
    p = str(tmp_path / "cp.csv")
    with open(p, "w") as f:
        f.write("# one\n# two\n1 2 3\n")
    with CSVStream(p, chunk_rows=1) as st:
        assert st.cols == 3  # must scan past the comment-only first chunk


def test_parser_long_mantissa_with_small_exponent(native_lib, tmp_path):
    # regression: "9.9999999999999991e-31" pushed the combined decimal
    # exponent to -47; the old table clamp misparsed it to 0
    p = str(tmp_path / "exp.csv")
    cases = [9.9999999999999991e-31, 1e-30, -1.2345678901234567e-35,
             9.87654321e37, 1.1754944e-38]
    with open(p, "w") as f:
        f.write(" ".join(f"{v:.17g}" for v in cases) + "\n")
    got = load_csv(p)[0]
    expect = np.asarray(cases, np.float32)
    ulp = np.spacing(np.abs(expect)) + 1e-45
    assert (np.abs(got - expect) <= ulp).all(), (got, expect)


def test_parser_huge_exponent_is_fast_and_saturates(native_lib, tmp_path):
    # a corrupt exponent must parse O(1) to inf/0 (like strtof), never
    # spin the stepped-pow10 loop or index the table out of bounds
    import time

    p = str(tmp_path / "huge.csv")
    with open(p, "w") as f:
        f.write("1e2000000000 1e-2000000000 1.0\n" * 64)
    t0 = time.perf_counter()
    got = load_csv(p)
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"corrupt exponents took {dt:.2f}s"
    assert np.isinf(got[0, 0]) and got[0, 1] == 0.0 and got[0, 2] == 1.0
