"""Tests for the Table/Partition data model and KV helpers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel.collective import Combiner
from harp_tpu.table import (
    Table,
    combine_by_key,
    modulo_partitioner,
    pull_rows,
    push_rows,
)

N = 8


def test_table_combiner_on_collision():
    t = Table(Combiner.ADD)
    t.add_partition(3, np.ones(4))
    t.add_partition(3, np.full(4, 2.0))
    np.testing.assert_allclose(t.get_partition(3), np.full(4, 3.0))
    assert t.num_partitions == 1


def test_table_max_combiner():
    t = Table("max")
    t.add_partition(0, np.array([1.0, 5.0]))
    t.add_partition(0, np.array([3.0, 2.0]))
    np.testing.assert_allclose(t.get_partition(0), [3.0, 5.0])


def test_table_stacked_roundtrip():
    t = Table()
    for pid in [4, 1, 9]:
        t.add_partition(pid, np.full(3, pid, np.float32))
    ids, stack = t.to_stacked()
    np.testing.assert_array_equal(ids, [1, 4, 9])
    t2 = Table.from_stacked(ids, stack)
    assert t2.partition_ids() == [1, 4, 9]
    np.testing.assert_allclose(t2.get_partition(9), np.full(3, 9))


def test_modulo_partitioner():
    owner = modulo_partitioner(4)
    assert [owner(i) for i in range(6)] == [0, 1, 2, 3, 0, 1]


def test_combine_by_key_ops():
    keys = jnp.array([0, 1, 0, 2, 1])
    vals = jnp.array([1.0, 2.0, 3.0, 4.0, 5.0])
    np.testing.assert_allclose(combine_by_key(keys, vals, 4), [4, 7, 4, 0])
    np.testing.assert_allclose(
        combine_by_key(keys, vals, 4, Combiner.AVG), [2, 3.5, 4, 0]
    )


def test_pull_push_rows(mesh):
    """Row-indexed pull/pull on a row-sharded global table."""
    global_table = np.arange(N * 2 * 3, dtype=np.float32).reshape(N * 2, 3)

    def prog(shard):
        rows = jnp.array([0, 5, 15])
        pulled = pull_rows(shard, rows)
        new_shard = push_rows(shard, rows, jnp.ones((3, 3), jnp.float32))
        return pulled, new_shard

    fn = jax.jit(
        mesh.shard_map(prog, in_specs=(mesh.spec(0),), out_specs=(P(), mesh.spec(0)))
    )
    pulled, updated = fn(global_table)
    np.testing.assert_allclose(np.asarray(pulled), global_table[[0, 5, 15]])
    expect = global_table.copy()
    expect[[0, 5, 15]] += N  # every one of the N workers pushed +1
    np.testing.assert_allclose(np.asarray(updated), expect)


def test_avg_combiner_is_true_mean_over_three():
    t = Table(Combiner.AVG)
    for v in (1.0, 2.0, 6.0):
        t.add_partition(0, np.full(2, v))
    np.testing.assert_allclose(t.get_partition(0), np.full(2, 3.0))


def test_empty_table_stacked_raises():
    with pytest.raises(ValueError, match="no partitions"):
        Table().to_stacked()
