"""Tests for the Table/Partition data model and KV helpers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel.collective import Combiner
from harp_tpu.table import (
    Int2DoubleKVTable,
    Int2IntKVTable,
    KVTable,
    Table,
    combine_by_key,
    kv_allreduce,
    modulo_partitioner,
    pull_rows,
    pull_rows_sparse,
    pull_rows_sparse_dedup,
    push_rows,
    push_rows_sparse,
    push_rows_sparse_dedup,
)

N = 8


def test_table_combiner_on_collision():
    t = Table(Combiner.ADD)
    t.add_partition(3, np.ones(4))
    t.add_partition(3, np.full(4, 2.0))
    np.testing.assert_allclose(t.get_partition(3), np.full(4, 3.0))
    assert t.num_partitions == 1


def test_table_max_combiner():
    t = Table("max")
    t.add_partition(0, np.array([1.0, 5.0]))
    t.add_partition(0, np.array([3.0, 2.0]))
    np.testing.assert_allclose(t.get_partition(0), [3.0, 5.0])


def test_table_stacked_roundtrip():
    t = Table()
    for pid in [4, 1, 9]:
        t.add_partition(pid, np.full(3, pid, np.float32))
    ids, stack = t.to_stacked()
    np.testing.assert_array_equal(ids, [1, 4, 9])
    t2 = Table.from_stacked(ids, stack)
    assert t2.partition_ids() == [1, 4, 9]
    np.testing.assert_allclose(t2.get_partition(9), np.full(3, 9))


def test_modulo_partitioner():
    owner = modulo_partitioner(4)
    assert [owner(i) for i in range(6)] == [0, 1, 2, 3, 0, 1]


def test_combine_by_key_ops():
    keys = jnp.array([0, 1, 0, 2, 1])
    vals = jnp.array([1.0, 2.0, 3.0, 4.0, 5.0])
    np.testing.assert_allclose(combine_by_key(keys, vals, 4), [4, 7, 4, 0])
    np.testing.assert_allclose(
        combine_by_key(keys, vals, 4, Combiner.AVG), [2, 3.5, 4, 0]
    )


def test_pull_push_rows(mesh):
    """Row-indexed pull/pull on a row-sharded global table."""
    global_table = np.arange(N * 2 * 3, dtype=np.float32).reshape(N * 2, 3)

    def prog(shard):
        rows = jnp.array([0, 5, 15])
        pulled = pull_rows(shard, rows)
        new_shard = push_rows(shard, rows, jnp.ones((3, 3), jnp.float32))
        return pulled, new_shard

    fn = jax.jit(
        mesh.shard_map(prog, in_specs=(mesh.spec(0),), out_specs=(P(), mesh.spec(0)))
    )
    pulled, updated = fn(global_table)
    np.testing.assert_allclose(np.asarray(pulled), global_table[[0, 5, 15]])
    expect = global_table.copy()
    expect[[0, 5, 15]] += N  # every one of the N workers pushed +1
    np.testing.assert_allclose(np.asarray(updated), expect)


def _sparse_pull_fn(mesh, capacity):
    return jax.jit(mesh.shard_map(
        lambda shard, ids: pull_rows_sparse(shard, ids, capacity=capacity),
        in_specs=(mesh.spec(0), mesh.spec(0)),
        out_specs=(mesh.spec(0), mesh.spec(0), P()),
    ))


def test_pull_rows_sparse_matches_dense(mesh):
    """Property: the request/serve pull returns exactly table[row_ids],
    per worker, with DIFFERENT ids on every worker (the dense-path test
    uses replicated ids; this is the general case)."""
    rng = np.random.default_rng(0)
    rpw, d, m = 6, 3, 7
    table = rng.normal(size=(N * rpw, d)).astype(np.float32)
    ids = rng.integers(0, N * rpw, size=(N * m)).astype(np.int32)

    rows, ok, dropped = _sparse_pull_fn(mesh, capacity=m)(table, ids)
    assert int(dropped) == 0
    assert np.asarray(ok).all()
    np.testing.assert_allclose(np.asarray(rows), table[ids])


def test_pull_rows_sparse_duplicates_and_1d(mesh):
    # duplicate ids on one worker + a 1-D value table
    rng = np.random.default_rng(1)
    rpw = 4
    table = rng.normal(size=(N * rpw,)).astype(np.float32)
    ids = np.tile(np.array([5, 5, 0, 31], np.int32), N)
    rows, ok, dropped = _sparse_pull_fn(mesh, capacity=4)(table, ids)
    assert int(dropped) == 0 and np.asarray(ok).all()
    np.testing.assert_allclose(np.asarray(rows), table[ids])


def test_pull_rows_sparse_capacity_overflow_counted(mesh):
    # every worker asks owner 0 for rpw*... more rows than capacity:
    # overflow must be dropped, masked, and counted globally
    rpw, d = 2, 3
    table = np.arange(N * rpw * d, dtype=np.float32).reshape(N * rpw, d)
    ids = np.zeros(N * 5, np.int32)  # all want row 0 (owner 0), 5 each
    rows, ok, dropped = _sparse_pull_fn(mesh, capacity=3)(table, ids)
    ok = np.asarray(ok).reshape(N, 5)
    assert (ok.sum(1) == 3).all()          # 3 kept per worker
    assert int(dropped) == N * 2           # 2 dropped per worker
    rows = np.asarray(rows).reshape(N, 5, d)
    np.testing.assert_allclose(rows[ok], np.tile(table[0], (N * 3, 1)))
    np.testing.assert_allclose(rows[~ok], 0.0)


def test_pull_rows_sparse_valid_mask_skips_padding(mesh):
    """valid=False entries issue no request, take no capacity slot, and
    are not counted dropped — padding must not crowd real requests."""
    rpw, d = 2, 3
    table = np.arange(N * rpw * d, dtype=np.float32).reshape(N * rpw, d)
    # per worker: 2 real requests for row 0 + 3 padding entries also
    # pointing at row 0; capacity 2 → without the mask, padding would
    # overflow the bucket and drop real requests
    ids = np.zeros(N * 5, np.int32)
    valid = np.tile(np.array([1, 1, 0, 0, 0], bool), N)

    fn = jax.jit(mesh.shard_map(
        lambda shard, i, v: pull_rows_sparse(shard, i, capacity=2, valid=v),
        in_specs=(mesh.spec(0), mesh.spec(0), mesh.spec(0)),
        out_specs=(mesh.spec(0), mesh.spec(0), P()),
    ))
    rows, ok, dropped = fn(table, ids, valid)
    assert int(dropped) == 0                      # padding never counts
    np.testing.assert_array_equal(np.asarray(ok), valid)
    rows = np.asarray(rows)
    np.testing.assert_allclose(rows[valid], np.tile(table[0], (N * 2, 1)))
    np.testing.assert_allclose(rows[~valid], 0.0)


def test_push_rows_sparse_matches_dense(mesh):
    """Property: sparse push ≡ np scatter-add of every worker's deltas."""
    rng = np.random.default_rng(2)
    rpw, d, m = 6, 3, 9
    table = rng.normal(size=(N * rpw, d)).astype(np.float32)
    ids = rng.integers(0, N * rpw, size=(N * m)).astype(np.int32)
    deltas = rng.normal(size=(N * m, d)).astype(np.float32)

    fn = jax.jit(mesh.shard_map(
        lambda shard, i, dv: push_rows_sparse(shard, i, dv, capacity=m),
        in_specs=(mesh.spec(0), mesh.spec(0), mesh.spec(0)),
        out_specs=(mesh.spec(0), P()),
    ))
    new_table, dropped = fn(table, ids, deltas)
    assert int(dropped) == 0
    expect = table.copy()
    np.add.at(expect, ids, deltas)
    np.testing.assert_allclose(np.asarray(new_table), expect, rtol=1e-5,
                               atol=1e-5)


def test_push_rows_sparse_capacity_overflow_not_folded(mesh):
    """Over-capacity pushes must be counted AND excluded — the trash-slot
    masking is the one place a bug would corrupt the table rather than
    just lose a read."""
    rpw, d = 8, 3
    table = np.zeros((N * rpw, d), np.float32)
    # every worker pushes 6 distinct rows of owner 0, capacity 4: rows
    # 0..3 (bucket order = appearance order) land, rows 4..5 are dropped
    ids = np.tile(np.arange(6, dtype=np.int32), N)
    deltas = np.ones((N * 6, d), np.float32)

    fn = jax.jit(mesh.shard_map(
        lambda shard, i, dv: push_rows_sparse(shard, i, dv, capacity=4),
        in_specs=(mesh.spec(0), mesh.spec(0), mesh.spec(0)),
        out_specs=(mesh.spec(0), P()),
    ))
    new_table, dropped = fn(table, ids, deltas)
    assert int(dropped) == N * 2
    expect = np.zeros_like(table)
    expect[:4] = N  # kept rows: +1 from every worker
    np.testing.assert_allclose(np.asarray(new_table), expect)


def test_push_then_pull_sparse_roundtrip(mesh):
    # push deltas then pull the same rows back: reads see the writes
    rpw, d = 3, 2
    table = np.zeros((N * rpw, d), np.float32)
    ids = (np.arange(N, dtype=np.int32) * rpw).repeat(2)  # 2 pushes each

    def prog(shard, i):
        dv = jnp.ones((i.shape[0], d), jnp.float32)
        shard, dropped = push_rows_sparse(shard, i, dv, capacity=4)
        rows, ok, _ = pull_rows_sparse(shard, i, capacity=4)
        return shard, rows, ok, dropped

    fn = jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0), mesh.spec(0)),
        out_specs=(mesh.spec(0), mesh.spec(0), mesh.spec(0), P())))
    shard, rows, ok, dropped = fn(table, ids)
    assert int(dropped) == 0 and np.asarray(ok).all()
    # each pushed row got +1 from each of its 2 duplicate pushes... from
    # every worker that owns the same id (ids differ per worker here)
    np.testing.assert_allclose(np.asarray(rows), 2.0)


def test_pull_rows_sparse_dedup_matches_raw(mesh):
    """Duplicates share one wire slot but every position still receives
    its row — bit-identical to the raw verb at ample capacity, padding
    honored, drop count zero."""
    rng = np.random.default_rng(5)
    rpw, d, m = 6, 3, 12
    table = rng.normal(size=(N * rpw, d)).astype(np.float32)
    # heavy duplication: only 4 distinct ids per worker
    ids = rng.integers(0, N * rpw, size=(N, 4)).astype(np.int32)
    ids = np.repeat(ids, 3, axis=1).reshape(-1)          # [N*m]
    valid = (np.arange(N * m) % 5 != 0)                  # some padding

    def prog(t, i, v):
        raw = pull_rows_sparse(t, i, capacity=m, valid=v)
        dd = pull_rows_sparse_dedup(t, i, capacity=m, valid=v)
        return raw + dd

    fn = jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0),) * 3,
        out_specs=(mesh.spec(0), mesh.spec(0), P()) * 2))
    r_rows, r_ok, r_drop, d_rows, d_ok, d_drop = fn(table, ids, valid)
    assert int(r_drop) == 0 and int(d_drop) == 0
    np.testing.assert_array_equal(np.asarray(r_ok), np.asarray(d_ok))
    np.testing.assert_array_equal(np.asarray(r_rows), np.asarray(d_rows))


def test_pull_rows_sparse_dedup_capacity_per_distinct(mesh):
    """The point of dedup: m requests for ONE hot row need capacity 1
    (the raw verb would drop m-1 of them)."""
    rpw, d, m = 4, 2, 8
    table = np.arange(N * rpw * d, dtype=np.float32).reshape(N * rpw, d)
    ids = np.zeros(N * m, np.int32)  # every worker: m copies of row 0

    def prog(t, i):
        return (pull_rows_sparse_dedup(t, i, capacity=1)
                + pull_rows_sparse(t, i, capacity=1))

    fn = jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0),) * 2,
        out_specs=(mesh.spec(0), mesh.spec(0), P()) * 2))
    d_rows, d_ok, d_drop, r_rows, r_ok, r_drop = fn(table, ids)
    assert int(d_drop) == 0 and np.asarray(d_ok).all()
    np.testing.assert_allclose(np.asarray(d_rows),
                               np.tile(table[0], (N * m, 1)))
    assert int(r_drop) == N * (m - 1)  # raw: one slot serves, m-1 drop


def test_push_rows_sparse_dedup_matches_dense(mesh):
    """Pre-summed dedup push ≡ np scatter-add (integer deltas ⇒ exact),
    with duplicate-heavy ids and a validity mask."""
    rng = np.random.default_rng(6)
    rpw, d, m = 5, 3, 12
    table = np.zeros((N * rpw, d), np.float32)
    ids = np.repeat(rng.integers(0, N * rpw, size=(N, 4)), 3,
                    axis=1).reshape(-1).astype(np.int32)
    deltas = rng.integers(-3, 4, size=(N * m, d)).astype(np.float32)
    valid = (np.arange(N * m) % 4 != 1)

    fn = jax.jit(mesh.shard_map(
        lambda t, i, dv, v: push_rows_sparse_dedup(t, i, dv, capacity=m,
                                                   valid=v),
        in_specs=(mesh.spec(0),) * 4, out_specs=(mesh.spec(0), P())))
    new_table, dropped = fn(table, ids, deltas, valid)
    assert int(dropped) == 0
    expect = table.copy()
    np.add.at(expect, ids[valid], deltas[valid])
    np.testing.assert_array_equal(np.asarray(new_table), expect)


def test_dedup_verbs_out_of_range_ids_drop_once_per_distinct(mesh):
    """Out-of-range ids stay counted drops (never served, never clamped)
    — once per DISTINCT bad id under dedup."""
    rpw, d = 4, 2
    table = np.zeros((N * rpw, d), np.float32)
    bad = N * rpw + 7
    ids = np.tile(np.array([0, bad, bad, bad], np.int32), N)

    def prog(t, i):
        rows, ok, dropped = pull_rows_sparse_dedup(t, i, capacity=4)
        return rows, ok, dropped

    fn = jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0),) * 2,
        out_specs=(mesh.spec(0), mesh.spec(0), P())))
    rows, ok, dropped = fn(table, ids)
    ok = np.asarray(ok).reshape(N, 4)
    assert ok[:, 0].all() and not ok[:, 1:].any()
    assert int(dropped) == N  # one distinct bad id per worker


def test_regroup_by_key_routes_to_owner(mesh):
    """Every pair lands on worker key % N; combined totals match host."""
    from harp_tpu.table import regroup_by_key
    from harp_tpu.parallel.mesh import worker_id

    rng = np.random.default_rng(0)
    n_per = 16
    keys = rng.integers(0, 32, (N, n_per)).astype(np.int32)
    vals = rng.normal(size=(N, n_per)).astype(np.float32)

    def prog(k, v):
        rk, rv, rm, dropped = regroup_by_key(k, v, capacity=n_per)
        # combine what this worker now owns over the global key space
        combined = combine_by_key(rk, rv * rm, 32)
        owned = jnp.arange(32) % N == worker_id()
        return combined * owned, dropped

    fn = jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0), mesh.spec(0)),
        out_specs=(mesh.spec(0), P()),
    ))
    per_worker, dropped = fn(keys.reshape(-1), vals.reshape(-1))
    assert int(dropped) == 0  # capacity == n_per can never overflow
    got = np.asarray(per_worker).reshape(N, 32).sum(0)
    ref = np.zeros(32, np.float32)
    np.add.at(ref, keys.ravel(), vals.ravel())
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_regroup_by_key_capacity_drops(mesh):
    from harp_tpu.table import regroup_by_key

    # every pair keyed 0 → all head to worker 0; capacity 2 of 8 per worker
    keys = np.zeros((N, 8), np.int32)
    vals = np.ones((N, 8), np.float32)

    def prog(k, v):
        rk, rv, rm, dropped = regroup_by_key(k, v, capacity=2)
        return rm.sum().reshape(1), dropped

    fn = jax.jit(mesh.shard_map(
        prog, in_specs=(mesh.spec(0), mesh.spec(0)), out_specs=(mesh.spec(0), P()),
    ))
    kept, dropped = fn(keys.reshape(-1), vals.reshape(-1))
    assert int(dropped) == N * (8 - 2)
    # worker 0 received 2 pairs from each of the N sources
    assert np.asarray(kept)[0] == N * 2


def test_avg_combiner_is_true_mean_over_three():
    t = Table(Combiner.AVG)
    for v in (1.0, 2.0, 6.0):
        t.add_partition(0, np.full(2, v))
    np.testing.assert_allclose(t.get_partition(0), np.full(2, 3.0))


def test_empty_table_stacked_raises():
    with pytest.raises(ValueError, match="no partitions"):
        Table().to_stacked()


def test_kvtable_valcombiner_on_collision():
    t = Int2IntKVTable()  # ADD combiner, int32 values
    t.add(7, 2)
    t.add(7, 3)
    t.add(1, 10)
    assert int(t.get(7)) == 5
    assert int(t.get(1)) == 10
    assert t.get(99, default=-1) == -1
    assert len(t) == 2 and 7 in t and t.keys() == [1, 7]
    assert t.get(7).dtype == np.int32


def test_kvtable_avg_is_true_mean():
    t = Int2DoubleKVTable(Combiner.AVG)
    for v in (1.0, 2.0, 6.0):
        t.add(5, v)
    np.testing.assert_allclose(t.get(5), 3.0)


def test_kvtable_array_values_and_roundtrip():
    t = KVTable("max", dtype=np.float32)
    t.add(2, [1.0, 5.0])
    t.add(2, [3.0, 2.0])
    t.add(0, [0.0, 0.0])
    keys, vals, counts = t.to_arrays()
    np.testing.assert_array_equal(keys, [0, 2])
    np.testing.assert_array_equal(counts, [1, 2])
    np.testing.assert_allclose(vals[1], [3.0, 5.0])
    t2 = KVTable.from_arrays(keys, vals, "max", counts=counts)
    np.testing.assert_allclose(t2.get(2), [3.0, 5.0])


def test_kvtable_empty_to_arrays_shapes():
    t = KVTable(dtype=np.float32)
    keys, vals, counts = t.to_arrays()
    assert keys.shape == (0,) and vals.shape == (0,) and counts.shape == (0,)
    t.add(1, [1.0, 2.0, 3.0])
    assert t.to_arrays()[1].shape == (1, 3)


def test_typed_kvtables_are_classes():
    t = Int2IntKVTable()
    assert isinstance(t, Int2IntKVTable) and isinstance(t, KVTable)


def test_typed_kvtable_from_arrays_roundtrip():
    t = Int2IntKVTable()
    t.add(1, 3)
    t.add(2, 4)
    keys, vals, counts = t.to_arrays()
    t2 = Int2IntKVTable.from_arrays(keys, vals, counts=counts)
    assert isinstance(t2, Int2IntKVTable)
    assert int(t2.get(1)) == 3 and t2.get(2).dtype == np.int32


def test_int_kvtable_avg_promotes_to_float():
    t = Int2IntKVTable(Combiner.AVG)
    t.add(0, 1)
    t.add(0, 2)
    np.testing.assert_allclose(t.get(0), 1.5)  # not truncated to int


def test_kv_process_union_single_process():
    """The multi-host union path, driven with process_count==1.

    Exercises the signature agreement, padding, float64 transport, and
    counts>0 validity (negative keys must survive).
    """
    from harp_tpu.table import _kv_process_union

    t = KVTable("add", dtype=np.float32)
    t.add(-3, [1.0, 2.0])  # negative key
    t.add(5, [3.0, 4.0])
    t.add(5, [1.0, 1.0])
    u = _kv_process_union(t)
    assert u.keys() == [-3, 5]
    np.testing.assert_allclose(u.get(-3), [1.0, 2.0])
    np.testing.assert_allclose(u.get(5), [4.0, 5.0])
    assert u.get(5).dtype == np.float32

    empty = KVTable("add", dtype=np.float32)
    assert _kv_process_union(empty).keys() == []


def test_kv_process_union_int64_exact_and_typed():
    """Byte transport: int64 counters above 2**53 survive exactly, and the
    union keeps the typed subclass."""
    from harp_tpu.table import Int2LongKVTable, _kv_process_union

    big = 2**60 + 1
    t = Int2LongKVTable()
    t.add(1, big)
    t.add(2**40, 7)  # key beyond int32 range survives too
    u = _kv_process_union(t)
    assert isinstance(u, Int2LongKVTable)
    assert int(u.get(1)) == big
    assert int(u.get(2**40)) == 7


def test_kv_allreduce_preserves_typed_class():
    from harp_tpu.table import Int2IntKVTable, kv_allreduce

    t = Int2IntKVTable()
    t.add(0, 1)
    assert isinstance(kv_allreduce(t), Int2IntKVTable)


def test_table_first_insert_stored_verbatim():
    t = Table()
    d = {"w": np.ones(2)}
    t.add_partition(0, d)
    assert t.get_partition(0) is d  # pytree payloads survive un-coerced


def test_kvtable_partitioning_matches_modulo():
    t = KVTable(num_partitions=4)
    assert [t.partition(k) for k in (0, 1, 5, 11)] == [0, 1, 1, 3]


def test_kv_allreduce_merges_worker_tables():
    workers = []
    for w in range(3):
        t = Int2IntKVTable()
        t.add(w, 1)       # unique key per worker
        t.add(100, w + 1)  # shared key: combined 1+2+3
        workers.append(t)
    merged = kv_allreduce(workers[0], worker_tables=workers[1:])
    assert merged.keys() == [0, 1, 2, 100]
    assert int(merged.get(100)) == 6


def test_kv_merge_avg_is_count_weighted():
    """Merging pre-combined AVG tables == AVG over all raw contributions."""
    a = KVTable(Combiner.AVG, dtype=np.float64)
    a.add(7, 0.0)
    a.add(7, 0.0)       # a holds mean 0.0 with count 2
    b = KVTable(Combiner.AVG, dtype=np.float64)
    b.add(7, 6.0)       # b holds mean 6.0 with count 1
    merged = kv_allreduce(a, worker_tables=[b])
    np.testing.assert_allclose(merged.get(7), 2.0)  # (0+0+6)/3, not 3.0


def test_kvtable_matches_combine_by_key():
    """Host KVTable and device combine_by_key agree (same ValCombiner math)."""
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 16, 200)
    vals = rng.normal(size=200).astype(np.float32)
    t = KVTable(Combiner.ADD, dtype=np.float32)
    for k, v in zip(keys, vals):
        t.add(k, v)
    dense = np.asarray(combine_by_key(jnp.asarray(keys), jnp.asarray(vals), 16))
    for k in t.keys():
        np.testing.assert_allclose(t.get(k), dense[k], rtol=1e-5)


def test_sparse_verbs_out_of_range_ids_counted_not_corrupting(mesh):
    """An out-of-range row id must come back ok=False and counted — the
    naive path would clamp it into the LAST worker's bucket and silently
    serve/corrupt the wrong row."""
    rpw, d = 2, 3
    table = np.arange(N * rpw * d, dtype=np.float32).reshape(N * rpw, d)
    # per worker: one good id, one out of range (beyond the table)
    ids = np.tile(np.array([3, N * rpw + 5], np.int32), N)

    rows, ok, dropped = _sparse_pull_fn(mesh, capacity=2)(table, ids)
    ok = np.asarray(ok)
    assert int(dropped) == N          # every bad id counted
    np.testing.assert_array_equal(ok, np.tile([True, False], N))
    np.testing.assert_allclose(np.asarray(rows)[ok],
                               np.tile(table[3], (N, 1)))
    np.testing.assert_allclose(np.asarray(rows)[~ok], 0.0)

    fn = jax.jit(mesh.shard_map(
        lambda shard, i, dv: push_rows_sparse(shard, i, dv, capacity=2),
        in_specs=(mesh.spec(0), mesh.spec(0), mesh.spec(0)),
        out_specs=(mesh.spec(0), P()),
    ))
    new_table, pdrop = fn(table, ids, np.ones((N * 2, d), np.float32))
    assert int(pdrop) == N
    expect = table.copy()
    expect[3] += N                    # only the in-range pushes landed
    np.testing.assert_allclose(np.asarray(new_table), expect)
