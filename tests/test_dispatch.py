"""Direct tests for the shared capacity-bucket dispatch helper."""

import jax.numpy as jnp
import numpy as np

from harp_tpu.parallel.dispatch import bucket_by_destination


def test_bucketing_places_items_in_order():
    dest = jnp.asarray([1, 0, 1, 1, 0])
    vals = jnp.asarray([10.0, 20.0, 30.0, 40.0, 50.0])
    (buf,), keep, slot, dropped = bucket_by_destination(dest, (vals,), 3, 2)
    assert int(dropped) == 0
    assert bool(keep.all())
    np.testing.assert_allclose(np.asarray(buf[0]), [20.0, 50.0, 0.0])
    np.testing.assert_allclose(np.asarray(buf[1]), [10.0, 30.0, 40.0])


def test_bucketing_drops_over_capacity_via_trash_slot():
    dest = jnp.zeros(5, jnp.int32)
    vals = jnp.arange(1.0, 6.0)
    (buf,), keep, slot, dropped = bucket_by_destination(dest, (vals,), 2, 2)
    assert int(dropped) == 3
    np.testing.assert_array_equal(np.asarray(keep), [True, True, False, False, False])
    # the kept items survive intact; no trash-slot bleed into real slots
    np.testing.assert_allclose(np.asarray(buf[0]), [1.0, 2.0])
    np.testing.assert_allclose(np.asarray(buf[1]), [0.0, 0.0])
    # dropped items all point at the (sliced-off) trash slot
    np.testing.assert_array_equal(np.asarray(slot[2:]), [2, 2, 2])


def test_bucketing_multi_payload_and_trailing_dims():
    dest = jnp.asarray([0, 1])
    a = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    b = jnp.asarray([7, 9], dtype=jnp.int32)
    (ba, bb), keep, _, dropped = bucket_by_destination(dest, (a, b), 1, 2)
    assert int(dropped) == 0
    np.testing.assert_allclose(np.asarray(ba[0, 0]), [1.0, 2.0])
    np.testing.assert_allclose(np.asarray(ba[1, 0]), [3.0, 4.0])
    np.testing.assert_array_equal(np.asarray(bb[:, 0]), [7, 9])
