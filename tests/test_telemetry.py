"""Comm ledger + span tracer (utils/telemetry.py) — the observability spine.

The load-bearing claims: bytes are counted once per *execution*, not once
per *trace* (jit caching), payloads match hand-computed wire sheets, spans
nest, and everything is off (and free) by default.
"""

import json

import numpy as np
import pytest

import harp_tpu.utils.telemetry as T
from harp_tpu.parallel import collective as C

NW = 8  # conftest mesh


def _per_shard_bytes(rows, cols=128, itemsize=4):
    return rows // NW * cols * itemsize


def test_disabled_records_nothing(mesh):
    T.ledger.reset()
    T.tracer.reset()  # earlier tests' records persist past their scope()
    assert not T.enabled()
    op = C.host_op(mesh, C.allreduce)
    with T.ledger.run("off", steps=1):
        op(np.ones((NW, 128), np.float32))
    with T.span("off-span"):
        pass
    assert T.ledger.summary() == {}
    assert T.tracer.records == []


def test_ledger_counts_per_execution_not_per_trace(mesh):
    """Satellite requirement: a jitted allreduce invoked twice executes its
    traced comm site twice but traces it once — the ledger must report
    2 × payload, not 1 × (trace undercount) or 3 × (trace+exec blend)."""
    with T.scope():
        op = C.host_op(mesh, C.allreduce)
        x = np.ones((64, 128), np.float32)
        with T.ledger.run("t", steps=1):
            op(x)  # traces here
        with T.ledger.run("t", steps=1):
            op(x)  # cached executable: no Python runs
        per = _per_shard_bytes(64)
        assert T.ledger.bytes_per_execution("t") == per
        assert T.ledger.executions("t") == 2
        assert T.ledger.volume("t") == 2 * per
        (site,) = T.ledger.summary()["t"]["sites"]
        assert site["verb"] == "allreduce"
        assert site["combiner"] == "add"
        assert site["calls_per_trace"] == 1


def test_ledger_retrace_does_not_double_count(mesh):
    """A NEW jit wrapper over the same call site re-traces the same
    program; the re-trace must overwrite the site's byte sheet, not add
    to it."""
    with T.scope():
        x = np.ones((64, 128), np.float32)
        for _ in range(2):  # two independent wrappers -> two traces
            op = C.host_op(mesh, C.allreduce)
            with T.ledger.run("t", steps=1):
                op(x)
        per = _per_shard_bytes(64)
        assert T.ledger.bytes_per_execution("t") == per
        assert T.ledger.volume("t") == 2 * per


def test_ledger_hand_computed_payloads(mesh):
    """allreduce / allgather / regroup payloads == hand-computed per-shard
    wire sheets (f32 [rows, 128] sharded over 8 workers on dim 0)."""
    rows = NW * NW  # regroup needs rows % nw^2 == 0
    x = np.ones((rows, 128), np.float32)
    per = _per_shard_bytes(rows)
    for verb, out_dim in ((C.allreduce, None), (C.allgather, None),
                          (C.regroup, 0)):
        with T.scope():
            op = C.host_op(mesh, verb, in_dim=0, out_dim=out_dim)
            with T.ledger.run("t", steps=1):
                op(x)
            assert T.ledger.bytes_per_execution("t") == per, verb
            assert T.ledger.volume("t") == per, verb


def test_ledger_quantized_wire_dtype_bytes(mesh):
    """The quantized verbs account float leaves at the WIRE width: bf16 =
    2 B/elem, int8 = 1 B/elem (the logical EQuARX-style wire)."""
    x = np.ones((64, 128), np.float32)
    elems = 64 // NW * 128
    for wire, expect in (("bfloat16", 2 * elems), ("int8", elems)):
        import jax.numpy as jnp

        with T.scope():
            op = C.host_op(mesh, C.allreduce_quantized,
                           wire_dtype=getattr(jnp, wire))
            with T.ledger.run("q", steps=1):
                op(x)
            assert T.ledger.bytes_per_execution("q") == expect, wire
            (site,) = T.ledger.summary()["q"]["sites"]
            assert site["wire_dtype"] == wire


def test_ledger_loop_sites_accumulate_within_one_trace(mesh):
    """A Python loop hitting the same call site N times within ONE trace
    is N distinct collectives per execution — they must sum."""
    import jax
    from jax.sharding import PartitionSpec as P

    def step(x):
        for _ in range(3):  # same site, three traced collectives
            x = C.allreduce(x)
        return x

    with T.scope():
        fn = jax.jit(mesh.shard_map(step, in_specs=(mesh.spec(0),),
                                    out_specs=P()))
        x = np.ones((NW, 128), np.float32)
        with T.ledger.run("loop", steps=1):
            fn(x)
        t = T.ledger.summary()["loop"]
        assert sum(s["calls_per_trace"] for s in t["sites"]) == 3
        assert t["bytes_per_execution"] == 3 * _per_shard_bytes(NW)


def test_span_nesting_and_depth():
    import time

    with T.scope():
        with T.span("parent"):
            with T.span("child"):
                time.sleep(0.01)
        recs = {r["span"]: r for r in T.tracer.records}
    child, parent = recs["child"], recs["parent"]
    assert child["depth"] == 1 and parent["depth"] == 0
    assert child["path"] == "parent/child"
    # child window inside parent window
    assert child["t0"] >= parent["t0"]
    assert child["t0"] + child["dur"] <= parent["t0"] + parent["dur"] + 1e-6
    # summary merges into the Timer.summary shape
    s = T.tracer.summary()
    assert s["parent"]["n"] == 1 and s["parent"]["total_s"] >= 0.01


def test_span_records_on_exception():
    with T.scope():
        with pytest.raises(RuntimeError):
            with T.span("boom"):
                raise RuntimeError("x")
        assert [r["span"] for r in T.tracer.records] == ["boom"]
        assert T.tracer._stack == []  # stack unwound


def test_export_jsonl_roundtrip(tmp_path, mesh):
    with T.scope():
        with T.span("epoch", epoch=0):
            op = C.host_op(mesh, C.allreduce)
            with T.ledger.run("rt", steps=4):
                op(np.ones((NW, 128), np.float32))
        path = str(tmp_path / "run.jsonl")
        T.export(path)
    spans, comms = T.load_jsonl(path)
    assert [s["span"] for s in spans] == ["epoch"]
    assert spans[0]["epoch"] == 0
    (c,) = comms
    assert c["verb"] == "allreduce" and c["tag"] == "rt"
    assert c["executions"] == 4
    assert c["payload_bytes"] == _per_shard_bytes(NW)
    # every exported line is valid JSON (the check_jsonl contract)
    for line in open(path):
        json.loads(line)


def test_model_epoch_loops_feed_ledger(mesh):
    """The wired-through epoch loops: MF-SGD's rotation epoch records
    rotate traffic under the mfsgd.epochs tag with executions == epochs
    counted through BOTH train_epoch and train_epochs."""
    from harp_tpu.models import mfsgd

    u, i, v = mfsgd.synthetic_ratings(64, 48, 500, rank=4, seed=0)
    cfg = mfsgd.MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                            entry_cap=64)
    with T.scope():
        model = mfsgd.MFSGD(64, 48, cfg, mesh, seed=0)
        model.set_ratings(u, i, v)
        model.train_epoch()       # 1 execution (traces the single-epoch fn)
        model.train_epochs(2)     # 2 more through the multi-epoch program
        assert T.ledger.executions("mfsgd.epochs") == 3
        tag = T.ledger.summary()["mfsgd.epochs"]
        verbs = {s["verb"] for s in tag["sites"]}
        # the rotation ring is on the ledger — since PR 11 through the
        # reshard shim (same ppermute, same bytes, new verb name)
        assert "reshard" in verbs
        assert tag["bytes_per_execution"] > 0
        assert tag["total_bytes"] == 3 * tag["bytes_per_execution"]
        spans = T.tracer.summary()
        assert spans["mfsgd.epoch"]["n"] == 1
        assert spans["mfsgd.epochs"]["n"] == 1


def test_kmeans_cli_report_matches_hand_computed_bytes(mesh, capsys):
    """Acceptance: `python -m harp_tpu kmeans` with telemetry enabled
    emits a run report whose allreduce byte count equals the hand-computed
    (k·d·4 + k·4 + 4) × iters × executions sheet (sums + counts + inertia
    per iteration, one invocation ⇒ executions == iters)."""
    import harp_tpu.__main__ as cli

    n, d, k, iters = 512, 16, 8, 3
    with T.scope():
        rc = cli.main(["kmeans", "--n", str(n), "--d", str(d), "--k",
                       str(k), "--iters", str(iters)])
    assert rc == 0
    out = capsys.readouterr()
    assert "== harp-tpu run report ==" in out.err
    line = [ln for ln in out.out.splitlines()
            if '"config": "kmeans_telemetry"' in ln]
    assert len(line) == 1, out.out
    rec = json.loads(line[0])
    tag = rec["comm_tags"]["kmeans.fit"]
    per_iter = k * d * 4 + k * 4 + 4
    assert tag["bytes_per_execution"] == per_iter
    assert tag["executions"] == iters
    assert tag["total_bytes"] == per_iter * iters
    assert rec["comm_verbs"]["allreduce"] == per_iter * iters
    # provenance stamped through benchmark_json
    assert rec["backend"] == "cpu" and "date" in rec and "commit" in rec
    # the span wired through fit() is in the same report
    assert "kmeans.fit" in rec["spans"]


def test_bench_verb_counts_reps(mesh):
    """benchmark.bench_verb: 1 warmup + reps timed executions land on the
    host-side counter; payload is the per-shard input sheet."""
    from harp_tpu import benchmark as B

    with T.scope():
        r = B.bench_verb("allreduce", mesh, size_bytes=64 * 1024, reps=3)
        tag = T.ledger.summary()["bench.allreduce"]
        assert tag["executions"] == 4  # 1 warmup + 3 timed
        n_rows = r["bytes"] // (4 * 128)
        assert tag["bytes_per_execution"] == _per_shard_bytes(n_rows)


def test_scope_restores_disabled_state():
    assert not T.enabled()
    with T.scope():
        assert T.enabled()
    assert not T.enabled()


@pytest.mark.slow
def test_full_lda_run_ledger_and_report(mesh, capsys):
    """Full multi-epoch LDA through the CLI with telemetry on: the Gibbs
    sweep's rotation ring and Nk allreduce land on the ledger under
    lda.epochs with executions == warmup + epochs, and the emitted report
    carries both span and ledger evidence.  slow: a real (small-shape)
    multi-epoch model run — tier-1 filters it via -m 'not slow'."""
    from harp_tpu.models import lda

    with T.scope():
        lda.main(["--docs", "64", "--vocab", "64", "--topics", "8",
                  "--tokens-per-doc", "8", "--epochs", "2",
                  "--d-tile", "8", "--w-tile", "8", "--entry-cap", "32"])
    out = capsys.readouterr()
    line = [ln for ln in out.out.splitlines()
            if '"config": "lda_telemetry"' in ln]
    assert len(line) == 1, out.out
    rec = json.loads(line[0])
    tag = rec["comm_tags"]["lda.epochs"]
    # benchmark(): 1 warmup sample_epoch + sample_epochs(2)
    assert tag["executions"] == 3
    assert {"reshard"} <= set(rec["comm_verbs"])  # the PR-11 ring-hop shim
    assert tag["total_bytes"] == 3 * tag["bytes_per_execution"] > 0
    assert rec["spans"]["lda.epochs"]["n"] == 1
