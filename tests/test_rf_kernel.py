"""On-chip one-hot histogram kernel (ops/rf_kernel.py) vs the dense arm.

Counts are integers accumulated exactly (int8 products ≤ 127 summed in
int32), so every comparison here is BIT-IDENTICAL — `assert_array_equal`
throughout, never allclose.  A single off-by-one count can change a Gini
argmin, so "close" is not a meaningful notion for this kernel.  The
tests pin the kernel against a numpy scatter-add golden, the dense XLA
arm through _grow_level, the tree-vmap batching the model runs under,
the full forest under the 8-worker mesh, and the offline guarantees
(VMEM rejection + Mosaic lowering at the registry/graded shapes).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from harp_tpu.models import rf as RF
from harp_tpu.ops import rf_kernel as K


def _golden(bins, y, w, node_id, f, B, nodeC, C):
    """Exact numpy scatter-add: hist[node·C + y, feat·B + bin] += w."""
    hist = np.zeros((nodeC, f * B), np.int64)
    for i in range(len(y)):
        for j in range(f):
            hist[node_id[i] * C + y[i], j * B + bins[i, j]] += w[i]
    return hist.astype(np.int32)


def _bo(bins, B):
    return np.asarray(RF.bins_onehot(jnp.asarray(bins), B))


def test_hist_matches_numpy_scatter():
    rng = np.random.default_rng(0)
    n, f, B, C, level = 300, 16, 8, 3, 2       # fB = 128, pads n → tn
    bins = rng.integers(0, B, (n, f)).astype(np.int32)
    y = rng.integers(0, C, n).astype(np.int32)
    w = rng.poisson(1.0, n).astype(np.int32)
    node_id = rng.integers(0, 2 ** level, n).astype(np.int32)
    nodeC = 2 ** level * C
    hist = K.hist_bins(jnp.asarray(_bo(bins, B)),
                       jnp.asarray(node_id * C + y), jnp.asarray(w),
                       nodeC, interpret=True)
    np.testing.assert_array_equal(
        np.asarray(hist), _golden(bins, y, w, node_id, f, B, nodeC, C))


def test_multi_tile_grid_accumulates_exactly():
    """n > tn drives the sequential-grid accumulation and the pad
    sentinel (rowcode = nodeCp, weight 0) — pad samples must count ZERO
    times, not once, and tiles must accumulate, not overwrite."""
    rng = np.random.default_rng(1)
    n, f, B, C = 700, 16, 8, 2                 # 700 → n_pad 768 at tn=128
    bins = rng.integers(0, B, (n, f)).astype(np.int32)
    y = rng.integers(0, C, n).astype(np.int32)
    w = rng.integers(1, 5, n).astype(np.int32)
    hist = K.hist_bins(jnp.asarray(_bo(bins, B)), jnp.asarray(y),
                       jnp.asarray(w), C, tn=128, interpret=True)
    exp = _golden(bins, y, w, np.zeros(n, np.int32), f, B, C, C)
    np.testing.assert_array_equal(np.asarray(hist), exp)
    assert int(np.asarray(hist).sum()) == int(w.sum()) * f  # pads add 0


def test_vmaps_like_the_tree_axis():
    """The model calls the kernel under the per-tree vmap — batching
    must add a grid dimension, not corrupt the accumulator."""
    rng = np.random.default_rng(2)
    T, n, f, B, C = 3, 200, 16, 8, 2
    bins = rng.integers(0, B, (T, n, f)).astype(np.int32)
    y = rng.integers(0, C, (T, n)).astype(np.int32)
    w = rng.integers(0, 4, (T, n)).astype(np.int32)
    BO = jnp.stack([jnp.asarray(_bo(b, B)) for b in bins])
    out = jax.vmap(lambda a, r, ww: K.hist_bins(a, r, ww, C,
                                                interpret=True))(
        BO, jnp.asarray(y), jnp.asarray(w))
    for t in range(T):
        np.testing.assert_array_equal(
            np.asarray(out[t]),
            _golden(bins[t], y[t], w[t], np.zeros(n, np.int32), f, B, C, C))


def test_grow_level_pallas_bit_identical_to_dense(mesh):
    """The hist_algo="pallas" arm through _grow_level must pick
    bit-identical splits and routes to the dense incumbent (same int8
    products, different memory schedule), so the rf_hist_pallas flip
    gate can demand equal train_acc."""
    rng = np.random.default_rng(3)
    n, f, B, C, level = 300, 16, 8, 3, 2       # fB = 128 engages pallas
    bins = rng.integers(0, B, (n, f)).astype(np.int32)
    y = rng.integers(0, C, n).astype(np.int32)
    w = rng.poisson(1.0, n).astype(np.float32)
    node_id = rng.integers(0, 2 ** level, n).astype(np.int32)
    feat_mask = np.ones(f, np.float32)
    BO = RF.bins_onehot(jnp.asarray(bins), B)
    outs = {}
    for algo in ("dense", "pallas"):
        cfg = RF.RFConfig(n_bins=B, n_classes=C, max_depth=3,
                          hist_algo=algo)
        outs[algo] = RF._grow_level(
            BO, jnp.asarray(bins), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(node_id), level, jnp.asarray(feat_mask), cfg)
    for a, b in zip(outs["dense"], outs["pallas"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_forest_pallas_bit_identical_to_dense(mesh):
    """Whole-forest fit under the 8-worker mesh (f=16 × 32 bins → the
    smoke fB=512): identical trees, identical predictions."""
    rng = np.random.default_rng(4)
    x = rng.normal(size=(512, 16)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    preds = {}
    for algo in ("dense", "pallas"):
        m = RF.RandomForest(RF.RFConfig(n_trees=8, max_depth=3,
                                        hist_algo=algo), mesh)
        m.fit(x, y)
        preds[algo] = m.predict(x)
    np.testing.assert_array_equal(preds["pallas"], preds["dense"])
    assert (preds["dense"] == y).mean() > 0.9


def test_odd_width_falls_back_to_dense(mesh):
    """f·B not a 128 multiple must fall back to the dense arm (not
    error): f=5, B=8 → fB=40."""
    rng = np.random.default_rng(5)
    x = rng.normal(size=(256, 5)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    m = RF.RandomForest(RF.RFConfig(n_trees=8, max_depth=3, n_bins=8,
                                    hist_algo="pallas"), mesh)
    m.fit(x, y)
    assert (m.predict(x) == y).mean() > 0.8


def test_pick_tile_is_largest_fitting():
    # the presize pin: graded 64 features × 32 bins, depth 6, 2 classes
    assert K.pick_tile(200_000, 64 * 32, 64) == 2048
    assert K.pick_tile(100, 2048, 64) == 128      # capped by n_pad
    with pytest.raises(ValueError, match="VMEM budget"):
        K.pick_tile(4096, 1 << 17, 8)             # no tile fits


def test_rejects_tile_over_vmem_budget():
    n, fB, tn = 2048, 4096, 2048        # 2·2048·4096 B ≈ 16.8 MB
    with pytest.raises(ValueError, match="VMEM budget"):
        K.hist_bins(jnp.zeros((n, fB), jnp.int8), jnp.zeros(n, jnp.int32),
                    jnp.zeros(n, jnp.int32), 8, tn=tn, interpret=True)


def test_rejects_unaligned_width_for_tpu():
    with pytest.raises(ValueError, match="multiple of 128"):
        K.hist_bins(jnp.zeros((128, 96), jnp.int8),
                    jnp.zeros(128, jnp.int32), jnp.zeros(128, jnp.int32),
                    8, tn=128, interpret=False)


@pytest.mark.parametrize("n,fB,tn,nodeC", [
    (512, 512, 128, 8),       # the registry-proven shape
    (4096, 2048, 2048, 64),   # the graded presized tile (64f × 32 bins,
                              # depth-6 frame: 32 nodes × 2 classes)
])
def test_kernel_lowers_for_tpu(n, fB, tn, nodeC):
    """Cross-platform lowering runs the Pallas->Mosaic verification
    (int8 one-hot build, iota compare, int32 MXU accumulation) without
    hardware (HL201 idiom)."""
    import functools

    f = functools.partial(K.hist_bins, n_node_classes=nodeC, tn=tn,
                          interpret=False)
    lowered = jax.jit(f).trace(
        jnp.zeros((n, fB), jnp.int8), jnp.zeros(n, jnp.int32),
        jnp.zeros(n, jnp.int32)).lower(lowering_platforms=("tpu",))
    assert "tpu_custom_call" in lowered.as_text()
