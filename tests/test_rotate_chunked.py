"""Chunked double-buffered rotation + quantized rotate/regroup wire.

The PR-2 overlap layer, pinned four ways:

1. ``rotate_pipeline(n_chunks=1)`` is bit-exact with the pre-chunking
   serial pipeline (compute-then-rotate scan, inlined here as the
   reference);
2. ``n_chunks=2`` reproduces the bespoke two-halves schedule MF-SGD/LDA
   shipped with, bit-for-bit, through an order-sensitive step function
   (the model goldens in test_mfsgd.py pin the same thing end-to-end);
3. any ``n_chunks`` covers every (worker, chunk) pair exactly once, lands
   chunks home, and agrees with ``resident_chunk_index`` — including a
   4-chunk MF-SGD epoch checked against a numpy replica of the
   generalized schedule;
4. the quantized wires round ONCE per hop with a worker-shared scale
   (ring-size-independent error — the property that makes int8 rotation
   better conditioned than int8 allreduce), and the CommLedger accounts
   them at wire width (int8 rotate bytes = ¼ of the f32 baseline).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from harp_tpu.models import lda as L
from harp_tpu.models import mfsgd as MF
from harp_tpu.parallel import collective as C
from harp_tpu.parallel.rotate import (resident_chunk_index,
                                      resident_half_index,
                                      rotate_pipeline)
from harp_tpu.utils import telemetry

N = 8  # simulated workers (conftest)


def run_spmd(mesh, fn, x, in_dim=0, out_dim=0):
    in_spec = mesh.spec(in_dim) if in_dim is not None else P()
    out_spec = mesh.spec(out_dim) if out_dim is not None else P()
    return jax.jit(mesh.shard_map(fn, in_specs=(in_spec,),
                                  out_specs=out_spec))(x)


# -- the pipeline schedule ---------------------------------------------------

def _order_sensitive_step(acc, cur, t):
    """Non-commutative in both carry and chunk: any schedule deviation
    (order, off-by-one, wrong chunk) changes the bits."""
    acc = acc * 1.0001 + cur.sum() * (t + 1).astype(jnp.float32)
    cur = cur * 1.01 + acc * 0.001
    return acc, cur


def test_n_chunks_1_bit_exact_with_serial_pipeline(mesh):
    """n_chunks=1 must be THE pre-chunking pipeline: compute on the whole
    resident slice, then rotate it — same scan, same bits."""
    slices = np.random.default_rng(0).normal(size=(N * 4, 3)).astype(
        np.float32)

    def serial(s):
        def body(state, t):
            c, cur = state
            c, cur = _order_sensitive_step(c, cur, t)
            return (c, C.rotate(cur)), None

        (c, cur), _ = lax.scan(body, (jnp.float32(0.0), s), jnp.arange(N))
        return jnp.concatenate([c[None, None].repeat(cur.shape[1], 1), cur])

    def chunked(s):
        c, cur = rotate_pipeline(_order_sensitive_step, jnp.float32(0.0), s,
                                 n_chunks=1)
        return jnp.concatenate([c[None, None].repeat(cur.shape[1], 1), cur])

    a = np.asarray(run_spmd(mesh, serial, slices))
    b = np.asarray(run_spmd(mesh, chunked, slices))
    np.testing.assert_array_equal(a, b)


def test_n_chunks_2_bit_exact_with_bespoke_two_halves(mesh):
    """The generic 2-chunk pipeline must reproduce the hand-rolled
    computing/inflight half-slice scan (the schedule mfsgd/lda shipped
    with) bit-for-bit."""
    slices = np.random.default_rng(1).normal(size=(N * 8, 3)).astype(
        np.float32)

    def bespoke(s):
        ib2 = s.shape[0] // 2
        computing, inflight = s[:ib2], s[ib2:]

        def body(carry, t):
            c, computing, inflight = carry
            received = C.rotate(inflight)
            c, computing = _order_sensitive_step(c, computing, t)
            return (c, received, computing), None

        (c, computing, inflight), _ = lax.scan(
            body, (jnp.float32(0.0), computing, inflight),
            jnp.arange(2 * N))
        out = jnp.concatenate([computing, inflight], axis=0)
        return jnp.concatenate([c[None, None].repeat(out.shape[1], 1), out])

    def chunked(s):
        c, out = rotate_pipeline(_order_sensitive_step, jnp.float32(0.0), s,
                                 n_chunks=2)
        return jnp.concatenate([c[None, None].repeat(out.shape[1], 1), out])

    a = np.asarray(run_spmd(mesh, bespoke, slices))
    b = np.asarray(run_spmd(mesh, chunked, slices))
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("nc", [2, 4, 8])
def test_chunked_coverage_and_home(mesh, nc):
    """Every worker computes on every one of the N·nc chunks exactly once
    per epoch, and every chunk ends back home (read-only step)."""
    slices = np.arange(N * 8, dtype=np.float32).reshape(N * 8, 1)

    def prog(s):
        def step(acc, cur, t):
            return acc + cur.sum(), cur

        acc, out = rotate_pipeline(step, jnp.float32(0.0), s, n_chunks=nc)
        return jnp.concatenate([acc[None, None], out], axis=0)

    out = np.asarray(run_spmd(mesh, prog, slices)).reshape(N, 9)
    total = slices.sum()
    np.testing.assert_allclose(out[:, 0], np.full(N, total))  # saw all
    np.testing.assert_array_equal(out[:, 1:].reshape(-1),
                                  slices.reshape(-1))  # chunks home


@pytest.mark.parametrize("nc", [2, 4])
def test_chunked_updates_travel(mesh, nc):
    """Updates made mid-rotation persist: every visitor increments the
    resident chunk, so every element ends at exactly N."""
    slices = np.zeros((N * 8, 1), np.float32)

    def prog(s):
        def step(acc, cur, t):
            return acc, cur + 1.0

        _, out = rotate_pipeline(step, jnp.float32(0.0), s, n_chunks=nc)
        return out

    out = np.asarray(run_spmd(mesh, prog, slices))
    np.testing.assert_array_equal(out, np.full((N * 8, 1), N))


@pytest.mark.parametrize("nc", [1, 2, 4])
def test_resident_chunk_index_names_the_resident_chunk(mesh, nc):
    """The index formula must agree with the pipeline's actual data
    movement: chunks carry their global id as payload, and the step
    asserts (via an error accumulator) that the id it sees equals
    resident_chunk_index(t, nc) at every step."""
    ids = np.repeat(np.arange(N * nc, dtype=np.float32), 8 // nc)[:, None]

    def prog(s):
        def step(err, cur, t):
            want = resident_chunk_index(t, nc).astype(jnp.float32)
            return err + jnp.abs(cur - want).sum(), cur

        err, _ = rotate_pipeline(step, jnp.float32(0.0), s, n_chunks=nc)
        return err[None, None]

    err = np.asarray(run_spmd(mesh, prog, ids))
    np.testing.assert_array_equal(err, np.zeros((N, 1)))


def test_resident_half_index_is_two_chunk_index(mesh):
    def prog(x):
        both = jnp.stack([
            jnp.stack([resident_half_index(jnp.int32(t)) for t in range(6)]),
            jnp.stack([resident_chunk_index(jnp.int32(t), 2)
                       for t in range(6)])])
        return both[None].astype(jnp.int32)

    out = np.asarray(run_spmd(mesh, prog, np.zeros((N, 1), np.float32)))
    out = out.reshape(N, 2, 6)
    np.testing.assert_array_equal(out[:, 0], out[:, 1])


def test_chunked_rejects_partial_coverage_shift(mesh):
    def prog(s):
        _, out = rotate_pipeline(lambda a, c, t: (a, c), jnp.zeros(()), s,
                                 n_chunks=2, shift=2)
        return out

    with pytest.raises(ValueError, match="shares a factor"):
        run_spmd(mesh, prog, np.zeros((N * 4, 1), np.float32))


def test_chunked_rejects_indivisible_slice(mesh):
    def prog(s):
        _, out = rotate_pipeline(lambda a, c, t: (a, c), jnp.zeros(()), s,
                                 n_chunks=3)
        return out

    with pytest.raises(ValueError, match="split into 3"):
        run_spmd(mesh, prog, np.zeros((N * 4, 1), np.float32))


def test_pipeline_rejects_unknown_wire(mesh):
    def prog(s):
        _, out = rotate_pipeline(lambda a, c, t: (a, c), jnp.zeros(()), s,
                                 n_chunks=2, wire="f16")
        return out

    with pytest.raises(ValueError, match="wire"):
        run_spmd(mesh, prog, np.zeros((N * 4, 1), np.float32))


# -- quantized rotate / regroup ---------------------------------------------

def test_rotate_quantized_bf16_lands_right_and_rounds_once(mesh):
    rng = np.random.default_rng(2)
    x = rng.normal(size=(N * 4, 16)).astype(np.float32)
    out = run_spmd(mesh, lambda v: C.rotate_quantized(v), x)
    expect = np.roll(x.reshape(N, 4, 16), 1, axis=0)
    got = np.asarray(out).reshape(N, 4, 16)
    assert got.dtype == np.float32
    # one bf16 rounding: rel error <= 2^-8
    np.testing.assert_allclose(got, expect, rtol=2 ** -8, atol=1e-7)


def test_rotate_quantized_int8_single_rounding_error(mesh):
    """Rotation never accumulates, so the int8 error is ONE rounding
    against the worker-shared scale — ≤ global_max/254 per element,
    independent of the ring size (the allreduce twin's bound is N× this)."""
    rng = np.random.default_rng(3)
    x = rng.normal(size=(N * 4, 32)).astype(np.float32)
    out = run_spmd(
        mesh, lambda v: C.rotate_quantized(v, wire_dtype=jnp.int8), x)
    expect = np.roll(x.reshape(N, 4, 32), 1, axis=0)
    tol = np.abs(x).max() / 127.0 / 2 + 1e-6
    assert np.abs(np.asarray(out).reshape(N, 4, 32) - expect).max() <= tol


def test_rotate_quantized_int8_per_leaf_scale(mesh):
    """Scales are per LEAF (one stacked pmax): a small-magnitude leaf must
    not inherit the big leaf's coarse scale."""
    rng = np.random.default_rng(4)
    tree = {"big": (1e3 * rng.normal(size=(N, 16))).astype(np.float32),
            "small": (1e-3 * rng.normal(size=(N, 16))).astype(np.float32)}
    fn = jax.jit(mesh.shard_map(
        lambda t: C.rotate_quantized(t, wire_dtype=jnp.int8),
        in_specs=(jax.tree.map(lambda _: mesh.spec(0), tree),),
        out_specs=jax.tree.map(lambda _: mesh.spec(0), tree)))
    out = fn(tree)
    for k in tree:
        expect = np.roll(tree[k].reshape(N, 1, 16), 1, axis=0).reshape(N, 16)
        tol = np.abs(tree[k]).max() / 127.0 / 2 + 1e-9
        assert np.abs(np.asarray(out[k]) - expect).max() <= tol, k


def test_rotate_quantized_int_leaves_exact(mesh):
    x = np.arange(N * 4, dtype=np.int32).reshape(N * 4, 1)
    out = run_spmd(mesh, lambda v: C.rotate_quantized(v, wire_dtype=jnp.int8),
                   x)
    expect = np.roll(x.reshape(N, 4, 1), 1, axis=0).reshape(N * 4, 1)
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_rotate_quantized_shift_and_rejects_unknown_wire(mesh):
    x = np.arange(N, dtype=np.float32)[:, None]
    out = run_spmd(mesh,
                   lambda v: C.rotate_quantized(v, shift=-1,
                                                wire_dtype=jnp.int8), x)
    np.testing.assert_allclose(np.asarray(out).reshape(N),
                               np.roll(np.arange(N), -1), atol=0.05)
    with pytest.raises(ValueError, match="wire_dtype"):
        run_spmd(mesh,
                 lambda v: C.rotate_quantized(v, wire_dtype=jnp.float16), x)


def test_regroup_quantized_matches_exact_within_scale(mesh):
    rng = np.random.default_rng(5)
    x = rng.normal(size=(N * N, 8)).astype(np.float32)
    exact = np.asarray(run_spmd(mesh, C.regroup, x))
    for wd, tol in ((jnp.bfloat16, 2 ** -8 * np.abs(x).max() + 1e-6),
                    (jnp.int8, np.abs(x).max() / 127.0 / 2 + 1e-6)):
        out = run_spmd(mesh,
                       lambda v: C.regroup_quantized(v, wire_dtype=wd), x)
        assert np.abs(np.asarray(out) - exact).max() <= tol


def test_regroup_quantized_int_leaves_exact(mesh):
    x = np.arange(N * N, dtype=np.int32).reshape(N * N, 1)
    exact = np.asarray(run_spmd(mesh, C.regroup, x))
    out = run_spmd(mesh,
                   lambda v: C.regroup_quantized(v, wire_dtype=jnp.int8), x)
    np.testing.assert_array_equal(np.asarray(out), exact)


# -- model adoption: MF-SGD / LDA at n_chunks != 2 ---------------------------

def numpy_rotation_epoch_chunks(W, H, blocks, n, nc, chunk, lr, reg):
    """Numpy replica of a scatter-algo epoch on the GENERALIZED schedule:
    at step t worker w computes chunk-slice
    ``nc*((w - t//nc - (t%nc == nc-1)) % n) + t%nc`` — reduces to
    test_mfsgd.numpy_rotation_epoch's half formula at nc=2."""
    bu, bi, bv, bm, u_bound, ibc = blocks
    ns = nc * n
    bu = bu.reshape(n, ns, -1)
    bi = bi.reshape(n, ns, -1)
    bv = bv.reshape(n, ns, -1)
    bm = bm.reshape(n, ns, -1)
    se = cnt = 0.0
    for t in range(ns):
        for w in range(n):
            r = t % nc
            s = nc * ((w - t // nc - (1 if r == nc - 1 else 0)) % n) + r
            Wv = W[w * u_bound:(w + 1) * u_bound]
            Hv = H[s * ibc:(s + 1) * ibc]
            B = bu.shape[-1]
            for lo in range(0, B, chunk):
                sl = slice(lo, lo + chunk)
                u, i, v, m = (bu[w, s, sl], bi[w, s, sl], bv[w, s, sl],
                              bm[w, s, sl])
                wu, hi = Wv[u], Hv[i]
                err = m * (v - (wu * hi).sum(-1))
                gw = err[:, None] * hi - reg * m[:, None] * wu
                gh = err[:, None] * wu - reg * m[:, None] * hi
                np.add.at(Wv, u, lr * gw)
                np.add.at(Hv, i, lr * gh)
                se += (err ** 2).sum()
                cnt += m.sum()
    return W, H, np.sqrt(se / max(cnt, 1))


def test_mfsgd_chunked4_epoch_matches_numpy_schedule(mesh):
    """End-to-end: partitioner (n_slices = 4n), bounds, pipeline and
    index formula all line up at rotate_chunks=4 — the device epoch
    equals the numpy replica of the generalized schedule."""
    rng = np.random.default_rng(7)
    n_users, n_items, nnz, rank, chunk = 64, 48, 600, 4, 16
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)

    cfg = MF.MFSGDConfig(rank=rank, chunk=chunk, lr=0.02, reg=0.01,
                         algo="scatter", rotate_chunks=4)
    model = MF.MFSGD(n_users, n_items, cfg, mesh, seed=3)
    W0 = np.asarray(model.W).copy()
    H0 = np.asarray(model.H).copy()
    model.set_ratings(u, i, v)
    rmse = model.train_epoch()

    blocks = MF.partition_ratings(u, i, v, n_users, n_items, N, chunk,
                                  n_slices=4 * N)
    Wr, Hr, rmse_ref = numpy_rotation_epoch_chunks(
        W0.copy(), H0.copy(), blocks, N, 4, chunk, cfg.lr, cfg.reg)
    np.testing.assert_allclose(np.asarray(model.W), Wr, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(model.H), Hr, rtol=2e-4, atol=2e-5)
    assert abs(rmse - rmse_ref) < 1e-3


@pytest.mark.parametrize("nc", [1, 4])
def test_mfsgd_chunked_factors_roundtrip_and_converge(mesh, nc):
    """Non-default chunk counts keep slices home across epochs (factors()
    correctness) and keep training: rmse must fall."""
    u, i, v = MF.synthetic_ratings(128, 96, 6_000, rank=4, noise=0.0, seed=2)
    cfg = MF.MFSGDConfig(rank=8, chunk=256, lr=0.05, reg=0.0,
                         algo="scatter", rotate_chunks=nc)
    model = MF.MFSGD(128, 96, cfg, mesh, seed=1)
    model.set_ratings(u, i, v)
    r1 = model.train_epoch()
    for _ in range(6):
        r_last = model.train_epoch()
    assert r_last < r1
    Wf, Hf = model.factors()
    assert Wf.shape == (128, 8) and Hf.shape == (96, 8)


def test_mfsgd_rotate_wire_close_to_exact(mesh):
    """One epoch per wire from identical state: the quantized wires may
    only perturb H/W within the per-hop rounding budget (and must
    actually engage — bit-identical output would mean the knob is dead)."""
    rng = np.random.default_rng(9)
    n_users, n_items, nnz = 64, 48, 600
    u = rng.integers(0, n_users, nnz).astype(np.int32)
    i = rng.integers(0, n_items, nnz).astype(np.int32)
    v = rng.normal(size=nnz).astype(np.float32)

    outs = {}
    for wire in ("exact", "bf16", "int8"):
        cfg = MF.MFSGDConfig(rank=4, chunk=64, lr=0.02, reg=0.01,
                             algo="scatter", rotate_wire=wire)
        model = MF.MFSGD(n_users, n_items, cfg, mesh, seed=3)
        model.set_ratings(u, i, v)
        model.train_epoch()
        outs[wire] = (np.asarray(model.W), np.asarray(model.H))
    for wire, atol in (("bf16", 0.02), ("int8", 0.05)):
        dw = np.abs(outs[wire][0] - outs["exact"][0]).max()
        dh = np.abs(outs[wire][1] - outs["exact"][1]).max()
        assert 0 < max(dw, dh) < atol, (wire, dw, dh)


@pytest.mark.parametrize("algo", ["scatter", "dense"])
def test_lda_chunked4_counts_invariant_and_likelihood(mesh, algo):
    """LDA at rotate_chunks=4: Gibbs count invariants survive the
    generalized schedule (token totals, Nk == column sums, non-negative)
    and the chain still improves the likelihood."""
    kw = ({"chunk": 64} if algo == "scatter"
          else {"d_tile": 8, "w_tile": 8, "entry_cap": 32})
    cfg = L.LDAConfig(n_topics=6, algo=algo, rotate_chunks=4, **kw)
    model = L.LDA(120, 64, cfg, mesh, seed=0)
    d_ids, w_ids = L.synthetic_corpus(120, 64, 3, 16, seed=1)
    model.set_tokens(d_ids, w_ids)
    ll0 = model.log_likelihood()
    for _ in range(4):
        model.sample_epoch()
    Ndk = model.doc_topic_table()
    Nwk = model.word_topic_table()
    Nk = np.asarray(model.Nk)
    assert Ndk.sum() == len(d_ids) and Nwk.sum() == len(d_ids)
    np.testing.assert_allclose(Nwk.sum(0), Nk)
    assert (Ndk >= 0).all() and (Nwk >= 0).all()
    assert model.log_likelihood() > ll0


def test_lda_rotate_wire_int8_chain_stays_sane(mesh):
    """int8 rotate wire on LDA: counts dequantize lossily, but the chain
    must stay a runnable sampler — finite likelihood, doc counts (carried,
    never rotated) still exact."""
    cfg = L.LDAConfig(n_topics=6, algo="dense", d_tile=8, w_tile=8,
                      entry_cap=32, rotate_wire="int8")
    model = L.LDA(120, 64, cfg, mesh, seed=0)
    d_ids, w_ids = L.synthetic_corpus(120, 64, 3, 16, seed=1)
    model.set_tokens(d_ids, w_ids)
    for _ in range(2):
        model.sample_epoch()
    assert np.isfinite(model.log_likelihood())
    # Ndk rides the carry, not the wire: token totals stay exact
    assert model.doc_topic_table().sum() == len(d_ids)


# -- telemetry: the wire-byte claims ----------------------------------------

def _mfsgd_rotate_site_bytes(mesh, **cfg_kwargs):
    """Per-trace ring-hop payload bytes of one MF-SGD epoch program.

    PR 11: the pipeline's ring hop is the ``reshard`` shim (same
    ppermute, same bytes — the verb name on the ledger changed, the
    wire accounting did not)."""
    u, i, v = MF.synthetic_ratings(64, 64, 500, seed=0)
    cfg = MF.MFSGDConfig(rank=8, algo="scatter", chunk=64, **cfg_kwargs)
    with telemetry.scope(True):
        model = MF.MFSGD(64, 64, cfg, mesh, seed=0)
        model.set_ratings(u, i, v)
        with telemetry.ledger.run("probe", steps=0):
            model._epoch_fn.lower(model.W, model.H, *model._blocks)
        probe = telemetry.ledger.summary()["probe"]
        return sum(s["payload_bytes"] for s in probe["sites"]
                   if s["verb"] == "reshard")


def test_ledger_int8_rotate_bytes_quarter_of_f32(mesh):
    """The acceptance claim, from the ledger itself: int8 rotate wire
    bytes are exactly ¼ of the f32 baseline for the same epoch."""
    exact = _mfsgd_rotate_site_bytes(mesh, rotate_wire="exact")
    int8 = _mfsgd_rotate_site_bytes(mesh, rotate_wire="int8")
    bf16 = _mfsgd_rotate_site_bytes(mesh, rotate_wire="bf16")
    assert exact > 0
    assert exact == 4 * int8
    assert exact == 2 * bf16


def test_ledger_records_per_chunk_wire_bytes(mesh):
    """Chunking shrinks what's on the wire PER HOP: the rotate site's
    per-trace payload at 4 chunks is half the 2-chunk payload (same
    slice, quarter-size in-flight chunks, one traced call either way)."""
    two = _mfsgd_rotate_site_bytes(mesh, rotate_chunks=2)
    four = _mfsgd_rotate_site_bytes(mesh, rotate_chunks=4)
    assert two > 0 and two == 2 * four


# -- Mosaic lowering: the chunked + quantized-wire pallas epochs -------------

def test_mfsgd_chunked_int8_pallas_epoch_lowers_for_tpu(mesh, monkeypatch):
    """kernel_equiv_check-style proof that the NEW rotation scaffolding
    (4-chunk queue, int8 wire quantize/ppermute/dequantize) composes with
    the Mosaic-compiled MF-SGD kernel — caught on CPU, not in a relay
    window."""
    monkeypatch.setenv("HARP_PALLAS_FORCE_MOSAIC", "1")
    cfg = MF.MFSGDConfig(rank=8, algo="pallas", u_tile=128, i_tile=128,
                         rotate_chunks=4, rotate_wire="int8")
    n, ns = 8, 4 * 8
    _, _, u_bound, ibc = MF._dense_bounds(2048, 8192, n, ns,
                                          *MF.tiles(cfg))
    NE, Cw = 4, 256
    i32, f32 = jnp.int32, jnp.float32
    shapes = [((u_bound * n, 8), f32), ((4 * ibc * n, 8), f32),
              ((n * ns, NE, Cw), i32), ((n * ns, NE, Cw), i32),
              ((n * ns, NE, Cw), f32), ((n * ns, NE), i32),
              ((n * ns, NE), i32)]
    sds = [jax.ShapeDtypeStruct(s, d, sharding=mesh.sharding(mesh.spec(0)))
           for s, d in shapes]
    fn = MF.make_multi_epoch_fn(mesh, cfg, epochs=2)
    text = fn.trace(*sds).lower(lowering_platforms=("tpu",)).as_text()
    assert "tpu_custom_call" in text  # the Mosaic kernel is in the program


def test_lda_chunked_bf16_pallas_epoch_lowers_for_tpu(mesh, monkeypatch):
    """Same proof for the LDA side's distinct path: topic-major tables
    chunked along axis 1 (chunk_axis=1) with a bf16 wire, through the
    Mosaic-compiled CGS kernel + carry_db cond."""
    monkeypatch.setenv("HARP_PALLAS_FORCE_MOSAIC", "1")
    cfg = L.LDAConfig(n_topics=8, algo="pallas", d_tile=128, w_tile=128,
                      entry_cap=64, sampler="exprace", rng_impl="rbg",
                      rotate_chunks=4, rotate_wire="bf16")
    shapes = L.epoch_arg_shapes(8, 2048, 8192, cfg, n_tokens=100_000)
    sds = [jax.ShapeDtypeStruct(
        shape, dt, sharding=(mesh.replicated() if i == 2
                             else mesh.sharding(mesh.spec(0))))
        for i, (shape, dt) in enumerate(shapes)]
    fn = L.make_multi_epoch_fn(mesh, cfg, 8192, epochs=2)
    text = fn.trace(*sds).lower(lowering_platforms=("tpu",)).as_text()
    assert "tpu_custom_call" in text
