"""Expert-parallel MoE: regroup dispatch == dense host reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from harp_tpu.ops.moe import moe_ffn, reference_moe

N = 8  # workers == experts
D, H = 8, 16


def make_weights(rng):
    return {
        "gate": rng.normal(size=(D, N)).astype(np.float32),
        "w1": rng.normal(size=(N, D, H)).astype(np.float32) * 0.5,
        "b1": rng.normal(size=(N, H)).astype(np.float32) * 0.1,
        "w2": rng.normal(size=(N, H, D)).astype(np.float32) * 0.5,
        "b2": rng.normal(size=(N, D)).astype(np.float32) * 0.1,
    }


def run_moe(mesh, weights, x, capacity):
    fn = jax.jit(mesh.shard_map(
        lambda xx, wt: moe_ffn(
            xx, wt["gate"],
            wt["w1"][0], wt["b1"][0], wt["w2"][0], wt["b2"][0],
            capacity=capacity),
        in_specs=(mesh.spec(0),
                  {"gate": P(), "w1": mesh.spec(0), "b1": mesh.spec(0),
                   "w2": mesh.spec(0), "b2": mesh.spec(0)}),
        out_specs=(mesh.spec(0), P()),
    ))
    return fn(x, weights)


@pytest.mark.parametrize("capacity", [16, 4])
def test_moe_matches_reference(mesh, capacity):
    """Large capacity: nothing dropped, exact match.  Small capacity: the
    same tokens drop (deterministic order) and survivors still match."""
    rng = np.random.default_rng(0)
    weights = make_weights(rng)
    x = rng.normal(size=(N * 16, D)).astype(np.float32)

    y, dropped = run_moe(mesh, weights, x, capacity)
    ref = reference_moe(x, weights["gate"], weights["w1"], weights["b1"],
                        weights["w2"], weights["b2"], capacity, N)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-5)
    # reference drop count from the same bucket semantics
    logits = x @ weights["gate"]
    idx = logits.argmax(-1)
    ref_dropped = 0
    for w in range(N):
        rows = idx[w * 16:(w + 1) * 16]
        for ei in range(N):
            ref_dropped += max(0, int((rows == ei).sum()) - capacity)
    assert int(dropped) == ref_dropped
    if capacity >= 16:
        assert ref_dropped == 0


def test_moe_capacity_drops_are_counted(mesh):
    """Routing everything to one expert overflows its buckets measurably."""
    rng = np.random.default_rng(1)
    weights = make_weights(rng)
    # gate forces expert 0 for every token
    weights["gate"] = np.zeros((D, N), np.float32)
    weights["gate"][:, 0] = 1.0
    x = np.abs(rng.normal(size=(N * 16, D))).astype(np.float32)  # positive dot
    capacity = 4
    y, dropped = run_moe(mesh, weights, x, capacity)
    # each of the 8 workers keeps `capacity` of its 16 tokens
    assert int(dropped) == N * (16 - capacity)
    nonzero_rows = (~(np.asarray(y) == 0).all(-1)).sum()
    assert nonzero_rows == N * capacity
