"""enwiki-1M graded-shape proofs (SURVEY.md §3.4 #3; VERDICT r2 item 3).

The graded LDA corpus is 1M docs × 1k topics (~100M tokens).  Executing
that needs TPU hours; what CAN be pinned on CPU, the way the 1B-point
KMeans program was pinned (tests/test_kmeans_stream.py), is that the
epoch programs TRACE AND LOWER at the true shapes — int16 doc-topic
table, 8-way shard — via jax.ShapeDtypeStruct (zero host memory).

``epoch_arg_shapes`` supplies the shapes; the first tests prove it
mirrors the real partitioners exactly on corpora small enough to build.
"""

import numpy as np
import pytest

import jax

from harp_tpu.models import lda as L


def _even_corpus(n_docs, vocab, tokens_per_doc):
    """Perfectly even corpus: every (worker, slice) block equally loaded,
    so the even-fill model in epoch_arg_shapes is EXACT, not approximate."""
    T = n_docs * tokens_per_doc
    d = np.repeat(np.arange(n_docs, dtype=np.int32), tokens_per_doc)
    w = (np.arange(T, dtype=np.int32)) % vocab
    return d, w


def _actual_args(model):
    return [model.Ndk, model.Nwk, model.Nk, model.z_grid,
            *model._tokens, model._keys]


def _check_shapes(model, predicted):
    actual = _actual_args(model)
    assert len(actual) == len(predicted)
    for a, (shape, dt) in zip(actual, predicted):
        assert tuple(a.shape) == tuple(shape), (a.shape, shape)
        assert np.dtype(a.dtype) == np.dtype(dt), (a.dtype, dt)


@pytest.mark.parametrize("chunk", [16, 2])
def test_shape_model_matches_partitioner_pushpull(mesh, chunk):
    n_docs, vocab, tpd = 64, 32, 4
    cfg = L.LDAConfig(n_topics=6, algo="pushpull", chunk=chunk)
    model = L.LDA(n_docs, vocab, cfg, mesh)
    model.set_tokens(*_even_corpus(n_docs, vocab, tpd))
    _check_shapes(model, L.epoch_arg_shapes(
        8, n_docs, vocab, cfg, n_tokens=n_docs * tpd))


@pytest.mark.parametrize("chunk", [16, 2])
def test_shape_model_matches_partitioner_scatter(mesh, chunk):
    # chunk=16 > bmax exercises the sublane-pad branch; chunk=2 the
    # chunk-multiple branch — both must mirror partition_ratings' B rule
    n_docs, vocab, tpd = 64, 32, 4
    cfg = L.LDAConfig(n_topics=6, algo="scatter", chunk=chunk)
    model = L.LDA(n_docs, vocab, cfg, mesh)
    model.set_tokens(*_even_corpus(n_docs, vocab, tpd))
    _check_shapes(model, L.epoch_arg_shapes(
        8, n_docs, vocab, cfg, n_tokens=n_docs * tpd))


def test_shape_model_matches_partitioner_dense(mesh):
    # entry_cap small enough that the real partitioner's entry width C
    # saturates at the cap (the regime the 1M model assumes); NE is
    # corpus-dependent, so the real partitioner's NE is passed through
    # and everything else must match
    n_docs, vocab, tpd = 64, 32, 8
    cfg = L.LDAConfig(n_topics=6, algo="dense", d_tile=4, w_tile=4,
                      entry_cap=8, ndk_dtype="int16")
    model = L.LDA(n_docs, vocab, cfg, mesh)
    model.set_tokens(*_even_corpus(n_docs, vocab, tpd))
    ne_real = model._tokens[0].shape[1]
    assert model._tokens[0].shape[2] == cfg.entry_cap  # C hit the cap
    _check_shapes(model, L.epoch_arg_shapes(
        8, n_docs, vocab, cfg, n_tokens=n_docs * tpd,
        entries_per_row=ne_real))
    # the tight-packing default is a lower bound on the real NE
    default_ne = L.epoch_arg_shapes(
        8, n_docs, vocab, cfg, n_tokens=n_docs * tpd)[4][0][1]
    assert default_ne <= ne_real


def _sds(mesh, shapes):
    return [jax.ShapeDtypeStruct(
        shape, dt, sharding=(mesh.replicated() if i == 2
                             else mesh.sharding(mesh.spec(0))))
        for i, (shape, dt) in enumerate(shapes)]


N_DOCS, VOCAB, K, N_TOK = 1_000_000, 50_000, 1000, 100_000_000


@pytest.mark.parametrize("algo", ["pushpull", "dense"])
def test_enwiki_1m_program_lowers(mesh, algo):
    """The REAL graded-shape program — 1M docs × 1k topics, 100M token
    slots, int16 Ndk, 8-way shard, 5 Gibbs sweeps in one scan — must
    trace and lower without executing (execution needs the TPU)."""
    cfg = L.LDAConfig(n_topics=K, algo=algo, ndk_dtype="int16")
    shapes = L.epoch_arg_shapes(8, N_DOCS, VOCAB, cfg, n_tokens=N_TOK)

    # the modeled layout really carries the corpus: >= 100M token slots
    if algo == "pushpull":
        slots = shapes[4][0][0]
    else:
        _, ne, c = shapes[4][0]
        slots = 16 * 8 * ne * c
    assert slots >= N_TOK

    # int16 halves the Ndk footprint: the whole 1M-doc table is 2 GB
    ndk_shape, ndk_dt = shapes[0]
    ndk_gb = np.prod(ndk_shape) * np.dtype(ndk_dt).itemsize / 1e9
    assert np.dtype(ndk_dt) == np.int16 and ndk_gb < 2.1

    fn = L.make_multi_epoch_fn(mesh, cfg, VOCAB, epochs=5)
    text = fn.lower(*_sds(mesh, shapes)).as_text()
    assert "while" in text       # the chunk/entry scans lowered
    assert "xi16" in text        # the int16 table is in the program


@pytest.mark.parametrize("carry_db", [False, True])
def test_enwiki_1m_pallas_program_lowers(mesh, monkeypatch, carry_db):
    """The fused-kernel epoch at the TRUE graded shapes, MOSAIC-compiled:
    HARP_PALLAS_FORCE_MOSAIC routes the kernel through the real Pallas→
    Mosaic lowering (not interpret), and the whole program — topic-major
    transposes, entry scan, scalar-prefetch grids, the kernel itself,
    and (round 4) the carry_db flush/load cond — lowers for TPU on this
    CPU host."""
    monkeypatch.setenv("HARP_PALLAS_FORCE_MOSAIC", "1")
    cfg = L.LDAConfig(n_topics=K, algo="pallas", ndk_dtype="int16",
                      sampler="exprace", rng_impl="rbg", carry_db=carry_db)
    shapes = L.epoch_arg_shapes(8, N_DOCS, VOCAB, cfg, n_tokens=N_TOK)
    fn = L.make_multi_epoch_fn(mesh, cfg, VOCAB, epochs=2)
    lowered = fn.trace(*_sds(mesh, shapes)).lower(
        lowering_platforms=("tpu",))
    text = lowered.as_text()
    assert "tpu_custom_call" in text  # the Mosaic kernel is in the program
    assert "xi16" in text             # on the int16 table


@pytest.mark.parametrize("exact", [True, False])
def test_hot_count_ab_shape_lowers_mosaic(mesh, monkeypatch, exact):
    """The round-5 LL A/B pair (`lda_pallas_hot` / `_approx_hot`,
    measure_all.py) runs at 20k docs x 256 vocab x 32 topics x 200
    tok/doc — avg Nwk cell ~488 > 256, where bf16 gather rounding CAN
    show.  The sprint must not discover a lowering error inside a scarce
    relay window: pin that BOTH gather variants Mosaic-compile at the
    exact sweep shape."""
    monkeypatch.setenv("HARP_PALLAS_FORCE_MOSAIC", "1")
    cfg = L.LDAConfig(n_topics=32, algo="pallas", d_tile=128, w_tile=128,
                      sampler="exprace", rng_impl="rbg",
                      pallas_exact_gathers=exact)
    shapes = L.epoch_arg_shapes(8, 20_000, 256, cfg,
                                n_tokens=20_000 * 200)
    fn = L.make_multi_epoch_fn(mesh, cfg, 256, epochs=2)
    text = fn.trace(*_sds(mesh, shapes)).lower(
        lowering_platforms=("tpu",)).as_text()
    assert "tpu_custom_call" in text
