"""Fused Pallas KMeans kernel vs the XLA partials path (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.models.kmeans import KMeansConfig, _partials_block, fit
from harp_tpu.ops import kmeans_kernel


def _blobs(n, d, k, seed=0, spread=8.0):
    """Well-separated clusters: assignment is unambiguous under bf16 scoring
    (the kernel computes distances in bf16 on the MXU, so boundary points of
    overlapping blobs may legitimately flip vs an f32 reference)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)).astype(np.float32) * spread
    assign = rng.integers(0, k, n)
    assign[:k] = np.arange(k)  # first-k init (seed=None) gets one per blob
    pts = centers[assign] + rng.normal(size=(n, d)).astype(np.float32) * 0.1
    return pts.astype(np.float32), centers


def test_kernel_matches_xla_partials():
    pts, centers = _blobs(512, 40, 7)
    c = jnp.asarray(centers)
    s1, n1, i1 = kmeans_kernel.kmeans_partials(jnp.asarray(pts), c,
                                               interpret=True)
    c2 = (c ** 2).sum(-1)
    s2, n2, i2 = _partials_block(jnp.asarray(pts), c, c2)
    np.testing.assert_array_equal(np.asarray(n1), np.asarray(n2))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                               rtol=2e-2, atol=2e-2)
    # inertia comes from the ||x||² − 2x·c + ||c||² decomposition, which
    # cancels catastrophically when cluster spread ≫ within-cluster distance;
    # under bf16 scoring the absolute error scales with Σ||x||², not with the
    # inertia itself (see kernel docstring)
    x2 = float((pts.astype(np.float64) ** 2).sum())
    assert abs(float(i1) - float(i2)) < 4e-3 * x2


def test_kernel_tie_breaks_to_lowest_index():
    # two identical centroids: every point must land on index 0, like argmin
    pts = jnp.asarray(np.random.default_rng(0).normal(size=(64, 8)),
                      jnp.float32)
    c = jnp.tile(pts[:1], (4, 1))
    _, counts, _ = kmeans_kernel.kmeans_partials(pts, c, interpret=True)
    assert counts[0] == 64 and counts[1:].sum() == 0


def test_supported_tile_sizes():
    assert kmeans_kernel.supported(1_000_000)
    assert kmeans_kernel.supported(512)
    assert not kmeans_kernel.supported(7)


def test_fit_use_pallas_matches_default(mesh):
    pts, _ = _blobs(mesh.num_workers * 64, 16, 4, seed=1)
    c1, i1 = fit(pts, k=4, iters=4, mesh=mesh, seed=None, use_pallas=True)
    c2, i2 = fit(pts, k=4, iters=4, mesh=mesh, seed=None)
    np.testing.assert_allclose(c1, c2, rtol=2e-2, atol=2e-2)
    x2 = float((pts.astype(np.float64) ** 2).sum())
    assert abs(i1 - i2) < 4e-3 * x2  # bf16 cancellation bound, see above


def test_kernel_rejects_unsupported_n():
    pts = jnp.zeros((7, 8), jnp.float32)
    c = jnp.zeros((2, 8), jnp.float32)
    with pytest.raises(ValueError, match="tile size"):
        kmeans_kernel.kmeans_partials(pts, c, interpret=True)


# ---- fused int8 kernel (round 3) --------------------------------------

def _quantized(pts):
    from harp_tpu.models.kmeans import quantize_points_int8

    q, scale = quantize_points_int8(pts)
    return jnp.asarray(q), jnp.asarray(scale)


def test_int8_kernel_matches_xla_int8_partials_exactly():
    # same requantization, exact integer matmuls on both sides → the
    # kernel must reproduce the XLA int8 path BITWISE (sums/counts) and
    # to f32-order rounding on inertia (different summation trees)
    from harp_tpu.models.kmeans import (_partials_block_int8,
                                        _quantize_centroids)

    pts, centers = _blobs(512, 40, 7)
    q, scale = _quantized(pts)
    c = jnp.asarray(centers)
    c_q, c_scale, c2 = _quantize_centroids(c, scale)
    s1, n1, best = kmeans_kernel.kmeans_partials_int8(
        q, c_q, c_scale, c2, scale, interpret=True)
    x2 = ((q.astype(jnp.float32) * scale[None, :]) ** 2).sum()
    i1 = best + x2
    s2, n2, i2 = _partials_block_int8(q, scale, c, c2)
    np.testing.assert_array_equal(np.asarray(n1), np.asarray(n2))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    np.testing.assert_allclose(float(i1), float(i2), rtol=1e-5)


def test_int8_kernel_k_not_lane_multiple():
    # k=5 pads to a full 128 MXU tile; padded rows must absorb nothing
    from harp_tpu.models.kmeans import _quantize_centroids

    pts, centers = _blobs(256, 16, 5)
    q, scale = _quantized(pts)
    c_q, c_scale, c2 = _quantize_centroids(jnp.asarray(centers), scale)
    s, n, _ = kmeans_kernel.kmeans_partials_int8(
        q, c_q, c_scale, c2, scale, interpret=True)
    assert s.shape == (5, 16) and n.shape == (5,)
    assert float(n.sum()) == 256.0


def test_int8_fit_pallas_matches_xla_int8_fit(mesh):
    # end-to-end: fit(quantize='int8', use_pallas=True) ≡ the XLA int8
    # fit — identical assignments → identical centroid chains
    pts, _ = _blobs(1024, 24, 6, seed=3)
    # use_pallas=False explicit: the int8 auto default IS the kernel
    # now, so an unset arm would compare the kernel with itself
    c_a, i_a = fit(pts, k=6, iters=5, mesh=mesh, seed=2, quantize="int8",
                   use_pallas=False)
    c_b, i_b = fit(pts, k=6, iters=5, mesh=mesh, seed=2, quantize="int8",
                   use_pallas=True)
    np.testing.assert_allclose(c_a, c_b, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(i_a, i_b, rtol=1e-4)


def test_int8_tile_chooser_respects_vmem_budget():
    # the byte model is calibrated by the measured silicon OOM
    # (tn=10000 → 16.23 MB scoped vs the 16 MB limit, 2026-08-01):
    # at the graded shape the biggest fitting divisor is 8000
    from harp_tpu.ops.kmeans_kernel import _tile_rows_int8, int8_supported
    assert _tile_rows_int8(1_000_000, 300, 128) == 8000
    # a wider d shrinks the chosen tile
    wide = _tile_rows_int8(1_000_000, 1000, 128)
    assert wide is not None and wide < 8000
    # a huge padded k can make no tile fit
    assert _tile_rows_int8(8, 300, 1 << 22) is None
    # d beyond the exact-f32-accumulation bound is unsupported regardless
    assert not int8_supported(1024, 1100, 4)
    assert int8_supported(1024, 300, 4)


def test_use_pallas_auto_per_path():
    import dataclasses

    from harp_tpu.models.kmeans import KMeansConfig, _use_pallas
    # the 2026-08-01 verdicts: auto = kernel ON for int8, OFF for f32
    assert _use_pallas(KMeansConfig(quantize="int8"))
    assert not _use_pallas(KMeansConfig())
    # explicit always wins
    assert not _use_pallas(KMeansConfig(quantize="int8", use_pallas=False))
    assert _use_pallas(KMeansConfig(use_pallas=True))
    # None stays None through replace, so auto keeps tracking the path
    cfg = KMeansConfig(quantize="int8")
    assert not _use_pallas(dataclasses.replace(cfg, quantize=None))


def test_int8_auto_falls_back_when_kernel_unsupported(mesh):
    # d=1048 exceeds the kernel's exact-accumulation bound (d <= 1040):
    # the auto default must route to the XLA int8 path, not raise
    pts = np.random.default_rng(0).normal(size=(64, 1048)).astype(np.float32)
    c, inertia = fit(pts, k=4, iters=2, mesh=mesh, seed=0, quantize="int8")
    assert np.isfinite(inertia) and c.shape == (4, 1048)
