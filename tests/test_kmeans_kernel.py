"""Fused Pallas KMeans kernel vs the XLA partials path (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.models.kmeans import KMeansConfig, _partials_block, fit
from harp_tpu.ops import kmeans_kernel


def _blobs(n, d, k, seed=0, spread=8.0):
    """Well-separated clusters: assignment is unambiguous under bf16 scoring
    (the kernel computes distances in bf16 on the MXU, so boundary points of
    overlapping blobs may legitimately flip vs an f32 reference)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)).astype(np.float32) * spread
    assign = rng.integers(0, k, n)
    assign[:k] = np.arange(k)  # first-k init (seed=None) gets one per blob
    pts = centers[assign] + rng.normal(size=(n, d)).astype(np.float32) * 0.1
    return pts.astype(np.float32), centers


def test_kernel_matches_xla_partials():
    pts, centers = _blobs(512, 40, 7)
    c = jnp.asarray(centers)
    s1, n1, i1 = kmeans_kernel.kmeans_partials(jnp.asarray(pts), c,
                                               interpret=True)
    c2 = (c ** 2).sum(-1)
    s2, n2, i2 = _partials_block(jnp.asarray(pts), c, c2)
    np.testing.assert_array_equal(np.asarray(n1), np.asarray(n2))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2),
                               rtol=2e-2, atol=2e-2)
    # inertia comes from the ||x||² − 2x·c + ||c||² decomposition, which
    # cancels catastrophically when cluster spread ≫ within-cluster distance;
    # under bf16 scoring the absolute error scales with Σ||x||², not with the
    # inertia itself (see kernel docstring)
    x2 = float((pts.astype(np.float64) ** 2).sum())
    assert abs(float(i1) - float(i2)) < 4e-3 * x2


def test_kernel_tie_breaks_to_lowest_index():
    # two identical centroids: every point must land on index 0, like argmin
    pts = jnp.asarray(np.random.default_rng(0).normal(size=(64, 8)),
                      jnp.float32)
    c = jnp.tile(pts[:1], (4, 1))
    _, counts, _ = kmeans_kernel.kmeans_partials(pts, c, interpret=True)
    assert counts[0] == 64 and counts[1:].sum() == 0


def test_supported_tile_sizes():
    assert kmeans_kernel.supported(1_000_000)
    assert kmeans_kernel.supported(512)
    assert not kmeans_kernel.supported(7)


def test_fit_use_pallas_matches_default(mesh):
    pts, _ = _blobs(mesh.num_workers * 64, 16, 4, seed=1)
    c1, i1 = fit(pts, k=4, iters=4, mesh=mesh, seed=None, use_pallas=True)
    c2, i2 = fit(pts, k=4, iters=4, mesh=mesh, seed=None)
    np.testing.assert_allclose(c1, c2, rtol=2e-2, atol=2e-2)
    x2 = float((pts.astype(np.float64) ** 2).sum())
    assert abs(i1 - i2) < 4e-3 * x2  # bf16 cancellation bound, see above


def test_kernel_rejects_unsupported_n():
    pts = jnp.zeros((7, 8), jnp.float32)
    c = jnp.zeros((2, 8), jnp.float32)
    with pytest.raises(ValueError, match="tile size"):
        kmeans_kernel.kmeans_partials(pts, c, interpret=True)
