"""Fused Pallas LDA-CGS kernel (ops/lda_kernel.py) + algo="pallas".

Interpret mode streams externally-drawn uniforms (the TPU hardware PRNG
is unavailable off-TPU), so the distributional tests exercise the exact
posterior/race math the TPU path runs — only the bit source differs.
"""

import numpy as np
import pytest

from harp_tpu.models import lda as L

N = 8


def _pallas_cfg(**kw):
    base = dict(n_topics=8, algo="pallas", d_tile=16, w_tile=16,
                entry_cap=64, alpha=0.5, beta=0.1,
                sampler="exprace", rng_impl="rbg")
    base.update(kw)
    return L.LDAConfig(**base)


def test_kernel_draws_from_posterior():
    """Direct kernel calls on a flat tile: frequencies must match
    p ∝ (ndk+α)(nwk+β)/(nk+Vβ).

    One 256-token chunk per call (all tokens score against the entry
    snapshot — no within-call drift), repeated over fresh seeds from the
    SAME initial counts; counts are large so the bf16-rounded gathers
    (module doc) shift p well under the statistical window."""
    import jax.numpy as jnp

    from harp_tpu.ops.lda_kernel import cgs_entry_update

    K, DR, WR, C = 8, 8, 8, 256
    av = np.array([1.0, 2, 3, 4, 1, 1, 1, 3]) * 10_000
    bv = np.array([4.0, 1, 2, 1, 1, 2, 1, 1]) * 10_000
    DbT = jnp.zeros((K, DR), jnp.float32).at[:, 0].set(jnp.asarray(av))
    WbT = jnp.zeros((K, WR), jnp.float32).at[:, 0].set(jnp.asarray(bv))
    nk = jnp.full((K,), 1e6)
    z = jnp.zeros(C, jnp.int32)  # current topic 0 (consistent: av[0] ≫ C)
    cd = jnp.zeros(C, jnp.int32)
    cw = jnp.zeros(C, jnp.int32)

    # remove-current: topic 0 scores (a0−1)(b0−1)/(c0−1)
    a, b, c = av.copy(), bv.copy(), np.full(K, 1e6)
    a[0] -= 1; b[0] -= 1; c[0] -= 1
    p = (a * b) / c
    p /= p.sum()

    reps = 24
    counts = np.zeros(K)
    for r in range(reps):
        _, _, z_new, dnk = cgs_entry_update(
            DbT, WbT, nk, z, cd, cw, jnp.array([3, 100 + r], jnp.int32),
            alpha=0.0, beta=0.0, vbeta=0.0, interpret=True)
        zn = np.asarray(z_new)
        counts += np.bincount(zn, minlength=K)
        # count bookkeeping: dnk ≡ assignment histogram delta, every call
        np.testing.assert_allclose(
            np.asarray(dnk),
            np.bincount(zn, minlength=K) - np.array([C] + [0] * (K - 1)))
    freq = counts / (reps * C)
    se = np.sqrt(p * (1 - p) / (reps * C)).max()
    np.testing.assert_allclose(freq, p, atol=5 * se + 0.005)


@pytest.mark.parametrize("ndk_dtype", ["float32", "int16"])
def test_pallas_chain_converges_counts_exact(mesh, ndk_dtype):
    cfg = _pallas_cfg(ndk_dtype=ndk_dtype)
    d, w = L.synthetic_corpus(n_docs=96, vocab_size=64, n_topics_true=4,
                              tokens_per_doc=50, seed=0)
    model = L.LDA(96, 64, cfg, mesh, seed=1)
    model.set_tokens(d, w)
    ll0 = model.log_likelihood()
    for _ in range(6):
        model.sample_epoch()
    assert model.log_likelihood() > ll0
    Ndk = np.asarray(model.Ndk)
    Nwk = np.asarray(model.Nwk)
    Nk = np.asarray(model.Nk)
    # the scatter side is exact: tables stay integer-valued invariants
    assert Ndk.sum() == model.n_tokens
    assert Nwk.sum() == model.n_tokens
    np.testing.assert_allclose(Nwk.sum(0), Nk)
    np.testing.assert_array_equal(Nwk, np.round(Nwk))
    assert (Ndk >= 0).all() and (Nwk >= 0).all()


def test_pallas_multi_epoch_program(mesh):
    """sample_epochs (one scanned device program) through the kernel."""
    cfg = _pallas_cfg()
    d, w = L.synthetic_corpus(n_docs=64, vocab_size=32, n_topics_true=4,
                              tokens_per_doc=40, seed=2)
    model = L.LDA(64, 32, cfg, mesh, seed=3)
    model.set_tokens(d, w)
    model.sample_epochs(3)
    Ndk = np.asarray(model.Ndk)
    assert Ndk.sum() == model.n_tokens and (Ndk >= 0).all()


def test_gather_planes_exact_above_256():
    """ADVICE r3: single-dot bf16 gathers round counts > 256; the base-256
    digit planes must reproduce the table values EXACTLY up to the f32
    integer ceiling (2 planes to 2^16, 3 planes to 2^24)."""
    import functools

    import jax.numpy as jnp
    from jax import lax

    from harp_tpu.ops.lda_kernel import _gather_planes

    # values chosen to be bf16-UNrepresentable: 257 (ties to 256),
    # 16385, 65537, 10_000_019 (prime > 2^23)
    vals = np.array([0, 1, 255, 256, 257, 16385, 65535, 65537, 10_000_019],
                    np.float64)
    K = 4
    tbl = np.tile(vals, (K, 1)).astype(np.float32)          # [K, R]
    ids = np.arange(len(vals), dtype=np.int32)              # gather all
    oh = (ids[:, None] == np.arange(len(vals))[None, :]).astype(np.float32)
    dot = functools.partial(lax.dot_general,
                            dimension_numbers=(((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)

    exact3 = np.asarray(_gather_planes(jnp.asarray(tbl),
                                       jnp.asarray(oh, jnp.bfloat16), dot, 3))
    np.testing.assert_array_equal(exact3, tbl)
    # 2 planes: exact for everything below 2^16 (the int16 doc-tile case)
    small = tbl.copy()
    small[:, vals > 65535] = 0
    exact2 = np.asarray(_gather_planes(jnp.asarray(small),
                                       jnp.asarray(oh, jnp.bfloat16), dot, 2))
    np.testing.assert_array_equal(exact2, small)
    # the single-dot path really does round 257 (this is what exact mode
    # fixes — if this ever passes, bf16 grew a mantissa and the planes
    # can be retired)
    approx = np.asarray(_gather_planes(jnp.asarray(tbl),
                                       jnp.asarray(oh, jnp.bfloat16), dot, 0))
    assert approx[0, list(vals).index(257)] != 257.0


def test_count_bounds_pick_fewer_planes_identically():
    """A static count bound lets the kernel gather with fewer digit
    planes (1 when every count ≤ 256 — the enwiki doc-length case);
    outputs must be IDENTICAL to the unbounded 2/3-plane paths when the
    bound really holds."""
    import jax.numpy as jnp

    from harp_tpu.ops.lda_kernel import _planes_for, cgs_entry_update

    assert _planes_for(256, jnp.float32) == 1
    assert _planes_for(257, jnp.float32) == 2
    assert _planes_for(2**16, jnp.float32) == 3
    assert _planes_for(None, jnp.int16) == 2
    assert _planes_for(None, jnp.float32) == 3

    K, DR, WR, C = 8, 8, 8, 256
    rng = np.random.default_rng(0)
    DbT = jnp.asarray(rng.integers(0, 200, (K, DR)).astype(np.float32))
    WbT = jnp.asarray(rng.integers(0, 200, (K, WR)).astype(np.float32))
    nk = jnp.asarray(DbT.sum(1) + 1000.0)
    z = jnp.zeros(C, jnp.int32)
    cd = jnp.asarray(rng.integers(0, DR, C).astype(np.int32))
    cw = jnp.asarray(rng.integers(0, WR, C).astype(np.int32))
    kw = dict(alpha=0.5, beta=0.1, vbeta=3.2, interpret=True)
    outs = {}
    for bounds in ((None, None), (200, 200)):
        outs[bounds] = cgs_entry_update(
            DbT, WbT, nk, z, cd, cw, jnp.array([7, 9], jnp.int32),
            ndk_count_bound=bounds[0], nwk_count_bound=bounds[1], **kw)
    for a, b in zip(outs[(None, None)], outs[(200, 200)]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# hypothesis is optional in some images: without it only this property
# test skips — a bare module-level import would fail the whole module's
# collection and take the deterministic kernel tests above down with it
try:
    from hypothesis import given, settings, strategies as st  # noqa: E402
except ImportError:  # pragma: no cover
    given = None


def _property_case(fn):
    if given is None:  # pragma: no cover
        return pytest.mark.skip(reason="hypothesis not installed")(fn)
    return settings(max_examples=25, deadline=None)(
        given(st.lists(st.integers(0, 2**24 - 1),
                       min_size=1, max_size=32))(fn))


@_property_case
def test_gather_planes_exact_for_arbitrary_f32_integers(vals):
    """Property form of the plane-exactness claim: ANY integer table the
    f32 count tables can represent (< 2^24) gathers exactly through 3
    bf16 digit planes."""
    import functools

    import jax.numpy as jnp
    from jax import lax

    from harp_tpu.ops.lda_kernel import _gather_planes

    tbl = np.asarray(vals, np.float32)[None, :]            # [1, R]
    oh = np.eye(len(vals), dtype=np.float32)               # gather all
    dot = functools.partial(lax.dot_general,
                            dimension_numbers=(((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    got = np.asarray(_gather_planes(jnp.asarray(tbl),
                                    jnp.asarray(oh, jnp.bfloat16), dot, 3))
    np.testing.assert_array_equal(got, tbl)


def test_pallas_exact_gathers_chain_quality_at_hot_counts(mesh):
    """ADVICE r3's likelihood A/B: a small vocab drives word-topic counts
    well past 256 (where bf16 gathers round), and the exact-gather pallas
    chain must track the dense chain's likelihood."""
    cfg_p = _pallas_cfg(ndk_dtype="int16")
    cfg_d = L.LDAConfig(n_topics=8, algo="dense", d_tile=16, w_tile=16,
                        entry_cap=1024, alpha=0.5, beta=0.1,
                        ndk_dtype="int16")
    d, w = L.synthetic_corpus(n_docs=64, vocab_size=16, n_topics_true=4,
                              tokens_per_doc=200, seed=5)
    lls = {}
    hot = {}
    for name, cfg in (("dense", cfg_d), ("pallas", cfg_p)):
        m = L.LDA(64, 16, cfg, mesh, seed=7)
        m.set_tokens(d, w)
        for _ in range(6):
            m.sample_epoch()
        lls[name] = m.log_likelihood()
        hot[name] = np.asarray(m.Nwk).max()
    # the corpus really reaches the rounding regime (12.8k tokens over a
    # 16-word vocab -> hot (word, topic) cells far beyond 256)
    assert hot["pallas"] > 256, hot
    # different random streams: same ballpark is the contract (the gate
    # drive_check uses); a rounding-biased sampler drifts well past this
    assert abs(lls["pallas"] - lls["dense"]) / abs(lls["dense"]) < 0.25, lls


def test_pallas_approx_gathers_still_converge(mesh):
    """The opt-out single-dot path stays a working chain (it is a sweep
    candidate, not dead code)."""
    cfg = _pallas_cfg(pallas_exact_gathers=False)
    d, w = L.synthetic_corpus(n_docs=64, vocab_size=32, n_topics_true=4,
                              tokens_per_doc=40, seed=4)
    m = L.LDA(64, 32, cfg, mesh, seed=2)
    m.set_tokens(d, w)
    ll0 = m.log_likelihood()
    for _ in range(5):
        m.sample_epoch()
    assert m.log_likelihood() > ll0
    Nwk = np.asarray(m.Nwk)
    assert Nwk.sum() == m.n_tokens  # updates stay exact even when
    np.testing.assert_array_equal(Nwk, np.round(Nwk))  # gathers round


def test_pallas_requires_fused_sampling_stack():
    # since the 2026-08-01 flip the DEFAULT stack is the kernel's own
    # (exprace + rbg), so a bare pallas config is valid...
    assert L.LDAConfig(n_topics=8, algo="pallas").sampler == "exprace"
    # ...but an EXPLICIT mismatched stack still refuses: the config must
    # never claim a sampler the kernel doesn't run
    with pytest.raises(ValueError, match="exprace"):
        L.LDAConfig(n_topics=8, algo="pallas", sampler="gumbel",
                    rng_impl="threefry")


def test_pallas_benchmark_defaults_upgrade(mesh):
    """benchmark(algo='pallas') silently upgrades the DEFAULT sampler
    knobs (an explicit gumbel request still errors)."""
    out = L.benchmark(n_docs=64, vocab_size=32, n_topics=8,
                      tokens_per_doc=8, epochs=1, mesh=mesh,
                      algo="pallas", d_tile=16, w_tile=16, entry_cap=64)
    assert out["tokens_per_sec_per_chip"] > 0
    with pytest.raises(ValueError, match="exprace"):
        L.benchmark(n_docs=64, vocab_size=32, n_topics=8,
                    tokens_per_doc=8, epochs=1, mesh=mesh,
                    algo="pallas", sampler="gumbel")


def test_kernel_vmem_gate():
    import jax.numpy as jnp

    from harp_tpu.ops.lda_kernel import cgs_entry_update

    K = 4096
    DbT = jnp.zeros((K, 512), jnp.float32)
    WbT = jnp.zeros((K, 512), jnp.float32)
    with pytest.raises(ValueError, match="VMEM"):
        cgs_entry_update(DbT, WbT, jnp.zeros(K), jnp.zeros(256, jnp.int32),
                         jnp.zeros(256, jnp.int32),
                         jnp.zeros(256, jnp.int32),
                         jnp.zeros(2, jnp.int32), alpha=0.1, beta=0.1,
                         vbeta=1.0, interpret=True)


@pytest.mark.parametrize("ndk_dtype", ["float32", "int16"])
@pytest.mark.parametrize("shape", [
    # (K, DR, WR, C) — graded enwiki tiling and the 128-tile smoke
    # shapes the driver bench compiles FIRST on real TPU
    (1000, 512, 512, 2048),
    (8, 128, 128, 256),
])
@pytest.mark.parametrize("bounds", [
    (None, None),   # dtype-based planes (2-3)
    (100, 2100),    # the bounds the sprint's graded corpora derive
                    # (doc length ≤ 256 → 1 Db plane; word freq → 2 Wb)
])
def test_kernel_lowers_for_tpu(ndk_dtype, shape, bounds):
    """Pallas->Mosaic verification at the graded tile shapes, no hardware
    (caught the uint32->f32 cast Mosaic rejects, pre-relay)."""
    import functools

    import jax
    import jax.numpy as jnp

    from harp_tpu.ops.lda_kernel import cgs_entry_update

    K, DR, WR, C = shape
    f = functools.partial(cgs_entry_update, alpha=0.1, beta=0.01,
                          vbeta=500.0, interpret=False,
                          ndk_count_bound=bounds[0],
                          nwk_count_bound=bounds[1])
    lowered = jax.jit(f).trace(
        jnp.zeros((K, DR), jnp.dtype(ndk_dtype)), jnp.zeros((K, WR)),
        jnp.zeros((K,)), jnp.zeros(C, jnp.int32), jnp.zeros(C, jnp.int32),
        jnp.zeros(C, jnp.int32),
        jnp.zeros(2, jnp.int32)).lower(lowering_platforms=("tpu",))
    assert "tpu_custom_call" in lowered.as_text()
