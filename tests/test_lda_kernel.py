"""Fused Pallas LDA-CGS kernel (ops/lda_kernel.py) + algo="pallas".

Interpret mode streams externally-drawn uniforms (the TPU hardware PRNG
is unavailable off-TPU), so the distributional tests exercise the exact
posterior/race math the TPU path runs — only the bit source differs.
"""

import numpy as np
import pytest

from harp_tpu.models import lda as L

N = 8


def _pallas_cfg(**kw):
    base = dict(n_topics=8, algo="pallas", d_tile=16, w_tile=16,
                entry_cap=64, alpha=0.5, beta=0.1,
                sampler="exprace", rng_impl="rbg")
    base.update(kw)
    return L.LDAConfig(**base)


def test_kernel_draws_from_posterior():
    """Direct kernel calls on a flat tile: frequencies must match
    p ∝ (ndk+α)(nwk+β)/(nk+Vβ).

    One 256-token chunk per call (all tokens score against the entry
    snapshot — no within-call drift), repeated over fresh seeds from the
    SAME initial counts; counts are large so the bf16-rounded gathers
    (module doc) shift p well under the statistical window."""
    import jax.numpy as jnp

    from harp_tpu.ops.lda_kernel import cgs_entry_update

    K, DR, WR, C = 8, 8, 8, 256
    av = np.array([1.0, 2, 3, 4, 1, 1, 1, 3]) * 10_000
    bv = np.array([4.0, 1, 2, 1, 1, 2, 1, 1]) * 10_000
    DbT = jnp.zeros((K, DR), jnp.float32).at[:, 0].set(jnp.asarray(av))
    WbT = jnp.zeros((K, WR), jnp.float32).at[:, 0].set(jnp.asarray(bv))
    nk = jnp.full((K,), 1e6)
    z = jnp.zeros(C, jnp.int32)  # current topic 0 (consistent: av[0] ≫ C)
    cd = jnp.zeros(C, jnp.int32)
    cw = jnp.zeros(C, jnp.int32)

    # remove-current: topic 0 scores (a0−1)(b0−1)/(c0−1)
    a, b, c = av.copy(), bv.copy(), np.full(K, 1e6)
    a[0] -= 1; b[0] -= 1; c[0] -= 1
    p = (a * b) / c
    p /= p.sum()

    reps = 24
    counts = np.zeros(K)
    for r in range(reps):
        _, _, z_new, dnk = cgs_entry_update(
            DbT, WbT, nk, z, cd, cw, jnp.array([3, 100 + r], jnp.int32),
            alpha=0.0, beta=0.0, vbeta=0.0, interpret=True)
        zn = np.asarray(z_new)
        counts += np.bincount(zn, minlength=K)
        # count bookkeeping: dnk ≡ assignment histogram delta, every call
        np.testing.assert_allclose(
            np.asarray(dnk),
            np.bincount(zn, minlength=K) - np.array([C] + [0] * (K - 1)))
    freq = counts / (reps * C)
    se = np.sqrt(p * (1 - p) / (reps * C)).max()
    np.testing.assert_allclose(freq, p, atol=5 * se + 0.005)


@pytest.mark.parametrize("ndk_dtype", ["float32", "int16"])
def test_pallas_chain_converges_counts_exact(mesh, ndk_dtype):
    cfg = _pallas_cfg(ndk_dtype=ndk_dtype)
    d, w = L.synthetic_corpus(n_docs=96, vocab_size=64, n_topics_true=4,
                              tokens_per_doc=50, seed=0)
    model = L.LDA(96, 64, cfg, mesh, seed=1)
    model.set_tokens(d, w)
    ll0 = model.log_likelihood()
    for _ in range(6):
        model.sample_epoch()
    assert model.log_likelihood() > ll0
    Ndk = np.asarray(model.Ndk)
    Nwk = np.asarray(model.Nwk)
    Nk = np.asarray(model.Nk)
    # the scatter side is exact: tables stay integer-valued invariants
    assert Ndk.sum() == model.n_tokens
    assert Nwk.sum() == model.n_tokens
    np.testing.assert_allclose(Nwk.sum(0), Nk)
    np.testing.assert_array_equal(Nwk, np.round(Nwk))
    assert (Ndk >= 0).all() and (Nwk >= 0).all()


def test_pallas_multi_epoch_program(mesh):
    """sample_epochs (one scanned device program) through the kernel."""
    cfg = _pallas_cfg()
    d, w = L.synthetic_corpus(n_docs=64, vocab_size=32, n_topics_true=4,
                              tokens_per_doc=40, seed=2)
    model = L.LDA(64, 32, cfg, mesh, seed=3)
    model.set_tokens(d, w)
    model.sample_epochs(3)
    Ndk = np.asarray(model.Ndk)
    assert Ndk.sum() == model.n_tokens and (Ndk >= 0).all()


def test_pallas_requires_fused_sampling_stack():
    with pytest.raises(ValueError, match="exprace"):
        L.LDAConfig(n_topics=8, algo="pallas")  # default gumbel/threefry


def test_pallas_benchmark_defaults_upgrade(mesh):
    """benchmark(algo='pallas') silently upgrades the DEFAULT sampler
    knobs (an explicit gumbel request still errors)."""
    out = L.benchmark(n_docs=64, vocab_size=32, n_topics=8,
                      tokens_per_doc=8, epochs=1, mesh=mesh,
                      algo="pallas", d_tile=16, w_tile=16, entry_cap=64)
    assert out["tokens_per_sec_per_chip"] > 0
    with pytest.raises(ValueError, match="exprace"):
        L.benchmark(n_docs=64, vocab_size=32, n_topics=8,
                    tokens_per_doc=8, epochs=1, mesh=mesh,
                    algo="pallas", sampler="gumbel")


def test_kernel_vmem_gate():
    import jax.numpy as jnp

    from harp_tpu.ops.lda_kernel import cgs_entry_update

    K = 4096
    DbT = jnp.zeros((K, 512), jnp.float32)
    WbT = jnp.zeros((K, 512), jnp.float32)
    with pytest.raises(ValueError, match="VMEM"):
        cgs_entry_update(DbT, WbT, jnp.zeros(K), jnp.zeros(256, jnp.int32),
                         jnp.zeros(256, jnp.int32),
                         jnp.zeros(256, jnp.int32),
                         jnp.zeros(2, jnp.int32), alpha=0.1, beta=0.1,
                         vbeta=1.0, interpret=True)


@pytest.mark.parametrize("ndk_dtype", ["float32", "int16"])
@pytest.mark.parametrize("shape", [
    # (K, DR, WR, C) — graded enwiki tiling and the 128-tile smoke
    # shapes the driver bench compiles FIRST on real TPU
    (1000, 512, 512, 2048),
    (8, 128, 128, 256),
])
def test_kernel_lowers_for_tpu(ndk_dtype, shape):
    """Pallas->Mosaic verification at the graded tile shapes, no hardware
    (caught the uint32->f32 cast Mosaic rejects, pre-relay)."""
    import functools

    import jax
    import jax.numpy as jnp

    from harp_tpu.ops.lda_kernel import cgs_entry_update

    K, DR, WR, C = shape
    f = functools.partial(cgs_entry_update, alpha=0.1, beta=0.01,
                          vbeta=500.0, interpret=False)
    lowered = jax.jit(f).trace(
        jnp.zeros((K, DR), jnp.dtype(ndk_dtype)), jnp.zeros((K, WR)),
        jnp.zeros((K,)), jnp.zeros(C, jnp.int32), jnp.zeros(C, jnp.int32),
        jnp.zeros(C, jnp.int32),
        jnp.zeros(2, jnp.int32)).lower(lowering_platforms=("tpu",))
    assert "tpu_custom_call" in lowered.as_text()
