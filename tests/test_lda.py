"""LDA-CGS tests: count invariants, likelihood ascent, topic recovery."""

import numpy as np
import pytest

from harp_tpu.models import lda as L

N = 8


@pytest.fixture(params=["dense", "scatter", "pushpull", "pallas"])
def small_model(mesh, request):
    """Fresh model per test (all four count-update algos — dense/scatter
    rotation, the pull/push variant, and the fused kernel): shared state
    would make assertions depend on test execution order."""
    extra = ({"sampler": "exprace", "rng_impl": "rbg"}
             if request.param == "pallas" else {})
    cfg = L.LDAConfig(n_topics=8, algo=request.param, chunk=64,
                      d_tile=16, w_tile=16, entry_cap=64,
                      alpha=0.5, beta=0.1, **extra)
    d, w = L.synthetic_corpus(n_docs=96, vocab_size=64, n_topics_true=4,
                              tokens_per_doc=50, seed=0)
    model = L.LDA(96, 64, cfg, mesh, seed=1)
    model.set_tokens(d, w)
    return model, d, w


def counts_consistent(model):
    Ndk = np.asarray(model.Ndk)
    Nwk = np.asarray(model.Nwk)
    Nk = np.asarray(model.Nk)
    assert Ndk.sum() == model.n_tokens
    assert Nwk.sum() == model.n_tokens
    np.testing.assert_allclose(Nwk.sum(0), Nk)
    np.testing.assert_allclose(Ndk.sum(1).max(), 50)  # tokens per doc
    assert (Ndk >= 0).all() and (Nwk >= 0).all() and (Nk >= 0).all()


def test_initial_counts_consistent(small_model):
    counts_consistent(small_model[0])


def test_counts_invariant_after_epochs(small_model):
    model, _, _ = small_model
    for _ in range(2):
        model.sample_epoch()
    counts_consistent(model)


def test_likelihood_improves(small_model):
    model, _, _ = small_model
    ll0 = model.log_likelihood()
    for _ in range(10):
        model.sample_epoch()
    ll1 = model.log_likelihood()
    assert ll1 > ll0 + 0.1, (ll0, ll1)


def test_topic_recovery(small_model):
    """Vocab bands are disjoint per true topic: learned word-topic rows
    should become concentrated (low entropy vs uniform init)."""
    model, _, _ = small_model
    for _ in range(5):
        model.sample_epoch()
    Nwk = model.word_topic_table()
    p = (Nwk + 1e-9) / (Nwk.sum(1, keepdims=True) + 1e-6)
    ent = -(p * np.log(p + 1e-12)).sum(1).mean()
    assert ent < 0.7 * np.log(model.cfg.n_topics)


def test_sample_before_set_raises(mesh):
    model = L.LDA(16, 16, L.LDAConfig(n_topics=4, chunk=16), mesh)
    with pytest.raises(RuntimeError, match="set_tokens"):
        model.sample_epoch()


def test_resume_rejects_mismatched_checkpoint_shapes(mesh, tmp_path):
    """A checkpoint from a different algo/tile config must refuse to resume
    (same contract as MF-SGD's guard)."""
    d, w = L.synthetic_corpus(32, 24, 2, tokens_per_doc=6, seed=0)
    ckpt = str(tmp_path / "lda")
    m1 = L.LDA(32, 24, L.LDAConfig(n_topics=4, algo="scatter", chunk=16),
               mesh, seed=0)
    m1.set_tokens(d, w)
    m1.fit(2, ckpt, ckpt_every=1)

    m2 = L.LDA(32, 24, L.LDAConfig(n_topics=4, algo="dense", d_tile=8,
                                   w_tile=8, entry_cap=16), mesh, seed=0)
    m2.set_tokens(d, w)
    with pytest.raises(ValueError, match="checkpoint shapes"):
        m2.fit(2, ckpt, ckpt_every=1)


def test_sample_epochs_matches_convergence_contract(small_model):
    """Multi-epoch single-dispatch sampling keeps the count invariants and
    improves likelihood like per-epoch dispatches."""
    model, _, _ = small_model
    ll0 = model.log_likelihood()
    model.sample_epochs(6)
    counts_consistent(model)
    assert model.log_likelihood() > ll0


def test_pushpull_word_table_never_materialized_contract(mesh):
    """The pushpull variant's word-topic table is row-sharded and exchanged
    only through the sparse pull/push verbs — counts stay exact integers
    and the chain converges, matching the rotation algos' invariants."""
    d, w = L.synthetic_corpus(n_docs=96, vocab_size=64, n_topics_true=4,
                              tokens_per_doc=50, seed=0)
    model = L.LDA(96, 64, L.LDAConfig(n_topics=8, algo="pushpull", chunk=64,
                                      alpha=0.5, beta=0.1), mesh, seed=1)
    model.set_tokens(d, w)
    ll0 = model.log_likelihood()
    for _ in range(6):
        model.sample_epoch()
    counts_consistent(model)
    Nwk = model.word_topic_table()
    assert np.all(Nwk == np.round(Nwk))  # pull/push kept counts integral
    assert model.log_likelihood() > ll0 + 0.2


def test_pushpull_small_pull_cap_still_valid_chain(mesh):
    """A pull_cap below the worst-case demand drops tokens (they keep
    their topic that sweep — still a valid Gibbs chain): count invariants
    must hold exactly and likelihood must still ascend."""
    d, w = L.synthetic_corpus(n_docs=64, vocab_size=32, n_topics_true=2,
                              tokens_per_doc=32, seed=1)
    model = L.LDA(64, 32, L.LDAConfig(n_topics=4, algo="pushpull", chunk=64,
                                      pull_cap=16), mesh, seed=1)
    model.set_tokens(d, w)
    ll0 = model.log_likelihood()
    for _ in range(8):
        model.sample_epoch()
    Ndk = np.asarray(model.Ndk)
    Nwk = np.asarray(model.Nwk)
    assert Ndk.sum() == model.n_tokens and Nwk.sum() == model.n_tokens
    np.testing.assert_allclose(Nwk.sum(0), np.asarray(model.Nk))
    assert model.log_likelihood() > ll0
    assert model.last_dropped >= 0  # surfaced, not swallowed


def test_pushpull_drop_counter_surfaces_capacity_pressure(mesh):
    """All tokens share one word → every request targets one owner; a
    tiny pull_cap must DROP most of them and say so via last_dropped.
    (dedup_pulls=False: the raw per-token wire is the one under pressure —
    the companion dedup test shows the same corpus needs ONE slot.)"""
    n_tok_per_doc = 8
    d = np.repeat(np.arange(16, dtype=np.int32), n_tok_per_doc)
    w = np.zeros(16 * n_tok_per_doc, np.int32)  # one hot word
    model = L.LDA(16, 16, L.LDAConfig(n_topics=4, algo="pushpull",
                                      chunk=16, pull_cap=1,
                                      dedup_pulls=False), mesh, seed=0)
    model.set_tokens(d, w)
    model.sample_epoch()
    assert model.last_dropped > 0
    # dropped tokens kept their topics; counts stay exactly consistent
    assert np.asarray(model.Ndk).sum() == model.n_tokens
    np.testing.assert_allclose(np.asarray(model.Nwk).sum(0),
                               np.asarray(model.Nk))


def test_pushpull_dedup_serves_hot_word_in_one_slot(mesh):
    """The Zipf mitigation (VERDICT r2 item 5): duplicates of a hot word
    collapse to one request, so the corpus that chokes the raw wire at
    pull_cap=1 samples with ZERO drops under dedup — and the exact
    sizing helper says cap=1 suffices."""
    n_tok_per_doc = 8
    d = np.repeat(np.arange(16, dtype=np.int32), n_tok_per_doc)
    w = np.zeros(16 * n_tok_per_doc, np.int32)  # one hot word
    model = L.LDA(16, 16, L.LDAConfig(n_topics=4, algo="pushpull",
                                      chunk=16, pull_cap=1), mesh, seed=0)
    model.set_tokens(d, w)
    assert model.suggest_pull_cap() == 1
    model.sample_epoch()
    assert model.last_dropped == 0
    assert np.asarray(model.Ndk).sum() == model.n_tokens
    np.testing.assert_allclose(np.asarray(model.Nwk).sum(0),
                               np.asarray(model.Nk))


def test_pushpull_dedup_bit_identical_at_zero_drops(mesh):
    """dedup_pulls rearranges the wire, not the math: at the zero-drop
    default cap the sampled chain is BIT-IDENTICAL to the raw exchange
    (pulled rows are the same values; pushed deltas are exact ±1 integer
    sums, so summation order cannot matter)."""
    dw = L.synthetic_corpus(n_docs=96, vocab_size=64, n_topics_true=4,
                            tokens_per_doc=50, seed=0)
    tables = []
    for dedup in (True, False):
        model = L.LDA(96, 64, L.LDAConfig(n_topics=8, algo="pushpull",
                                          chunk=64, dedup_pulls=dedup),
                      mesh, seed=1)
        model.set_tokens(*dw)
        for _ in range(3):
            model.sample_epoch()
        assert model.last_dropped == 0
        tables.append((model.doc_topic_table(), model.word_topic_table()))
    np.testing.assert_array_equal(tables[0][0], tables[1][0])
    np.testing.assert_array_equal(tables[0][1], tables[1][1])


def test_pushpull_zipf_corpus_dedup_vs_raw_drops(mesh):
    """A Zipf-1.1 corpus under a tight cap: the deduped wire must drop
    strictly fewer tokens than the raw wire, and the suggest_pull_cap
    rule must deliver ZERO drops when applied."""
    rng = np.random.default_rng(0)
    n_docs, vocab, tpd = 64, 256, 32
    d = np.repeat(np.arange(n_docs, dtype=np.int32), tpd)
    w = ((rng.zipf(1.1, size=n_docs * tpd) - 1) % vocab).astype(np.int32)
    drops = {}
    for dedup in (True, False):
        model = L.LDA(n_docs, vocab,
                      L.LDAConfig(n_topics=4, algo="pushpull", chunk=64,
                                  pull_cap=8, dedup_pulls=dedup),
                      mesh, seed=1)
        model.set_tokens(d, w)
        model.sample_epoch()
        drops[dedup] = model.last_dropped
        # drops never corrupt counts
        assert np.asarray(model.Ndk).sum() == model.n_tokens
    assert drops[True] < drops[False]

    model = L.LDA(n_docs, vocab,
                  L.LDAConfig(n_topics=4, algo="pushpull", chunk=64),
                  mesh, seed=1)
    model.set_tokens(d, w)
    cap = model.suggest_pull_cap(apply=True)
    assert model.cfg.pull_cap == cap < 64  # dedup: below the chunk size
    model.sample_epoch()
    assert model.last_dropped == 0


def test_suggest_pull_cap_exact_small_case():
    """Hand-checkable sizing: nw=2 workers, T_pad=8 each, chunk=4 → two
    chunks per worker; vocab=8 → owner 0 owns words 0-3, owner 1 owns
    4-7.  Per-(chunk, owner) loads, computed by hand:
      worker0 chunk [0,0,0,1]: raw 4 → owner0, distinct {0,1} = 2
      worker0 chunk [4,4,5,6]: raw 4 → owner1, distinct {4,5,6} = 3
      worker1 chunk [3,3,3,3]: raw 4 → owner0, distinct {3} = 1
      worker1 chunk [0,1,2,3]: raw 4 → owner0, distinct {0,1,2,3} = 4
    """
    w = np.array([0, 0, 0, 1,   4, 4, 5, 6,
                  3, 3, 3, 3,   0, 1, 2, 3], np.int32)
    m = np.ones(16, np.float32)
    assert L.suggest_pull_cap(w, m, 2, 4, 8, dedup=False) == 4
    assert L.suggest_pull_cap(w, m, 2, 4, 8, dedup=True) == 4
    # masking out worker1's second chunk removes the distinct-4 load:
    # the dedup max falls to worker0-chunk1's 3; raw stays 4
    m2 = m.copy()
    m2[12:] = 0.0
    assert L.suggest_pull_cap(w, m2, 2, 4, 8, dedup=True) == 3
    assert L.suggest_pull_cap(w, m2, 2, 4, 8, dedup=False) == 4


@pytest.mark.parametrize("algo", ["dense", "scatter", "pushpull"])
def test_int16_ndk_bit_identical_to_f32(mesh, algo):
    """ndk_dtype='int16' halves the doc-topic HBM (the 1M-doc × 1k-topic
    graded config: 2 GB vs 4 GB) and must be EXACT: counts are integers
    bounded by doc length and deltas are ±1, so the sampled chain —
    same corpus, same seed — is bit-identical to f32."""
    d, w = L.synthetic_corpus(n_docs=48, vocab_size=32, n_topics_true=3,
                              tokens_per_doc=24, seed=2)
    kw = dict(n_topics=6, algo=algo, chunk=32, d_tile=8, w_tile=8,
              entry_cap=32)
    models = []
    for ndk_dtype in ("float32", "int16"):
        m = L.LDA(48, 32, L.LDAConfig(ndk_dtype=ndk_dtype, **kw),
                  mesh, seed=3)
        m.set_tokens(d, w)
        m.sample_epochs(4)
        models.append(m)
    f32m, i16m = models
    assert np.asarray(i16m.Ndk).dtype == np.int16
    np.testing.assert_array_equal(f32m.doc_topic_table(),
                                  i16m.doc_topic_table().astype(np.float32))
    np.testing.assert_array_equal(np.asarray(f32m.z_grid),
                                  np.asarray(i16m.z_grid))
    np.testing.assert_array_equal(np.asarray(f32m.Nwk), np.asarray(i16m.Nwk))


@pytest.mark.parametrize("algo", ["dense", "pallas"])
def test_carry_db_bit_identical_chain(mesh, algo):
    """carry_db=True (VERDICT r3 item 2's Db-carry) shares the tile cores
    with the slice-per-entry path, so the sampled chain — same corpus,
    same seed — must be BIT-identical: same z trajectory, same tables.
    The corpus has more docs than one d_tile so real od changes exercise
    the flush/load cond, and pad entries jump od back to 0 (the re-slice
    case the switch-ordering argument covers)."""
    extra = ({"sampler": "exprace", "rng_impl": "rbg"}
             if algo == "pallas" else {})
    d, w = L.synthetic_corpus(n_docs=96, vocab_size=48, n_topics_true=4,
                              tokens_per_doc=30, seed=6)
    kw = dict(n_topics=8, algo=algo, d_tile=16, w_tile=16, entry_cap=64,
              **extra)
    models = []
    for carry in (False, True):
        m = L.LDA(96, 48, L.LDAConfig(carry_db=carry, **kw), mesh, seed=5)
        m.set_tokens(d, w)
        m.sample_epochs(3)
        models.append(m)
    base, carry = models
    np.testing.assert_array_equal(np.asarray(base.z_grid),
                                  np.asarray(carry.z_grid))
    np.testing.assert_array_equal(np.asarray(base.Ndk),
                                  np.asarray(carry.Ndk))
    np.testing.assert_array_equal(np.asarray(base.Nwk),
                                  np.asarray(carry.Nwk))
    np.testing.assert_array_equal(np.asarray(base.Nk),
                                  np.asarray(carry.Nk))


def test_carry_db_rejects_non_tiled_algos():
    with pytest.raises(ValueError, match="carry_db"):
        L.LDAConfig(algo="scatter", carry_db=True)
    with pytest.raises(ValueError, match="carry_db"):
        L.LDAConfig(algo="pushpull", carry_db=True)


def test_pack_cache_key_shared_across_non_layout_knobs(tmp_path):
    """The prewarm script relies on sampler/rng/carry knobs NOT changing
    the pack key (one pack serves lda/lda_carry/lda_exprace/lda_fast),
    while algo and tiling MUST change it."""
    args = (1, 1000, 50_000, 1000, 100, 0)

    def path(**kw):
        cfg = L._make_cfg(1000, kw.pop("algo", "dense"), **kw)
        return L._pack_cache_path(str(tmp_path), cfg, args[0], *args[1:-1],
                                  seed=args[-1])

    base = path()
    assert path(sampler="exprace") == base
    assert path(sampler="exprace", rng_impl="rbg") == base
    assert path(carry_db=True) == base
    assert path(algo="pallas") != base
    assert path(algo="scatter") != base
    assert path(ndk_dtype="int16") != base
    assert path(entry_cap=1024) != base


def test_benchmark_pack_cache_roundtrip(mesh, tmp_path):
    """pack_cache: the second benchmark run must install the cached pack
    (one file, shared across sampler variants of the same tiling) and
    produce an identical chain; a different tiling gets its own key."""
    kw = dict(n_docs=128, vocab_size=64, n_topics=8, tokens_per_doc=8,
              epochs=1, d_tile=16, w_tile=16, entry_cap=64, mesh=mesh,
              pack_cache=str(tmp_path))
    r1 = L.benchmark(**kw)
    assert len(list(tmp_path.iterdir())) == 1
    r2 = L.benchmark(**kw)  # cache hit
    assert r1["log_likelihood"] == r2["log_likelihood"]
    # sampler variants share the pack (layout-relevant knobs only)...
    L.benchmark(sampler="exprace", **kw)
    assert len(list(tmp_path.iterdir())) == 1
    # ...a different tiling does not
    L.benchmark(**{**kw, "entry_cap": 32})
    assert len(list(tmp_path.iterdir())) == 2


def test_ndk_dtype_validation():
    with pytest.raises(ValueError, match="ndk_dtype"):
        L.LDAConfig(ndk_dtype="int8")


def test_int16_rejects_overlong_document(mesh, monkeypatch):
    # a doc longer than int16 max would WRAP counts silently; set_tokens
    # must refuse (real limit needs 33k tokens — shrink via monkeypatch
    # is impossible for np.iinfo, so build the real thing, tiny vocab)
    n_tok = np.iinfo(np.int16).max + 1
    d = np.zeros(n_tok, np.int32)
    w = np.zeros(n_tok, np.int32)
    model = L.LDA(8, 8, L.LDAConfig(n_topics=2, algo="scatter", chunk=64,
                                    ndk_dtype="int16"), mesh, seed=0)
    with pytest.raises(ValueError, match="would[\\s\\S]*wrap|wrap"):
        model.set_tokens(d, w)


def test_pushpull_rejects_dense_knobs():
    with pytest.raises(ValueError, match="pull_cap only applies"):
        L.LDAConfig(algo="dense", pull_cap=8)
    with pytest.raises(ValueError, match="dense.pallas-only"):
        L._make_cfg(4, algo="pushpull", d_tile=8)
    with pytest.raises(ValueError, match="pushpull-only"):
        L._make_cfg(4, algo="scatter", chunk=16, pull_cap=8)
    with pytest.raises(ValueError, match="pull_cap must be >= 1"):
        L.LDAConfig(algo="pushpull", pull_cap=0)


def test_exprace_sampler_draws_from_posterior():
    """The exponential race must land on topic k with probability
    p_k/Σp — same distribution as Gumbel-argmax, fewer transcendentals
    (LDAConfig.sampler).  Frequency test over many rows of a known
    posterior."""
    import jax
    import jax.numpy as jnp

    K, n = 4, 8000
    cfg = L.LDAConfig(n_topics=K, alpha=0.0, beta=0.0, sampler="exprace")
    # posterior p ∝ (ndk)(nwk)/nk with nk constant → p ∝ ndk·nwk
    ndk = jnp.broadcast_to(jnp.array([1.0, 2.0, 3.0, 4.0]), (n, K))
    nwk = jnp.broadcast_to(jnp.array([4.0, 1.0, 2.0, 1.0]), (n, K))
    nk = jnp.ones((n, K))
    z0 = jnp.zeros(n, jnp.int32)
    m = jnp.ones(n)
    z = np.asarray(L._cgs_resample(ndk, nwk, nk, z0, m,
                                   jax.random.key(7), cfg, vocab_size=0))
    p = np.array([4.0, 2.0, 6.0, 4.0])
    p /= p.sum()
    freq = np.bincount(z, minlength=K) / n
    # n=8000 → se ≈ sqrt(p(1-p)/n) ≤ 0.0056; 4σ window
    np.testing.assert_allclose(freq, p, atol=4 * 0.0056)


def test_exprace_full_chain_converges(mesh):
    """Likelihood ascent + count invariants hold on the exprace chain."""
    cfg = L.LDAConfig(n_topics=8, algo="dense", d_tile=16, w_tile=16,
                      entry_cap=64, alpha=0.5, beta=0.1, sampler="exprace")
    d, w = L.synthetic_corpus(n_docs=96, vocab_size=64, n_topics_true=4,
                              tokens_per_doc=50, seed=0)
    model = L.LDA(96, 64, cfg, mesh, seed=1)
    model.set_tokens(d, w)
    lls = [model.log_likelihood()]
    for _ in range(6):
        model.sample_epoch()
        lls.append(model.log_likelihood())
    assert lls[-1] > lls[0]
    Ndk = np.asarray(model.Ndk)
    assert Ndk.sum() == model.n_tokens and (Ndk >= 0).all()


@pytest.mark.parametrize("sampler", ["gumbel", "exprace"])
def test_rbg_rng_full_chain_converges(mesh, sampler):
    """Hardware-RNG bits (rng_impl='rbg') keep the chain valid under BOTH
    samplers: counts invariant, likelihood ascends."""
    cfg = L.LDAConfig(n_topics=8, algo="dense", d_tile=16, w_tile=16,
                      entry_cap=64, alpha=0.5, beta=0.1,
                      sampler=sampler, rng_impl="rbg")
    d, w = L.synthetic_corpus(n_docs=96, vocab_size=64, n_topics_true=4,
                              tokens_per_doc=50, seed=0)
    model = L.LDA(96, 64, cfg, mesh, seed=1)
    model.set_tokens(d, w)
    ll0 = model.log_likelihood()
    for _ in range(6):
        model.sample_epoch()
    assert model.log_likelihood() > ll0
    Ndk = np.asarray(model.Ndk)
    Nwk = np.asarray(model.Nwk)
    assert Ndk.sum() == model.n_tokens and (Ndk >= 0).all()
    assert Nwk.sum() == model.n_tokens and (Nwk >= 0).all()


def test_rng_impl_validation():
    # algo="dense" so the rng_impl whitelist itself is reached — on the
    # default (pallas since 2026-08-01) the pallas-stack check fires
    # first and would mask a deleted whitelist branch
    with pytest.raises(ValueError, match="rng_impl"):
        L.LDAConfig(n_topics=4, algo="dense", rng_impl="philox")
