"""L5 scheduler tests — schstatic/schdynamic parity (SURVEY.md §3.1)."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.schedule import DynamicScheduler, StaticScheduler, Task, device_map


def test_static_scheduler_order_and_coverage():
    sched = StaticScheduler(lambda x: x * x, n_threads=4)
    out = sched.schedule(list(range(23)))
    assert out == [x * x for x in range(23)]


def test_static_scheduler_per_task_state():
    # one task instance per thread → thread-private state, like Harp tasks
    class Counting(Task):
        def __init__(self):
            self.seen = []

        def run(self, item):
            self.seen.append(item)
            return item

    tasks = [Counting() for _ in range(3)]
    StaticScheduler(tasks).schedule(list(range(9)))
    for t, task in enumerate(tasks):
        assert task.seen == list(range(t, 9, 3))  # round-robin assignment


def test_static_scheduler_propagates_errors():
    def boom(x):
        raise ValueError("task died")

    with pytest.raises(ValueError, match="task died"):
        StaticScheduler(boom, n_threads=2).schedule([1, 2, 3])


def test_dynamic_scheduler_schedule():
    sched = DynamicScheduler(lambda x: x + 1, n_threads=4)
    out = sched.schedule(list(range(50)))
    assert out == [x + 1 for x in range(50)]


def test_dynamic_scheduler_streaming_lifecycle():
    sched = DynamicScheduler(lambda x: -x, n_threads=2)
    sched.start()
    try:
        for i in range(5):
            sched.submit(i)
        got = dict(sched.wait_output() for _ in range(5))
        assert got == {i: -i for i in range(5)}
        # queue drained; a second wave works on the same scheduler
        sched.submit(100)
        assert sched.wait_output() == (5, -100)
    finally:
        sched.stop()


def test_dynamic_scheduler_uses_multiple_threads():
    barrier = threading.Barrier(2, timeout=10)

    def rendezvous(x):
        barrier.wait()  # deadlocks unless 2 threads run concurrently
        return x

    out = DynamicScheduler(rendezvous, n_threads=2).schedule([0, 1])
    assert sorted(out) == [0, 1]


def test_dynamic_scheduler_propagates_errors():
    def boom(x):
        if x == 3:
            raise RuntimeError("worker task failed")
        return x

    with pytest.raises(RuntimeError, match="worker task failed"):
        DynamicScheduler(boom, n_threads=2).schedule(range(8))


def test_dynamic_scheduler_reusable_after_failure():
    # a failed batch must not leave stale results queued: the next
    # schedule() on the same object has to see a clean output queue
    def boom(x):
        if x == 3:
            raise RuntimeError("worker task failed")
        return x * 10

    sched = DynamicScheduler(boom, n_threads=2)
    with pytest.raises(RuntimeError, match="worker task failed"):
        sched.schedule(range(6))
    assert sched.schedule([1, 2]) == [10, 20]


def test_device_map_matches_loop():
    xs = jnp.arange(12.0).reshape(6, 2)
    out = device_map(lambda row: row.sum() * 2, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(xs).sum(1) * 2)
    out2 = device_map(lambda row: row.sum() * 2, xs, batched=False)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(xs).sum(1) * 2)
