"""Guard the driver entry points: single-chip compile check + multichip dry run.

The driver imports ``__graft_entry__`` and runs these out-of-process; this
in-suite copy catches regressions earlier.  The conftest already forces an
8-device CPU topology, which is exactly what ``dryrun_multichip`` needs.
"""

import jax

import __graft_entry__ as ge


def test_entry_compiles_and_runs():
    fn, args = ge.entry()
    out = jax.jit(fn).lower(*args).compile()(*args)
    centroids, inertia = out
    # real graded kernel shapes (k=100, d=300) on real data: the check
    # runs the production program, not a toy
    assert centroids.shape == (100, 300)
    assert inertia.shape == ()
    assert float(inertia) > 0


def test_dryrun_multichip_8(mesh):
    # mesh fixture guarantees the 8-device CPU topology is initialized
    ge.dryrun_multichip(8)
