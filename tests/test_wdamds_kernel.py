"""Fused SMACOF distance + B·X kernel (ops/wdamds_kernel.py) vs the XLA body.

The kernel promises the SAME Guttman row-block update as
`models/wdamds.py:make_smacof_fn`'s XLA ``body`` (D and ratio never
leaving VMEM is a schedule change, not a math change) — these tests pin
it against a numpy golden of that body, the live-masking contract for
padded rows/columns, the bf16 δ arm, the full model under the 8-worker
mesh, and the offline guarantees (VMEM rejection + Mosaic lowering).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from harp_tpu.models import wdamds as MDS
from harp_tpu.ops import wdamds_kernel as K

EPS = 1e-7


def _golden(delta_rows, row_mask, Xl, X, n_real, eps=EPS):
    """The XLA body's math (models/wdamds.py) in numpy, f32."""
    x2 = (Xl ** 2).sum(-1)[:, None]
    y2 = (X ** 2).sum(-1)[None, :]
    D = np.sqrt(np.maximum(x2 - 2.0 * (Xl @ X.T) + y2, 0.0))
    live = row_mask[:, None] * (np.arange(X.shape[0])[None, :]
                                < n_real).astype(np.float32)
    ratio = np.where(D > eps, delta_rows / np.maximum(D, eps), 0.0) * live
    bx = -ratio @ X + ratio.sum(1)[:, None] * Xl
    return bx / max(n_real, 1.0)


def test_fused_block_matches_numpy():
    rng = np.random.default_rng(0)
    N, n_loc, dim = 64, 24, 3           # pads rows → tn, dim → 128
    pts = rng.normal(size=(N, dim)).astype(np.float32)
    delta = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))
    X = rng.normal(size=(N, dim)).astype(np.float32)
    out = K.smacof_bx(jnp.asarray(delta[:n_loc]), jnp.ones(n_loc),
                      jnp.asarray(X[:n_loc]), jnp.asarray(X),
                      jnp.float32(N), eps=EPS, tn=8, interpret=True)
    exp = _golden(delta[:n_loc], np.ones(n_loc, np.float32),
                  X[:n_loc], X, N)
    np.testing.assert_allclose(np.asarray(out), exp, rtol=1e-4, atol=1e-5)


def test_masked_rows_and_columns_drop_out():
    """Padded rows (row_mask 0) must come out zero and padded columns
    (index ≥ n_real) must not contribute — junk in the pad coordinates
    must be invisible, exactly as in the XLA body's ``live`` mask."""
    rng = np.random.default_rng(1)
    N, n_real, n_loc, dim = 48, 41, 48, 2
    X = rng.normal(size=(N, dim)).astype(np.float32)
    X[n_real:] = 1e6                    # junk pad coordinates
    delta = np.abs(rng.normal(size=(n_loc, N))).astype(np.float32)
    rm = np.zeros(n_loc, np.float32)
    rm[:n_real] = 1.0
    out = np.asarray(K.smacof_bx(
        jnp.asarray(delta), jnp.asarray(rm), jnp.asarray(X),
        jnp.asarray(X), jnp.float32(n_real), eps=EPS, tn=8,
        interpret=True))
    exp = _golden(delta, rm, X, X, n_real)
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-5)
    assert (out[n_real:] == 0.0).all()  # masked rows exactly zero


def test_bf16_delta_arm_matches_bf16_golden():
    """The delta_dtype="bf16" composition: a bf16-staged δ promotes to
    f32 in-kernel, so the result matches the golden computed on the
    SAME bf16-rounded δ (rounding is the only difference)."""
    rng = np.random.default_rng(2)
    N, n_loc, dim = 32, 16, 3
    pts = rng.normal(size=(N, dim)).astype(np.float32)
    delta = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))[:n_loc]
    d_bf = np.asarray(jnp.asarray(delta).astype(jnp.bfloat16))
    X = rng.normal(size=(N, dim)).astype(np.float32)
    out = K.smacof_bx(jnp.asarray(d_bf), jnp.ones(n_loc),
                      jnp.asarray(X[:n_loc]), jnp.asarray(X),
                      jnp.float32(N), eps=EPS, tn=8, interpret=True)
    exp = _golden(d_bf.astype(np.float32), np.ones(n_loc, np.float32),
                  X[:n_loc], X, N)
    np.testing.assert_allclose(np.asarray(out), exp, rtol=1e-4, atol=1e-5)


def test_model_pallas_matches_xla(mesh):
    """End-to-end mds() under the 8-worker mesh at a 128-multiple n_pad
    (n=250 → n_pad=256, so pad rows AND pad columns are live in the
    masking path): same geometry recovery and matching stress."""
    rng = np.random.default_rng(3)
    n = 250
    pts = rng.normal(size=(n, 2)).astype(np.float32)
    delta = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))
    out = {}
    for algo in ("xla", "pallas"):
        cfg = MDS.MDSConfig(dim=2, iters=60, algo=algo)
        out[algo] = MDS.mds(delta, cfg, mesh, seed=0)
    Xp, sp = out["pallas"]
    Xx, sx = out["xla"]
    np.testing.assert_allclose(sp, sx, rtol=1e-3)
    demb = np.sqrt(((Xp[:, None] - Xp[None]) ** 2).sum(-1))
    rel = np.abs(demb - delta)[np.triu_indices(n, 1)].mean() / delta.mean()
    assert rel < 0.1, rel


def test_odd_n_pad_falls_back_to_xla(mesh):
    """algo="pallas" at an n_pad that is not a 128 multiple must fall
    back to the XLA body (not error): n=60 → n_pad=64 on 8 workers."""
    rng = np.random.default_rng(4)
    pts = rng.normal(size=(60, 2)).astype(np.float32)
    delta = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))
    X, stress = MDS.mds(delta, MDS.MDSConfig(dim=2, iters=30,
                                             algo="pallas"), mesh, seed=0)
    assert np.isfinite(stress) and X.shape == (60, 2)


def test_pick_tile_is_largest_fitting():
    assert K.pick_tile(512, 4096, 4) == 128       # the presize pin
    assert K.pick_tile(16, 4096, 4) == 16         # capped by n_loc
    with pytest.raises(ValueError, match="VMEM budget"):
        K.pick_tile(512, 1 << 20, 4)              # no tile fits


def test_rejects_tile_over_vmem_budget():
    N, tn = 2048, 512                   # ~21 MB working set
    with pytest.raises(ValueError, match="VMEM budget"):
        K.smacof_bx(jnp.zeros((tn, N)), jnp.ones(tn), jnp.zeros((tn, 2)),
                    jnp.zeros((N, 2)), jnp.float32(N), eps=EPS, tn=tn,
                    interpret=True)


def test_rejects_unaligned_n_for_tpu():
    with pytest.raises(ValueError, match="multiple of 128"):
        K.smacof_bx(jnp.zeros((8, 96)), jnp.ones(8), jnp.zeros((8, 2)),
                    jnp.zeros((96, 2)), jnp.float32(96), eps=EPS, tn=8,
                    interpret=False)


@pytest.mark.parametrize("N,n_loc,tn,dim,dtype", [
    (256, 32, 32, 2, jnp.float32),     # the registry-proven shape
    (4096, 512, 128, 3, jnp.float32),  # the graded presized tile
    (4096, 512, 128, 3, jnp.bfloat16),  # the delta_dtype-composed arm
])
def test_kernel_lowers_for_tpu(N, n_loc, tn, dim, dtype):
    """Cross-platform lowering runs the Pallas->Mosaic verification
    without hardware (HL201 idiom) — this caught the 0-d scalar
    arith.maximumf mix before any relay time was spent."""
    import functools

    f = functools.partial(K.smacof_bx, eps=EPS, tn=tn, interpret=False)
    lowered = jax.jit(f).trace(
        jnp.zeros((n_loc, N), dtype), jnp.zeros(n_loc),
        jnp.zeros((n_loc, dim)), jnp.zeros((N, dim)),
        jnp.float32(N)).lower(lowering_platforms=("tpu",))
    assert "tpu_custom_call" in lowered.as_text()
