"""Property-based collective tests — hypothesis over values, ops, shifts.

The deterministic tests in test_collective.py pin exact cases; these sweep
random inputs against straight-line numpy models of each verb's contract.
Shapes stay fixed so XLA compiles each (verb, static-arg) pair once.
"""

import jax
import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="property tests need hypothesis")
from hypothesis import given, settings, strategies as st
from hypothesis.extra.numpy import arrays

from harp_tpu.parallel import collective as C

N = 8
SHAPE = (N, 3, 4)  # dim 0 shards over the workers

finite_f32 = st.floats(-1e3, 1e3, allow_nan=False, allow_infinity=False,
                       width=32)
data_st = arrays(np.float32, SHAPE, elements=finite_f32)

_OPS = {
    C.Combiner.ADD: lambda a: a.sum(0),
    C.Combiner.MAX: lambda a: a.max(0),
    C.Combiner.MIN: lambda a: a.min(0),
    C.Combiner.AVG: lambda a: a.mean(0),
    C.Combiner.MULTIPLY: lambda a: a.prod(0),
}

_op_cache = {}


def _host(mesh, verb, out_dim, **kw):
    """Compile-once per (verb, static args): hypothesis replays many value
    examples and must not recompile each time."""
    key = (verb.__name__, out_dim, tuple(sorted(kw.items())))
    if key not in _op_cache:
        _op_cache[key] = C.host_op(mesh, verb, in_dim=0, out_dim=out_dim, **kw)
    return _op_cache[key]


@settings(max_examples=20, deadline=None)
@given(data=data_st, op=st.sampled_from(list(_OPS)))
def test_allreduce_matches_numpy(mesh, data, op):
    # MULTIPLY overflows easily at 8 factors of up to 1e3: tame the scale
    if op is C.Combiner.MULTIPLY:
        data = np.clip(data, -3.0, 3.0)
    out = np.asarray(_host(mesh, C.allreduce, 0, op=op)(data))
    ref = _OPS[op](data)
    # every worker must hold the same reduced value
    for w in range(N):
        np.testing.assert_allclose(out[w], ref, rtol=2e-5, atol=2e-4)


@settings(max_examples=15, deadline=None)
@given(data=data_st, shift=st.sampled_from([-9, -2, -1, 0, 1, 2, 7, 8, 17]))
def test_rotate_matches_roll(mesh, data, shift):
    out = np.asarray(_host(mesh, C.rotate, 0, shift=shift)(data))
    # shift=+1 sends to the next worker: worker w holds worker (w-shift)'s
    np.testing.assert_array_equal(out, np.roll(data, shift, axis=0))


@settings(max_examples=15, deadline=None)
@given(data=data_st)
def test_allgather_replicates_everything(mesh, data):
    out = np.asarray(_host(mesh, C.allgather, None)(data))
    np.testing.assert_array_equal(out, data)


@settings(max_examples=15, deadline=None)
@given(data=data_st, root=st.integers(0, N - 1))
def test_broadcast_takes_root_shard(mesh, data, root):
    out = np.asarray(_host(mesh, C.broadcast, 0, root=root)(data))
    for w in range(N):
        np.testing.assert_array_equal(out[w], data[root])


@settings(max_examples=15, deadline=None)
@given(data=arrays(np.float32, (N * N, 4), elements=finite_f32))
def test_push_pull_roundtrip_is_allreduce(mesh, data):
    """pull(push(x)) over worker blocks == allreduce(ADD) of the blocks."""
    pushed = _host(mesh, C.push, 0)(data)          # reduce-scatter
    out = np.asarray(_host(mesh, C.pull, None)(np.asarray(pushed)))
    blocks = data.reshape(N, N, 4)
    ref = blocks.sum(0)                       # [N, 4]
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-4)


@settings(max_examples=15, deadline=None)
@given(data=arrays(np.float32, (N * N, 4), elements=finite_f32))
def test_regroup_is_block_transpose(mesh, data):
    """Worker w's block j lands on worker j as block w (all_to_all)."""
    out = np.asarray(_host(mesh, C.regroup, 0)(data))
    blocks = data.reshape(N, N, 4)            # [src, dst, payload]
    ref = blocks.transpose(1, 0, 2).reshape(N * N, 4)
    np.testing.assert_array_equal(out, ref)


@settings(max_examples=15, deadline=None)
@given(data=data_st)
def test_allreduce_quantized_int8_error_bound(mesh, data):
    """int8 wire: |result − exact| ≤ N·scale/2 with scale = global_max/127
    (each worker rounds once; int32 accumulation adds nothing)."""
    import jax.numpy as jnp

    out = np.asarray(_host(mesh, C.allreduce_quantized, None,
                           wire_dtype=jnp.int8)(data))
    ref = data.sum(0)
    tol = N * np.abs(data).max() / 127.0 / 2 + 1e-6
    assert np.abs(out - ref).max() <= tol
