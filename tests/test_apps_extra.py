"""Tests for CCD++, SVM, WDA-MDS, and the collective micro-benchmark."""

import numpy as np
import pytest

from harp_tpu.models import ccd as CCD
from harp_tpu.models import svm as SVM
from harp_tpu.models import wdamds as MDS
from harp_tpu.models.mfsgd import synthetic_ratings


def test_ccd_converges(mesh):
    u, i, v = synthetic_ratings(128, 96, 8_000, rank=4, noise=0.01, seed=0)
    model = CCD.CCD(128, 96, CCD.CCDConfig(rank=8, reg=0.02), mesh, seed=0)
    model.set_ratings(u, i, v)
    first = model.train_epoch()
    last = None
    for _ in range(8):
        last = model.train_epoch()
    assert last < 0.6 * first, (first, last)


def test_ccd_requires_ratings(mesh):
    with pytest.raises(RuntimeError, match="set_ratings"):
        CCD.CCD(16, 16, CCD.CCDConfig(rank=4), mesh).train_epoch()


def test_svm_separable(mesh):
    rng = np.random.default_rng(0)
    d = 16
    true_w = rng.normal(size=d).astype(np.float32)
    x = rng.normal(size=(2048, d)).astype(np.float32)
    y = np.sign(x @ true_w).astype(np.float32)
    model = SVM.SVM(SVM.SVMConfig(inner_steps=150, outer_rounds=3,
                                  sv_per_worker=64), mesh)
    model.fit(x, y)
    assert model.accuracy(x, y) > 0.95


def test_svm_label_validation(mesh):
    with pytest.raises(AssertionError, match="±1"):
        SVM.SVM(mesh=mesh).fit(np.zeros((16, 4)), np.array([0, 1] * 8))


def test_mds_recovers_geometry(mesh):
    rng = np.random.default_rng(1)
    pts = rng.normal(size=(60, 2)).astype(np.float32)  # non-divisible n
    delta = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))
    X, stress = MDS.mds(delta, MDS.MDSConfig(dim=2, iters=200), mesh, seed=0)
    # embedded distances match the input dissimilarities (up to rigid motion)
    demb = np.sqrt(((X[:, None] - X[None]) ** 2).sum(-1))
    rel = np.abs(demb - delta)[np.triu_indices(60, 1)].mean() / delta.mean()
    assert rel < 0.05, rel
    assert stress >= 0


def test_mds_stress_decreases_with_iters(mesh):
    rng = np.random.default_rng(2)
    pts = rng.normal(size=(32, 3)).astype(np.float32)
    delta = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))
    _, s_short = MDS.mds(delta, MDS.MDSConfig(dim=3, iters=5), mesh, seed=0)
    _, s_long = MDS.mds(delta, MDS.MDSConfig(dim=3, iters=80), mesh, seed=0)
    assert s_long < s_short


def test_collective_bench_runs(mesh):
    from harp_tpu import benchmark as B

    out = B.bench_verb("allreduce", mesh, 64 * 1024, reps=2)
    assert out["gb_per_sec"] > 0 and out["verb"] == "allreduce"
    out = B.bench_verb("rotate", mesh, 64 * 1024, reps=2)
    assert out["sec"] > 0


def test_svm_default_config_small_data(mesh):
    """sv_per_worker larger than the local shard must not crash top_k."""
    rng = np.random.default_rng(3)
    x = rng.normal(size=(100, 8)).astype(np.float32)  # 12 rows/worker < 256
    y = np.sign(x[:, 0]).astype(np.float32)
    y[y == 0] = 1.0
    model = SVM.SVM(mesh=mesh)  # default sv_per_worker=256
    model.fit(x, y)
    assert model.accuracy(x, y) > 0.8


def test_collective_bench_all_verbs_run(mesh):
    """Every verb in the sweep table (incl. the quantized wires) runs —
    a kwargs rename in a verb would otherwise only surface on real TPU."""
    from harp_tpu import benchmark as B

    for verb in sorted(B.VERBS):
        out = B.bench_verb(verb, mesh, 64 * 1024, reps=1)
        assert out["sec"] > 0, verb
    for verb in B.SPARSE_VERBS:  # request/serve sparse row exchange
        out = B.bench_sparse(verb, mesh, 64 * 1024, reps=1)
        assert out["sec"] > 0
        assert out["table_rows"] > out["requested_rows_per_worker"]


def test_sparse_capacity_sweep_skew_contract(mesh):
    """The pull_cap sizing table (VERDICT r2 item 5): drop rates are
    monotone non-increasing in capacity, full capacity never drops, the
    even spread reaches zero drops at cap = m/nw, and dedup strictly
    beats the raw Zipf stream at every under-provisioned capacity."""
    from harp_tpu import benchmark as B

    recs = list(B.sweep_sparse_capacity(mesh, m=512, d=16, reps=1,
                                        caps=(1 / 8, 1 / 4, 1.0)))
    by = {}
    for r in recs:
        by.setdefault(r["dist"], []).append(r)
    for dist, rows in by.items():
        rates = [r["drop_rate"] for r in rows]
        assert rates == sorted(rates, reverse=True), dist
        assert rows[-1]["drop_rate"] == 0.0, dist  # cap = m never drops
    # even: zero drops from cap >= m/nw (= m/8 here)
    assert by["even"][0]["drop_rate"] == 0.0
    # skew hurts: zipf drops where even doesn't; dedup <= raw throughout
    assert by["zipf"][0]["drop_rate"] > 0.0
    for dd, zz in zip(by["zipf_dedup"], by["zipf"]):
        assert dd["drop_rate"] <= zz["drop_rate"]
        assert dd["wire_mb"] == zz["wire_mb"]  # capacity defines wire


def test_moments_large_mean_no_cancellation(mesh):
    rng = np.random.default_rng(4)
    from harp_tpu.models import stats as S
    x = (1e4 + rng.normal(size=(256, 4))).astype(np.float32)
    m = S.moments(x, mesh)
    np.testing.assert_allclose(m["variance"], x.var(0), rtol=0.05)


def test_tsqr_pads_and_validates(mesh):
    from harp_tpu.models import stats as S
    rng = np.random.default_rng(5)
    x = rng.normal(size=(250, 8)).astype(np.float32)  # non-divisible rows
    q, r = S.tsqr(x, mesh)
    np.testing.assert_allclose(q @ r, x, rtol=1e-3, atol=1e-4)
    with pytest.raises(ValueError, match="tall-skinny"):
        S.tsqr(rng.normal(size=(64, 32)).astype(np.float32), mesh)


def test_ccd_train_epochs_matches_per_epoch_protocol(mesh):
    """Multi-epoch CCD program: RMSEs keep descending, counts stay sane,
    and compile_epochs is side-effect-free."""
    from harp_tpu.models import ccd as CD
    from harp_tpu.models.mfsgd import synthetic_ratings

    u, i, v = synthetic_ratings(128, 96, 4000, rank=4, noise=0.02, seed=0)
    m = CD.CCD(128, 96, CD.CCDConfig(rank=8), mesh, seed=0)
    m.set_ratings(u, i, v)
    w_before = np.asarray(m.W).copy()
    m.compile_epochs(3)
    np.testing.assert_array_equal(np.asarray(m.W), w_before)  # no training
    r1 = m.train_epoch()
    rs = m.train_epochs(3)
    assert rs[-1] < r1 and all(np.isfinite(rs))


def test_ccd_multi_fn_cache_invalidates_on_new_ratings(mesh):
    """Reloading a dataset with a different nnz must recompile, not crash
    on the stale executable's shapes."""
    from harp_tpu.models import ccd as CD
    from harp_tpu.models.mfsgd import synthetic_ratings

    m = CD.CCD(64, 48, CD.CCDConfig(rank=4), mesh, seed=0)
    u, i, v = synthetic_ratings(64, 48, 2000, rank=2, seed=0)
    m.set_ratings(u, i, v)
    m.train_epochs(2)
    u2, i2, v2 = synthetic_ratings(64, 48, 900, rank=2, seed=1)
    m.set_ratings(u2, i2, v2)
    rs = m.train_epochs(2)  # recompiles at the new block width
    assert all(np.isfinite(rs))


def test_wdamds_weighted_matches_unweighted_with_unit_weights(mesh):
    from harp_tpu.models.wdamds import MDSConfig, mds

    rng = np.random.default_rng(0)
    pts = rng.normal(size=(48, 3)).astype(np.float32)
    delta = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))
    cfg = MDSConfig(dim=3, iters=30, cg_iters=12)
    _, s_u = mds(delta, cfg, mesh, seed=1)
    _, s_w = mds(delta, cfg, mesh, seed=1, weights=np.ones_like(delta))
    # same objective: stresses agree (CG vs closed form, loose tolerance)
    assert abs(s_w - s_u) < 0.05 * max(s_u, 1e-3) + 1e-3, (s_u, s_w)


def test_wdamds_zero_weights_ignore_corrupted_entries(mesh):
    """The point of the W: zero-weighted (corrupt) dissimilarities must not
    distort the embedding, while the unweighted solver is thrown off."""
    from harp_tpu.models.wdamds import MDSConfig, mds

    rng = np.random.default_rng(1)
    pts = rng.normal(size=(48, 3)).astype(np.float32)
    delta = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))
    corrupt = delta.copy()
    ii, jj = np.triu_indices(48, k=1)
    sel = rng.choice(len(ii), size=80, replace=False)
    corrupt[ii[sel], jj[sel]] = 50.0  # garbage entries
    corrupt[jj[sel], ii[sel]] = 50.0
    w = np.ones_like(delta)
    w[ii[sel], jj[sel]] = 0.0
    w[jj[sel], ii[sel]] = 0.0

    cfg = MDSConfig(dim=3, iters=40, cg_iters=12)
    Xw, _ = mds(corrupt, cfg, mesh, seed=1, weights=w)
    Xu, _ = mds(corrupt, cfg, mesh, seed=1)

    def true_stress(X):
        d = np.sqrt(((X[:, None] - X[None]) ** 2).sum(-1))
        return ((delta - d) ** 2)[np.triu_indices(48, k=1)].sum()

    assert true_stress(Xw) < 0.3 * true_stress(Xu), (
        true_stress(Xw), true_stress(Xu))


def test_wdamds_disconnected_weight_graph_stays_finite(mesh):
    """Zero weights can disconnect the weight graph entirely — V becomes
    block-diagonal with a per-component translation null space (bigger
    than the global-translation one centering removes).  The CG guards
    (absolute residual floor + curvature gate) must keep the solve finite
    and still recover within-component geometry."""
    from harp_tpu.models.wdamds import MDSConfig, mds

    rng = np.random.default_rng(3)
    pts = rng.normal(size=(48, 3)).astype(np.float32)
    delta = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))
    # two components: no weight crosses the 24/24 split
    w = np.zeros_like(delta)
    w[:24, :24] = 1.0
    w[24:, 24:] = 1.0
    X, stress = mds(delta, MDSConfig(dim=3, iters=60, cg_iters=12),
                    mesh, seed=1, weights=w)
    assert np.isfinite(X).all() and np.isfinite(stress)
    # within-component distances recovered (cross-component are free)
    d = np.sqrt(((X[:, None] - X[None]) ** 2).sum(-1))
    for sl in (slice(0, 24), slice(24, 48)):
        blk_err = np.abs(delta[sl, sl] - d[sl, sl])
        assert blk_err.mean() < 0.15 * delta[sl, sl].mean(), blk_err.mean()


def test_wdamds_weighted_long_run_past_convergence_stays_finite(mesh):
    """Once the outer SMACOF loop converges, every later CG solve starts
    at (f32-noise) convergence: rs0 is already noise, so the old
    relative-only freeze kept stepping and alpha = rs/~0 exploded.  A long
    run must stay finite and keep the converged embedding accurate."""
    from harp_tpu.models.wdamds import MDSConfig, mds

    rng = np.random.default_rng(4)
    pts = rng.normal(size=(32, 3)).astype(np.float32)
    delta = np.sqrt(((pts[:, None] - pts[None]) ** 2).sum(-1))
    w = np.ones_like(delta)
    X, stress = mds(delta, MDSConfig(dim=3, iters=300, cg_iters=10),
                    mesh, seed=2, weights=w)
    assert np.isfinite(X).all() and np.isfinite(stress)
    d = np.sqrt(((X[:, None] - X[None]) ** 2).sum(-1))
    rel = np.abs(delta - d)[np.triu_indices(32, 1)].mean()
    assert rel < 0.05 * delta[np.triu_indices(32, 1)].mean(), rel


def test_wdamds_weights_validation(mesh):
    from harp_tpu.models.wdamds import mds

    d = np.ones((8, 8), np.float32)
    with pytest.raises(ValueError, match="shape"):
        mds(d, mesh=mesh, weights=np.ones((4, 4), np.float32))
    with pytest.raises(ValueError, match="nonnegative"):
        mds(d, mesh=mesh, weights=-np.ones((8, 8), np.float32))
