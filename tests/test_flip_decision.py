"""The ≥10%-at-equal-quality flip gate (VERDICT r3 weak #5 / next #6).

BASELINE.md's decision rule — "a candidate that wins ≥10% at equal
quality becomes the default" — must live in code: a fast-but-degraded
kernel may never flip a default silently, and missing quality evidence
must refuse the flip (fail closed), not pass it.
"""

import importlib.util
import json
import os
import sys

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "flip_decision", os.path.join(os.path.dirname(__file__), "..",
                                  "scripts", "flip_decision.py"))
fd = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(fd)

MFSGD_SPEC = fd.CANDIDATES["mfsgd_pallas"]
LDA_SPEC = fd.CANDIDATES["lda_pallas"]
SG_SPEC = fd.CANDIDATES["subgraph_onehot"]


def test_flips_at_ten_percent_and_equal_quality():
    v = fd.decide({"updates_per_sec_per_chip": 120e6, "rmse_final": 0.366},
                  {"updates_per_sec_per_chip": 92.7e6, "rmse_final": 0.366},
                  MFSGD_SPEC)
    assert v["flip"] and v["quality_ok"]
    assert v["speedup"] == pytest.approx(120 / 92.7, rel=1e-3)
    assert "MFSGDConfig" in v["reason"]


def test_refuses_degraded_quality_regardless_of_speed():
    # 2x faster but rmse 10% worse → the gate must refuse
    v = fd.decide({"updates_per_sec_per_chip": 200e6, "rmse_final": 0.403},
                  {"updates_per_sec_per_chip": 92.7e6, "rmse_final": 0.366},
                  MFSGD_SPEC)
    assert not v["flip"] and v["quality_ok"] is False
    assert "QUALITY DEGRADED" in v["reason"]


def test_keeps_incumbent_below_threshold():
    v = fd.decide({"updates_per_sec_per_chip": 97e6, "rmse_final": 0.366},
                  {"updates_per_sec_per_chip": 92.7e6, "rmse_final": 0.366},
                  MFSGD_SPEC)
    assert not v["flip"] and v["quality_ok"]
    assert "keep incumbent" in v["reason"]


def test_fails_closed_on_missing_quality_field():
    v = fd.decide({"updates_per_sec_per_chip": 200e6},
                  {"updates_per_sec_per_chip": 92.7e6, "rmse_final": 0.366},
                  MFSGD_SPEC)
    assert not v["flip"] and v["quality_ok"] is None
    assert "fails closed" in v["reason"]


def test_fails_closed_on_missing_or_error_rows():
    good = {"updates_per_sec_per_chip": 92.7e6, "rmse_final": 0.366}
    assert not fd.decide(None, good, MFSGD_SPEC)["flip"]
    assert not fd.decide(good, {"error": "hang"}, MFSGD_SPEC)["flip"]


def test_log_likelihood_sense_handles_negative_values():
    # LL is negative; "higher" means closer to zero.  Candidate 0.02 nats
    # better → flip; 0.2 nats worse → refuse.
    inc = {"tokens_per_sec_per_chip": 6.58e6, "log_likelihood": -9.10}
    better = {"tokens_per_sec_per_chip": 8.0e6, "log_likelihood": -9.08}
    worse = {"tokens_per_sec_per_chip": 8.0e6, "log_likelihood": -9.30}
    assert fd.decide(better, inc, LDA_SPEC)["flip"]
    v = fd.decide(worse, inc, LDA_SPEC)
    assert not v["flip"] and v["quality_ok"] is False


def test_subgraph_estimates_match_within_order_drift():
    # rel_tol 1e-3 (round 5): the two formulations reorder an f32 sum
    # whose value exceeds 2^24, so ~3.7e-4 rel drift was MEASURED on
    # silicon between correct implementations (2026-08-01); a real
    # counting bug (dropped overflow edges) moves the estimate by
    # percents and must still refuse
    inc = {"vertices_per_sec": 117.3e3, "estimate": 4.37e18}
    same = {"vertices_per_sec": 150e3, "estimate": 4.37e18 * (1 + 3.7e-4)}
    diff = {"vertices_per_sec": 150e3, "estimate": 4.37e18 * 1.01}
    assert fd.decide(same, inc, SG_SPEC)["flip"]
    assert not fd.decide(diff, inc, SG_SPEC)["flip"]


def test_stream_metric_falls_back_to_end_to_end_rate():
    spec = fd.CANDIDATES["kmeans_stream_int8"]
    inc = {"iters_per_sec": 0.53, "iters_per_sec_ex_gen": 1.09,
           "inertia": 2.9e10}
    cand = {"iters_per_sec": 0.9, "iters_per_sec_ex_gen": 2.2,
            "inertia": 2.9e10}
    v = fd.decide(cand, inc, spec)
    assert v["speedup"] == pytest.approx(2.2 / 1.09, rel=1e-3)
    # ex_gen absent on both → falls back to end-to-end
    v2 = fd.decide({k: v_ for k, v_ in cand.items() if k != "iters_per_sec_ex_gen"},
                   {k: v_ for k, v_ in inc.items() if k != "iters_per_sec_ex_gen"},
                   spec)
    assert v2["speedup"] == pytest.approx(0.9 / 0.53, rel=1e-3)


def test_stream_metric_refuses_mixed_basis():
    # ADVICE r4: ex_gen on only ONE side would divide an ex-gen rate by an
    # end-to-end rate, overstating the speedup — must refuse, both ways.
    spec = fd.CANDIDATES["kmeans_stream_int8"]
    with_ex = {"iters_per_sec": 0.9, "iters_per_sec_ex_gen": 2.2,
               "inertia": 2.9e10}
    without = {"iters_per_sec": 0.53, "inertia": 2.9e10}
    for cand, inc in ((with_ex, without), (without, with_ex)):
        v = fd.decide(cand, inc, spec)
        assert not v["flip"] and v["speedup"] is None
        assert "mixed" in v["reason"]


def test_latest_rows_last_full_shape_non_error_wins(tmp_path):
    p = tmp_path / "bench.jsonl"
    p.write_text("\n".join([
        json.dumps({"config": "mfsgd", "updates_per_sec_per_chip": 1.0}),
        "{'config': 'subgraph_cli'}",  # old dict-repr tee line: skipped
        json.dumps({"config": "mfsgd", "updates_per_sec_per_chip": 2.0}),
        json.dumps({"config": "mfsgd", "smoke": True,
                    "updates_per_sec_per_chip": 99.0}),
        json.dumps({"config": "mfsgd", "error": "hang"}),
        # CPU-sim relative speeds are non-predictive of TPU (the repo's
        # own onehot 7.8x CPU inversion) — must never authorize a flip
        json.dumps({"config": "mfsgd", "backend": "cpu",
                    "updates_per_sec_per_chip": 500.0}),
    ]) + "\n")
    rows = fd.latest_rows(str(p))
    assert rows["mfsgd"]["updates_per_sec_per_chip"] == 2.0


def test_cli_exits_nonzero_when_undecidable(tmp_path, capsys):
    p = tmp_path / "bench.jsonl"
    p.write_text(json.dumps(
        {"config": "mfsgd", "updates_per_sec_per_chip": 92.7e6,
         "rmse_final": 0.366}) + "\n")
    rc = fd.main(["--bench", str(p), "--only", "mfsgd_pallas"])
    assert rc == 1  # candidate row missing → undecidable → nonzero
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    rec = json.loads(out[0])
    assert rec["flip_decision"] == "mfsgd_pallas" and not rec["flip"]


def test_cli_decides_all_candidates_when_rows_present(tmp_path, capsys):
    rows = [
        {"config": "mfsgd", "updates_per_sec_per_chip": 92.7e6,
         "rmse_final": 0.366},
        {"config": "mfsgd_pallas", "updates_per_sec_per_chip": 140e6,
         "rmse_final": 0.3661},
    ]
    p = tmp_path / "bench.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    rc = fd.main(["--bench", str(p), "--only", "mfsgd_pallas"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["flip"] and rec["quality_ok"]


def test_sprint_order_prices_scarcity():
    """VERDICT r4 weak #3: the sweep must measure every flip candidate
    BEFORE the first incumbent re-measure, and every name the gate needs
    (candidates + incumbents) must actually be in the sweep — a short
    relay window then yields verdicts, not re-confirmations."""
    spec = importlib.util.spec_from_file_location(
        "measure_all", os.path.join(os.path.dirname(__file__), "..",
                                    "scripts", "measure_all.py"))
    ma = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ma)
    order = ma.SPRINT_ORDER
    boundary = order.index(ma.FIRST_REMEASURE)
    for name, cspec in fd.CANDIDATES.items():
        assert name in order, name
        assert cspec["incumbent"] in order, cspec["incumbent"]
        assert order.index(name) < boundary, (
            f"{name} must run before the re-measure block")
    # host-bound ingest pair stays last (f16 then its int8-wire twin)
    assert order[-2:] == ["kmeans_ingest", "kmeans_ingest_int8"]


def test_joint_gate_vetoes_half_passed_knob(tmp_path, capsys):
    # the pallas_exact_gathers knob has TWO gates (default-shape speed,
    # hot-count LL); a FLIP line may only print if BOTH flip — prose in
    # the 'flips' string is not enforcement (review finding, round 5)
    rows = [
        {"config": "lda_pallas", "tokens_per_sec_per_chip": 6e6,
         "log_likelihood": -9.1},
        {"config": "lda_pallas_approx", "tokens_per_sec_per_chip": 7.5e6,
         "log_likelihood": -9.1},     # 1.25x at equal quality: flips
        {"config": "lda_pallas_hot", "tokens_per_sec_per_chip": 6e6,
         "log_likelihood": -7.0},
        {"config": "lda_pallas_approx_hot",
         "tokens_per_sec_per_chip": 7.5e6,
         "log_likelihood": -7.3},     # LL degraded: refuses
    ]
    p = tmp_path / "bench.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    fd.main(["--bench", str(p),
             "--only", "lda_pallas_approx", "lda_pallas_approx_hot"])
    out = {json.loads(ln)["flip_decision"]: json.loads(ln)
           for ln in capsys.readouterr().out.strip().splitlines()}
    assert not out["lda_pallas_approx_hot"]["flip"]
    assert not out["lda_pallas_approx"]["flip"]          # vetoed
    assert "joint gate" in out["lda_pallas_approx"]["reason"]
    # an operator grepping for the FLIP: marker must not match a veto
    assert "FLIP:" not in out["lda_pallas_approx"]["reason"]
    # both flipping → the joint gate lets them through
    rows[3]["log_likelihood"] = -7.0
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    rc = fd.main(["--bench", str(p),
                  "--only", "lda_pallas_approx", "lda_pallas_approx_hot"])
    assert rc == 0
    out = {json.loads(ln)["flip_decision"]: json.loads(ln)
           for ln in capsys.readouterr().out.strip().splitlines()}
    assert out["lda_pallas_approx"]["flip"]
    assert out["lda_pallas_approx_hot"]["flip"]


def test_subgraph_joint_gate_requires_both_scales(tmp_path, capsys):
    # overflow_algo flips only when onehot wins at BOTH the controlled
    # powerlaw shape and the graded 1M scale (round 5)
    rows = [
        {"config": "subgraph_pl", "vertices_per_sec": 100e3,
         "estimate": 1.0e12},
        {"config": "subgraph_onehot", "vertices_per_sec": 130e3,
         "estimate": 1.0e12},          # wins off-scale
        {"config": "subgraph_1m", "vertices_per_sec": 110e3,
         "estimate": 4.0e18},
        {"config": "subgraph_1m_onehot", "vertices_per_sec": 112e3,
         "estimate": 4.0e18},          # <10% at graded scale
    ]
    p = tmp_path / "bench.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    fd.main(["--bench", str(p),
             "--only", "subgraph_onehot", "subgraph_1m_onehot"])
    out = {json.loads(ln)["flip_decision"]: json.loads(ln)
           for ln in capsys.readouterr().out.strip().splitlines()}
    assert not out["subgraph_onehot"]["flip"]      # vetoed by the pair
    assert not out["subgraph_1m_onehot"]["flip"]
    assert "FLIP:" not in out["subgraph_onehot"]["reason"]


def test_joint_gate_fails_closed_under_only(tmp_path, capsys):
    # --only with ONE half of a gated pair must still evaluate the
    # partner and veto when it refuses — selection must not bypass the
    # gate (fail open, review finding round 5)
    rows = [
        {"config": "subgraph_pl", "vertices_per_sec": 100e3,
         "estimate": 1.0e12},
        {"config": "subgraph_onehot", "vertices_per_sec": 130e3,
         "estimate": 1.0e12},  # wins — but the 1M half has no rows
    ]
    p = tmp_path / "bench.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    fd.main(["--bench", str(p), "--only", "subgraph_onehot"])
    out = [json.loads(ln)
           for ln in capsys.readouterr().out.strip().splitlines()]
    assert len(out) == 1  # the partner is evaluated, not printed
    assert out[0]["flip_decision"] == "subgraph_onehot"
    assert not out[0]["flip"]
    assert "FLIP:" not in out[0]["reason"]


def test_exclusive_gate_keeps_only_the_faster(tmp_path, capsys):
    # both mfsgd candidates pass: applying both would crash
    # MFSGDConfig's own validation — only the faster prints FLIP
    rows = [
        {"config": "mfsgd", "updates_per_sec_per_chip": 92.7e6,
         "rmse_final": 0.366},
        {"config": "mfsgd_pallas", "updates_per_sec_per_chip": 150e6,
         "rmse_final": 0.366},
        {"config": "mfsgd_carry", "updates_per_sec_per_chip": 120e6,
         "rmse_final": 0.366},
    ]
    p = tmp_path / "bench.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    fd.main(["--bench", str(p), "--only", "mfsgd_pallas", "mfsgd_carry"])
    out = {json.loads(ln)["flip_decision"]: json.loads(ln)
           for ln in capsys.readouterr().out.strip().splitlines()}
    assert out["mfsgd_pallas"]["flip"]
    assert not out["mfsgd_carry"]["flip"]
    assert "exclusive" in out["mfsgd_carry"]["reason"]
    assert "FLIP:" not in out["mfsgd_carry"]["reason"]


def test_conditional_gate_binds_carry_to_its_stack(tmp_path, capsys):
    # lda_carry's evidence is the DENSE stack: if lda_pallas flips the
    # default algo, lda_carry's row no longer describes the default and
    # must not print FLIP (lda_pallas_carry's would instead)
    rows = [
        {"config": "lda", "tokens_per_sec_per_chip": 6.58e6,
         "log_likelihood": -9.1},
        {"config": "lda_pallas", "tokens_per_sec_per_chip": 9e6,
         "log_likelihood": -9.1},   # flips the algo
        {"config": "lda_carry", "tokens_per_sec_per_chip": 7.5e6,
         "log_likelihood": -9.1},   # passed, but on the dense stack
    ]
    p = tmp_path / "bench.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    fd.main(["--bench", str(p), "--only", "lda_carry"])
    out = [json.loads(ln)
           for ln in capsys.readouterr().out.strip().splitlines()]
    assert len(out) == 1 and not out[0]["flip"]
    assert "conditional" in out[0]["reason"]
    # and with lda_pallas NOT flipping, lda_carry's flip stands
    rows[1]["tokens_per_sec_per_chip"] = 6.6e6  # <10%: no algo flip
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    fd.main(["--bench", str(p), "--only", "lda_carry"])
    out = [json.loads(ln)
           for ln in capsys.readouterr().out.strip().splitlines()]
    assert out[0]["flip"], out


def test_unmeasured_gate_partner_counts_as_undecidable(tmp_path, capsys):
    # exit 1 is the "rerun the benches" signal; a veto caused by a
    # MISSING partner row must carry it even though the partner's own
    # line never prints (round 5)
    rows = [
        {"config": "subgraph_pl", "vertices_per_sec": 100e3,
         "estimate": 1.0e12},
        {"config": "subgraph_onehot", "vertices_per_sec": 130e3,
         "estimate": 1.0e12},
    ]
    p = tmp_path / "bench.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    rc = fd.main(["--bench", str(p), "--only", "subgraph_onehot"])
    capsys.readouterr()
    assert rc == 1  # the 1M partner is unmeasured -> undecidable


def test_conditional_gate_vetoes_on_unmeasured_anchor(tmp_path, capsys):
    # requires_not must NOT read an unmeasured anchor as "does not
    # flip" — carry applied on the dense stack today could be off-stack
    # evidence after the next sprint flips the algo (round 5)
    rows = [
        {"config": "lda", "tokens_per_sec_per_chip": 6.58e6,
         "log_likelihood": -9.1},
        {"config": "lda_carry", "tokens_per_sec_per_chip": 7.5e6,
         "log_likelihood": -9.1},
        # no lda_pallas row at all (e.g. the sprint --skip'd pallas)
    ]
    p = tmp_path / "bench.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    rc = fd.main(["--bench", str(p), "--only", "lda_carry"])
    out = [json.loads(ln)
           for ln in capsys.readouterr().out.strip().splitlines()]
    assert rc == 1                       # rerun-the-benches signal
    assert not out[0]["flip"]
    assert "UNMEASURED" in out[0]["reason"]
    assert "FLIP:" not in out[0]["reason"]


def test_applied_flips_match_committed_verdicts():
    """The gate's contract: an authorized FLIP line is APPLIED (defaults
    follow verdicts, same commit).  This pins the coupling so an
    accidental default revert — or a FLIP line committed unapplied —
    fails loudly.  Reads the committed FLIP_DECISIONS.jsonl (round-5
    window verdicts, 2026-08-01)."""
    path = os.path.join(os.path.dirname(__file__), "..",
                        "FLIP_DECISIONS.jsonl")
    verdicts = {}
    with open(path) as f:
        for line in f:
            r = json.loads(line)
            verdicts[r["flip_decision"]] = r["flip"]
    # the five round-5 flips (lda_fast's edit is subsumed by lda_pallas)
    assert verdicts["mfsgd_pallas"] and verdicts["lda_pallas"]
    assert verdicts["lda_pallas_carry"] and verdicts["lda_fast"]
    assert verdicts["kmeans_int8_fused"]

    from harp_tpu.models.kmeans import KMeansConfig, _use_pallas
    from harp_tpu.models.lda import LDAConfig, carry_db_resolved
    from harp_tpu.models.mfsgd import MFSGDConfig

    assert MFSGDConfig().algo == "pallas"
    lcfg = LDAConfig()
    assert (lcfg.algo, lcfg.sampler, lcfg.rng_impl) == (
        "pallas", "exprace", "rbg")
    # carry_db resolves at READ time (ADVICE r5): None stays stored, the
    # resolver applies the verdict — ON for the pallas stack
    assert carry_db_resolved(lcfg) is True
    assert _use_pallas(KMeansConfig(quantize="int8"))
    # and the VETOED arms stayed un-applied
    assert not verdicts["lda_carry"] and not verdicts["mfsgd_carry"]
    assert carry_db_resolved(LDAConfig(algo="dense")) is False
    assert MFSGDConfig().carry_w is False
    assert not _use_pallas(KMeansConfig())  # f32 arm: XLA stays
