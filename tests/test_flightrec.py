"""Flight recorder — compile/transfer telemetry + budget guards.

Three layers of evidence, all on the CPU backend with zero hardware:

1. the collectors see what actually happened (CompileWatch counts XLA
   backend compiles with span attribution; TransferLedger counts
   shard_array H2D bytes, device_sync/readback round trips, tracked
   dispatches);
2. the budget guard catches the documented CLAUDE.md relay traps — a
   per-step ``PRNGKey(int)`` re-seed trips ``compiles=1``, a per-epoch
   readback loop trips ``readbacks=1``;
3. the shipped kmeans/lda/mfsgd epoch loops PASS their pinned budgets
   (one compile per config, zero recompiles across reruns, one readback
   per run) — the dispatch-discipline contract every future perf PR
   must keep.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.utils import flightrec, prng, telemetry

needs_compile_events = pytest.mark.skipif(
    not flightrec.COMPILE_EVENTS_AVAILABLE,
    reason="this jax lacks the monitoring hook")


# ---------------------------------------------------------------------------
# collectors
# ---------------------------------------------------------------------------

@needs_compile_events
def test_compile_watch_counts_and_attributes_spans(mesh):
    with telemetry.scope():
        with telemetry.span("phase"):
            jax.jit(lambda x: x * 3.0 + 1.0)(jnp.ones(7))
        n = flightrec.compile_watch.count
        assert n >= 1
        summ = flightrec.compile_watch.summary()
        assert summ["count"] == n
        assert summ["total_s"] > 0
        assert "phase" in summ["by_span"]
        # a cached re-invocation compiles nothing
        jax.jit(lambda x: x * 3.0 + 1.0)  # new wrapper but not called
        assert flightrec.compile_watch.count == n


def test_shard_array_records_h2d_bytes(mesh):
    x = np.ones((64, 16), np.float32)
    with telemetry.scope():
        mesh.shard_array(x, 0)
        assert flightrec.transfers.h2d_bytes == x.nbytes
        assert flightrec.transfers.h2d_calls == 1
        sites = flightrec.transfers.summary()["sites"]
        assert sites[0]["op"] == "h2d"
        # the site is THIS test file, not the mesh wrapper
        assert "test_flightrec.py" in sites[0]["site"]


def test_device_sync_and_readback_count_round_trips(mesh):
    from harp_tpu.utils.timing import device_sync

    y = jnp.arange(8.0)
    with telemetry.scope():
        device_sync(y)
        out = flightrec.readback(y)
        assert flightrec.transfers.readbacks == 2
        # device_sync reads one scalar; readback() reads the whole array
        assert flightrec.transfers.d2h_bytes == 4 + y.size * 4
        assert np.array_equal(out, np.arange(8.0))


def test_track_counts_dispatches(mesh):
    f = flightrec.track(jax.jit(lambda x: x + 1), "unit.f")
    x = jnp.ones(4)
    with telemetry.scope():
        f(x)
        f(x)
        assert flightrec.transfers.dispatches == 2
        sites = flightrec.transfers.summary()["sites"]
        assert {"unit.f"} == {s["site"] for s in sites
                              if s["op"] == "dispatch"}


def test_bucket_by_destination_records_staged_bytes(mesh):
    from harp_tpu.parallel.dispatch import bucket_by_destination

    dest = jnp.array([0, 1, 0, 1], jnp.int32)
    pay = jnp.ones((4, 3), jnp.float32)
    with telemetry.scope():
        bucket_by_destination(dest, (pay,), capacity=2, n_dest=2)
        # 2 dests x 2 slots x 3 f32 = 48 B staged exchange buffer
        assert flightrec.transfers.bucket_bytes == 48


# ---------------------------------------------------------------------------
# budget guard
# ---------------------------------------------------------------------------

def test_budget_passes_within_limits(mesh):
    with telemetry.scope():
        with flightrec.budget(readbacks=2, dispatches=1) as b:
            flightrec.record_readback(4)
        assert b.spent()["readbacks"] == 1


def test_budget_raises_and_names_every_violated_counter(mesh):
    with telemetry.scope():
        with pytest.raises(flightrec.BudgetExceeded) as ei:
            with flightrec.budget(readbacks=1, h2d_bytes=10, tag="unit"):
                flightrec.record_readback(4)
                flightrec.record_readback(4)
                flightrec.record_h2d(100)
        msg = str(ei.value)
        assert "readbacks used 2 > budget 1" in msg
        assert "h2d_bytes used 100 > budget 10" in msg
        assert "[unit]" in msg


def test_budget_warn_mode_warns_instead_of_raising(mesh):
    with telemetry.scope():
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            with flightrec.budget(readbacks=0, action="warn"):
                flightrec.record_readback(4)
        assert any("readbacks used 1 > budget 0" in str(x.message)
                   for x in w)


def test_budget_is_noop_when_telemetry_disabled(mesh):
    with telemetry.scope(False):
        with flightrec.budget(readbacks=0) as b:
            from harp_tpu.utils.timing import device_sync

            device_sync(jnp.ones(2))  # would trip if armed
        assert b is None


def test_budget_propagates_body_exception_unchecked(mesh):
    with telemetry.scope():
        with pytest.raises(ValueError, match="inner"):
            with flightrec.budget(readbacks=0):
                flightrec.record_readback(4)  # would also violate
                raise ValueError("inner")


def test_mapper_budget_warns_on_violation(mesh):
    """CollectiveApp(budget=...) enforces warn-mode over map_collective."""
    from harp_tpu.mapper import CollectiveApp
    from harp_tpu.utils.timing import device_sync

    class App(CollectiveApp):
        def map_collective(self):
            y = jnp.ones(2)
            device_sync(y)
            device_sync(y)  # second round trip busts readbacks=1
            return 0

    with telemetry.scope():
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            App(mesh=mesh, budget={"readbacks": 1}).run()
        assert any("readbacks used 2 > budget 1" in str(x.message)
                   for x in w)


# ---------------------------------------------------------------------------
# the documented relay traps, machine-checked (acceptance criteria)
# ---------------------------------------------------------------------------

@needs_compile_events
def test_reseeding_prngkey_per_step_trips_compile_budget(mesh):
    """CLAUDE.md trap: a step function that bakes a fresh
    ``PRNGKey(python_int)`` into its traced program compiles once PER
    SEED — the compiles budget turns that from a wall-clock anomaly into
    a test failure.  The raw-key-bits fix (utils.prng) passes the same
    budget with zero compiles once warm."""
    x = jnp.ones(16)

    def trapped_step(seed):
        # fresh jit wrapper per step, seed baked in as a constant — the
        # shape the trap takes in real driver code
        f = jax.jit(lambda v, s=seed: v * jax.random.normal(
            jax.random.PRNGKey(s), v.shape).sum())
        return f(x)

    with telemetry.scope():
        trapped_step(0)  # warm the shared sub-ops
        with pytest.raises(flightrec.BudgetExceeded, match="compiles"):
            with flightrec.budget(compiles=1):
                for seed in (1, 2, 3):
                    trapped_step(seed)

        # the fix: ONE program, key bits as an argument
        g = jax.jit(lambda v, k: v * jax.random.normal(k, v.shape).sum())
        g(x, jnp.asarray(prng.key_bits(0)))  # warm: the only compile
        with flightrec.budget(compiles=0):
            for seed in (1, 2, 3):
                g(x, jnp.asarray(prng.key_bits(seed)))


def test_per_epoch_readback_trips_readback_budget(mesh):
    """CLAUDE.md trap: reading a metric back every epoch pays the
    20-150 ms dispatch/readback round trip per epoch; one stacked
    readback per run is the contract the budget pins."""
    from harp_tpu.utils.timing import device_sync

    f = jax.jit(lambda x: x * 1.01)
    x = jnp.ones(8)
    x = f(x)  # warm

    with telemetry.scope():
        with pytest.raises(flightrec.BudgetExceeded, match="readbacks"):
            with flightrec.budget(readbacks=1):
                y = x
                for _ in range(4):
                    y = f(y)
                    device_sync(y)  # the per-epoch readback loop
        # the fix: sync once per run
        with flightrec.budget(readbacks=1):
            y = x
            for _ in range(4):
                y = f(y)
            device_sync(y)


# ---------------------------------------------------------------------------
# pinned budgets for the shipped epoch loops (acceptance criteria)
# ---------------------------------------------------------------------------

@needs_compile_events
def test_mfsgd_epoch_loop_passes_pinned_budget(mesh):
    """One AOT compile per epoch count, then one dispatch + ONE stacked
    readback per train_epochs run, and ZERO recompiles on rerun."""
    import harp_tpu.models.mfsgd as MF

    cfg = MF.MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                         entry_cap=32)
    with telemetry.scope():
        m = MF.MFSGD(64, 48, cfg, mesh, seed=3)
        u, i, v = MF.synthetic_ratings(64, 48, 600, rank=4, seed=3)
        m.set_ratings(u, i, v)
        m.train_epoch()  # warmup: the single-epoch compile
        with flightrec.budget(compiles=1, dispatches=0, readbacks=0,
                              tag="mfsgd.compile_epochs"):
            m.compile_epochs(3)
        # first run: +2 small-op compiles (the stacked-stats readback
        # program), one dispatch, one readback — then steady state
        with flightrec.budget(compiles=2, dispatches=1, readbacks=1,
                              tag="mfsgd.train_epochs#1"):
            m.train_epochs(3)
        with flightrec.budget(compiles=0, dispatches=1, readbacks=1,
                              h2d_bytes=0, tag="mfsgd.train_epochs#2") as b:
            m.train_epochs(3)
        assert b.spent()["dispatches"] == 1
        assert b.spent()["readbacks"] == 1


@needs_compile_events
def test_lda_epoch_loop_passes_pinned_budget(mesh):
    """One AOT compile per epoch count; each sample_epochs run is one
    dispatch + one readback + only the per-worker keys' H2D (64 B at 8
    workers), with zero recompiles — including across _advance_keys
    re-seeds (the raw-key-bits fix)."""
    import harp_tpu.models.lda as L

    cfg = L.LDAConfig(n_topics=8, algo="dense", d_tile=16, w_tile=16,
                      entry_cap=64)
    with telemetry.scope():
        lda = L.LDA(64, 48, cfg, mesh, seed=0)
        d_ids, w_ids = L.benchmark_corpus(64, 48, 4, 0)
        lda.set_tokens(d_ids, w_ids)
        lda.sample_epoch()  # warmup: the single-epoch compile
        with flightrec.budget(compiles=1, dispatches=0, readbacks=0,
                              tag="lda.compile_epochs"):
            lda.compile_epochs(2)
        keys_bytes = mesh.num_workers * 2 * 4
        for rerun in range(2):  # steady from the FIRST run
            with flightrec.budget(compiles=0, dispatches=1, readbacks=1,
                                  h2d_bytes=keys_bytes,
                                  tag=f"lda.sample_epochs#{rerun}") as b:
                lda.sample_epochs(2)
            assert b.spent()["dispatches"] == 1
            assert b.spent()["readbacks"] == 1


@needs_compile_events
def test_kmeans_fit_passes_pinned_budget(mesh):
    """Steady-state fit: one compile (the per-call jit), one dispatch
    for ALL iterations, two readbacks (inertia + centroids), and H2D of
    exactly the points once."""
    import harp_tpu.models.kmeans as KM

    pts = np.random.default_rng(0).normal(size=(256, 8)).astype(np.float32)
    with telemetry.scope():
        KM.fit(pts, k=4, iters=3, mesh=mesh, seed=0)  # warm shared ops
        with flightrec.budget(compiles=1, dispatches=1, readbacks=2,
                              h2d_bytes=pts.nbytes, tag="kmeans.fit") as b:
            KM.fit(pts, k=4, iters=3, mesh=mesh, seed=0)
        assert b.spent()["h2d_bytes"] == pts.nbytes
        assert b.spent()["dispatches"] == 1


# ---------------------------------------------------------------------------
# zero-cost when disabled (satellite)
# ---------------------------------------------------------------------------

def test_zero_cost_when_disabled(mesh):
    """With telemetry off the flight-recorder entry points must not touch
    arrays or add dispatches: the traced epoch program is bit-identical
    (jaxpr equality — no instrumentation ops), the numeric result is
    identical, and every counter stays at zero.  With telemetry on, the
    same single tracked dispatch is simply *counted* — so the recorded
    dispatch count is also the disabled run's dispatch count."""
    import harp_tpu.models.mfsgd as MF

    def build_and_run():
        cfg = MF.MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                             entry_cap=32)
        m = MF.MFSGD(64, 48, cfg, mesh, seed=3)
        u, i, v = MF.synthetic_ratings(64, 48, 600, rank=4, seed=3)
        m.set_ratings(u, i, v)
        rmse = m.train_epoch()
        jaxpr = str(jax.make_jaxpr(m._epoch_fn.__wrapped__)(
            m.W, m.H, *m._blocks))
        return rmse, jaxpr

    with telemetry.scope(False):
        rmse_off, jaxpr_off = build_and_run()
        assert flightrec.compile_watch.count == 0
        assert flightrec.transfers.h2d_bytes == 0
        assert flightrec.transfers.dispatches == 0
        assert flightrec.transfers.readbacks == 0
    with telemetry.scope(True):
        rmse_on, jaxpr_on = build_and_run()
        assert flightrec.transfers.dispatches == 1  # the train_epoch call
    assert rmse_on == rmse_off
    assert jaxpr_on == jaxpr_off


# ---------------------------------------------------------------------------
# export / report / checker round trips
# ---------------------------------------------------------------------------

@needs_compile_events
def test_export_rows_carry_provenance_and_pass_check_jsonl(mesh, tmp_path):
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import check_jsonl

    with telemetry.scope():
        with telemetry.span("unit"):
            flightrec.track(jax.jit(lambda x: x - 2.0), "unit")(jnp.ones(5))
        mesh.shard_array(np.ones((8, 4), np.float32), 0)
        p = tmp_path / "flight.jsonl"
        telemetry.export(str(p))
    rows = telemetry.load_rows(str(p))
    assert rows["compile"] and rows["transfer"]
    for r in rows["compile"] + rows["transfer"]:
        for f in ("backend", "date", "commit"):
            assert f in r, (f, r)
    assert check_jsonl.check_file(str(p)) == []


@needs_compile_events
def test_live_report_surfaces_compile_and_transfer_sections(mesh):
    from harp_tpu import report

    with telemetry.scope():
        with telemetry.span("unit"):
            flightrec.track(jax.jit(lambda x: x / 2.0), "unit")(jnp.ones(5))
        mesh.shard_array(np.ones((8, 4), np.float32), 0)
        row, spans = report.live_report()
    assert row["compile"]["count"] >= 1
    assert row["transfer"]["h2d_bytes"] == 8 * 4 * 4
    assert row["transfer"]["dispatches"] == 1
    text = report.render(row, spans)
    assert "compiles (XLA backend):" in text
    assert "transfers (host<->device):" in text
