"""Two real processes form a mesh via jax.distributed — the multi-host path.

The reference's analogue is pseudo-distributed Hadoop: real sockets over
loopback (SURVEY.md §5).  Ours is two OS processes joined by
``jax.distributed.initialize``, with collectives crossing the boundary over
Gloo (the CPU stand-in for DCN) — no mocks anywhere.
"""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


import pytest


def _run_workers(n_procs: int, local_devices: int = 1,
                 timeout: int = 360) -> None:
    here = os.path.dirname(os.path.abspath(__file__))
    script = os.path.join(here, "multiproc_worker.py")
    port = str(_free_port())
    # strip the harness overrides: conftest forces 8 CPU devices per process
    # via XLA_FLAGS; the worker sets its own per-process device count
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen([sys.executable, script, str(i), port,
                          str(n_procs), str(local_devices)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for i in range(n_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert "MULTIPROC OK" in out


@pytest.mark.parametrize("n_procs", [2, 4])
def test_multi_process_distributed(n_procs):
    """Every collective family crosses a REAL process boundary (see
    multiproc_worker.py), at 2 and at 4 processes — ring direction,
    all_to_all block layout and bucket routing all degenerate at 2."""
    _run_workers(n_procs)


def test_pod_shaped_topology():
    """The v4-32 shape (VERDICT r2 item 6): 2 processes × 4 simulated
    devices each, ONE 8-worker mesh spanning both — intra-process (ICI
    stand-in) and inter-process (Gloo/DCN stand-in) links coexist, and
    every check validates all 4 local shards per process against the
    global expectation, so a layout that is only right at one device per
    process cannot pass."""
    _run_workers(2, local_devices=4)
