"""Two real processes form a mesh via jax.distributed — the multi-host path.

The reference's analogue is pseudo-distributed Hadoop: real sockets over
loopback (SURVEY.md §5).  Ours is two OS processes joined by
``jax.distributed.initialize``, with collectives crossing the boundary over
Gloo (the CPU stand-in for DCN) — no mocks anywhere.
"""

import os
import socket
import subprocess
import sys


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed():
    here = os.path.dirname(os.path.abspath(__file__))
    script = os.path.join(here, "multiproc_worker.py")
    port = str(_free_port())
    # strip the harness overrides: conftest forces 8 CPU devices per process
    # via XLA_FLAGS, but this test wants 1 device per process (2 total)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [
        subprocess.Popen([sys.executable, script, str(i), port],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert "MULTIPROC OK" in out
