"""Aux subsystem tests: mapper lifecycle, metrics, checkpoint, config, profiler."""

import dataclasses
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.mapper import CollectiveApp, run_app
from harp_tpu.utils.checkpoint import CheckpointManager
from harp_tpu.utils.config import parse_into
from harp_tpu.utils.metrics import MetricsLogger


def test_collective_app_lifecycle(mesh, tmp_path):
    path = str(tmp_path / "metrics.jsonl")

    class MiniKMeans(CollectiveApp):
        def map_collective(self):
            from harp_tpu.models.kmeans import fit

            pts = np.random.default_rng(0).normal(size=(64, 4)).astype(np.float32)
            c, inertia = fit(pts, k=2, iters=2, mesh=self.mesh, seed=None)
            self.metrics.log(step=1, inertia=inertia)
            return c

    c = run_app(MiniKMeans, config={"k": 2}, mesh=mesh, metrics_path=path)
    assert c.shape == (2, 4)
    recs = [json.loads(l) for l in open(path)]
    assert recs and "inertia" in recs[0] and recs[0]["step"] == 1


def test_keyval_reader(mesh, tmp_path):
    """KeyValReader hands this worker its whole-file splits (L4 parity)."""
    from harp_tpu.mapper import KeyValReader

    paths = []
    for i in range(3):
        p = tmp_path / f"part{i}.csv"
        p.write_text("\n".join(f"{i}.0,{j}.0" for j in range(4)))
        paths.append(str(p))

    class App(CollectiveApp):
        def map_collective(self):
            return {k: v for k, v in self.reader}

    app = App(mesh=mesh, input_paths=paths)
    assert isinstance(app.reader, KeyValReader)
    assert sorted(app.reader.paths) == sorted(paths)
    data = app.run()
    assert len(data) == 3
    assert data[paths[0]].shape == (4, 2)

    # imperative Harp-style API
    r = KeyValReader(paths[:1])
    with pytest.raises(RuntimeError, match="next_key_value"):
        r.current_key()  # before the first advance
    assert r.next_key_value()
    assert r.current_key() == paths[0]
    v = r.current_value()
    assert v.shape == (4, 2)
    assert r.current_value() is v  # cached per position, not re-parsed
    assert not r.next_key_value()


def test_example_kmeans_app_runs():
    """The MIGRATING.md example app runs end-to-end on the CPU sim."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "examples", "kmeans_app.py"),
         "--cpu8", "--n", "512", "--d", "4", "--k", "2", "--iters", "2"],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "centroid_norm" in out.stdout


def test_metrics_logger_without_file():
    m = MetricsLogger()
    rec = m.log(step=3, loss=1.5)
    assert rec["loss"] == 1.5 and rec["step"] == 3
    m.close()


def test_metrics_logger_context_manager_closes_idempotently(tmp_path):
    import json

    path = str(tmp_path / "m.jsonl")
    with MetricsLogger(path) as m:
        m.log(step=0, loss=2.0)
        m.close()  # explicit close inside the with: __exit__ must tolerate
    assert m._fh is None
    m.close()  # and again after exit
    rows = [json.loads(ln) for ln in open(path)]
    assert rows[0]["loss"] == 2.0 and rows[0]["step"] == 0


def test_checkpoint_roundtrip(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ckpt"), keep=2)
    assert mgr.latest_step() is None
    state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
             "step_count": np.int32(7)}
    for s in (1, 5, 9):
        mgr.save(s, state)
    assert mgr.steps() == [5, 9]  # keep=2 pruned step 1
    step, restored = mgr.restore()
    assert step == 9
    np.testing.assert_array_equal(restored["w"], state["w"])


def test_checkpoint_restore_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        CheckpointManager(str(tmp_path / "empty")).restore()


def test_checkpoint_restore_latest(tmp_path):
    """The serve load path: newest step without the caller enumerating
    steps; empty root fails loudly like restore()."""
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    for s in (2, 11, 7):
        mgr.save(s, {"v": np.float32(s)})
    step, state = mgr.restore_latest()
    assert step == 11 and float(state["v"]) == 11.0
    with pytest.raises(FileNotFoundError):
        CheckpointManager(str(tmp_path / "none")).restore_latest()


def test_parse_into():
    @dataclasses.dataclass
    class Cfg:
        k: int = 100
        lr: float = 0.1
        name: str = "x"
        verbose: bool = False

    cfg = parse_into(Cfg, ["--k", "7", "--lr", "0.5", "--verbose"])
    assert cfg == Cfg(k=7, lr=0.5, name="x", verbose=True)
    cfg = parse_into(Cfg, [], k=9)  # programmatic default override
    assert cfg.k == 9


def test_resume_flow(mesh, tmp_path):
    """The --resume pattern: train, checkpoint, restore, continue."""
    from harp_tpu.models.mlp import MLPConfig, MLPTrainer, synthetic_mnist

    mgr = CheckpointManager(str(tmp_path / "run"))
    cfg = MLPConfig(sizes=(8, 16, 2))
    x, y = synthetic_mnist(n=64, d=8, classes=2, seed=0)
    tr = MLPTrainer(cfg, mesh, seed=0)
    tr.train_batch(x, y)
    mgr.save(1, {"params": tr.params})

    tr2 = MLPTrainer(cfg, mesh, seed=1)  # different init
    step, state = mgr.restore()
    tr2.params = state["params"]
    for a, b in zip(np.asarray(tr.params[0]["w"]).ravel(),
                    np.asarray(tr2.params[0]["w"]).ravel()):
        assert a == b
    tr2.train_batch(x, y)  # continues without error


def test_parse_into_tuple_field():
    @dataclasses.dataclass
    class Cfg:
        sizes: tuple = (8, 16, 2)

    cfg = parse_into(Cfg, ["--sizes", "4,8"])
    assert cfg.sizes == (4, 8)
    assert parse_into(Cfg, []).sizes == (8, 16, 2)


def test_hang_watchdog_fires_with_record_and_exit():
    import time

    from harp_tpu.utils.timing import HangWatchdog

    fired, exits = [], []
    wd = HangWatchdog(timeout_s=0.05, on_fire=fired.append,
                      _exit=exits.append)
    wd.arm("lda")
    time.sleep(0.4)
    assert fired == ["lda"] and exits == [3]


def test_hang_watchdog_cancel_and_rearm():
    import time

    from harp_tpu.utils.timing import HangWatchdog

    fired = []
    wd = HangWatchdog(timeout_s=0.05, on_fire=fired.append, _exit=lambda c: None)
    wd.arm("a")
    wd.arm("b")   # re-arm replaces the pending timer
    wd.cancel()   # cancel before expiry: nothing fires
    time.sleep(0.2)
    assert fired == []
    wd.arm("c")
    time.sleep(0.2)
    assert fired == ["c"]


def test_hang_watchdog_stale_fire_is_noop():
    """A timer that left the waiting stage right as cancel()/arm() ran must
    not emit a hang record for a config that actually finished."""
    from harp_tpu.utils.timing import HangWatchdog

    fired, exits = [], []
    wd = HangWatchdog(timeout_s=60, on_fire=fired.append, _exit=exits.append)
    wd.arm("a")
    stale_gen = wd._gen
    wd.cancel()               # config "a" finished in time
    wd._fire("a", stale_gen)  # the race: _fire already dispatched
    assert fired == [] and exits == []
    wd._fire("a", wd._gen)    # current generation still fires
    assert fired == ["a"] and exits == [3]


def test_example_mfsgd_app_runs():
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "examples", "mfsgd_app.py"),
         "--cpu8", "--users", "64", "--items", "48", "--nnz", "600",
         "--rank", "4", "--epochs", "4"],
        capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr[-800:]
    assert "rmse_final" in out.stdout


def test_example_longctx_layer_runs():
    """The long-context stack example (RoPE + windowed GQA ring attention +
    DP allreduce) trains and its loss descends."""
    import ast
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "examples", "longctx_layer.py"),
         "--cpu8", "--seq", "128", "--steps", "12", "--window", "24"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-800:]
    rec = ast.literal_eval(out.stdout.strip().splitlines()[-1])
    assert rec["loss_final"] < rec["loss_first"]


def test_example_pipeline_moe_app_runs():
    """The PP+EP composition example: GPipe loss descends over the
    stage ring; the MoE dispatch matches the dense reference."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable,
         os.path.join(root, "examples", "pipeline_moe_app.py"),
         "--cpu8", "--steps", "8"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr[-800:]
    assert "pipeline[8 stages" in out.stdout
    assert "== dense reference" in out.stdout


def test_profiling_op_breakdown(mesh, tmp_path):
    """trace() + op_breakdown: capture a jitted run, get a per-op table."""
    import jax
    import jax.numpy as jnp

    from harp_tpu.utils.profiling import op_breakdown, trace

    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: (a @ a).sum())
    float(f(x))  # compile outside the trace
    with trace(str(tmp_path / "tr")) as d:
        float(f(x))
    rows = op_breakdown(d, top=5)
    assert rows and all(isinstance(n, str) and s >= 0 for n, s in rows)

    # a second capture into the SAME dir: totals must come from the newest
    # session only, not the sum of both (reused default logdirs double)
    import time

    time.sleep(1.1)  # session dirs are timestamped at second granularity
    with trace(d):
        float(f(x))
    rows2 = op_breakdown(d, top=5)
    # newest-session-only, asserted structurally (device-op durations vary
    # run to run, so a wall-clock ratio between captures would flake):
    # the logdir parse must equal a parse of the newest session dir alone
    import glob

    sessions = sorted(glob.glob(f"{d}/plugins/profile/*/"))
    assert len(sessions) == 2, sessions
    assert rows2 == op_breakdown(sessions[-1], top=5)

    with pytest.raises(FileNotFoundError, match="trace.json.gz"):
        op_breakdown(str(tmp_path / "nope"))


def test_op_breakdown_self_time_unnests_parent_spans(tmp_path):
    """TPU device tracks nest (jit module ⊃ while ⊃ fusions); the table
    must charge parents only their uncovered time or shares triple-count
    (the 2026-07-31 kmeans capture read jit_run at 28% this way)."""
    import gzip
    import json

    from harp_tpu.utils.profiling import op_breakdown

    #            0         10        20        30        40
    # jit_run    [----------------------------------------]   40 us
    #   while.1      [------------------]                      20 us
    #     fusion.1     [------]  [------]                      8+8 us
    #   fusion.2                              [------]         8 us
    events = [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 7, "tid": 1, "name": "jit_run", "ts": 0,
         "dur": 40},
        {"ph": "X", "pid": 7, "tid": 1, "name": "while.1", "ts": 4,
         "dur": 20},
        {"ph": "X", "pid": 7, "tid": 1, "name": "fusion.1", "ts": 5,
         "dur": 8},
        {"ph": "X", "pid": 7, "tid": 1, "name": "fusion.1", "ts": 14,
         "dur": 8},
        {"ph": "X", "pid": 7, "tid": 1, "name": "fusion.2", "ts": 30,
         "dur": 8},
        # host-track span must stay filtered out
        {"ph": "X", "pid": 1, "tid": 1, "name": "host_thing", "ts": 0,
         "dur": 999},
    ]
    d = tmp_path / "fake"
    d.mkdir()
    with gzip.open(d / "x.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)

    got = dict(op_breakdown(str(d)))
    assert "host_thing" not in got
    assert abs(got["fusion.1"] - 16e-6) < 1e-12
    assert abs(got["fusion.2"] - 8e-6) < 1e-12
    assert abs(got["while.1"] - 4e-6) < 1e-12   # 20 − 16 covered
    assert abs(got["jit_run"] - 12e-6) < 1e-12  # 40 − 20 − 8 covered
    assert abs(sum(got.values()) - 40e-6) < 1e-12  # shares sum to wall

    raw = dict(op_breakdown(str(d), self_time=False))
    assert abs(raw["jit_run"] - 40e-6) < 1e-12  # old behavior, opt-in
