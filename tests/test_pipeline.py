"""Pipeline parallelism: forward equals serial composition; grads match."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel.pipeline import pipeline_forward, pipeline_loss_and_grads

N = 8  # workers / stages (conftest topology)
WIDTH = 16
MB = 4


def stage_fn(params, h):
    return jax.nn.tanh(h @ params["w"] + params["b"])


def make_stage_params(rng, n_stages):
    return {
        "w": rng.normal(size=(n_stages, WIDTH, WIDTH)).astype(np.float32) * 0.5,
        "b": rng.normal(size=(n_stages, WIDTH)).astype(np.float32) * 0.1,
    }


def serial_forward(stacked, x):
    """Reference: apply all stages in sequence on the host."""
    h = jnp.asarray(x)
    for i in range(stacked["w"].shape[0]):
        h = stage_fn({"w": jnp.asarray(stacked["w"][i]),
                      "b": jnp.asarray(stacked["b"][i])}, h)
    return h


@pytest.mark.parametrize("m", [1, 3, 8])
def test_pipeline_forward_matches_serial(mesh, m):
    rng = np.random.default_rng(0)
    stacked = make_stage_params(rng, N)
    x = rng.normal(size=(m, MB, WIDTH)).astype(np.float32)

    fn = jax.jit(mesh.shard_map(
        lambda p, xx: pipeline_forward(stage_fn, jax.tree.map(lambda a: a[0], p), xx),
        in_specs=({"w": mesh.spec(0), "b": mesh.spec(0)}, P()),
        out_specs=P(),
    ))
    out = np.asarray(fn(stacked, x))
    for i in range(m):
        np.testing.assert_allclose(
            out[i], np.asarray(serial_forward(stacked, x[i])),
            rtol=2e-5, atol=2e-6)


def test_pipeline_grads_match_serial(mesh):
    """Autodiff through the ring == serial chain-rule, stage by stage."""
    rng = np.random.default_rng(1)
    stacked = make_stage_params(rng, N)
    m = 4
    x = rng.normal(size=(m, MB, WIDTH)).astype(np.float32)
    tgt = rng.normal(size=(m, MB, WIDTH)).astype(np.float32)

    def loss_fn(outs, targets):
        return ((outs - targets) ** 2).mean()

    fn = jax.jit(mesh.shard_map(
        lambda p, xx, tt: pipeline_loss_and_grads(
            stage_fn, loss_fn, jax.tree.map(lambda a: a[0], p), xx, tt),
        in_specs=({"w": mesh.spec(0), "b": mesh.spec(0)}, P(), P()),
        out_specs=(P(), {"w": mesh.spec(0), "b": mesh.spec(0)}),
    ))
    loss, grads = fn(stacked, x, tgt)

    # serial reference gradient over the STACKED params
    def serial_loss(p):
        outs = jnp.stack([serial_forward(p, x[i]) for i in range(m)])
        return loss_fn(outs, tgt)

    ref_loss, ref_grads = jax.value_and_grad(serial_loss)(
        jax.tree.map(jnp.asarray, stacked))
    assert abs(float(loss) - float(ref_loss)) < 1e-5
    # shard_map concatenated the per-stage grads along dim 0: re-stack
    gw = np.asarray(grads["w"]).reshape(N, WIDTH, WIDTH)
    gb = np.asarray(grads["b"]).reshape(N, WIDTH)
    np.testing.assert_allclose(gw, np.asarray(ref_grads["w"]),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(gb, np.asarray(ref_grads["b"]),
                               rtol=1e-4, atol=1e-6)
