"""Fused Pegasos hinge-gradient kernel (ops/svm_kernel.py) vs the XLA arm.

The kernel promises the SAME per-step sums as `models/svm.py:_pegasos`
(gw = Σ coef·x, gs = Σ coef) — these tests pin the fused pass against a
numpy golden, the full inner solve against the XLA scan, the bf16 arm's
composition with ``x_dtype``, and the offline guarantees (presized VMEM
rejection + Mosaic lowering at the registry/graded shapes).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from harp_tpu.models import svm as SV
from harp_tpu.ops import svm_kernel as K


def _golden(w, b, x, y, sw):
    """The per-step sums of _pegasos, un-normalised (numpy, f64-free:
    integer-free f32 math matches the kernel's f32 accumulation)."""
    margin = y * (x @ w + b)
    coef = np.where(margin < 1.0, sw, 0.0) * y
    return coef @ x, coef.sum()


def _call(w, b, x, y, sw, tn, dtype=np.float32, cd=jnp.float32):
    n, d = x.shape
    dp = 128 * -(-d // 128)
    n_pad = tn * -(-n // tn)
    xT = np.zeros((dp, n_pad), dtype)
    xT[:d, :n] = x.T
    yp = np.zeros(n_pad, np.float32)
    yp[:n] = y
    swp = np.zeros(n_pad, np.float32)        # pad samples: sw = 0
    swp[:n] = sw
    gw, gs = K.pegasos_grad(
        jnp.pad(jnp.asarray(w), (0, dp - d)), jnp.float32(b),
        jnp.asarray(xT), jnp.asarray(yp), jnp.asarray(swp),
        tn=tn, compute_dtype=cd, interpret=True)
    return np.asarray(gw)[:d], float(gs)


def test_fused_grad_matches_numpy():
    rng = np.random.default_rng(0)
    n, d = 100, 20                       # pads d → 128, n → tn
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.sign(rng.normal(size=n)).astype(np.float32)
    sw = rng.uniform(0.5, 2.0, n).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32)
    gw, gs = _call(w, 0.3, x, y, sw, tn=128)
    egw, egs = _golden(w, 0.3, x, y, sw)
    np.testing.assert_allclose(gw, egw, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(gs, egs, rtol=1e-5)


def test_multi_tile_grid_accumulates():
    """n_pad/tn > 1 drives the sequential-grid accumulation path (the
    zero-init-at-step-0 contract) — a wrong index map or a missing
    @pl.when would double-count or drop tiles here."""
    rng = np.random.default_rng(1)
    n, d = 500, 48                       # 500 → n_pad 512 = 4 tiles
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.sign(rng.normal(size=n)).astype(np.float32)
    sw = rng.uniform(0.0, 2.0, n).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32)
    gw, gs = _call(w, -0.1, x, y, sw, tn=128)
    egw, egs = _golden(w, -0.1, x, y, sw)
    np.testing.assert_allclose(gw, egw, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(gs, egs, rtol=1e-5, atol=1e-5)


def test_bf16_arm_matches_bf16_golden():
    """The bf16 arm (x staged bf16, dots bf16×bf16→f32) must match the
    numpy golden computed on the SAME bf16-rounded features — precision
    loss comes from the rounding, not the kernel schedule."""
    rng = np.random.default_rng(2)
    n, d = 128, 16
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.sign(rng.normal(size=n)).astype(np.float32)
    sw = np.ones(n, np.float32)
    w = (0.1 * rng.normal(size=d)).astype(np.float32)  # margins far from 1
    x_bf = np.asarray(jnp.asarray(x).astype(jnp.bfloat16))
    gw, gs = _call(w, 0.0, x_bf, y, sw, tn=128,
                   dtype=jnp.bfloat16, cd=jnp.bfloat16)
    egw, egs = _golden(w, 0.0, x_bf.astype(np.float32), y, sw)
    np.testing.assert_allclose(gw, egw, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(gs, egs, rtol=1e-4, atol=1e-4)


def test_inner_solve_matches_xla_scan():
    """_pegasos_pallas runs the same update sequence as _pegasos — the
    whole inner solve must agree to accumulation-order rounding."""
    rng = np.random.default_rng(3)
    n, d = 300, 24
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.sign(x[:, 0] + 0.1 * rng.normal(size=n)).astype(np.float32)
    y[y == 0] = 1.0
    sw = rng.uniform(0.0, 2.0, n).astype(np.float32)
    cfg = SV.SVMConfig(inner_steps=12, algo="pallas")
    w0 = jnp.zeros(d, jnp.float32)
    wx, bx = SV._pegasos(w0, jnp.float32(0.0), jnp.asarray(x),
                         jnp.asarray(y), jnp.asarray(sw), cfg)
    wp, bp = SV._pegasos_pallas(w0, jnp.float32(0.0), jnp.asarray(x),
                                jnp.asarray(y), jnp.asarray(sw), cfg)
    np.testing.assert_allclose(np.asarray(wp), np.asarray(wx),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(bp), float(bx), rtol=1e-4, atol=1e-6)


def test_model_pallas_matches_xla(mesh):
    """End-to-end under the 8-worker mesh: the algo="pallas" model must
    learn the same separable task to the same weights (the SV exchange,
    padding and round structure all ride along)."""
    rng = np.random.default_rng(4)
    d = 16
    true_w = rng.normal(size=d).astype(np.float32)
    x = rng.normal(size=(1024, d)).astype(np.float32)
    y = np.sign(x @ true_w).astype(np.float32)
    y[y == 0] = 1.0
    out = {}
    for algo in ("xla", "pallas"):
        m = SV.SVM(SV.SVMConfig(inner_steps=60, outer_rounds=2,
                                sv_per_worker=32, algo=algo), mesh)
        m.fit(x, y)
        out[algo] = (m.w, m.b, m.accuracy(x, y))
    assert out["pallas"][2] > 0.93
    np.testing.assert_allclose(out["pallas"][0], out["xla"][0],
                               rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(out["pallas"][1], out["xla"][1],
                               rtol=1e-3, atol=1e-6)


def test_pick_tile_is_largest_fitting():
    assert K.pick_tile(500_000, 128, 4) == 8192   # the presize pin
    assert K.pick_tile(100, 128, 4) == 128        # capped by n_pad
    # bf16 halves tile bytes → same largest tile fits with room
    assert set(K.fit_tiles(128, 2)) >= set(K.fit_tiles(128, 4))


def test_rejects_tile_over_vmem_budget():
    d, tn = 1024, 2048                  # 2·1024·2048·4 B ≈ 16.8 MB
    with pytest.raises(ValueError, match="VMEM budget"):
        K.pegasos_grad(jnp.zeros(d), jnp.float32(0.0),
                       jnp.zeros((d, tn)), jnp.zeros(tn), jnp.zeros(tn),
                       tn=tn, interpret=True)


def test_rejects_unaligned_shapes_for_tpu():
    with pytest.raises(ValueError, match="multiple of 128"):
        K.pegasos_grad(jnp.zeros(64), jnp.float32(0.0),
                       jnp.zeros((64, 128)), jnp.zeros(128),
                       jnp.zeros(128), tn=128, interpret=False)


@pytest.mark.parametrize("dp,n_pad,tn,dtype", [
    (128, 512, 128, jnp.float32),    # the registry-proven shape
    (128, 8192, 8192, jnp.float32),  # the graded presized tile
    (128, 8192, 8192, jnp.bfloat16),  # the x_dtype-composed bf16 arm
])
def test_kernel_lowers_for_tpu(dp, n_pad, tn, dtype):
    """Cross-platform lowering runs the Pallas->Mosaic verification
    (layouts, block shapes, casts) without hardware (HL201 idiom)."""
    import functools

    cd = jnp.bfloat16 if dtype == jnp.bfloat16 else jnp.float32
    f = functools.partial(K.pegasos_grad, tn=tn, compute_dtype=cd,
                          interpret=False)
    lowered = jax.jit(f).trace(
        jnp.zeros(dp), jnp.float32(0.0), jnp.zeros((dp, n_pad), dtype),
        jnp.zeros(n_pad), jnp.zeros(n_pad)).lower(
        lowering_platforms=("tpu",))
    assert "tpu_custom_call" in lowered.as_text()
