"""Driver-contract tests for bench.py — ONE JSON line, north-star pair.

The driver parses bench.py's stdout as a single JSON record
(`BENCH_r*.json`); round-1 VERDICT item 3 requires it to carry kmeans
AND mfsgd values.  Runs bench.main() in-process (conftest already forced
the 8-device CPU sim; a subprocess would hit the axon platform pin).
"""

import io
import json
import os
import sys
from contextlib import redirect_stdout

BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


def _run_bench(argv):
    import runpy

    buf = io.StringIO()
    old = sys.argv
    sys.argv = ["bench.py"] + argv
    try:
        with redirect_stdout(buf):
            runpy.run_path(BENCH, run_name="__main__")
    finally:
        sys.argv = old
    return buf.getvalue()


def _load_bench_ingest():
    """Fresh scripts/bench_ingest module (shared by the chunk-sizing and
    int8-wire preset tests)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_ingest", os.path.join(os.path.dirname(__file__), "..",
                                     "scripts", "bench_ingest.py"))
    bi = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bi)
    return bi


def _load_bench(tmp_path=None):
    """Fresh bench module; optionally point its __file__ at tmp_path so
    the _last_measured/_flip_state file lookups read fixtures there."""
    import importlib.util

    name = f"bench_mod_{_load_bench.n}"
    _load_bench.n += 1
    spec = importlib.util.spec_from_file_location(name, BENCH)
    b = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(b)
    if tmp_path is not None:
        b.__dict__["__file__"] = str(tmp_path / "bench.py")
    return b


_load_bench.n = 0


def test_bench_tables_stay_consistent():
    # BASELINES, _CONFIG_KEYS and UNITS are parallel tables — a config
    # added to one but not the others would KeyError only on the error
    # path (_last_measured), the worst place to discover it
    b = _load_bench()
    assert set(b.BASELINES) == {name for name, _ in b._CONFIG_KEYS}
    assert {key for _, key in b._CONFIG_KEYS} <= set(b.UNITS)


def test_last_measured_uses_declared_config_key(tmp_path):
    # ADVICE r4: a kmeans_ingest row carries iters_per_sec AND
    # points_per_sec; _last_measured must report the config's DECLARED
    # headline (points/s), not the first UNITS hit (iter/s)
    b = _load_bench(tmp_path)
    (tmp_path / "BENCH_local.jsonl").write_text(json.dumps(
        {"config": "kmeans_ingest", "iters_per_sec": 3.0,
         "points_per_sec": 5.5e7, "date": "2026-08-01"}) + "\n")
    lm = b._last_measured()
    assert lm["kmeans_ingest"]["unit"] == "points/s"
    assert lm["kmeans_ingest"]["value"] == 5.5e7
    # unknown configs still fall back to the UNITS scan
    (tmp_path / "BENCH_local.jsonl").write_text(json.dumps(
        {"config": "mystery", "trees_per_sec": 2.0,
         "date": "2026-08-01"}) + "\n")
    lm = b._last_measured()
    assert lm["mystery"]["unit"] == "trees/s"


def test_relay_sized_chunk_follows_measured_h2d(tmp_path, monkeypatch):
    """VERDICT r3 item 4: ingest chunks size themselves from the teed
    probe_h2d record — slow tunnel -> small dispatches; no record or a
    fast link -> the tuned default."""
    import json

    bi = _load_bench_ingest()

    fake = tmp_path / "BENCH_local.jsonl"

    def sized(rate_mb_s):
        fake.write_text(json.dumps(
            {"config": "probe_h2d",
             "probes": [{"mb": 157, "h2d_mb_s": rate_mb_s}]}) + "\n")
        return bi.relay_sized_chunk(bench_path=str(fake))

    # 50 MB/s tunnel -> ~2 s * 50 MB / 600 B per row ~ 166k rows,
    # rounded down to a 8192 multiple and below the default
    assert sized(50.0) == (int(50.0 * 2.0 * 1e6 / 600) // 8192) * 8192
    # fast link -> clamped at the tuned default
    assert sized(10_000.0) == 262_144
    # crawling link -> floor, never zero
    assert sized(0.5) == 16_384
    # no probe on record -> the tuned default
    assert bi.relay_sized_chunk(
        bench_path=str(tmp_path / "missing.jsonl")) == 262_144


def test_bench_smoke_emits_one_line_with_north_star_pair(mesh):
    out = _run_bench(["--smoke", "kmeans", "mfsgd"])
    lines = [ln for ln in out.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, out
    rec = json.loads(lines[0])
    # headline contract fields
    assert {"metric", "value", "unit", "vs_baseline"} <= rec.keys()
    assert rec["unit"] == "iter/s"
    assert rec["value"] > 0, rec
    # the north-star pair: kmeans (headline) AND mfsgd (submetric)
    assert rec["submetrics"]["mfsgd"]["value"] > 0, rec
    assert rec["submetrics"]["mfsgd"]["unit"] == "updates/s/chip"
    assert "error" not in rec


def test_bench_rejects_unknown_config_names(mesh):
    import pytest

    with pytest.raises(SystemExit) as ei:
        _run_bench(["--smoke", "kmaens"])
    assert ei.value.code == 2


def test_bench_headline_failure_surfaces_error(mesh, monkeypatch):
    # a kmeans exception must appear as rec["error"], not parse as a
    # clean 0× regression; vs_baseline must be absent, not 0.0
    from harp_tpu.models import kmeans

    def boom(**kw):
        raise RuntimeError("synthetic kmeans failure")

    monkeypatch.setattr(kmeans, "benchmark", boom)
    out = _run_bench(["kmeans"])  # full mode so vs_baseline logic runs
    lines = [ln for ln in out.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, out
    rec = json.loads(lines[0])
    assert rec["value"] == 0.0
    assert rec["vs_baseline"] is None
    assert "synthetic kmeans failure" in rec["error"]
    # VERDICT r3 item 3: an error record must carry the last committed
    # TPU numbers so the driver can still read the framework's real speed
    lm = rec["last_measured"]
    assert lm["kmeans"]["value"] > 0
    assert lm["kmeans"]["date"]
    # compact entries (VERDICT r5 weak #1): a BENCH_local-sourced entry
    # carries no baseline flag; per-entry source strings are gone
    assert "baseline" not in lm["kmeans"] and "source" not in lm["kmeans"]
    assert lm["mfsgd"]["unit"] == "updates/s/chip"
    # configs with no committed row fall back to the BASELINES constants
    assert all(v["value"] > 0 for v in lm.values())
    # and the one line is bounded under the driver's tail capture
    assert len(lines[0]) < 2000


def test_bench_dead_relay_reports_relay_down_in_seconds(mesh, monkeypatch):
    # HARP_RELAY_PROBE=force probes even on the CPU sim; a 0.05 s timeout
    # guarantees the subprocess probe cannot finish -> relay_down record
    # with last_measured, exit code 3, all within seconds (not the 1200 s
    # watchdog)
    import io
    import runpy
    import sys
    from contextlib import redirect_stdout

    import pytest

    monkeypatch.setenv("HARP_RELAY_PROBE", "force")
    monkeypatch.setenv("HARP_RELAY_PROBE_TIMEOUT", "0.05")
    buf = io.StringIO()
    old = sys.argv
    sys.argv = ["bench.py", "kmeans"]
    try:
        with redirect_stdout(buf), pytest.raises(SystemExit) as ei:
            runpy.run_path(BENCH, run_name="__main__")
    finally:
        sys.argv = old
    assert ei.value.code == 3
    rec = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert rec["error"].startswith("relay_down")
    assert rec["value"] == 0.0
    assert rec["last_measured"]["kmeans"]["value"] > 0


def test_bench_probe_skipped_on_cpu_sim(mesh):
    # the default probe path must not fire on the simulated-CPU backend
    # (tests would otherwise spawn doomed axon subprocesses); smoke run
    # completing without error proves the skip
    out = _run_bench(["--smoke", "kmeans"])
    rec = json.loads(out.strip().splitlines()[-1])
    assert "error" not in rec


def test_bench_record_carries_flip_state(mesh):
    # the driver record must MIRROR FLIP_DECISIONS.jsonl: summarized
    # when the file has verdicts, absent when it doesn't.  The relay
    # pipeline rewrites and auto-commits that artifact unattended (tee
    # truncation on a crashed gate can even leave it empty), so the test
    # checks record/file consistency, not a hardcoded table size
    out = _run_bench(["--smoke", "kmeans"])
    rec = json.loads([ln for ln in out.strip().splitlines()
                      if ln.startswith("{")][0])
    fs = rec.get("flip_state")
    rows = []
    try:
        with open(os.path.join(os.path.dirname(BENCH),
                               "FLIP_DECISIONS.jsonl")) as f:
            for ln in f:
                try:
                    row = json.loads(ln)
                except ValueError:
                    continue
                if "flip_decision" in row:
                    rows.append(row)
    except OSError:
        pass
    if not rows:
        assert fs is None
        return
    assert fs["candidates"] == len(rows)
    assert 0 <= fs["decided"] <= fs["candidates"]
    assert 0 <= fs["flips_authorized"] <= fs["decided"]


def test_bench_per_config_watchdog_parses_and_bounds(mesh):
    """Satellite (PR 10): --max-seconds-per-config=S parses strictly and
    the subprocess-free timer skips a hung thunk after ~S seconds (the
    thread is abandoned; the sweep moves on) while fast thunks and their
    exceptions pass through untouched."""
    import threading
    import time

    import pytest

    b = _load_bench()
    assert b._parse_max_seconds(["--smoke"]) is None
    assert b._parse_max_seconds(["--max-seconds-per-config=2.5"]) == 2.5
    for bad in (["--max-seconds-per-config"],       # no '=' form
                ["--max-seconds-per-config=nope"],  # non-numeric
                ["--max-seconds-per-config=0"]):    # non-positive
        with pytest.raises(SystemExit):
            b._parse_max_seconds(bad)

    # fast thunk: result passes through, no error
    res, err = b._run_with_timeout(lambda: {"v": 7}, 30.0)
    assert res == {"v": 7} and err is None
    # no timer requested: straight call
    assert b._run_with_timeout(lambda: 3, None) == (3, None)
    # thunk exceptions re-raise for the existing per-config handling
    with pytest.raises(ValueError, match="boom"):
        b._run_with_timeout(lambda: (_ for _ in ()).throw(
            ValueError("boom")), 30.0)

    # hung thunk: warn-and-skip within the bound, not forever
    release = threading.Event()

    def hang():
        release.wait(60)
        return "too late"

    t0 = time.monotonic()
    res, err = b._run_with_timeout(hang, 0.2)
    took = time.monotonic() - t0
    release.set()  # let the abandoned worker die promptly
    assert res is None
    assert "timeout" in err and "0.2" in err
    assert took < 5  # bounded: nowhere near the 60 s hang


def test_bench_timed_out_config_is_recorded_and_skipped(mesh):
    """End to end: a config that overruns --max-seconds-per-config shows
    up in the record as an error submetric (the timeout string), and the
    sweep still measures the configs after it."""
    import threading

    b = _load_bench()
    release = threading.Event()
    real = b._configs

    def patched(smoke):
        cfgs = real(smoke)
        out = []
        for name, unit, key, thunk in cfgs:
            if name == "kmeans":
                out.append((name, unit, key,
                            lambda: release.wait(60) or {"iters_per_sec":
                                                         1.0}))
            elif name == "subgraph":  # fast fake: the sweep-continues pin
                out.append((name, unit, key,
                            lambda: {"vertices_per_sec": 123.0}))
            else:
                out.append((name, unit, key, thunk))
        return out

    b._configs = patched
    old = sys.argv
    sys.argv = ["bench.py", "--smoke", "--cpu", "kmeans", "subgraph",
                "--max-seconds-per-config=0.5"]
    buf = io.StringIO()
    try:
        with redirect_stdout(buf):
            b.main()
    finally:
        sys.argv = old
        release.set()
    rec = json.loads(buf.getvalue())
    assert "timeout" in rec["error"]  # surfaced on the headline (kmeans)
    # the timed-out config reads 0.0; the config AFTER it still measured
    assert rec["value"] == 0.0
    assert rec["submetrics"]["subgraph"]["value"] > 0


def test_flip_state_tolerates_truncated_tee_lines(tmp_path):
    # a sprint killed mid-write leaves a truncated last line; the summary
    # must count the valid rows, not vanish (review finding, round 5)
    b = _load_bench(tmp_path)
    (tmp_path / "FLIP_DECISIONS.jsonl").write_text(
        json.dumps({"flip_decision": "a", "flip": True, "speedup": 1.2,
                    "quality_ok": True}) + "\n"
        + json.dumps({"flip_decision": "b", "flip": False,
                      "speedup": None, "quality_ok": None}) + "\n"
        + '{"flip_decision": "c", "flip": fal')  # truncated mid-write
    fs = b._flip_state()
    assert fs == {"candidates": 2, "decided": 1, "flips_authorized": 1}
    # no file at all -> None (no flip_state key in the record)
    b.__dict__["__file__"] = str(tmp_path / "nowhere" / "bench.py")
    assert b._flip_state() is None


def test_ingest_smoke_preset_runs_int8_wire(tmp_path, monkeypatch, mesh):
    """run_smoke(quantize='int8') executes the int8-WIRE ingest end to
    end (round 5: the kmeans_ingest_int8 sweep twin — measured 1.55x on
    the tunnel-bound relay).  The full-mode binding test stubs
    _bench_ingest, so without this nothing exercises the preset's
    quantize threading."""
    bi = _load_bench_ingest()
    # REAL isolation: the module's DATA_DIR is an absolute repo path
    # (cwd-independent), so redirect it — a chdir would silently share
    # .bench_data with concurrent bench/measure runs (review finding)
    monkeypatch.setattr(bi, "DATA_DIR", str(tmp_path))

    res = bi.run_smoke(quantize="int8")
    assert res["wire_dtype"] == "int8"
    assert res["points_per_sec"] > 0 and res["inertia"] > 0
    # and the exact-wire default is unchanged
    res_f = bi.run_smoke()
    assert res_f["wire_dtype"] != "int8"
    # same data, same seed: int8 quantization moves inertia by well
    # under the contract's 1% (measured 1.6e-4 rel on the 12 GB run)
    assert abs(res["inertia"] - res_f["inertia"]) / res_f["inertia"] < 0.01


def test_error_record_bounded_under_driver_tail_capture(tmp_path):
    """VERDICT r5 weak #1 (BENCH_r05 parsed:null): the one emitted JSON
    line must stay under the driver's ~2000-char tail capture in the
    WORST case — error path, a last_measured entry for every BASELINES
    config PLUS a pile of unknown configs from committed rows, and a
    long error string.  _fit_record trims lowest-priority-first and the
    graded headline configs survive."""
    b = _load_bench(tmp_path)
    # worst-case committed file: every graded config + 15 unknowns
    rows = [{"config": name, key: 123.456, "date": "2026-08-01"}
            for name, key in b._CONFIG_KEYS]
    rows += [{"config": f"mystery_config_number_{i:02d}",
              "trees_per_sec": 1.0 + i, "date": "2026-08-01"}
             for i in range(15)]
    (tmp_path / "BENCH_local.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in rows))
    lm = b._last_measured()
    assert len(lm) >= len(b._CONFIG_KEYS) + 15

    rec = {"metric": "kmeans_iters_per_sec_1Mx300_k100", "value": 0.0,
           "unit": "iter/s", "vs_baseline": None,
           "submetrics": {name: {"value": 0.0, "unit": "u",
                                 "error": "timeout: config exceeded "
                                          "--max-seconds-per-config"}
                          for name, _ in b._CONFIG_KEYS},
           "error": "relay_down: jax.devices() probe timed out after "
                    "90s - TPU relay hung before any config ran",
           "last_measured": lm}
    out = b._fit_record(rec)
    line = json.dumps(out)
    assert "\n" not in line
    assert len(line) <= b.RECORD_CAP_BYTES < 2000
    assert json.loads(line)["error"].startswith("relay_down")
    # trimming dropped the unknowns first; the graded headline configs
    # (the _CONFIG_KEYS front) survive
    kept = out["last_measured"]
    assert out["last_measured_dropped"] >= 1
    assert kept  # something survives, and headline-first:
    prio = [name for name, _ in b._CONFIG_KEYS]
    assert list(kept) == prio[:len(kept)]  # a PREFIX of priority order
    assert "kmeans" in kept  # the headline survives longest
    assert not any(c.startswith("mystery") for c in kept)

    # a record already under the cap is untouched (no spurious field)
    small = {"metric": "m", "value": 1.0,
             "last_measured": {"kmeans": {"value": 1.0, "unit": "iter/s",
                                          "date": "2026-08-01"}}}
    assert "last_measured_dropped" not in b._fit_record(dict(small))


def test_live_error_record_measures_under_cap(mesh, monkeypatch):
    """Integration: a real bench.py error record (the headline-failure
    path against the REAL committed BENCH_local) emits one line under
    the cap — the exact scenario that produced BENCH_r05."""
    from harp_tpu.models import kmeans

    def boom(**kw):
        raise RuntimeError("synthetic kmeans failure " + "x" * 120)

    monkeypatch.setattr(kmeans, "benchmark", boom)
    out = _run_bench(["kmeans"])
    lines = [ln for ln in out.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert "error" in rec and rec["last_measured"]
    assert len(lines[0]) <= 1800
