"""Driver-contract tests for bench.py — ONE JSON line, north-star pair.

The driver parses bench.py's stdout as a single JSON record
(`BENCH_r*.json`); round-1 VERDICT item 3 requires it to carry kmeans
AND mfsgd values.  Runs bench.main() in-process (conftest already forced
the 8-device CPU sim; a subprocess would hit the axon platform pin).
"""

import io
import json
import os
import sys
from contextlib import redirect_stdout

BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


def _run_bench(argv):
    import runpy

    buf = io.StringIO()
    old = sys.argv
    sys.argv = ["bench.py"] + argv
    try:
        with redirect_stdout(buf):
            runpy.run_path(BENCH, run_name="__main__")
    finally:
        sys.argv = old
    return buf.getvalue()


def test_bench_smoke_emits_one_line_with_north_star_pair(mesh):
    out = _run_bench(["--smoke", "kmeans", "mfsgd"])
    lines = [ln for ln in out.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, out
    rec = json.loads(lines[0])
    # headline contract fields
    assert {"metric", "value", "unit", "vs_baseline"} <= rec.keys()
    assert rec["unit"] == "iter/s"
    assert rec["value"] > 0, rec
    # the north-star pair: kmeans (headline) AND mfsgd (submetric)
    assert rec["submetrics"]["mfsgd"]["value"] > 0, rec
    assert rec["submetrics"]["mfsgd"]["unit"] == "updates/s/chip"
    assert "error" not in rec


def test_bench_rejects_unknown_config_names(mesh):
    import pytest

    with pytest.raises(SystemExit) as ei:
        _run_bench(["--smoke", "kmaens"])
    assert ei.value.code == 2


def test_bench_headline_failure_surfaces_error(mesh, monkeypatch):
    # a kmeans exception must appear as rec["error"], not parse as a
    # clean 0× regression; vs_baseline must be absent, not 0.0
    from harp_tpu.models import kmeans

    def boom(**kw):
        raise RuntimeError("synthetic kmeans failure")

    monkeypatch.setattr(kmeans, "benchmark", boom)
    out = _run_bench(["kmeans"])  # full mode so vs_baseline logic runs
    lines = [ln for ln in out.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1, out
    rec = json.loads(lines[0])
    assert rec["value"] == 0.0
    assert rec["vs_baseline"] is None
    assert "synthetic kmeans failure" in rec["error"]
