"""The topology-aware collective planner (PR 11, harp_tpu/plan).

Pins, in order: the topology price list's algebra; the frozen plan-row
vocabularies' sync with scripts/check_jsonl.py (invariant 10 stays a
standalone mirror, like the lint rule ids); the acceptance criterion —
planner-predicted per-site bytes equal the CommGraph byte sheets
EXACTLY for every registered program; fail-closed decisions (schedule
is always "keep"; candidates only where the topology predicts a real
win AND a measure_all config exists); and the plan CLI's stamped,
invariant-10-clean JSON rows.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts"))

import check_jsonl  # noqa: E402
from harp_tpu.plan import planner, topology  # noqa: E402


# -- topology ---------------------------------------------------------------

def test_topology_validation_and_classes():
    with pytest.raises(ValueError, match="group into hosts"):
        topology.Topology("x", 8, 3, 10.0, 10.0)
    with pytest.raises(ValueError, match="positive"):
        topology.Topology("x", 8, 8, 0.0, 10.0)
    t = topology.v4_32()
    assert t.hosts == 4 and t.n_workers == 16
    assert t.rates_source == "declared"


def test_single_chip_prices_zero_wire():
    t = topology.single_chip()
    assert t.wire_bytes("psum", 1024) == 0.0
    assert t.cost_s("ppermute", 1024) == 0.0


def test_ring_cost_algebra():
    """bytes × hops / rate: the sim ring's psum moves 2(n-1)/n of the
    payload at the intra rate; amplification multiplies linearly."""
    t = topology.sim_ring(8)
    b = 1000
    expect = b * 2 * 7 / 8 / (10.0 * 1e9)
    assert abs(t.cost_s("psum", b) - expect) < 1e-18
    assert abs(t.cost_s("psum", b, amplification=3)
               - 3 * expect) < 1e-18
    with pytest.raises(ValueError, match="unknown collective"):
        t.cost_s("send_recv", b)


def test_hier_psum_wins_only_across_hosts():
    """The decision the whole subsystem exists for: on a one-host ring
    the two-stage psum prices >= the one-shot; on v4_32 (4 hosts, slow
    inter class) it prices strictly cheaper."""
    flat, multi = topology.sim_ring(8), topology.v4_32()
    b = 1 << 20
    assert flat.hier_stage_cost_s(b) >= flat.cost_s("psum", b) * 0.999
    assert multi.hier_stage_cost_s(b) < multi.cost_s("psum", b)


def test_detect_names_the_sim_ring(mesh):
    t = topology.detect(mesh)
    assert t.name == "sim_ring_8" and t.n_workers == 8


def test_probed_rates_stamp(mesh):
    t = topology.probed(topology.sim_ring(8), mesh, size_mb=0.5)
    assert t.rates_source == "probed" and t.intra_gbs > 0


# -- frozen vocabulary sync pins (check_jsonl stays standalone) -------------

def test_plan_vocabularies_in_sync():
    assert tuple(planner.SCHEDULES) == check_jsonl.KNOWN_PLAN_SCHEDULES
    assert tuple(topology.TOPOLOGY_NAMES) == \
        check_jsonl.KNOWN_PLAN_TOPOLOGIES
    # the frozen byte-scaling math must agree for every schedule on
    # awkward (odd, tiny, huge) sheet sizes
    for sched in planner.SCHEDULES:
        for b in (0, 1, 3, 7, 1060, 131072, 10**9 + 7):
            assert planner.predicted_bytes(sched, b) == \
                check_jsonl._plan_predicted_bytes(sched, b), (sched, b)


def test_flip_candidate_configs_exist_in_measure_all():
    """Every candidate the planner can name must be measurable: the
    mapped config exists in SPRINT_ORDER's candidates block and in
    flip_decision's gate table."""
    import flip_decision
    import measure_all

    for cfg in planner.FLIP_CANDIDATE_CONFIGS.values():
        assert cfg in measure_all.SPRINT_ORDER, cfg
        assert measure_all.SPRINT_ORDER.index(cfg) < \
            measure_all.SPRINT_ORDER.index(measure_all.FIRST_REMEASURE), \
            f"{cfg} must ride the unmeasured-candidates block"
        assert cfg in flip_decision.CANDIDATES, cfg
    # and the named programs are registered drivers
    from harp_tpu.analysis.drivers import DRIVERS

    for prog, _, _ in planner.FLIP_CANDIDATE_CONFIGS:
        assert prog in DRIVERS, prog


# -- the acceptance criterion: predictions == byte sheets -------------------

def test_predicted_bytes_match_byte_sheets_for_all_programs(mesh):
    """Plan every registered program and check each site's fail-closed
    prediction equals the CommGraph byte sheet's amplified bytes for
    that site, exactly — and the plan total equals the sheet total."""
    from harp_tpu.analysis import commgraph
    from harp_tpu.analysis.drivers import DRIVERS

    topo = topology.detect(mesh)
    for name in sorted(DRIVERS):
        fn, args = DRIVERS[name]()
        graph = commgraph.extract(name, fn, args)
        plan = planner.plan_sheet(
            name, {"collectives": [s.row() for s in graph.sites]}, topo)
        sheet_by_site = {}
        for s in graph.sites:
            key = (s.site, s.primitive)
            sheet_by_site[key] = sheet_by_site.get(key, 0) + \
                s.per_shard_bytes * max(s.amplification, 1)
        got_by_site = {}
        for d in plan.sites:
            key = (d.site, d.primitive)
            got_by_site[key] = got_by_site.get(key, 0) + d.predicted_bytes
        assert got_by_site == sheet_by_site, name
        assert plan.predicted_bytes_total() == graph.amplified_bytes(), \
            name


def test_every_decision_fails_closed(mesh):
    """No topology — not even one where every alternative wins — may
    change a chosen schedule: 'keep' is the only choice; alternatives
    surface exclusively as flip candidates."""
    for topo in (topology.sim_ring(8), topology.v4_32(),
                 topology.single_chip()):
        plans = planner.plan_all(topo)
        assert set(plans) == set(check_jsonl.KNOWN_LINT_PROGRAMS)
        for plan in plans.values():
            for site in plan.sites:
                assert site.schedule == "keep", (plan.program, site.site)
                assert site.predicted_bytes == site.sheet_bytes


def test_candidates_follow_the_topology(mesh):
    """kmeans.fit's hier candidate appears ONLY where the price list
    says it wins (v4_32's slow inter-host class), never on the flat
    ring; the lda wire candidates win everywhere bytes halve."""
    flat = planner.plan_program("kmeans.fit", topology.sim_ring(8))
    multi = planner.plan_program("kmeans.fit", topology.v4_32())
    assert flat.flip_candidates() == []
    assert multi.flip_candidates() == ["kmeans_hier_psum"]

    lda = planner.plan_program("lda.epoch", topology.sim_ring(8))
    assert set(lda.flip_candidates()) == {"lda_planner_wire",
                                          "lda_rotate_int8"}
    (ring_site,) = [s for s in lda.sites if s.verb == "reshard"]
    # the cheapest mapped winner is the headline candidate
    assert ring_site.flip_candidate == "lda_rotate_int8"
    assert ring_site.candidates == {"wire_bf16": "lda_planner_wire",
                                    "wire_int8": "lda_rotate_int8"}


def test_quantized_sites_take_no_second_wire_trade():
    """A site whose ledger wire is already narrow must not be offered a
    wire_* alternative (it took its trade; re-quantizing compounds)."""
    entry = {"site": "x.py:1", "primitive": "ppermute", "verb": "reshard",
             "per_shard_bytes": 1024, "amplification": 4,
             "ledger_wire": "int8"}
    dec = planner.decide_site("lda.epoch", entry, topology.sim_ring(8))
    assert not any(a.startswith("wire_") for a in dec.alternatives)
    assert dec.candidates == {}


def test_plan_program_rejects_unknown_names():
    with pytest.raises(KeyError, match="not a registered driver"):
        planner.plan_program("no.such.program")


# -- the serialized row + CLI -----------------------------------------------

def _stamp(row):
    return {**row, "backend": "cpu", "date": "2026-08-04",
            "commit": "test"}


def test_plan_row_passes_invariant_10(mesh):
    plan = planner.plan_program("mfsgd.epoch", topology.detect(mesh))
    assert check_jsonl._check_plan_row("t", 1, _stamp(plan.row())) == []


def test_cli_emits_stamped_invariant_clean_rows(mesh, capsys):
    from harp_tpu.plan import cli

    rc = cli.main(["--program", "kmeans.fit", "--program", "lda.epoch",
                   "--json", "--topology", "v4_32"])
    assert rc == 0
    rows = [json.loads(ln) for ln in
            capsys.readouterr().out.strip().splitlines()]
    assert [r["program"] for r in rows] == ["kmeans.fit", "lda.epoch"]
    for row in rows:
        assert row["kind"] == "plan" and row["config"] == "plan"
        assert all(k in row for k in ("backend", "date", "commit"))
        assert check_jsonl._check_plan_row("cli", 1, row) == []
    assert rows[0]["flip_candidates"] == ["kmeans_hier_psum"]


def test_cli_rejects_unknown_program(mesh, capsys):
    from harp_tpu.plan import cli

    assert cli.main(["--program", "nope", "--json"]) == 2
