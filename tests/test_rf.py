"""Random forest tests: learnability, forest allgather, determinism."""

import numpy as np
import pytest

from harp_tpu.models import rf as RF



def test_learns_axis_aligned_task(mesh):
    x, y = RF.synthetic_classification(n=8_000, f=16, seed=0)
    model = RF.RandomForest(RF.RFConfig(n_trees=16, max_depth=5), mesh)
    model.fit(x, y)
    acc = model.accuracy(x, y)
    assert acc > 0.85, acc
    # generalizes (same distribution, fresh draw)
    xt, yt = RF.synthetic_classification(n=4_000, f=16, seed=9)
    assert model.accuracy(xt, yt) > 0.8


def test_forest_gathered_from_all_workers(mesh):
    x, y = RF.synthetic_classification(n=1_024, f=8, seed=0)
    model = RF.RandomForest(RF.RFConfig(n_trees=16, max_depth=3), mesh)
    model.fit(x, y)
    feats, thresh, leaves = model.forest
    assert feats.shape[0] == 16  # all workers' trees present
    assert leaves.shape == (16, 2 ** 3)
    # trees differ (bootstrap + per-worker shards): not all identical
    assert len({feats[t].tobytes() for t in range(16)}) > 1


def test_single_class_degenerate(mesh):
    x = np.random.default_rng(0).normal(size=(512, 8)).astype(np.float32)
    y = np.zeros(512, np.int32)
    model = RF.RandomForest(RF.RFConfig(n_trees=8, max_depth=3), mesh)
    model.fit(x, y)
    assert (model.predict(x[:100]) == 0).all()


def test_trees_not_divisible_raises(mesh):
    with pytest.raises(ValueError, match="divisible"):
        RF.RandomForest(RF.RFConfig(n_trees=9), mesh)


def test_predict_before_fit_raises(mesh):
    with pytest.raises(RuntimeError, match="fit"):
        RF.RandomForest(RF.RFConfig(n_trees=8), mesh).predict(np.zeros((4, 8)))
