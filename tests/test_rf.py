"""Random forest tests: learnability, forest allgather, determinism."""

import numpy as np
import pytest

from harp_tpu.models import rf as RF



def test_learns_axis_aligned_task(mesh):
    x, y = RF.synthetic_classification(n=8_000, f=16, seed=0)
    model = RF.RandomForest(RF.RFConfig(n_trees=16, max_depth=5), mesh)
    model.fit(x, y)
    acc = model.accuracy(x, y)
    assert acc > 0.85, acc
    # generalizes (same distribution, fresh draw)
    xt, yt = RF.synthetic_classification(n=4_000, f=16, seed=9)
    assert model.accuracy(xt, yt) > 0.8


def test_forest_gathered_from_all_workers(mesh):
    x, y = RF.synthetic_classification(n=1_024, f=8, seed=0)
    model = RF.RandomForest(RF.RFConfig(n_trees=16, max_depth=3), mesh)
    model.fit(x, y)
    feats, thresh, leaves = model.forest
    assert feats.shape[0] == 16  # all workers' trees present
    assert leaves.shape == (16, 2 ** 3)
    # trees differ (bootstrap + per-worker shards): not all identical
    assert len({feats[t].tobytes() for t in range(16)}) > 1


def test_single_class_degenerate(mesh):
    x = np.random.default_rng(0).normal(size=(512, 8)).astype(np.float32)
    y = np.zeros(512, np.int32)
    model = RF.RandomForest(RF.RFConfig(n_trees=8, max_depth=3), mesh)
    model.fit(x, y)
    assert (model.predict(x[:100]) == 0).all()


def test_trees_not_divisible_raises(mesh):
    with pytest.raises(ValueError, match="divisible"):
        RF.RandomForest(RF.RFConfig(n_trees=9), mesh)


def test_predict_before_fit_raises(mesh):
    with pytest.raises(RuntimeError, match="fit"):
        RF.RandomForest(RF.RFConfig(n_trees=8), mesh).predict(np.zeros((4, 8)))


def test_grow_level_histogram_matches_numpy(mesh):
    """The int8 one-hot matmul histogram must equal an exact numpy
    scatter-add histogram (counts are integers; no rounding anywhere)."""
    import jax.numpy as jnp
    from harp_tpu.models.rf import RFConfig, _grow_level, bins_onehot

    rng = np.random.default_rng(0)
    n, f, B, C = 300, 5, 8, 3
    cfg = RFConfig(n_bins=B, n_classes=C, max_depth=3)
    bins = rng.integers(0, B, (n, f)).astype(np.int32)
    y = rng.integers(0, C, n).astype(np.int32)
    w = rng.poisson(1.0, n).astype(np.float32)
    level = 2
    node_id = rng.integers(0, 2 ** level, n).astype(np.int32)
    feat_mask = np.ones(f, np.float32)

    BO = bins_onehot(jnp.asarray(bins), B)
    sf, sb, new_id = _grow_level(BO, jnp.asarray(bins), jnp.asarray(y),
                                 jnp.asarray(w), jnp.asarray(node_id),
                                 level, jnp.asarray(feat_mask), cfg)

    # numpy reference: exact weighted histogram + same gini/argmin rules
    hist = np.zeros((2 ** level, f, B, C), np.float64)
    for i in range(n):
        for j in range(f):
            hist[node_id[i], j, bins[i, j], y[i]] += w[i]
    left = hist.cumsum(axis=2)
    total = left[:, :, -1:, :]
    right = total - left

    def gini(cnt):
        sz = cnt.sum(-1)
        p = cnt / np.maximum(sz[..., None], 1e-9)
        return sz * (1.0 - (p * p).sum(-1))

    score = gini(left) + gini(right)
    score[:, :, -1] = np.inf
    best = score.reshape(2 ** level, f * B).argmin(axis=1)
    np.testing.assert_array_equal(np.asarray(sf), (best // B).astype(np.int32))
    np.testing.assert_array_equal(np.asarray(sb), (best % B).astype(np.int32))
    # routing: right iff sample's bin at its node's split feature > split bin
    exp_right = (bins[np.arange(n), np.asarray(sf)[node_id]]
                 > np.asarray(sb)[node_id]).astype(np.int32)
    np.testing.assert_array_equal(np.asarray(new_id), node_id * 2 + exp_right)


def test_hist_algo_scatter_matches_dense(mesh):
    """PR-16 flip candidate: the scatter-add histogram formulation must
    pick bit-identical splits to the dense one-hot matmul incumbent
    (integer counts, two exact formulations — any divergence is a bug,
    not noise), so the rf_dense_hist/rf_scatter_hist pair's flip gate
    can demand equal train_acc."""
    import jax.numpy as jnp
    from harp_tpu.models.rf import RFConfig, _grow_level, bins_onehot

    rng = np.random.default_rng(3)
    n, f, B, C = 300, 5, 8, 3
    bins = rng.integers(0, B, (n, f)).astype(np.int32)
    y = rng.integers(0, C, n).astype(np.int32)
    w = rng.poisson(1.0, n).astype(np.float32)
    level = 2
    node_id = rng.integers(0, 2 ** level, n).astype(np.int32)
    feat_mask = np.ones(f, np.float32)
    BO = bins_onehot(jnp.asarray(bins), B)

    outs = {}
    for algo in ("dense", "scatter"):
        cfg = RFConfig(n_bins=B, n_classes=C, max_depth=3,
                       hist_algo=algo)
        outs[algo] = _grow_level(
            BO, jnp.asarray(bins), jnp.asarray(y), jnp.asarray(w),
            jnp.asarray(node_id), level, jnp.asarray(feat_mask), cfg)
    for a, b in zip(outs["dense"], outs["scatter"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_hist_algo_validated():
    with pytest.raises(ValueError, match="hist_algo"):
        RF.RFConfig(hist_algo="sparse")
