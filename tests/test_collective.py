"""Property tests: every Harp verb ≡ its numpy reference on gathered arrays.

Mirrors the role of ``edu.iu.benchmark`` + pseudo-distributed runs in the
reference (SURVEY.md §5): each verb runs through the real shard_map path on
8 simulated workers and is checked against a straight-line numpy model of
Harp's documented semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from harp_tpu.parallel import collective as C
from harp_tpu.parallel.collective import Combiner
from harp_tpu.parallel.rotate import rotate_pipeline, resident_slice_index

N = 8  # simulated workers (conftest)


def run_spmd(mesh, fn, x, in_dim=0, out_dim=0):
    """shard_map fn over x (sharded on in_dim; None = replicated)."""
    in_spec = mesh.spec(in_dim) if in_dim is not None else P()
    out_spec = mesh.spec(out_dim) if out_dim is not None else P()
    return jax.jit(mesh.shard_map(fn, in_specs=(in_spec,), out_specs=out_spec))(x)


@pytest.fixture
def data():
    rng = np.random.default_rng(0)
    return rng.normal(size=(N * 4, 16)).astype(np.float32)


# -- allreduce --------------------------------------------------------------

@pytest.mark.parametrize(
    "op,ref",
    [
        (Combiner.ADD, lambda s: s.sum(0)),
        (Combiner.MAX, lambda s: s.max(0)),
        (Combiner.MIN, lambda s: s.min(0)),
        (Combiner.AVG, lambda s: s.mean(0)),
        (Combiner.MULTIPLY, lambda s: s.prod(0)),
    ],
)
def test_allreduce(mesh, data, op, ref):
    out = run_spmd(mesh, lambda x: C.allreduce(x, op), data, out_dim=None)
    shards = data.reshape(N, 4, 16)
    np.testing.assert_allclose(np.asarray(out), ref(shards), rtol=2e-5)


def test_allreduce_pytree(mesh, data):
    tree = {"a": data, "b": data * 2}
    out = run_spmd(mesh, C.allreduce, tree, out_dim=None)
    shards = data.reshape(N, 4, 16)
    np.testing.assert_allclose(np.asarray(out["a"]), shards.sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["b"]), 2 * shards.sum(0), rtol=1e-5)


# -- allgather --------------------------------------------------------------

def test_allgather(mesh, data):
    out = run_spmd(mesh, C.allgather, data, out_dim=None)
    # every worker ends with the full concatenation, original order
    np.testing.assert_array_equal(np.asarray(out), data)


# -- broadcast / reduce -----------------------------------------------------

@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast(mesh, data, root):
    out = run_spmd(mesh, lambda x: C.broadcast(x, root=root), data, out_dim=None)
    np.testing.assert_array_equal(np.asarray(out), data.reshape(N, 4, 16)[root])


def test_reduce_root_only(mesh, data):
    # keep the per-worker outputs to check root vs non-root
    out = run_spmd(mesh, lambda x: C.reduce(x, root=2)[None], data, out_dim=0)
    out = np.asarray(out).reshape(N, 4, 16)
    shards = data.reshape(N, 4, 16)
    np.testing.assert_allclose(out[2], shards.sum(0), rtol=1e-5)
    assert np.all(out[[i for i in range(N) if i != 2]] == 0)


# -- regroup ----------------------------------------------------------------

def test_regroup_is_all_to_all(mesh):
    # worker w holds rows laid out in destination order: block j goes to j.
    x = np.arange(N * N, dtype=np.int32).reshape(N * N, 1)
    out = run_spmd(mesh, C.regroup, x)
    out = np.asarray(out).reshape(N, N)
    blocks = np.arange(N * N).reshape(N, N)  # [src, dst]
    np.testing.assert_array_equal(out, blocks.T)  # [dst, src] after regroup


# -- rotate -----------------------------------------------------------------

@pytest.mark.parametrize("shift", [1, 2, -1])
def test_rotate(mesh, data, shift):
    out = run_spmd(mesh, lambda x: C.rotate(x, shift=shift), data)
    shards = data.reshape(N, 4, 16)
    expect = np.roll(shards, shift, axis=0)  # worker i's data lands on i+shift
    np.testing.assert_array_equal(np.asarray(out).reshape(N, 4, 16), expect)


# -- push / pull ------------------------------------------------------------

def test_push_add(mesh):
    # every worker contributes a full-size table; owners get combined blocks
    x = np.stack([np.full((N * 2, 3), w, np.float32) for w in range(N)])  # [N, rows, 3]
    x = x.reshape(N * N * 2, 3)  # stack worker contributions along leading dim
    out = run_spmd(mesh, C.push, x)
    out = np.asarray(out).reshape(N * 2, 3)
    np.testing.assert_allclose(out, np.full((N * 2, 3), sum(range(N))))


def test_pull_then_push_roundtrip(mesh, data):
    def step(shard):
        full = C.pull(shard)  # local replica of global table
        return full

    out = run_spmd(mesh, step, data, out_dim=None)
    np.testing.assert_array_equal(np.asarray(out), data)


def test_push_max(mesh):
    x = np.stack([np.full((N, 2), w, np.float32) for w in range(N)]).reshape(N * N, 2)
    out = run_spmd(mesh, lambda v: C.push(v, Combiner.MAX), x)
    np.testing.assert_allclose(np.asarray(out).reshape(N, 2), np.full((N, 2), N - 1))


# -- barrier ----------------------------------------------------------------

def test_barrier_compiles(mesh):
    out = run_spmd(mesh, lambda x: x + C.barrier().astype(x.dtype),
                   np.ones((N, 1), np.float32))
    np.testing.assert_array_equal(np.asarray(out), np.ones((N, 1)))


# -- rotation pipeline ------------------------------------------------------

def test_rotate_pipeline_full_revolution(mesh):
    """After N steps each worker has seen every slice once; slices are home."""
    slices = np.arange(N, dtype=np.float32).reshape(N, 1)

    def prog(s):
        def step(acc, cur, t):
            return acc + cur, cur

        acc, final = rotate_pipeline(step, jnp.zeros((1, 1), jnp.float32), s)
        return jnp.concatenate([acc, final], axis=0)

    out = np.asarray(run_spmd(mesh, prog, slices)).reshape(N, 2)
    np.testing.assert_allclose(out[:, 0], np.full(N, sum(range(N))))  # saw all
    np.testing.assert_allclose(out[:, 1], np.arange(N))  # slices back home


def test_rotate_pipeline_updates_travel(mesh):
    """Slice updates made mid-rotation persist when the slice returns home."""
    slices = np.zeros((N, 1), np.float32)

    def prog(s):
        def step(acc, cur, t):
            return acc, cur + 1.0  # every visitor increments the slice

        _, final = rotate_pipeline(step, jnp.zeros(()), s)
        return final

    out = np.asarray(run_spmd(mesh, prog, slices)).reshape(N)
    np.testing.assert_allclose(out, np.full(N, N))  # visited by all N workers


def test_resident_slice_index(mesh):
    def prog(x):
        idx = jnp.stack([resident_slice_index(t) for t in range(3)])
        return idx[None].astype(jnp.int32)

    out = np.asarray(run_spmd(mesh, prog, np.zeros((N, 1), np.float32)))
    out = out.reshape(N, 3)
    for w in range(N):
        for t in range(3):
            assert out[w, t] == (w - t) % N


# -- regression: review findings --------------------------------------------

def test_broadcast_ignores_nonroot_nan(mesh):
    """Non-root buffers full of NaN/inf must not poison the broadcast."""
    x = np.full((N, 2), np.nan, np.float32)
    x[0] = 7.0
    out = run_spmd(mesh, lambda v: C.broadcast(v, root=0), x, out_dim=None)
    np.testing.assert_array_equal(np.asarray(out), np.full((1, 2), 7.0))


def test_broadcast_bit_exact_on_subnormals(mesh):
    """Broadcast is data movement: subnormal payloads must survive bit-for-bit
    even though XLA CPU runs with FTZ/DAZ (a float psum would flush them)."""
    x = np.full((N, 2), 2.1e-43, np.float32)  # subnormal for f32
    x[1:] = np.nan
    out = run_spmd(mesh, lambda v: C.broadcast(v, root=0), x, out_dim=None)
    assert (np.asarray(out).view(np.uint32) == x[0].view(np.uint32)).all()


def test_broadcast_is_differentiable(mesh):
    """Autodiff through broadcast (pipeline-parallel training relies on it):
    the cotangent must flow back to the root shard, not vanish in a bitcast."""
    x = np.arange(N, dtype=np.float32)[:, None] + 1.0

    def loss(v):
        return (C.broadcast(v, root=2) ** 2).sum()

    g = run_spmd(mesh, jax.grad(loss), x, out_dim=0)
    g = np.asarray(g).reshape(N, 1)
    # d/dx_root sum_w (x_root^2) = 2*N*x_root on the root shard, 0 elsewhere
    expect = np.zeros((N, 1), np.float32)
    expect[2] = 2.0 * N * x[2]
    np.testing.assert_allclose(g, expect, rtol=1e-6)


def test_broadcast_supports_forward_mode(mesh):
    """jvp/jacfwd must work through broadcast too (custom_jvp, not
    custom_vjp — the latter rejects forward-mode)."""
    x = np.arange(N, dtype=np.float32)[:, None] + 1.0

    def f(v):
        return C.broadcast(v, root=1) * 2.0

    def jvp_fn(v):
        _, tang = jax.jvp(f, (v,), (jnp.ones_like(v),))
        return tang

    t = run_spmd(mesh, jvp_fn, x, out_dim=None)
    np.testing.assert_allclose(np.asarray(t), np.full((1, 1), 2.0), rtol=1e-6)


def test_broadcast_float8_traces(mesh):
    """1-byte floats ride the uint8 bitcast path (pytree-polymorphic contract)."""
    x = np.arange(N, dtype=np.float32)[:, None]
    out = run_spmd(
        mesh,
        lambda v: C.broadcast(v.astype(jnp.float8_e4m3fn), root=3).astype(jnp.float32),
        x, out_dim=None)
    np.testing.assert_array_equal(np.asarray(out), np.full((1, 1), 3.0))


def test_reduce_inf_safe_on_nonroot(mesh):
    x = np.full((N, 2), np.inf, np.float32)
    out = run_spmd(mesh, lambda v: C.reduce(v, Combiner.MAX, root=0)[None], x, out_dim=0)
    out = np.asarray(out).reshape(N, 2)
    assert np.all(np.isinf(out[0])) and np.all(out[1:] == 0)


def test_push_max_nondivisible_raises(mesh):
    x = np.ones((N * 10, 2), np.float32)  # 10 rows/worker, not divisible by 8
    with pytest.raises(ValueError, match="divisible"):
        run_spmd(mesh, lambda v: C.push(v, Combiner.MAX), x)


def test_bool_dtype_preserved(mesh):
    x = np.array([False, True] + [False] * (N - 2))[:, None]
    out = run_spmd(mesh, lambda v: C.broadcast(v, root=1), x, out_dim=None)
    assert np.asarray(out).dtype == np.bool_ and bool(np.asarray(out)[0, 0])


def test_allreduce_bool_dtype_preserved(mesh):
    x = np.array([True] * N)[:, None]
    out = run_spmd(mesh, lambda v: C.allreduce(v, Combiner.MIN), x, out_dim=None)
    assert np.asarray(out).dtype == np.bool_ and bool(np.asarray(out)[0, 0])


def test_rotate_pipeline_rejects_partial_coverage_shift(mesh):
    def prog(s):
        _, final = rotate_pipeline(lambda a, c, t: (a, c), jnp.zeros(()), s, shift=2)
        return final

    with pytest.raises(ValueError, match="shares a factor"):
        run_spmd(mesh, prog, np.zeros((N, 1), np.float32))


def test_allreduce_quantized_bf16(mesh):
    x = np.linspace(-3, 3, N * 8, dtype=np.float32).reshape(N, 8)
    out = run_spmd(mesh, lambda v: C.allreduce_quantized(v), x, out_dim=None)
    np.testing.assert_allclose(np.asarray(out)[0], x.sum(0), rtol=2e-2, atol=1e-2)
    assert np.asarray(out).dtype == np.float32


def test_allreduce_quantized_int8(mesh):
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    x = rng.normal(size=(N, 64)).astype(np.float32)
    out = run_spmd(
        mesh, lambda v: C.allreduce_quantized(v, wire_dtype=jnp.int8),
        x, out_dim=None)
    ref = x.sum(0)
    # per-worker error ≤ scale/2 with scale = max|x|/127; N workers add up
    tol = N * np.abs(x).max() / 127.0 / 2 + 1e-6
    assert np.abs(np.asarray(out)[0] - ref).max() <= tol


def test_allreduce_quantized_int_leaves_exact(mesh):
    x = np.arange(N * 4, dtype=np.int32).reshape(N, 4)
    out = run_spmd(mesh, lambda v: C.allreduce_quantized(v), x, out_dim=None)
    np.testing.assert_array_equal(np.asarray(out)[0], x.sum(0))


def test_allreduce_quantized_rejects_unknown_wire(mesh):
    import jax.numpy as jnp

    x = np.ones((N, 4), np.float32)
    with pytest.raises(ValueError, match="wire_dtype"):
        run_spmd(mesh, lambda v: C.allreduce_quantized(v, wire_dtype=jnp.float16),
                 x, out_dim=None)


def test_allreduce_quantized_bool_stays_bool(mesh):
    import jax.numpy as jnp

    tree = {"g": np.ones((N, 8), np.float32),
            "flag": np.zeros((N, 1), bool)}
    tree["flag"][2] = True
    out = run_spmd(
        mesh, lambda t: C.allreduce_quantized(t, wire_dtype=jnp.int8),
        tree, out_dim=None)
    assert np.asarray(out["flag"]).dtype == np.bool_
    assert bool(np.asarray(out["flag"])[0, 0])  # ADD on bool == any


def test_allreduce_quantized_int8_one_pmax_for_tree(mesh):
    """All leaves' scales ride a single fused pmax collective."""
    import jax
    import jax.numpy as jnp

    tree = {chr(97 + i): np.ones((N, 4), np.float32) * (i + 1)
            for i in range(6)}
    fn = jax.jit(mesh.shard_map(
        lambda t: C.allreduce_quantized(t, wire_dtype=jnp.int8),
        in_specs=(jax.tree.map(lambda _: mesh.spec(0), tree),),
        out_specs=jax.tree.map(lambda _: P(), tree)))
    txt = fn.lower(tree).compile().as_text()
    # count all-reduce ops with MAX reductions: must be 1, not 6
    n_max_ar = sum(1 for line in txt.splitlines()
                   if "all-reduce" in line and "max" in line.lower()
                   and "=" in line)
    assert n_max_ar <= 1, n_max_ar
    out = fn(tree)
    for i, k in enumerate(sorted(tree)):
        np.testing.assert_allclose(np.asarray(out[k]),
                                   np.full((1, 4), N * (i + 1.0)), rtol=0.02)


def test_push_quantized_bf16(mesh):
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    # per worker a [N*4] contribution; push scatters tiled → [4] per worker
    x = rng.normal(size=(N, N * 4)).astype(np.float32)
    out = run_spmd(mesh, lambda v: C.push_quantized(v.reshape(-1)),
                   x, out_dim=0)
    ref = x.sum(0).reshape(N, 4)  # worker w owns rows [w*4, (w+1)*4)
    np.testing.assert_allclose(np.asarray(out).reshape(N, 4), ref,
                               rtol=2e-2, atol=2e-2)


def test_push_quantized_int8_matches_exact_within_scale(mesh):
    import jax.numpy as jnp

    rng = np.random.default_rng(4)
    x = rng.normal(size=(N, N * 8)).astype(np.float32)
    out = run_spmd(
        mesh, lambda v: C.push_quantized(v.reshape(-1), wire_dtype=jnp.int8),
        x, out_dim=0)
    ref = x.sum(0).reshape(N, 8)
    tol = N * np.abs(x).max() / 127.0 / 2 + 1e-6
    assert np.abs(np.asarray(out).reshape(N, 8) - ref).max() <= tol


def test_push_quantized_int_leaves_exact(mesh):
    x = np.arange(N * N * 2, dtype=np.int32).reshape(N, N * 2)
    out = run_spmd(mesh, lambda v: C.push_quantized(v.reshape(-1)),
                   x, out_dim=0)
    np.testing.assert_array_equal(np.asarray(out).reshape(N, 2),
                                  x.sum(0).reshape(N, 2))


def test_push_quantized_bool_leaves_match_allreduce_twin(mesh):
    # ADVICE r3 (collective.py:181): the docstring promises bool leaves the
    # same exact-ADD semantics as allreduce_quantized (int32 round-trip,
    # back to bool = scattered OR); raw psum_scatter of bool would fail or
    # mis-reduce instead
    x = np.zeros((N, N * 2), np.bool_)
    x[0, :] = True          # worker 0 contributes True everywhere
    x[1, ::2] = True        # worker 1 overlaps on even slots
    out = run_spmd(mesh, lambda v: C.push_quantized(v.reshape(-1)),
                   x, out_dim=0)
    got = np.asarray(out).reshape(N, 2)
    assert got.dtype == np.bool_
    np.testing.assert_array_equal(got, x.sum(0).reshape(N, 2) > 0)


def test_push_quantized_rejects_unknown_wire(mesh):
    import jax.numpy as jnp

    x = np.ones((N, N), np.float32)
    with pytest.raises(ValueError, match="wire_dtype"):
        run_spmd(mesh,
                 lambda v: C.push_quantized(v.reshape(-1),
                                            wire_dtype=jnp.float16),
                 x, out_dim=0)
