"""Test harness: multi-worker simulation on host CPU.

Harp's test story was "pseudo-distributed Hadoop on localhost — real sockets
over loopback" (SURVEY.md §5).  Our analogue: 8 simulated XLA CPU devices in
one process, so every collective runs through the real shard_map/collective
code path with no mocks.  (The axon site config pins JAX_PLATFORMS=axon, so
the platform override must go through jax.config, before any backend use.)
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from harp_tpu.parallel.mesh import WorkerMesh, set_mesh  # noqa: E402


@pytest.fixture(scope="session")
def mesh() -> WorkerMesh:
    m = WorkerMesh()
    assert m.num_workers == 8, f"expected 8 simulated workers, got {m.num_workers}"
    set_mesh(m)
    return m
