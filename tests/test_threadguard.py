"""threadguard (PR 20) — the runtime twin of harplint Layer 5.

Four contracts pinned here: (1) the ownership map the guard arms is
GENERATED from the static thread-root graph and matches the names real
threads actually run under (the sync pin — hand-editing either side
breaks a test); (2) armed, a forbidden thread is caught at every
flightrec observer site and at every unlocked-spine mutator, while the
whole serve plane under chaos (real socket, injected dispatch faults)
runs clean; (3) disarmed, NOTHING is installed — observer lists and
spine callables restore to the exact originals; (4) the flagship
budgets are bit-identical with the guard armed (the PR-3 pattern).
"""

import fnmatch
import json
import os
import sys
import threading

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import check_jsonl  # noqa: E402
from harp_tpu.analysis import threadgraph  # noqa: E402
from harp_tpu.serve.engines import ENGINES  # noqa: E402
from harp_tpu.serve.server import Server  # noqa: E402
from harp_tpu.utils import flightrec, reqtrace, telemetry  # noqa: E402
from harp_tpu.utils import threadguard  # noqa: E402
from harp_tpu.utils.threadguard import ThreadOwnershipError  # noqa: E402


def _run_named(name, fn):
    """Run ``fn`` on a thread named ``name``; return the exception it
    raised (or None)."""
    box = []

    def run():
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - re-raised by caller
            box.append(e)

    t = threading.Thread(target=run, name=name, daemon=True)
    t.start()
    t.join(30)
    return box[0] if box else None


# ---------------------------------------------------------------------------
# The guard itself: observer sites + unlocked-spine mutators
# ---------------------------------------------------------------------------

def test_forbidden_thread_caught_at_observer_site():
    """A thread matching a forbidden pattern trips the guard the moment
    it crosses a flightrec observer site; the same op from main (an
    owner everywhere) is clean."""
    omap = {"forbidden_thread_patterns": ["evil-*"], "spines": {}}
    with threadguard.armed(omap) as g:
        flightrec.readback(jnp.zeros(2))          # main: allowed
        before = g.checks
        err = _run_named("evil-1",
                         lambda: flightrec.readback(jnp.zeros(2)))
        assert isinstance(err, ThreadOwnershipError)
        assert "evil-1" in str(err) and "evil-*" in str(err)
        assert g.checks >= before + 1
        assert g.violations
    assert threadguard.stats()["active"] is False


def test_forbidden_thread_caught_at_unlocked_spine_mutator():
    """A spine the static layer could NOT verify as locked gets its
    mutators wrapped: a forbidden thread writing it raises BEFORE the
    write lands."""
    omap = {"forbidden_thread_patterns": ["evil-*"],
            "spines": {"comm_ledger": {
                "locked": False, "module": "harp_tpu.utils.telemetry",
                "obj": "ledger", "mutators": ["record"]}}}
    with telemetry.scope(True):
        with threadguard.armed(omap):
            telemetry.ledger.record("allreduce", jnp.zeros(4),
                                    axis="workers")  # main: allowed
            before = str(telemetry.ledger._tags)
            err = _run_named(
                "evil-2",
                lambda: telemetry.ledger.record(
                    "allreduce", jnp.zeros(4), axis="workers"))
            assert isinstance(err, ThreadOwnershipError)
            assert "comm_ledger.record" in str(err)
            assert str(telemetry.ledger._tags) == before  # write rejected
    # restored: the spine records unguarded again
    assert telemetry.ledger.record.__name__ == "record"


def test_verified_locked_spine_is_not_wrapped():
    """THE asymmetry sync pin: the runtime honors the static lock
    verdicts — reqtrace (verified RLocked at HEAD) keeps its original
    mutators while unlocked spines are wrapped."""
    omap = threadgraph.ownership_map(ROOT)
    assert omap["spines"]["reqtrace"]["locked"] is True
    orig_begin = reqtrace.tracer.begin
    orig_record = telemetry.ledger.record
    with threadguard.armed():
        assert reqtrace.tracer.begin == orig_begin       # untouched
        assert telemetry.ledger.record != orig_record    # wrapped
        for sp_name, sp in omap["spines"].items():
            if sp["locked"]:
                continue
            mod = __import__(sp["module"], fromlist=["_"])
            target = getattr(mod, sp["obj"]) if sp["obj"] else mod
            for mut in sp["mutators"]:
                assert getattr(target, mut).__wrapped__ is not None
    assert telemetry.ledger.record == orig_record        # restored


def test_disarmed_installs_nothing():
    """The zero-cost contract: before arm and after disarm the observer
    registries hold exactly what they held, and every spine callable is
    the exact original (identity, not equality-of-behavior)."""
    registries = (flightrec._READBACK_OBSERVERS,
                  flightrec._DISPATCH_OBSERVERS,
                  flightrec._H2D_OBSERVERS,
                  flightrec._CKPT_WRITE_OBSERVERS)
    before = [list(r) for r in registries]
    orig = (flightrec.record_h2d, flightrec.record_readback,
            flightrec.record_bucket, telemetry.ledger.record)
    with threadguard.armed():
        assert all(len(r) == len(b) + 1
                   for r, b in zip(registries, before))
    assert [list(r) for r in registries] == before
    assert (flightrec.record_h2d, flightrec.record_readback,
            flightrec.record_bucket) == orig[:3]
    assert flightrec.record_h2d is orig[0]
    assert telemetry.ledger.record == orig[3]
    assert threadguard.stats()["active"] is False
    assert threadguard.stats()["patterns"] == []


def test_arm_is_idempotent_and_disarm_total():
    with threadguard.armed() as g:
        threadguard.arm()                   # second arm: no double-wrap
        assert len(flightrec._READBACK_OBSERVERS) == 1
        flightrec.readback(jnp.zeros(1))
        assert g.checks >= 1
    assert flightrec._READBACK_OBSERVERS == []


# ---------------------------------------------------------------------------
# Sync pins: static map <-> the names real threads run under
# ---------------------------------------------------------------------------

def test_scheduler_worker_names_match_the_static_patterns():
    """The f-string thread names in schedule.py and the patterns the
    graph extracted from them must agree — renaming either side without
    the other fails here."""
    from harp_tpu.schedule import DynamicScheduler, StaticScheduler

    pats = threadgraph.ownership_map(ROOT)["forbidden_thread_patterns"]
    seen = []
    StaticScheduler(lambda x: seen.append(
        threading.current_thread().name), n_threads=2).schedule([1, 2])
    DynamicScheduler(lambda x: seen.append(
        threading.current_thread().name), n_threads=2).schedule([1, 2])
    assert len(seen) == 4
    for name in seen:
        assert any(fnmatch.fnmatch(name, p) for p in pats), (name, pats)


def test_watchdog_timer_name_matches_the_static_pattern():
    from harp_tpu.utils.timing import HangWatchdog

    pats = threadgraph.ownership_map(ROOT)["forbidden_thread_patterns"]
    wd = HangWatchdog(timeout_s=600, _exit=lambda code: None)
    wd.arm("sync-pin")
    try:
        assert wd._timer.name == "harp-watchdog"
        assert any(fnmatch.fnmatch(wd._timer.name, p) for p in pats)
    finally:
        wd.cancel()


# ---------------------------------------------------------------------------
# THE chaos drill: real socket, injected faults, guard armed
# ---------------------------------------------------------------------------

def test_tcp_chaos_serve_runs_clean_with_guard_armed(mesh, tmp_path):
    """The acceptance run: a real-socket TCP serve under injected
    transient dispatch faults with the guard ARMED — zero ownership
    violations (the dispatcher owns jax; the accept loop, forbidden,
    never crosses a guarded site), the guard non-vacuously checked, the
    invariant-9 ledger reconciles, and the exported request timeline is
    invariant-11 clean.  Along the way the serve plane's live thread
    names are pinned to the static map: the TCP loop IS forbidden, the
    dispatcher is NOT."""
    import socket

    from harp_tpu.serve.transport import TCPFrontEnd
    from harp_tpu.utils.fault import FaultInjector

    pats = threadgraph.ownership_map(ROOT)["forbidden_thread_patterns"]
    rng = np.random.default_rng(20)
    with telemetry.scope(True):
        state = ENGINES["kmeans"].synthetic_state(rng, k=8, d=16)
        srv = Server("kmeans", state=state, mesh=mesh, ladder=(1, 8),
                     cache_dir=str(tmp_path / "aot"),
                     budget_action="warn")
        srv.startup()
        inj = FaultInjector(seed=0, fail={"dispatch": (2,)})
        with threadguard.armed() as g, inj.arm():
            fe = TCPFrontEnd(srv, port=0, max_retries=2).start_in_thread()
            try:
                live = {t.name for t in threading.enumerate()}
                assert "harp-serve-tcp" in live
                assert "harp-serve-dispatch" in live
                assert any(fnmatch.fnmatch("harp-serve-tcp", p)
                           for p in pats)
                assert not any(fnmatch.fnmatch("harp-serve-dispatch", p)
                               for p in pats)
                s = socket.create_connection(("127.0.0.1", fe.port),
                                             timeout=60)
                f = s.makefile("rw")
                xs = [rng.normal(size=(1 + i % 3, 16)).astype(np.float32)
                      for i in range(12)]
                for i, x in enumerate(xs):
                    f.write(json.dumps({"id": i, "x": x.tolist()}) + "\n")
                f.flush()
                got = [json.loads(f.readline()) for _ in range(12)]
                s.close()
            finally:
                fe.shutdown()
                fe.join(60)
        assert inj.injected["dispatch"] == 1      # chaos actually ran
        assert fe.runner.fault_retries >= 1
        cent = state["centroids"]
        for r, x in zip(got, xs):
            ref = np.argmin(((x[:, None, :] - cent[None]) ** 2).sum(-1), 1)
            assert r["result"] == ref.tolist()
        # the guard saw real traffic and objected to none of it
        assert g.checks > 0
        assert g.violations == []
        # invariant 9: every offered request terminated exactly once
        # (served rides the reqtrace ledger; shed/failed on the runner)
        rs = fe.runner
        tr = reqtrace.tracer
        assert tr.counts["served"] + rs.shed + rs.failed == 12
        assert tr.counts["served"] == 12 and tr.summary()["open"] == 0
        p = tmp_path / "chaos.jsonl"
        telemetry.export_timeline(str(p))
    assert check_jsonl.check_file(str(p)) == []


# ---------------------------------------------------------------------------
# Flagship budget pins: armed guard costs no flight traffic
# ---------------------------------------------------------------------------

def test_flagship_budget_pin_unchanged_with_guard_armed(mesh):
    """The PR-3 flagship budget — 1 dispatch, 1 stacked readback, 0
    steady compiles, 0 H2D — must hold bit-for-bit with the ownership
    guard armed: checks run, traffic does not change."""
    import harp_tpu.models.mfsgd as MF

    cfg = MF.MFSGDConfig(rank=4, algo="dense", u_tile=8, i_tile=8,
                         entry_cap=32)
    with telemetry.scope():
        m = MF.MFSGD(64, 48, cfg, mesh, seed=3)
        u, i, v = MF.synthetic_ratings(64, 48, 600, rank=4, seed=3)
        m.set_ratings(u, i, v)
        m.train_epoch()       # warmup
        m.compile_epochs(3)
        m.train_epochs(3)     # steady (stacked-readback ops compiled)
        with threadguard.armed() as g:
            with flightrec.budget(compiles=0, dispatches=1, readbacks=1,
                                  h2d_bytes=0,
                                  tag="mfsgd.train_epochs.guard") as b:
                m.train_epochs(3)
        assert b.spent()["dispatches"] == 1
        assert b.spent()["readbacks"] == 1
        assert g.checks > 0               # the guard actually audited
        assert g.violations == []
