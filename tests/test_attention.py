"""Ring attention (multi-worker) and Pallas flash attention (interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from harp_tpu.ops.a2a_attention import make_a2a_attention_fn
from harp_tpu.ops.flash_attention import flash_attention, reference_attention
from harp_tpu.ops.ring_attention import make_ring_attention_fn


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(mesh, causal):
    rng = np.random.default_rng(0)
    b, n, h, d = 2, 64, 4, 16  # n sharded over 8 workers → 8 per worker
    q, k, v = (rng.normal(size=(b, n, h, d)).astype(np.float32) for _ in range(3))
    fn = make_ring_attention_fn(mesh, causal=causal)
    out = np.asarray(fn(q, k, v))

    # reference: full attention, fold heads
    qf = jnp.asarray(q).transpose(0, 2, 1, 3).reshape(b * h, n, d)
    kf = jnp.asarray(k).transpose(0, 2, 1, 3).reshape(b * h, n, d)
    vf = jnp.asarray(v).transpose(0, 2, 1, 3).reshape(b * h, n, d)
    ref = np.asarray(reference_attention(qf, kf, vf, causal=causal))
    ref = ref.reshape(b, h, n, d).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_k", [None, 16])
def test_a2a_attention_matches_full(mesh, causal, block_k):
    """Ulysses all-to-all sequence parallelism == dense reference."""
    rng = np.random.default_rng(2)
    b, n, h, d = 2, 64, 8, 16  # 8 heads over 8 workers → 1 head each
    q, k, v = (rng.normal(size=(b, n, h, d)).astype(np.float32) for _ in range(3))
    fn = make_a2a_attention_fn(mesh, causal=causal, block_k=block_k)
    out = np.asarray(fn(q, k, v))

    qf = jnp.asarray(q).transpose(0, 2, 1, 3).reshape(b * h, n, d)
    kf = jnp.asarray(k).transpose(0, 2, 1, 3).reshape(b * h, n, d)
    vf = jnp.asarray(v).transpose(0, 2, 1, 3).reshape(b * h, n, d)
    ref = np.asarray(reference_attention(qf, kf, vf, causal=causal))
    ref = ref.reshape(b, h, n, d).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_a2a_attention_rejects_indivisible_heads(mesh):
    rng = np.random.default_rng(3)
    q = rng.normal(size=(1, 64, 6, 8)).astype(np.float32)  # 6 heads, 8 workers
    fn = make_a2a_attention_fn(mesh)
    with pytest.raises(ValueError, match="divisible"):
        fn(q, q, q)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_interpret(causal):
    rng = np.random.default_rng(1)
    bh, n, d = 3, 128, 32
    q, k, v = (rng.normal(size=(bh, n, d)).astype(np.float32) for _ in range(3))
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=causal, block_q=32, block_k=32, interpret=True)
    ref = reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_rejects_ragged_blocks():
    q = jnp.zeros((1, 100, 16))
    with pytest.raises(AssertionError):
        flash_attention(q, q, q, block_q=32, block_k=32, interpret=True)


def _gqa_ref(q, k, v, causal):
    """Dense GQA reference: repeat KV heads up to H, fold heads, attend."""
    b, n, h, d = q.shape
    g = k.shape[2]
    kf = np.repeat(k, h // g, axis=2)
    vf = np.repeat(v, h // g, axis=2)
    qf = jnp.asarray(q).transpose(0, 2, 1, 3).reshape(b * h, n, d)
    kf = jnp.asarray(kf).transpose(0, 2, 1, 3).reshape(b * h, n, d)
    vf = jnp.asarray(vf).transpose(0, 2, 1, 3).reshape(b * h, n, d)
    ref = np.asarray(reference_attention(qf, kf, vf, causal=causal))
    return ref.reshape(b, h, n, d).transpose(0, 2, 1, 3)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("g", [1, 2])  # MQA and 2-group GQA
def test_ring_attention_gqa(mesh, causal, g):
    """K/V ride the ring with g heads; output == dense GQA reference."""
    rng = np.random.default_rng(4)
    b, n, h, d = 2, 64, 4, 16
    q = rng.normal(size=(b, n, h, d)).astype(np.float32)
    k = rng.normal(size=(b, n, g, d)).astype(np.float32)
    v = rng.normal(size=(b, n, g, d)).astype(np.float32)
    out = np.asarray(make_ring_attention_fn(mesh, causal=causal)(q, k, v))
    np.testing.assert_allclose(out, _gqa_ref(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_a2a_attention_gqa(mesh, causal):
    """Ulysses reshards the smaller KV head dim too (g=8 over 8 workers)."""
    rng = np.random.default_rng(5)
    b, n, h, d = 2, 64, 16, 8
    q = rng.normal(size=(b, n, h, d)).astype(np.float32)
    k = rng.normal(size=(b, n, 8, d)).astype(np.float32)
    v = rng.normal(size=(b, n, 8, d)).astype(np.float32)
    out = np.asarray(make_a2a_attention_fn(mesh, causal=causal)(q, k, v))
    np.testing.assert_allclose(out, _gqa_ref(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


def test_attention_gqa_rejects_bad_group(mesh):
    rng = np.random.default_rng(6)
    q = rng.normal(size=(1, 64, 4, 8)).astype(np.float32)
    k = rng.normal(size=(1, 64, 3, 8)).astype(np.float32)  # 3 ∤ 4
    with pytest.raises(ValueError, match="multiple of KV heads"):
        make_ring_attention_fn(mesh)(q, k, k)
    # a2a: g=2 divides h=16 but not the 8 workers
    q2 = rng.normal(size=(1, 64, 16, 8)).astype(np.float32)
    k2 = rng.normal(size=(1, 64, 2, 8)).astype(np.float32)
    with pytest.raises(ValueError, match="KV heads"):
        make_a2a_attention_fn(mesh)(q2, k2, k2)


@pytest.mark.parametrize("scheme", ["ring", "a2a"])
@pytest.mark.parametrize("window", [None, 12])
def test_attention_gradients_match_dense(mesh, scheme, window):
    """Training through sequence-parallel attention: grads w.r.t. q/k/v via
    autodiff (through the ppermute ring / all_to_alls) == dense grads."""
    from harp_tpu.ops.a2a_attention import a2a_attention
    from harp_tpu.ops.ring_attention import ring_attention

    rng = np.random.default_rng(7)
    b, n, h, d = 1, 64, 8, 8
    q, k, v = (rng.normal(size=(b, n, h, d)).astype(np.float32)
               for _ in range(3))
    attn = ring_attention if scheme == "ring" else a2a_attention
    spec = mesh.spec(1, ndim=4)

    def loss(q, k, v):
        return (attn(q, k, v, causal=True, window=window) ** 2).sum()

    gq, gk, gv = jax.jit(mesh.shard_map(
        lambda q, k, v: jax.grad(loss, argnums=(0, 1, 2))(q, k, v),
        in_specs=(spec,) * 3, out_specs=(spec,) * 3))(q, k, v)

    def dense_loss(q, k, v):
        qf = q.transpose(0, 2, 1, 3).reshape(b * h, n, d)
        kf = k.transpose(0, 2, 1, 3).reshape(b * h, n, d)
        vf = v.transpose(0, 2, 1, 3).reshape(b * h, n, d)
        s = jnp.einsum("bqd,bkd->bqk", qf, kf) / (d ** 0.5)
        delta = jnp.arange(n)[:, None] - jnp.arange(n)[None, :]
        mask = delta >= 0
        if window is not None:
            mask = mask & (delta < window)
        s = jnp.where(mask[None], s, -jnp.inf)
        o = jnp.einsum("bqk,bkd->bqd", jax.nn.softmax(s, axis=-1), vf)
        return (o ** 2).sum()

    ref = jax.grad(dense_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, r in zip((gq, gk, gv), ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=5e-3, atol=5e-4)


def _windowed_ref(q, k, v, causal, window):
    """Dense sliding-window reference with the documented mask contract."""
    b, n, h, d = q.shape
    scale = 1.0 / (q.shape[-1] ** 0.5)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, n, d).astype(np.float64)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, n, d).astype(np.float64)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, n, d).astype(np.float64)
    s = np.einsum("bqd,bkd->bqk", qf, kf) * scale
    delta = np.arange(n)[:, None] - np.arange(n)[None, :]
    mask = np.ones((n, n), bool)
    if causal:
        mask &= delta >= 0
    mask &= (delta < window) if causal else (np.abs(delta) < window)
    s = np.where(mask[None], s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    out = np.einsum("bqk,bkd->bqd", p, vf)
    return out.reshape(b, h, n, d).transpose(0, 2, 1, 3).astype(np.float32)


@pytest.mark.parametrize("scheme", ["ring", "a2a"])
@pytest.mark.parametrize("causal", [False, True])
def test_sliding_window_attention(mesh, scheme, causal):
    """window spanning worker boundaries == dense windowed reference."""
    rng = np.random.default_rng(8)
    b, n, h, d = 1, 64, 8, 8
    q, k, v = (rng.normal(size=(b, n, h, d)).astype(np.float32)
               for _ in range(3))
    window = 12  # crosses the 8-token worker shards
    make = make_ring_attention_fn if scheme == "ring" else make_a2a_attention_fn
    out = np.asarray(make(mesh, causal=causal, window=window)(q, k, v))
    np.testing.assert_allclose(out, _windowed_ref(q, k, v, causal, window),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_sliding_window_with_block_k(mesh, causal):
    """a2a windowed attention with multi-block K/V (fully-masked blocks in
    the scan) still matches the dense windowed reference."""
    rng = np.random.default_rng(9)
    b, n, h, d = 1, 64, 8, 8
    q, k, v = (rng.normal(size=(b, n, h, d)).astype(np.float32)
               for _ in range(3))
    out = np.asarray(make_a2a_attention_fn(
        mesh, causal=causal, window=10, block_k=16)(q, k, v))
    np.testing.assert_allclose(out, _windowed_ref(q, k, v, causal, 10),
                               rtol=2e-4, atol=2e-5)


def test_window_zero_rejected(mesh):
    rng = np.random.default_rng(10)
    q = rng.normal(size=(1, 64, 8, 8)).astype(np.float32)
    with pytest.raises(ValueError, match="window must be >= 1"):
        make_ring_attention_fn(mesh, window=0)(q, q, q)
    with pytest.raises(ValueError, match="window must be >= 1"):
        make_a2a_attention_fn(mesh, window=0)(q, q, q)


def test_sharded_rope_matches_full_array(mesh):
    """RoPE over 8 sequence shards (global positions from the worker index)
    == RoPE applied to the unsharded array."""
    from harp_tpu.ops.rope import apply_rope, make_rope_fn, rope_angles

    rng = np.random.default_rng(11)
    b, n, h, d = 2, 64, 4, 16
    x = rng.normal(size=(b, n, h, d)).astype(np.float32)
    out = np.asarray(make_rope_fn(mesh)(x))

    cos, sin = rope_angles(jnp.arange(n), d)
    cos, sin = np.asarray(cos), np.asarray(sin)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    ref = np.stack([x1 * c - x2 * s, x1 * s + x2 * c], -1).reshape(b, n, h, d)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)

    # rotation preserves norms (sanity of the pairing/reshape)
    np.testing.assert_allclose(np.linalg.norm(out, axis=-1),
                               np.linalg.norm(x, axis=-1), rtol=2e-5)

    with pytest.raises(ValueError, match="even head_dim"):
        rope_angles(jnp.arange(4), 7)


def test_rope_attention_shift_consistency(mesh):
    """The RoPE+causal-ring pipeline is usable end to end: rotating q/k
    before ring attention runs and yields finite, position-dependent out."""
    from harp_tpu.ops.ring_attention import ring_attention
    from harp_tpu.ops.rope import apply_rope

    rng = np.random.default_rng(12)
    b, n, h, d = 1, 64, 2, 8
    q, k, v = (rng.normal(size=(b, n, h, d)).astype(np.float32)
               for _ in range(3))
    spec = mesh.spec(1, ndim=4)

    def prog(q, k, v):
        return ring_attention(apply_rope(q), apply_rope(k), v, causal=True)

    out = np.asarray(jax.jit(mesh.shard_map(
        prog, in_specs=(spec,) * 3, out_specs=spec))(q, k, v))
    assert np.isfinite(out).all()
    # without RoPE the first token's output equals v[0]; with RoPE too
    # (single attendable key) — but later rows must differ from no-RoPE
    def prog2(q, k, v):
        return ring_attention(q, k, v, causal=True)
    base = np.asarray(jax.jit(mesh.shard_map(
        prog2, in_specs=(spec,) * 3, out_specs=spec))(q, k, v))
    assert not np.allclose(out[0, -1], base[0, -1])


def test_rope_scores_depend_only_on_relative_position():
    """The RoPE invariant: ⟨rope_p(q), rope_k(k)⟩ is a function of p−k
    alone — the property that makes shard-local global-position rotation
    equivalent to any consistent position offset."""
    from harp_tpu.ops.rope import rope_angles

    rng = np.random.default_rng(13)
    d = 16
    q = rng.normal(size=d).astype(np.float64)
    k = rng.normal(size=d).astype(np.float64)

    def rot(x, p):
        cos, sin = rope_angles(jnp.asarray([p]), d)
        c, s = np.asarray(cos, np.float64)[0], np.asarray(sin, np.float64)[0]
        x1, x2 = x[0::2], x[1::2]
        out = np.empty_like(x)
        out[0::2] = x1 * c - x2 * s
        out[1::2] = x1 * s + x2 * c
        return out

    # same relative offset (5), different absolute positions
    s1 = rot(q, 9) @ rot(k, 4)
    s2 = rot(q, 104) @ rot(k, 99)
    np.testing.assert_allclose(s1, s2, rtol=1e-6)
    # different offsets disagree (the invariant is not a constant)
    s3 = rot(q, 9) @ rot(k, 2)
    assert abs(s1 - s3) > 1e-6


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_window_interpret(causal):
    """Pallas kernel with a sliding window (block skipping + in-block mask)
    == the dense windowed reference, in interpret mode."""
    rng = np.random.default_rng(14)
    bh, n, d = 2, 128, 16
    q, k, v = (rng.normal(size=(bh, n, d)).astype(np.float32)
               for _ in range(3))
    out = np.asarray(flash_attention(q, k, v, causal=causal, window=20,
                                     block_q=32, block_k=32, interpret=True))
    ref = np.asarray(reference_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        causal=causal, window=20))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)
