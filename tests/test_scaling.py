"""Scaling-evidence tooling (VERDICT r4 item 5): sweep + projection.

The sweep's absolute CPU rates are explicitly non-predictive (1-core
host serializes the simulated devices); what these tests pin is the
MACHINERY — cells run and emit well-formed rows with a collective-op
share, and the projection emits an (app × N) grid with efficiencies
that are probabilities and rotation comm that hides under compute at
the graded shapes.
"""

import importlib.util
import json
import math
import os

import pytest

_HERE = os.path.dirname(__file__)


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_HERE, "..", "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_sweep_shapes_cover_every_app_and_divide():
    ss = _load("scaling_sweep")
    for app in ss.APPS:
        assert app in ss.RATE_KEYS
        for mode in ("strong", "weak"):
            for n in (1, 2, 4, 8):
                kw = ss.shapes(app, mode, n)
                first = next(iter(kw.values()))
                assert first % n == 0, (app, mode, n, kw)
    # strong mode: total work must not depend on n
    assert ss.shapes("kmeans", "strong", 1) == ss.shapes("kmeans", "strong", 8)
    assert ss.shapes("lda", "weak", 8)["n_docs"] == \
        8 * ss.shapes("lda", "weak", 1)["n_docs"]


def test_sweep_child_emits_row_with_comm_share(mesh):
    # subgraph is the fastest cell (~0.1 s); conftest pins 8 devices, so
    # the in-process child must be asked for exactly 8 workers
    ss = _load("scaling_sweep")
    lines = []
    ss.child("subgraph", "strong", 8,
             emit=lambda line, **kw: lines.append(line))
    row = json.loads(lines[-1])
    assert row["app"] == "subgraph" and row["n_workers"] == 8
    assert row["rate"] > 0 and row["traced_sec"] > 0
    assert 0.0 <= row["comm_fraction"] <= 1.0
    assert row["cpu_sim"] is True  # the non-predictive marker


def test_projection_grid_is_complete_and_sane():
    ps = _load("project_scaling")
    rows = ps.project()
    apps = {r["app"] for r in rows}
    assert apps == {"kmeans", "kmeans_stream_1b", "mfsgd", "lda", "mlp",
                    "subgraph", "rf"}
    for r in rows:
        assert 0.0 < r["efficiency"] <= 1.0, r
        assert r["projected"] > 0
        assert r["measured_date"], r  # every projection cites a dated rate
        assert "ICI" in r["assumptions"]
    # rotation comm must hide under compute at the graded shapes: the
    # lda slice hop (200 MB/N at 90 GB/s) is ~200x under the compute
    # step — if a model change breaks the double-buffer accounting,
    # these drop below 1 and the BASELINE.md table is stale
    for r in rows:
        if r["pattern"] == "rotate":
            assert r["efficiency"] == pytest.approx(1.0), r
    # the one real cliff: small-problem kmeans goes latency-bound by 32
    km = {r["n_workers"]: r for r in rows if r["app"] == "kmeans"}
    assert km[32]["efficiency"] < km[4]["efficiency"]


def test_projection_ring_bytes_formula():
    ps = _load("project_scaling")
    assert ps.ring_bytes(100.0, 1) == 0.0        # 1 worker: no wire
    assert ps.ring_bytes(100.0, 2) == pytest.approx(100.0)
    assert ps.ring_bytes(100.0, 32) == pytest.approx(2 * 31 / 32 * 100)
    # allgather forwards every OTHER chip's shard: (n-1)·S, not the
    # allreduce 2(n-1)/n — review finding, round 5
    assert ps.allgather_bytes(100.0, 32) == pytest.approx(31 * 100.0)
    # ring allreduce = reduce-scatter (n-1 hops) + allgather (n-1 hops)
    assert ps.ring_hops(32) == 62
    assert math.isclose(ps.t_wire(90e9, 0), 1.0)  # 1 s at 90 GB/s


def test_projection_north_star_is_absolute_rate():
    # the 1B row's projected value is iter/s ON THE 1B PROBLEM — the
    # review-caught 10x inflation (rate1·n·eff at the measured 100M
    # shape) would put N=32 above 10 iter/s; the absolute rate cannot
    # exceed rate1·n/10 (10x the measured work per chip)
    ps = _load("project_scaling")
    rows = {r["n_workers"]: r for r in ps.project()
            if r["app"] == "kmeans_stream_1b"}
    r32 = rows[32]
    ceiling = r32["measured_rate_1chip"] * 32 / 10
    assert r32["projected"] <= ceiling * 1.01, (r32["projected"], ceiling)
    assert r32["projected"] == pytest.approx(
        1.0 / (r32["compute_sec_per_chip_per_quantum"]
               + ps.t_wire(r32["wire_bytes_per_chip"], ps.ring_hops(32))),
        rel=1e-2)


def test_sweep_parent_survives_hung_and_failed_cells(tmp_path, monkeypatch):
    """A hung cell (TimeoutExpired) or a crashed child must cost only
    itself: the parent records an error row and keeps going (review
    finding, round 5)."""
    import subprocess
    import types

    ss = _load("scaling_sweep")

    def fake_run(cmd, **kw):
        app = cmd[cmd.index("--child") + 1]
        if app == "kmeans":
            raise subprocess.TimeoutExpired(cmd, 1800)
        if app == "mfsgd":
            return types.SimpleNamespace(returncode=1, stdout="",
                                         stderr="boom\ndied")
        return types.SimpleNamespace(
            returncode=0, stdout='{"app": "%s", "ok": 1}\n' % app,
            stderr="")

    monkeypatch.setattr(ss.subprocess, "run", fake_run)
    out = tmp_path / "scaling.jsonl"
    rc = ss.main(["--out", str(out), "--workers", "2",
                  "--apps", "kmeans", "mfsgd", "lda",
                  "--modes", "strong"])
    assert rc == 1  # failures are reported in the exit status
    rows = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert len(rows) == 3  # every cell produced a row, good or bad
    by_app = {r["app"]: r for r in rows}
    assert "timeout" in by_app["kmeans"]["error"]
    assert by_app["mfsgd"]["error"] == "died"
    assert by_app["lda"]["ok"] == 1
