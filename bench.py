#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line for the driver.

Primary metric: KMeans iter/sec on the graded config #1 (k=100, 1M×300
dense, BASELINE.json) on real TPU.  ``vs_baseline`` compares against the
v0 number recorded in BASELINE.md (measured on this machine's single
v5e chip, 2026-07-29, commit of first kmeans milestone).

Timing notes (see harp_tpu/utils/timing.py): all iterations run inside one
jitted fori_loop; sync is a scalar readback, because block_until_ready can
return early on this machine's relay transport.
"""

import json
import sys
import threading

sys.path.insert(0, __file__.rsplit("/", 1)[0])

# v0 regression baseline: KMeans 1M×300 k=100 f32, 1× TPU v5e, 2026-07-29.
BASELINE_KMEANS_ITERS_PER_SEC = 400.0


def main():
    from harp_tpu.utils.timing import HangWatchdog

    smoke = "--smoke" in sys.argv
    done = threading.Event()  # set once the real result line is out

    def emit_hang_record(what):
        # the driver expects ONE JSON line; a hang should still produce a
        # parseable record rather than silence + exit code 3 — but never a
        # SECOND line if the timer fires in the completion/cancel window
        if done.is_set():
            return
        print(json.dumps({
            "metric": ("kmeans_iters_per_sec_smoke" if smoke
                       else "kmeans_iters_per_sec_1Mx300_k100"),
            "value": 0.0,
            "unit": "iter/s",
            "vs_baseline": None if smoke else 0.0,
            "error": f"TPU relay hang during {what} (watchdog)",
        }), flush=True)

    watchdog = HangWatchdog(on_fire=emit_hang_record)  # HARP_BENCH_TIMEOUT
    watchdog.arm("bench.py kmeans")
    from harp_tpu.models import kmeans as KM

    if smoke:
        res = KM.benchmark(n=8192, d=32, k=16, iters=20, warmup=2)
    else:
        res = KM.benchmark(n=1_000_000, d=300, k=100, iters=100, warmup=5)

    value = res["iters_per_sec"]
    watchdog.cancel()
    done.set()
    print(json.dumps({
        "metric": "kmeans_iters_per_sec_1Mx300_k100" if not smoke else "kmeans_iters_per_sec_smoke",
        "value": round(value, 2),
        "unit": "iter/s",
        "vs_baseline": round(value / BASELINE_KMEANS_ITERS_PER_SEC, 4) if not smoke else None,
    }))


if __name__ == "__main__":
    main()
