#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line for the driver.

Covers the north-star pair (SURVEY.md §1: KMeans iter/s + MF-SGD
updates/s/chip) and the other graded configs (LDA, MLP, subgraph, RF) in
a single record: the headline metric/value/unit/vs_baseline fields are
KMeans on graded config #1 (k=100, 1M×300 dense), and ``submetrics``
carries one entry per additional config so `BENCH_r*.json` parses with
kmeans AND mfsgd values (VERDICT round 1, item 3).

``vs_baseline`` compares against the v0 numbers in BASELINE.md (measured
on this machine's single v5e chip, 2026-07-29/30) — a regression guard
vs our own best, not a reference claim (no published Harp figure is
pinned; BASELINE.json ``published`` is empty).

Timing notes (see harp_tpu/utils/timing.py): all iterations run inside
one jitted program; sync is a scalar readback, because block_until_ready
can return early on this machine's relay transport.  The watchdog
re-arms per config; if the TPU relay hangs mid-sweep the record still
carries every config measured before the hang, with ``error`` naming the
hung one.
"""

import json
import sys
import threading

sys.path.insert(0, __file__.rsplit("/", 1)[0])

# Regression baselines, 1× TPU v5e (BASELINE.md) — re-measured on
# ROUND-3 code 2026-07-31 (every config, same day, same chip; the stale
# round-1 values and the refactor caveat are retired).
# None = no TPU number recorded yet (vs_baseline stays null until one is).
BASELINES = {
    "kmeans": 399.3,        # iter/s, 1M×300 k=100 f32
    "kmeans_stream": 0.53,  # iter/s end-to-end, 100M×300 k=1000 (1.09 ex-gen)
    "kmeans_ingest": None,  # points/s, 20M×300 f16 disk npy (round 3)
    "mfsgd": 92.7e6,        # updates/s/chip, ML-20M shapes, dense algo
    "mfsgd_pallas": None,   # fused-kernel algo (round 3; no TPU number yet)
    "lda": 6.58e6,          # tokens/s/chip, 100k docs × 1k topics, dense
    "lda_pallas": None,     # fused-kernel algo (round 3; no TPU number yet)
    "mlp": 22.2e6,          # samples/s, MNIST shapes, device-resident
    "subgraph": 93.8e3,     # vertices/s, u5-tree on 100k vertices
                            # (pre-compaction code — the compact-DP-table
                            # rewrite measured 2.4x on the CPU sim, so a
                            # big vs_baseline jump here is expected)
    "rf": 7.92,             # trees/s, 32 trees depth 6 on 200k×64
}


def _ingest_bench(smoke):
    """Real disk ingest through fit_streaming (VERDICT r2 item 2): full
    mode streams a reusable 20M×300 f16 npy from .bench_data/ — the
    first run pays a ~4 min generation, later runs reuse the file.
    Presets live in scripts/bench_ingest.py (run_smoke/run_full) so this
    and measure_all can never drift apart."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import bench_ingest

    return bench_ingest.run_smoke() if smoke else bench_ingest.run_full()


def _configs(smoke):
    """(name, unit, result_key, thunk) per graded config, headline first."""
    from harp_tpu.models import (kmeans, kmeans_stream, lda, mfsgd, mlp, rf,
                                 subgraph)

    import jax

    return [
        ("kmeans", "iter/s", "iters_per_sec", lambda: kmeans.benchmark(
            **({"n": 8192, "d": 32, "k": 16, "iters": 20, "warmup": 2}
               if smoke else
               {"n": 1_000_000, "d": 300, "k": 100, "iters": 100,
                "warmup": 5}))),
        ("kmeans_stream", "iter/s", "iters_per_sec",
         lambda: kmeans_stream.benchmark_streaming(
             **({"n": 65536, "d": 16, "k": 16, "iters": 2,
                 "chunk_points": 8192} if smoke else
                {"n": 100_000_000, "d": 300, "k": 1000, "iters": 2,
                 "chunk_points": 262_144}))),
        ("kmeans_ingest", "points/s", "points_per_sec",
         lambda: _ingest_bench(smoke)),
        ("mfsgd", "updates/s/chip", "updates_per_sec_per_chip",
         lambda: mfsgd.benchmark(
             **({"n_users": 512, "n_items": 256, "nnz": 20_000, "rank": 8,
                 "epochs": 2, "u_tile": 16, "i_tile": 16, "entry_cap": 256}
                if smoke else {}))),
        ("mfsgd_pallas", "updates/s/chip", "updates_per_sec_per_chip",
         lambda: mfsgd.benchmark(
             algo="pallas",
             # smoke tiles must pass the kernel's TPU gate (128-multiples)
             **({"n_users": 512, "n_items": 256, "nnz": 20_000, "rank": 8,
                 "epochs": 2, "u_tile": 128, "i_tile": 128,
                 "entry_cap": 256} if smoke else {}))),
        ("lda", "tokens/s/chip", "tokens_per_sec_per_chip",
         lambda: lda.benchmark(
             **({"n_docs": 256, "vocab_size": 128, "n_topics": 8,
                 "tokens_per_doc": 16, "epochs": 1, "d_tile": 16,
                 "w_tile": 16, "entry_cap": 64} if smoke else {}))),
        ("lda_pallas", "tokens/s/chip", "tokens_per_sec_per_chip",
         lambda: lda.benchmark(
             algo="pallas",
             # smoke tiles must pass the kernel's TPU gate (128-multiples)
             **({"n_docs": 256, "vocab_size": 128, "n_topics": 8,
                 "tokens_per_doc": 16, "epochs": 1, "d_tile": 128,
                 "w_tile": 128, "entry_cap": 64} if smoke else {}))),
        ("mlp", "samples/s", "samples_per_sec", lambda: mlp.benchmark(
            **({"n": 4096, "batch": 512, "steps": 5} if smoke else {}))),
        ("subgraph", "vertices/s", "vertices_per_sec",
         lambda: subgraph.benchmark(
             **({"n_vertices": 2000, "avg_degree": 4} if smoke else {}))),
        ("rf", "trees/s", "trees_per_sec", lambda: rf.benchmark(
            **({"n": 4096, "f": 16, "max_depth": 3,
                "n_trees": 2 * jax.device_count()} if smoke else {}))),
    ]


def main():
    from harp_tpu.utils.timing import HangWatchdog

    smoke = "--smoke" in sys.argv
    only = [a for a in sys.argv[1:] if not a.startswith("-")]
    unknown = set(only) - set(BASELINES)
    if unknown:
        # typo → loud error, not a clean-looking all-zero record
        print(f"bench.py: unknown config(s) {sorted(unknown)}; "
              f"choose from {sorted(BASELINES)}", file=sys.stderr)
        raise SystemExit(2)
    done = threading.Event()  # set once the result line is out
    sub: dict = {}            # filled as configs complete (thread-shared)
    suffix = "_smoke" if smoke else ""

    kmeans_selected = not only or "kmeans" in only

    def record(error=None):
        km = sub.get("kmeans", {})
        rec = {
            "metric": ("kmeans_iters_per_sec" + suffix if smoke
                       else "kmeans_iters_per_sec_1Mx300_k100"),
            # a filtered-out headline must not parse as a measured 0 iter/s
            "value": km.get("value", 0.0 if kmeans_selected else None),
            # vs_baseline only when kmeans actually ran: an unmeasured or
            # failed headline must not parse as a clean 0× regression
            "unit": "iter/s",
            "vs_baseline": (km.get("vs_baseline") if not smoke else None),
            "submetrics": {k: v for k, v in sub.items() if k != "kmeans"},
        }
        for k in ("achieved_tflops", "achieved_gbs", "pct_peak_flops",
                  "pct_peak_bw", "bound"):  # headline roofline context
            if k in km:
                rec[k] = km[k]
        if not kmeans_selected:
            rec["headline_skipped"] = True
        # a kmeans exception must surface on the headline, not vanish
        # when submetrics drops the kmeans key
        error = error or km.get("error")
        if error:
            rec["error"] = error
        return rec

    def emit_hang_record(what):
        # the driver expects ONE JSON line; a hang should still produce a
        # parseable record (with every config measured so far) rather than
        # silence + exit code 3 — but never a SECOND line if the timer
        # fires in the completion/cancel window
        if done.is_set():
            return
        done.set()
        print(json.dumps(record(
            error=f"TPU relay hang during {what} (watchdog)")), flush=True)

    watchdog = HangWatchdog(on_fire=emit_hang_record)  # HARP_BENCH_TIMEOUT
    watchdog.arm("backend init")  # first backend use is inside _configs
    for name, unit, key, thunk in _configs(smoke):
        if only and name not in only:
            continue
        watchdog.arm(f"bench.py {name}")
        try:
            res = thunk()
        except Exception as e:  # keep measuring the rest
            sub[name] = {"value": 0.0, "unit": unit,
                         "error": f"{type(e).__name__}: {e}"}
            continue
        value = float(res[key])
        base = BASELINES[name]
        # roofline context travels with the driver record (BENCH_r*.json),
        # so a measured rate reads as %-of-datasheet-peak, not a bare number
        from harp_tpu.utils.roofline import annotate

        ann = annotate(name, res)
        roof = {k: ann[k] for k in ("achieved_tflops", "achieved_gbs",
                                    "pct_peak_flops", "pct_peak_bw",
                                    "bound") if k in ann and k not in res}
        sub[name] = {"value": round(value, 2), "unit": unit,
                     "vs_baseline": (None if smoke or base is None else
                                     round(value / base, 4)), **roof}
    watchdog.cancel()
    done.set()
    print(json.dumps(record()), flush=True)


if __name__ == "__main__":
    main()
