#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line for the driver.

Covers the north-star pair (SURVEY.md §1: KMeans iter/s + MF-SGD
updates/s/chip) and the other graded configs (LDA, MLP, subgraph, RF) in
a single record: the headline metric/value/unit/vs_baseline fields are
KMeans on graded config #1 (k=100, 1M×300 dense), and ``submetrics``
carries one entry per additional config so `BENCH_r*.json` parses with
kmeans AND mfsgd values (VERDICT round 1, item 3).

``vs_baseline`` compares against the v0 numbers in BASELINE.md (measured
on this machine's single v5e chip, 2026-07-29/30) — a regression guard
vs our own best, not a reference claim (no published Harp figure is
pinned; BASELINE.json ``published`` is empty).

Timing notes (see harp_tpu/utils/timing.py): all iterations run inside
one jitted program; sync is a scalar readback, because block_until_ready
can return early on this machine's relay transport.  The watchdog
re-arms per config; if the TPU relay hangs mid-sweep the record still
carries every config measured before the hang, with ``error`` naming the
hung one.  ``--max-seconds-per-config=SECONDS`` (PR 10) adds a bounded
per-config timer UNDER that whole-run watchdog: the config runs on a
worker thread, and on overrun the sweep warns, records the timeout in
that config's submetric, abandons the thread, and keeps measuring — one
hung relay config eats its own budget, not the measurement window.

Outage behavior (VERDICT r3 item 3): a bounded subprocess probe runs
BEFORE the first config, so a dead relay yields a ``relay_down`` record
in seconds; and every error record (probe or watchdog) carries a
``last_measured`` block — the last committed TPU number per config with
date + source — so an outage never reads as a bare 0.0.
"""

import json
import os
import subprocess
import sys
import threading

sys.path.insert(0, __file__.rsplit("/", 1)[0])

# reusable benchmark artifacts (shared with scripts/measure_all.py) —
# absolute, so the driver can invoke bench.py from any cwd
_BENCH_DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           ".bench_data")

# Regression baselines, 1× TPU v5e (BASELINE.md) — re-measured on
# ROUND-5 code 2026-08-01, the window that measured every candidate and
# flipped the winners (FLIP_DECISIONS.jsonl): MFSGDConfig.algo and
# LDAConfig.algo/sampler/rng_impl/carry_db now default to the measured
# winners; the dense arms remain pinned configs for regression tracking.
# None = no TPU number recorded yet (vs_baseline stays null until one is).
BASELINES = {
    "kmeans": 381.2,        # iter/s, 1M×300 k=100 f32 (±5% window spread)
    "kmeans_int8_fused": 555.1,  # fused int8 kernel — the int8-path
                            # default since the 2026-08-01 flip (1.14×
                            # XLA int8 at equal inertia, 8000-row tiles)
    "kmeans_stream": 0.53,  # iter/s end-to-end, 100M×300 k=1000 (1.09 ex-gen)
    "kmeans_ingest": 66.4e3,  # points/s, 20M×300 f16 disk npy — relay-
                            # tunnel-bound (44.6 MB/s host == probed H2D)
    "mfsgd": 83.1e6,        # updates/s/chip, ML-20M shapes, dense algo
    "mfsgd_pallas": 246.5e6,  # fused kernel — the DEFAULT algo since the
                            # 2026-08-01 flip; 256×256 auto-tile after
                            # the same-day sweep (250.2M vs 195.5M at
                            # 512; 246.5M re-confirmed through the
                            # default path) = 2.97× dense, equal RMSE
    "lda": 6.46e6,          # tokens/s/chip, 100k docs × 1k topics, dense
    "lda_pallas": 7.92e6,   # fused kernel, carry pinned off (incumbent arm)
    "lda_pallas_carry": 10.50e6,  # kernel + Db-carry — the DEFAULT
                            # LDAConfig stack since the 2026-08-01 flip
                            # (1.63× dense at equal likelihood)
    "mlp": 22.1e6,          # samples/s, MNIST shapes, device-resident
    "subgraph": 75.8e3,     # vertices/s, u5-tree on 100k vertices —
                            # post-compaction: the compact tables win
                            # +10% at the graded 1M shape (129.2k) but
                            # cost ~19% at this small uniform shape
    "rf": 8.80,             # trees/s, 32 trees depth 6 on 200k×64
}

# result_key → display unit; shared by _configs and _last_measured so a
# committed BENCH_local row and a live measurement can't disagree on units
UNITS = {
    "iters_per_sec": "iter/s",
    "points_per_sec": "points/s",
    "updates_per_sec_per_chip": "updates/s/chip",
    "tokens_per_sec_per_chip": "tokens/s/chip",
    "samples_per_sec": "samples/s",
    "vertices_per_sec": "vertices/s",
    "trees_per_sec": "trees/s",
}


# The driver's tail capture is ~2000 chars (VERDICT r5 weak #1: the
# round-5 outage record grew a 22-config last_measured block, crossed it,
# and parsed as null — the driver got ZERO machine-readable numbers from
# the mechanism built so an outage "never reads as a bare 0.0").  Every
# emitted record — success, outage, and watchdog paths alike — is bounded
# UNDER the cap by _fit_record; tests/test_bench.py pins the worst case.
RECORD_CAP_BYTES = 1800


def _last_measured():
    """Last committed TPU number per config (BENCH_local.jsonl rows,
    then the BASELINES constants) — so a relay outage yields a record
    the driver can read the framework's real measured speed from
    instead of a bare zero (VERDICT r3 item 3).  Entries are compact
    {value, unit, date} dicts; ``baseline: true`` marks a constants-
    sourced entry (everything else is BENCH_local.jsonl), replacing the
    old per-entry source strings, and _fit_record trims the block —
    non-graded configs first — whenever the one emitted line would
    cross the driver's tail capture (VERDICT r5 weak #1)."""
    out = {}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_local.jsonl")
    declared_by_cfg = dict(_CONFIG_KEYS)
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if row.get("smoke") or row.get("backend") == "cpu":
                    continue
                cfg = row.get("config", "?")
                # the config's DECLARED headline key first (a kmeans_ingest
                # row carries iters_per_sec too; reporting that would swap
                # the points/s headline for iter/s — ADVICE r4); the UNITS
                # scan is only for configs _CONFIG_KEYS doesn't know (and
                # those are the FIRST entries _fit_record trims)
                declared = declared_by_cfg.get(cfg)
                keys = [declared] if declared else list(UNITS)
                for key in keys:
                    if row.get(key) is not None:
                        # later rows overwrite earlier: last measurement wins
                        out[cfg] = {"value": round(float(row[key]), 2),
                                    "unit": UNITS[key],
                                    "date": row.get("date")}
                        break
    except OSError:
        pass
    # configs never measured in a committed row fall back to the constants
    # (themselves transcribed from BASELINE.md's dated tables)
    units_by_config = {name: UNITS[key] for name, key in _CONFIG_KEYS}
    for name, base in BASELINES.items():
        if base is not None and name not in out \
                and name in units_by_config:
            out[name] = {"value": base, "unit": units_by_config[name],
                         "date": "2026-07-31", "baseline": True}
    return out


def _fit_record(rec, cap=RECORD_CAP_BYTES):
    """Bound the one emitted JSON line under the driver's tail capture.

    Only ``last_measured`` is trimmable (lowest-priority config first —
    _CONFIG_KEYS order is headline-first, so the graded five survive
    longest); every measured submetric always ships.
    ``last_measured_dropped`` records how many entries were cut."""
    lm = rec.get("last_measured")
    if not lm:
        return rec
    prio = [c for c, _ in _CONFIG_KEYS if c in lm]
    prio += [c for c in lm if c not in prio]  # unknowns drop first
    dropped = 0
    while len(json.dumps(rec)) > cap and prio:
        lm.pop(prio.pop())
        dropped += 1
        rec["last_measured_dropped"] = dropped
    return rec


def _flip_state():
    """Summary of FLIP_DECISIONS.jsonl for the driver record: how much of
    the candidates table has real verdicts, and how many flips the gate
    has authorized.  None before the gate has ever produced the file."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "FLIP_DECISIONS.jsonl")
    rows = []
    try:
        with open(path) as f:
            for ln in f:
                if not ln.strip():
                    continue
                try:
                    row = json.loads(ln)
                except ValueError:
                    continue  # truncated tee line (sprint killed mid-write)
                if "flip_decision" in row:
                    rows.append(row)
    except OSError:
        return None
    if not rows:
        return None
    return {"candidates": len(rows),
            "decided": sum(1 for r in rows
                           if r.get("speedup") is not None
                           and r.get("quality_ok") is not None),
            "flips_authorized": sum(1 for r in rows if r.get("flip"))}


def _relay_probe_error():
    """Bounded jax.devices() probe in a subprocess BEFORE the first config,
    so a dead relay is reported as ``relay_down`` in seconds instead of
    discovered at watchdog minute 20 (VERDICT r3 item 3).  The probe runs
    out-of-process because an in-process hang is uninterruptible (CLAUDE.md
    gotchas).  Skipped on simulated-CPU runs (tests); HARP_RELAY_PROBE=0
    disables, =force probes regardless of platform (test hook)."""
    mode = os.environ.get("HARP_RELAY_PROBE", "1")
    if mode in ("0", "off"):
        return None
    if mode != "force":
        import jax  # importing jax does NOT touch the backend

        plat = (jax.config.jax_platforms or
                os.environ.get("JAX_PLATFORMS", ""))
        if plat.split(",")[0] == "cpu":
            return None  # simulated-CPU run: no relay to probe
    timeout_s = float(os.environ.get("HARP_RELAY_PROBE_TIMEOUT", "90"))
    code = "import jax; d = jax.devices(); print(d[0].platform, len(d))"
    try:
        p = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return (f"relay_down: jax.devices() probe timed out after "
                f"{timeout_s:.0f}s — TPU relay hung before any config ran")
    if p.returncode != 0:
        lines = (p.stderr or "").strip().splitlines()
        tail = lines[-1] if lines else ""
        return f"relay_down: probe exited rc {p.returncode}: {tail}"
    return None


def _warn_if_watcher_unarmed():
    """Round-5 postmortem (CLAUDE.md): relay_watch.sh is NOT self-starting
    after an environment reset, and a forgotten arm silently loses the
    next relay window.  Warn loudly on every real (non-CPU) bench run
    when ``pgrep -f relay_watch`` finds nothing; never fail the run over
    it (the warning is for the operator, the measurement still counts).
    HARP_WATCHER_CHECK=0 disables (e.g. deliberate end-of-round runs)."""
    if os.environ.get("HARP_WATCHER_CHECK", "1") in ("0", "off"):
        return
    import jax  # importing jax does NOT touch the backend

    plat = (jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS", ""))
    if plat.split(",")[0] == "cpu":
        return  # simulated-CPU run (tests / rehearsal): no relay to watch
    try:
        alive = subprocess.run(["pgrep", "-f", "relay_watch"],
                               capture_output=True).returncode == 0
    except OSError:
        return  # no pgrep on this host: nothing to check
    if not alive:
        print("bench.py WARNING: no relay_watch.sh process is running "
              "(pgrep -f relay_watch found nothing). The watcher is NOT "
              "self-starting after resets — arm it detached (see its "
              "header) or the next relay window may be missed.",
              file=sys.stderr, flush=True)


def _ingest_bench(smoke):
    """Real disk ingest through fit_streaming (VERDICT r2 item 2): full
    mode streams a reusable 20M×300 f16 npy from .bench_data/ — the
    first run pays a ~4 min generation, later runs reuse the file.
    Presets live in scripts/bench_ingest.py (run_smoke/run_full) so this
    and measure_all can never drift apart."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "scripts"))
    import bench_ingest

    return bench_ingest.run_smoke() if smoke else bench_ingest.run_full()


# config name → result_key, in run order (headline first).  Module-level
# (no model imports) so _last_measured can map units without touching jax.
# kmeans_ingest runs LAST: it is the config that hung the relay in the
# 2026-07-31 window (12 GB of chunks through the tunnel) and full mode
# can pay ~864 s of file generation — a hang or overrun there must cost
# only itself, not the configs after it (same rule as measure_all).
_CONFIG_KEYS = [
    ("kmeans", "iters_per_sec"),
    ("kmeans_int8_fused", "iters_per_sec"),
    ("kmeans_stream", "iters_per_sec"),
    ("mfsgd", "updates_per_sec_per_chip"),
    ("mfsgd_pallas", "updates_per_sec_per_chip"),
    ("lda", "tokens_per_sec_per_chip"),
    ("lda_pallas", "tokens_per_sec_per_chip"),
    ("lda_pallas_carry", "tokens_per_sec_per_chip"),
    ("mlp", "samples_per_sec"),
    ("subgraph", "vertices_per_sec"),
    ("rf", "trees_per_sec"),
    ("kmeans_ingest", "points_per_sec"),
]


def _configs(smoke):
    """(name, unit, result_key, thunk) per graded config, headline first."""
    from harp_tpu.models import (kmeans, kmeans_stream, lda, mfsgd, mlp, rf,
                                 subgraph)

    import jax

    thunks = {
        "kmeans": lambda: kmeans.benchmark(
            # use_pallas=False pins the f32 XLA arm (the f32 auto is
            # also False today, but the row identity must not follow a
            # future default change)
            use_pallas=False,
            **({"n": 8192, "d": 32, "k": 16, "iters": 20, "warmup": 2}
               if smoke else
               {"n": 1_000_000, "d": 300, "k": 100, "iters": 100,
                "warmup": 5})),
        # the int8-path default since the 2026-08-01 flip, knobs pinned
        "kmeans_int8_fused": lambda: kmeans.benchmark(
            quantize="int8", use_pallas=True,
            **({"n": 8192, "d": 32, "k": 16, "iters": 20, "warmup": 2}
               if smoke else
               {"n": 1_000_000, "d": 300, "k": 100, "iters": 100,
                "warmup": 5})),
        "kmeans_stream": lambda: kmeans_stream.benchmark_streaming(
            **({"n": 65536, "d": 16, "k": 16, "iters": 2,
                "chunk_points": 8192} if smoke else
               {"n": 100_000_000, "d": 300, "k": 1000, "iters": 2,
                "chunk_points": 262_144})),
        "kmeans_ingest": lambda: _ingest_bench(smoke),
        "mfsgd": lambda: mfsgd.benchmark(
            **({"n_users": 512, "n_items": 256, "nnz": 20_000, "rank": 8,
                "epochs": 2, "u_tile": 16, "i_tile": 16, "entry_cap": 256}
               if smoke else {})),
        "mfsgd_pallas": lambda: mfsgd.benchmark(
            algo="pallas",
            # smoke tiles must pass the kernel's TPU gate (128-multiples)
            **({"n_users": 512, "n_items": 256, "nnz": 20_000, "rank": 8,
                "epochs": 2, "u_tile": 128, "i_tile": 128,
                "entry_cap": 256} if smoke else {})),
        "lda": lambda: lda.benchmark(
            **({"n_docs": 256, "vocab_size": 128, "n_topics": 8,
                "tokens_per_doc": 16, "epochs": 1, "d_tile": 16,
                "w_tile": 16, "entry_cap": 64} if smoke else
               # pack cache shared with measure_all: full-shape host
               # packing (~31 s) is paid once per tiling, not per run
               {"pack_cache": _BENCH_DATA})),
        "lda_pallas": lambda: lda.benchmark(
            algo="pallas",
            # smoke tiles must pass the kernel's TPU gate (128-multiples)
            **({"n_docs": 256, "vocab_size": 128, "n_topics": 8,
                "tokens_per_doc": 16, "epochs": 1, "d_tile": 128,
                "w_tile": 128, "entry_cap": 64} if smoke else
               {"pack_cache": _BENCH_DATA})),
        # the DEFAULT LDAConfig stack since the 2026-08-01 flip (the
        # benchmark entry pins every knob explicitly so this row's
        # identity survives any future default change)
        "lda_pallas_carry": lambda: lda.benchmark(
            algo="pallas", carry_db=True,
            **({"n_docs": 256, "vocab_size": 128, "n_topics": 8,
                "tokens_per_doc": 16, "epochs": 1, "d_tile": 128,
                "w_tile": 128, "entry_cap": 64} if smoke else
               {"pack_cache": _BENCH_DATA})),
        "mlp": lambda: mlp.benchmark(
            **({"n": 4096, "batch": 512, "steps": 5} if smoke else {})),
        "subgraph": lambda: subgraph.benchmark(
            **({"n_vertices": 2000, "avg_degree": 4} if smoke else {})),
        "rf": lambda: rf.benchmark(
            **({"n": 4096, "f": 16, "max_depth": 3,
                "n_trees": 2 * jax.device_count()} if smoke else {})),
    }
    return [(name, UNITS[key], key, thunks[name])
            for name, key in _CONFIG_KEYS]


def _parse_max_seconds(argv):
    """``--max-seconds-per-config=SECONDS`` (the ``=`` form only: a bare
    following token would be swallowed by the positional config filter).
    None when absent; SystemExit on a malformed value."""
    for a in argv:
        if a.startswith("--max-seconds-per-config"):
            if "=" not in a:
                print("bench.py: use --max-seconds-per-config=SECONDS "
                      "(the '=' form)", file=sys.stderr)
                raise SystemExit(2)
            try:
                v = float(a.split("=", 1)[1])
            except ValueError:
                print(f"bench.py: bad --max-seconds-per-config value "
                      f"{a.split('=', 1)[1]!r}", file=sys.stderr)
                raise SystemExit(2)
            if v <= 0:
                print("bench.py: --max-seconds-per-config must be > 0",
                      file=sys.stderr)
                raise SystemExit(2)
            return v
    return None


def _run_with_timeout(thunk, max_s):
    """Per-config watchdog (subprocess-free): run ``thunk`` on a daemon
    worker thread and wait at most ``max_s`` seconds.  On timeout the
    thread is ABANDONED (an in-process relay hang is uninterruptible —
    CLAUDE.md gotchas) and ``(None, error_string)`` returns so the sweep
    moves on: one hung config costs its own budget, not the rest of the
    measurement window.  Exceptions from the thunk re-raise in the
    caller (the existing per-config error handling owns them)."""
    if max_s is None:
        return thunk(), None
    box = {}

    def run():
        try:
            box["res"] = thunk()
        except BaseException as e:  # noqa: BLE001 - re-raised below
            box["exc"] = e

    t = threading.Thread(target=run, daemon=True,
                         name="bench-config-worker")
    t.start()
    t.join(max_s)
    if t.is_alive():
        return None, (f"timeout: config exceeded "
                      f"--max-seconds-per-config={max_s:g}s; skipped "
                      "(worker thread abandoned)")
    if "exc" in box:
        raise box["exc"]
    return box["res"], None


def main():
    from harp_tpu.utils.timing import HangWatchdog

    smoke = "--smoke" in sys.argv
    max_seconds = _parse_max_seconds(sys.argv[1:])
    if "--cpu" in sys.argv:
        # rehearsal hook (measure_on_relay.sh --rehearse): the axon site
        # pin would otherwise send even --smoke runs to the TPU relay,
        # which can hang (CLAUDE.md); the relay probe auto-skips on cpu
        import jax

        jax.config.update("jax_platforms", "cpu")
    only = [a for a in sys.argv[1:] if not a.startswith("-")]
    unknown = set(only) - set(BASELINES)
    if unknown:
        # typo → loud error, not a clean-looking all-zero record
        print(f"bench.py: unknown config(s) {sorted(unknown)}; "
              f"choose from {sorted(BASELINES)}", file=sys.stderr)
        raise SystemExit(2)
    _warn_if_watcher_unarmed()
    done = threading.Event()  # set once the result line is out
    sub: dict = {}            # filled as configs complete (thread-shared)
    suffix = "_smoke" if smoke else ""

    kmeans_selected = not only or "kmeans" in only

    def record(error=None):
        km = sub.get("kmeans", {})
        rec = {
            "metric": ("kmeans_iters_per_sec" + suffix if smoke
                       else "kmeans_iters_per_sec_1Mx300_k100"),
            # a filtered-out headline must not parse as a measured 0 iter/s
            "value": km.get("value", 0.0 if kmeans_selected else None),
            # vs_baseline only when kmeans actually ran: an unmeasured or
            # failed headline must not parse as a clean 0× regression
            "unit": "iter/s",
            "vs_baseline": (km.get("vs_baseline") if not smoke else None),
            "submetrics": {k: v for k, v in sub.items() if k != "kmeans"},
        }
        for k in ("achieved_tflops", "achieved_gbs", "pct_peak_flops",
                  "pct_peak_bw", "bound"):  # headline roofline context
            if k in km:
                rec[k] = km[k]
        if not kmeans_selected:
            rec["headline_skipped"] = True
        fs = _flip_state()
        if fs is not None:
            # protocol state travels with the record: the judge/driver can
            # see how much of the candidates table has verdicts without
            # opening FLIP_DECISIONS.jsonl
            rec["flip_state"] = fs
        # a kmeans exception must surface on the headline, not vanish
        # when submetrics drops the kmeans key
        error = error or km.get("error")
        if error:
            rec["error"] = error
            # an outage record still reads the framework's real speed
            rec["last_measured"] = _last_measured()
        # bounded in EVERY path: an oversized line parses as null at the
        # driver, which is worse than a trimmed last_measured (BENCH_r05)
        return _fit_record(rec)

    def emit_hang_record(what):
        # the driver expects ONE JSON line; a hang should still produce a
        # parseable record (with every config measured so far) rather than
        # silence + exit code 3 — but never a SECOND line if the timer
        # fires in the completion/cancel window
        if done.is_set():
            return
        done.set()
        print(json.dumps(record(
            error=f"TPU relay hang during {what} (watchdog)")), flush=True)

    # dead relay → informative record in seconds, not at watchdog minute 20
    probe_err = _relay_probe_error()
    if probe_err:
        done.set()
        print(json.dumps(record(error=probe_err)), flush=True)
        raise SystemExit(3)

    # flight recorder (HARP_TELEMETRY=1): each config gets a span plus a
    # per-config delta of the execution counters in its submetric — a
    # silent recompile or an extra readback inside a measured config is
    # visible in the driver record, not re-derived from wall-clock.
    # The memory ledger (PR 19) rides the same pattern: per-config peak
    # HBM + headroom beside the flight delta.
    from harp_tpu.utils import flightrec, memrec, telemetry

    watchdog = HangWatchdog(on_fire=emit_hang_record)  # HARP_BENCH_TIMEOUT
    watchdog.arm("backend init")  # first backend use is inside _configs
    for name, unit, key, thunk in _configs(smoke):
        if only and name not in only:
            continue
        watchdog.arm(f"bench.py {name}")
        flight_base = flightrec.snapshot() if telemetry.enabled() else None
        mem_base = memrec.snapshot() if telemetry.enabled() else None
        try:
            with telemetry.span(f"bench.{name}"):
                res, timeout_err = _run_with_timeout(thunk, max_seconds)
        except Exception as e:  # keep measuring the rest
            sub[name] = {"value": 0.0, "unit": unit,
                         "error": f"{type(e).__name__}: {e}"}
            continue
        if timeout_err is not None:
            # warn + skip + record: a hung config must cost only itself,
            # never the rest of the measurement window
            print(f"bench.py WARNING: {name}: {timeout_err}",
                  file=sys.stderr, flush=True)
            sub[name] = {"value": 0.0, "unit": unit, "error": timeout_err}
            continue
        value = float(res[key])
        base = BASELINES[name]
        # roofline context travels with the driver record (BENCH_r*.json),
        # so a measured rate reads as %-of-datasheet-peak, not a bare number
        from harp_tpu.utils.roofline import annotate

        ann = annotate(name, res)
        roof = {k: ann[k] for k in ("achieved_tflops", "achieved_gbs",
                                    "pct_peak_flops", "pct_peak_bw",
                                    "bound") if k in ann and k not in res}
        sub[name] = {"value": round(value, 2), "unit": unit,
                     "vs_baseline": (None if smoke or base is None else
                                     round(value / base, 4)), **roof}
        if flight_base is not None:
            sub[name]["flight"] = flightrec.delta_since(flight_base)
        if mem_base is not None:
            sub[name]["memory"] = memrec.delta_since(mem_base)
    watchdog.cancel()
    done.set()
    print(json.dumps(record()), flush=True)


if __name__ == "__main__":
    main()
