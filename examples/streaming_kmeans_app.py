"""Runnable beyond-HBM KMeans app — the 1B-point pattern, end to end.

Shows the round-2 streaming stack on a dataset the device never holds:
a CSV written to disk, streamed through the native double-buffered
reader (``harp_tpu.native.CSVPoints``), clustered by the blocked-epoch
Lloyd (``kmeans_stream.fit_streaming``) with checkpoint/resume, and
verified against the device-resident ``kmeans.fit`` on the same data.
The production north-star config swaps the toy shapes for
``--n 1000000000 --d 300 --k 1000`` and a real corpus.

Run:  python examples/streaming_kmeans_app.py [--cpu8] [--n 20000]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu8", action="store_true",
                   help="simulate 8 workers on host CPU")
    p.add_argument("--n", type=int, default=20_000)
    p.add_argument("--d", type=int, default=16)
    p.add_argument("--k", type=int, default=8)
    p.add_argument("--iters", type=int, default=6)
    p.add_argument("--chunk", type=int, default=4096)
    args = p.parse_args()

    if args.cpu8:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    import jax

    if args.cpu8:
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from harp_tpu.models import kmeans, kmeans_stream
    from harp_tpu.native import CSVPoints
    from harp_tpu.parallel.mesh import WorkerMesh, set_mesh

    mesh = WorkerMesh()
    set_mesh(mesh)
    print(f"mesh: {mesh}")

    rng = np.random.default_rng(0)
    pts = (rng.normal(size=(args.n, args.d))
           + rng.integers(0, args.k, size=(args.n, 1)) * 6).astype(np.float32)

    with tempfile.TemporaryDirectory() as tmp:
        # "HDFS split" stand-in: the dataset lives on disk as text
        csv = os.path.join(tmp, "points.csv")
        with open(csv, "w") as f:
            f.write("# synthetic blobs\n")
            for row in pts:
                f.write(",".join(f"{v:.9e}" for v in row) + "\n")  # f32 round-trips at 9 sig digits

        src = CSVPoints(csv, chunk_rows=args.chunk)
        print(f"source: {src.shape[0]} rows x {src.shape[1]} cols "
              f"(streamed, chunk={args.chunk})")

        ck = os.path.join(tmp, "ckpt")
        c_stream, inertia, hist = kmeans_stream.fit_streaming(
            src, k=args.k, iters=args.iters, chunk_points=args.chunk,
            mesh=mesh, seed=1, return_history=True,
            ckpt_dir=ck, ckpt_every=2)
        src.close()
        print("streamed inertia per epoch:",
              [round(float(h), 1) for h in hist])

        # ground truth: the device-resident fit on the same data/init
        c_res, inertia_res = kmeans.fit(pts, k=args.k, iters=args.iters,
                                        mesh=mesh, seed=1)
        rel = abs(inertia - inertia_res) / max(abs(inertia_res), 1e-9)
        print(f"resident inertia {inertia_res:.1f} vs streamed "
              f"{inertia:.1f}  (rel diff {rel:.2e})")
        assert rel < 1e-3, "streamed != resident Lloyd"
        print("OK: beyond-HBM streaming == device-resident KMeans")


if __name__ == "__main__":
    main()
