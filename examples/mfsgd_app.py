"""Runnable Harp-style MF-SGD app — the model-rotation pattern, complete.

Shows the signature Harp pattern (``edu.iu.sgd``): item factors travel the
worker ring while each worker trains on its resident slice.  The production
implementation (dense one-hot MXU updates, multi-epoch single-dispatch,
checkpoint/resume) is ``harp_tpu.models.mfsgd``; this example drives it
through the ``CollectiveApp`` lifecycle the way a Harp ``mapCollective``
program would.

Run:  python examples/mfsgd_app.py [--cpu8] [--users 600] [--items 400]
      [--nnz 20000] [--epochs 10]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu8", action="store_true",
                   help="simulate 8 workers on host CPU")
    p.add_argument("--users", type=int, default=600)
    p.add_argument("--items", type=int, default=400)
    p.add_argument("--nnz", type=int, default=20_000)
    p.add_argument("--rank", type=int, default=16)
    p.add_argument("--epochs", type=int, default=10)
    args = p.parse_args()
    if args.epochs < 1:
        p.error("--epochs must be >= 1")

    if args.cpu8:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    import jax

    if args.cpu8:
        jax.config.update("jax_platforms", "cpu")

    from harp_tpu import CollectiveApp, run_app
    from harp_tpu.models.mfsgd import MFSGD, MFSGDConfig, synthetic_ratings

    class MFSGDApp(CollectiveApp):
        def map_collective(self):
            # load this job's ratings (a real app would read file splits
            # through self.reader; see `python -m harp_tpu mfsgd --input`)
            u, i, v = synthetic_ratings(args.users, args.items, args.nnz,
                                        rank=4, noise=0.05, seed=0)
            # algo="dense" explicitly: the demo's 64-row tiles are below
            # the default pallas kernel's 128-multiple TPU minimum
            cfg = MFSGDConfig(rank=args.rank, lr=0.05, algo="dense",
                              u_tile=64, i_tile=64, entry_cap=256)
            model = MFSGD(args.users, args.items, cfg, self.mesh, seed=0)
            model.set_ratings(u, i, v)

            # every epoch is a full ring rotation of the item factors; all
            # epochs run as ONE device program (no per-epoch dispatches)
            rmses = model.train_epochs(args.epochs)
            for e, r in enumerate(rmses):
                self.metrics.log(epoch=e, rmse=round(r, 4))
            return {"rmse_first": round(rmses[0], 4),
                    "rmse_final": round(rmses[-1], 4),
                    "workers": self.num_workers}

    print(run_app(MFSGDApp))


if __name__ == "__main__":
    main()
