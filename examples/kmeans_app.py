"""Runnable Harp-style KMeans app — the MIGRATING.md side-by-side, complete.

Shows the ``CollectiveApp`` / ``mapCollective`` programming model (Harp L4)
on synthetic data; the production implementation with the fused MXU path
and on-device iteration loop is ``harp_tpu.models.kmeans``.

Run:  python examples/kmeans_app.py [--cpu8] [--n 4096] [--k 8] [--iters 10]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu8", action="store_true",
                   help="simulate 8 workers on host CPU")
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--d", type=int, default=16)
    p.add_argument("--k", type=int, default=8)
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args()

    if args.cpu8:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    import jax

    if args.cpu8:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from harp_tpu import CollectiveApp, Combiner, run_app
    from harp_tpu.parallel import collective as C

    class KMeansApp(CollectiveApp):
        def load_shard(self):
            rng = np.random.default_rng(0)
            n = args.n // self.num_workers * self.num_workers
            pts = rng.normal(size=(n, args.d)).astype(np.float32)
            return self.mesh.shard_array(pts, 0), pts

        def map_collective(self):
            pts_sharded, pts_host = self.load_shard()
            cents = jax.device_put(
                jnp.asarray(pts_host[: args.k]), self.mesh.replicated()
            )

            def step(pts, cents):  # one SPMD program per iteration
                d2 = ((pts[:, None] - cents[None]) ** 2).sum(-1)
                one_hot = jax.nn.one_hot(d2.argmin(1), cents.shape[0],
                                         dtype=pts.dtype)
                sums = one_hot.T @ pts
                counts = one_hot.sum(0)
                sums, counts = C.allreduce((sums, counts), Combiner.ADD)
                return sums / jnp.maximum(counts[:, None], 1.0)

            fit = jax.jit(self.mesh.shard_map(
                step, in_specs=(self.mesh.spec(0), P()), out_specs=P()))
            for i in range(args.iters):
                cents = fit(pts_sharded, cents)
                self.metrics.log(step=i)
            return np.asarray(cents)

    cents = run_app(KMeansApp, config=vars(args))
    print({"k": args.k, "iters": args.iters,
           "centroid_norm": float(np.linalg.norm(cents))})


if __name__ == "__main__":
    main()
