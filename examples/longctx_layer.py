"""Runnable long-context transformer layer — the sequence-parallel stack.

Long-context composition demo (SURVEY.md §6 "long-context / sequence
parallelism"; the attention/rope modules carry the per-piece parity notes).
Composes the long-context toolkit end to end the way a Harp app composes
collective verbs: sequence-sharded activations, shard-local RoPE
(`harp_tpu.ops.rope`), windowed causal GQA ring attention
(`harp_tpu.ops.ring_attention`), and a data-parallel gradient allreduce
through the same `collective.allreduce` verb every app uses — one training
step of a transformer layer whose sequence never fits on one chip.

Run:  python examples/longctx_layer.py [--cpu8] [--seq 512] [--window 64]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu8", action="store_true",
                   help="simulate 8 workers on host CPU")
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--kv-heads", type=int, default=2)
    p.add_argument("--dim", type=int, default=16)
    p.add_argument("--window", type=int, default=64)
    p.add_argument("--steps", type=int, default=10)
    args = p.parse_args()
    if args.steps < 1:
        p.error("--steps must be >= 1")

    if args.cpu8:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    import jax

    if args.cpu8:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from harp_tpu import WorkerMesh, Combiner, collective as C
    from harp_tpu.ops import apply_rope, ring_attention

    mesh = WorkerMesh()
    h, g, d = args.heads, args.kv_heads, args.dim
    model_d = h * d
    rng = np.random.default_rng(0)

    params = {
        "wq": rng.normal(size=(model_d, h * d)).astype(np.float32) * 0.05,
        "wk": rng.normal(size=(model_d, g * d)).astype(np.float32) * 0.05,
        "wv": rng.normal(size=(model_d, g * d)).astype(np.float32) * 0.05,
        "wo": rng.normal(size=(h * d, model_d)).astype(np.float32) * 0.05,
    }
    x = rng.normal(size=(1, args.seq, model_d)).astype(np.float32)

    def layer(params, x):
        b, s, _ = x.shape
        q = apply_rope((x @ params["wq"]).reshape(b, s, h, d))
        k = apply_rope((x @ params["wk"]).reshape(b, s, g, d))
        v = (x @ params["wv"]).reshape(b, s, g, d)
        o = ring_attention(q, k, v, causal=True, window=args.window)
        return o.reshape(b, s, h * d) @ params["wo"]

    # teacher-student: the target is the same layer under different weights,
    # so the regression is realizable and the loss visibly descends
    teacher = {k2: rng.normal(size=v2.shape).astype(np.float32) * 0.05
               for k2, v2 in params.items()}

    def step(params, x, y):
        def loss_fn(p):
            return ((layer(p, x) - y) ** 2).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # the Harp verb: sequence shards each see part of the loss surface;
        # one allreduce makes the update identical everywhere
        grads, loss = C.allreduce((grads, loss), Combiner.AVG)
        return jax.tree.map(lambda p, g: p - 2.0 * g, params, grads), loss

    spec = mesh.spec(1, ndim=3)  # shard the sequence dim
    fit = jax.jit(mesh.shard_map(
        step, in_specs=(P(), spec, spec), out_specs=(P(), P())))
    target = np.asarray(jax.jit(mesh.shard_map(
        layer, in_specs=(P(), spec), out_specs=spec))(teacher, x))

    losses = []
    for _ in range(args.steps):
        params, loss = fit(params, x, target)
        losses.append(float(np.asarray(loss)))
    print({"workers": mesh.num_workers, "seq": args.seq,
           "heads": f"{h}q/{g}kv", "window": args.window,
           "loss_first": round(losses[0], 5), "loss_final": round(losses[-1], 5)})


if __name__ == "__main__":
    main()
