"""Runnable pipeline-parallel + expert-parallel training demo.

Composes the two parallelism strategies Harp lacked (SURVEY.md §3.5
marks PP and EP ❌ upstream; `parallel/pipeline.py` and `ops/moe.py`
carry the design notes) the way a Harp app composes verbs:

1. GPipe pipeline: each worker owns ONE stage of a deep tanh-MLP;
   microbatches enter at stage 0 and activations hop the worker ring
   (`rotate`/ppermute) — `pipeline_loss_and_grads` differentiates
   through the hops, so plain SGD on each worker's stage trains the
   whole stack.  The loss must visibly descend.
2. Switch MoE layer: the same mesh, one expert per worker, tokens
   routed by a gating argmax through ONE `regroup` (all-to-all) each
   way — checked against the dense host reference.

Run:  python examples/pipeline_moe_app.py [--cpu8] [--steps 20]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu8", action="store_true",
                   help="simulate 8 workers on host CPU")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--width", type=int, default=16)
    p.add_argument("--microbatches", type=int, default=4)
    p.add_argument("--lr", type=float, default=0.2)
    args = p.parse_args()
    if args.steps < 2:
        p.error("--steps must be >= 2 (the descent check compares "
                "first and last step)")

    if args.cpu8:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    import jax

    if args.cpu8:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from harp_tpu import WorkerMesh
    from harp_tpu.ops.moe import moe_ffn, reference_moe
    from harp_tpu.parallel.pipeline import pipeline_loss_and_grads

    mesh = WorkerMesh()
    nw = mesh.num_workers
    w = args.width
    rng = np.random.default_rng(0)

    # --- 1. GPipe pipeline training over the worker ring ---
    def stage_fn(params, h):
        return jax.nn.tanh(h @ params["w"] + params["b"])

    params = {
        "w": (rng.normal(size=(nw, w, w)) * 0.5).astype(np.float32),
        "b": np.zeros((nw, w), np.float32),
    }
    # teacher-student: targets from the same stack under other weights,
    # so the regression is realizable and the loss visibly descends
    teacher = {
        "w": (rng.normal(size=(nw, w, w)) * 0.5).astype(np.float32),
        "b": (rng.normal(size=(nw, w)) * 0.1).astype(np.float32),
    }
    x = rng.normal(size=(args.microbatches, 8, w)).astype(np.float32)
    tgt = np.asarray(x)
    for s in range(nw):
        tgt = np.tanh(tgt @ teacher["w"][s] + teacher["b"][s])

    def loss_fn(outs, targets):
        return ((outs - targets) ** 2).mean()

    spec = {"w": mesh.spec(0), "b": mesh.spec(0)}

    @jax.jit
    def sgd_step(params, x, tgt):
        def device(p, xx, tt):
            loss, grads = pipeline_loss_and_grads(
                stage_fn, loss_fn, jax.tree_util.tree_map(
                    lambda a: a[0], p), xx, tt)
            # each worker updates ITS stage; re-add the leading stage dim
            new = jax.tree_util.tree_map(
                lambda a, g: a - args.lr * g[None],
                jax.tree_util.tree_map(lambda a: a[0], p), grads)
            return loss, new

        return mesh.shard_map(
            device, in_specs=(spec, P(), P()), out_specs=(P(), spec))(
            params, x, tgt)

    losses = []
    for _ in range(args.steps):
        loss, params = sgd_step(params, x, tgt)
        losses.append(float(jax.device_get(loss)))
    print(f"pipeline[{nw} stages x {args.microbatches} microbatches] "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    assert losses[-1] < losses[0], "pipeline training must descend"

    # --- 2. Switch MoE layer through the regroup dispatch ---
    d, hdim, cap = w, 2 * w, 8
    moe_w = {
        "gate": rng.normal(size=(d, nw)).astype(np.float32),
        "w1": (rng.normal(size=(nw, d, hdim)) * 0.5).astype(np.float32),
        "b1": np.zeros((nw, hdim), np.float32),
        "w2": (rng.normal(size=(nw, hdim, d)) * 0.5).astype(np.float32),
        "b2": np.zeros((nw, d), np.float32),
    }
    tokens = rng.normal(size=(nw * cap, d)).astype(np.float32)
    y, dropped = jax.jit(mesh.shard_map(
        lambda xx, wt: moe_ffn(xx, wt["gate"], wt["w1"][0], wt["b1"][0],
                               wt["w2"][0], wt["b2"][0], capacity=cap),
        in_specs=(mesh.spec(0),
                  {"gate": P(), "w1": mesh.spec(0), "b1": mesh.spec(0),
                   "w2": mesh.spec(0), "b2": mesh.spec(0)}),
        out_specs=(mesh.spec(0), P())))(tokens, moe_w)
    ref = reference_moe(tokens, moe_w["gate"], moe_w["w1"], moe_w["b1"],
                        moe_w["w2"], moe_w["b2"], cap, nw)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-5)
    print(f"moe[{nw} experts, capacity {cap}] == dense reference "
          f"(dropped={int(jax.device_get(dropped))})")


if __name__ == "__main__":
    main()
