#!/usr/bin/env python
"""Prewarm .bench_data/ so a relay window is spent on silicon, not prep.

The sprint's two big host costs are pure CPU work with no TPU
dependency: the LDA corpus packs (~675 s at enwiki-1M, ~30-320 s for
the others, identical bytes whatever backend later installs them) and
the 12 GB ingest npy.  Run this script any time the relay is down (it
forces the CPU backend, one device — matching the 1-chip sprint mesh,
which the pack key includes) and the next `measure_on_relay.sh` run
hits warm caches for every lda config and the ingest file.

Usage: python scripts/prewarm_bench_cache.py [--skip-ingest]
Idempotent: existing cache files are kept.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

jax.config.update("jax_platforms", "cpu")

from measure_all import BENCH_DATA  # the one shared artifacts dir

# every FULL-mode lda config in measure_all, by distinct pack layout:
# dense covers lda/lda_carry/lda_exprace/lda_fast; pallas covers
# lda_pallas/_approx/_carry (sampler/rng/carry knobs don't touch layout)
PACKS = [
    dict(algo="dense"),
    dict(algo="pallas", sampler="exprace", rng_impl="rbg"),
    dict(algo="scatter"),
    dict(algo="dense", n_docs=500_000, ndk_dtype="int16"),
    dict(algo="dense", n_docs=1_000_000, ndk_dtype="int16"),
    # round 5: the hot-count LL A/B pair (lda_pallas_hot/_approx_hot) —
    # exact_gathers is not layout-relevant, one pack serves both
    dict(algo="pallas", sampler="exprace", rng_impl="rbg", n_docs=20_000,
         vocab_size=256, n_topics=32, tokens_per_doc=200, d_tile=128,
         w_tile=128),
]


def prewarm_pack(n_docs=100_000, vocab_size=50_000, n_topics=1000,
                 tokens_per_doc=100, seed=0, algo="dense", sampler=None,
                 rng_impl=None, ndk_dtype="float32", d_tile=None,
                 w_tile=None):
    from harp_tpu import WorkerMesh
    from harp_tpu.models import lda as L

    mesh = WorkerMesh()  # 1 CPU device == the 1-chip sprint mesh
    assert mesh.num_workers == 1, mesh.num_workers
    cfg = L._make_cfg(n_topics, algo, sampler=sampler, rng_impl=rng_impl,
                      ndk_dtype=ndk_dtype, d_tile=d_tile, w_tile=w_tile)
    path = L._pack_cache_path(BENCH_DATA, cfg, mesh.num_workers, n_docs,
                              vocab_size, n_topics, tokens_per_doc, seed)
    label = f"{algo} n_docs={n_docs} ndk={cfg.ndk_dtype}"
    if os.path.exists(path):
        print(f"pack ok (cached): {label} -> {os.path.basename(path)}")
        return
    t0 = time.time()
    # the SAME corpus constructor benchmark uses — a second construction
    # here would let the cached bytes drift from the key's promise
    d_ids, w_ids = L.benchmark_corpus(n_docs, vocab_size, tokens_per_doc,
                                      seed)
    model = L.LDA(n_docs, vocab_size, cfg, mesh, seed)
    pack = model.pack_tokens(d_ids, w_ids)
    L._save_pack(path, pack)
    print(f"pack built: {label} -> {os.path.basename(path)} "
          f"({time.time() - t0:.0f}s, {os.path.getsize(path) / 2**30:.2f} GiB)")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--skip-ingest", action="store_true")
    args = p.parse_args()
    for kw in PACKS:
        prewarm_pack(**kw)
    if not args.skip_ingest:
        # same presets the sprint uses (bench_ingest --ensure-only)
        import bench_ingest

        bench_ingest.main(["--rows", "20000000", "--ensure-only"])
    print("prewarm done")


if __name__ == "__main__":
    main()
