#!/usr/bin/env python
"""Run every graded-config benchmark and record JSONL — the L8 scripts layer.

The reference wraps its canonical configs in shell scripts
(SURVEY.md §2 L8: bin/, test_scripts/); this is the harp-tpu equivalent,
and the protocol behind BASELINE.md's measured rows.

Usage:  python scripts/measure_all.py [--out results.jsonl] [--smoke]
        [--only kmeans mfsgd ...]

--smoke shrinks every config for a fast correctness pass (CPU-safe);
without it the full graded shapes run (real TPU recommended).  Each line
of output is one JSON record with the config, metric, and environment.
"""

import argparse
import datetime
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))  # bench_common

# reusable benchmark artifacts (ingest npy, LDA pack cache) live here
BENCH_DATA = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    ".bench_data")


def _git_commit() -> str:
    """Short HEAD hash (records must be attributable to exact code)."""
    import subprocess

    try:
        return subprocess.run(
            ["git", "describe", "--always", "--dirty", "--abbrev=7"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            capture_output=True, text=True, timeout=10).stdout.strip() or "?"
    except Exception:
        return "?"


def _bench_ingest(smoke: bool, quantize=None):
    # shared presets (bench_ingest.run_smoke/run_full) keep this and
    # bench.py's kmeans_ingest config measuring the same shapes; the
    # synthetic compute twin is the sweep-only extra.  quantize="int8"
    # is the int8-WIRE twin (half the tunnel bytes on the H2D-bound
    # path — measured 1.55× on the relay 2026-08-01 (102,711 vs 66,373
    # points/s, BENCH_local); lossy, so it stays a recommendation for
    # wire-bound links, never a silent default)
    import bench_ingest

    return (bench_ingest.run_smoke(quantize=quantize) if smoke
            else bench_ingest.run_full(compare_synthetic=quantize is None,
                                       quantize=quantize))


# Sprint priority (VERDICT r4 weak #3: scarcity pricing).  The round-3
# relay window lasted ~2.5 h and died 20 min after the sweep; a short
# window must yield NEW information, so UNMEASURED candidates run first —
# their incumbents already have committed BENCH_local rows that
# flip_decision.py compares against — then incumbent re-measures, then
# the ladder/graded-scale shapes.  kmeans_ingest stays last (host-bound
# file generation can only cost itself there).  FIRST_REMEASURE marks the
# candidates/re-measures boundary for the priority test.
FIRST_REMEASURE = "kmeans"
SPRINT_ORDER = [
    # unmeasured candidates (BASELINE.md candidates table)
    "kmeans_int8_fused", "kmeans_stream_int8",
    "mfsgd_pallas", "mfsgd_carry", "mfsgd_chunked_rotate",
    "lda_pallas", "lda_pallas_approx",
    "lda_pallas_hot", "lda_pallas_approx_hot",
    "lda_pallas_carry", "lda_carry", "lda_exprace", "lda_fast",
    "lda_rotate_int8",
    # PR 11: planner-named flip candidates (harp_tpu/plan emits these as
    # fail-closed Plan rows; the schedules exist in code TODAY —
    # collective.allreduce_hier and the bf16 reshard wire — and flip
    # only through flip_decision's gates like every other candidate)
    "kmeans_hier_psum", "lda_planner_wire",
    # PR 6: serving latency/throughput (harp_tpu/serve) — no committed
    # TPU row yet, so they ride the candidates block: the next armed
    # relay window yields the first serve verdicts (p50/p95/p99 + qps
    # at the graded state shapes); check_jsonl invariant 7 refuses any
    # row whose steady state compiled
    "serve_kmeans", "serve_mfsgd_topk",
    # PR 7: sustained continuous-batching A/B (burst-drain vs
    # admit-while-in-flight on one seeded arrival trace) — the first
    # relay window yields the TPU qps_ratio_vs_burst + queue-depth
    # verdicts; invariant 7's sustained extension refuses rows without
    # offered>=achieved and queue evidence
    "serve_kmeans_sustained", "serve_mfsgd_sustained",
    # PR 8: quantized gradient-wire flip candidates (ROADMAP "decision
    # machinery" item; EQuARX motivates ~2x wire savings) — the DP
    # allreduce rides collective.allreduce_quantized; flip_decision
    # gates on train_acc and the pair is EXCLUSIVE (one grad_wire
    # default).  Defaults stay exact until a relay window measures them.
    "mlp_grad_bf16", "mlp_grad_int8",
    # PR 12: the LAST two per-app wires get measurement paths (ROADMAP
    # planner item) — svm's per-round SV exchange and wdamds's
    # per-iteration coordinate exchange now ride reshard with a wire
    # knob, their drivers are byte-sheeted, and the planner names these
    # configs.  Each pair is EXCLUSIVE (one wire slot per knob); gates:
    # train_acc (svm) / final_stress (wdamds).  Incumbent svm/wdamds
    # rows ride the remaining-apps block below.
    "svm_sv_bf16", "svm_sv_int8",
    "wdamds_coord_bf16", "wdamds_coord_int8",
    # PR 16: the wall-attribution observatory priced the four previously
    # unpriced apps, and each gets ≥1 flip candidate here.  rf's pair is
    # the dense-one-hot-MXU vs scatter histogram A/B (the measured
    # 25 GB/s scatter wall, CLAUDE.md); svm/wdamds flip the STAGED data
    # dtype (the committed walls are relay-H2D-bound at ~30 MB/s, so
    # halving staged bytes is the model's top-ranked lever); subgraph
    # flips the padded-CSR width (32 columns stage half the bytes of the
    # 64-wide default; the overflow path absorbs the clipped tail).
    "rf_dense_hist", "rf_scatter_hist",
    "svm_x_bf16", "wdamds_delta_bf16", "subgraph_csr32",
    # PR 17: the kernelized arms of the newly priced half — Pallas
    # kernels for svm/wdamds/rf (ops/{svm,wdamds,rf}_kernel.py),
    # presized offline (perfmodel.presize) and Mosaic-proven (HL201)
    # before first silicon contact.  Gates: train_acc (svm/rf) /
    # final_stress (wdamds); rf_hist_pallas is CONDITIONAL on
    # rf_dense_hist holding the hist_algo slot.
    "svm_kernel_pallas", "wdamds_dist_pallas", "rf_hist_pallas",
    # post-compaction subgraph rows (the committed 117.3k vertices/s
    # predates the compact-DP rewrite) + the overflow A/B pairs
    "subgraph_1m", "subgraph_1m_onehot",
    "subgraph_pl", "subgraph_onehot",
    # incumbent re-measures (known numbers, regression check)
    FIRST_REMEASURE, "kmeans_int8", "kmeans_stream",
    "mfsgd", "mfsgd_scatter", "lda", "lda_scatter",
    # ladder / graded-scale / remaining apps
    "lda_scale", "lda_scale_1m", "lda_scale_1m_pallas",
    "mlp", "subgraph", "rf",
    # PR 12: first-ever svm/wdamds rows — the incumbents the new wire
    # candidates' verdicts compare against
    "svm", "wdamds",
    # host-bound ingest: last, outside everyone else's window
    "kmeans_ingest", "kmeans_ingest_int8",
]


def gate_closure(selected) -> set:
    """Expand a candidate selection with every gate partner/anchor the
    verdict machinery needs (PR 13, reusing flip_decision's OWN gate
    tables): a JOINT partner (the knob flips only if every gate flips),
    an EXCLUSIVE partner (the verdict picks the faster — absent rows
    cannot be compared), and a CONDITIONAL anchor (an unmeasured anchor
    vetoes with exit 1).  Pruning that dropped any of these would turn
    a short window into re-run homework; tests pin that it never can.
    """
    import flip_decision

    out = set(selected)
    changed = True
    while changed:
        changed = False
        for group in flip_decision.JOINT_GATES + flip_decision.EXCLUSIVE_GATES:
            if out & set(group) and not set(group) <= out:
                out |= set(group)
                changed = True
        for name, (_, anchor) in flip_decision.CONDITIONAL_GATES.items():
            if name in out and anchor not in out:
                out.add(anchor)
                changed = True
    return out


def predicted_only(top_n: int, topology: str) -> tuple:
    """The perfmodel-pruned ``--only`` list: rank every priceable flip
    candidate by predicted speedup on the chosen topology, keep the top
    N, close over the flip gates, and order by SPRINT_ORDER (the
    unmeasured-candidates-first priority stays exactly as committed —
    the model proposes, the gates and the sprint order dispose).
    Returns (ordered config list, ranked [(cand, speedup)], unpriced).

    FAIL-CLOSED preflight (PR 14, ROADMAP autotuning item 3): before
    the model may prune anything, :func:`harp_tpu.health.grade.
    model_gate` re-runs the perfmodel's self-grade against ALL
    committed evidence — including any rows the last sprint just
    landed.  A ``model_invalidated`` verdict REFUSES the pruning
    (SystemExit 1): a model that fresh silicon evidence contradicts
    must not choose which configs get the next scarce relay window.
    The refusal lifts the moment the model is re-calibrated (the gate
    re-grades live each time; no stale ack file).
    """
    from harp_tpu.perfmodel.cli import _topology, candidate_ranking
    from harp_tpu.perfmodel.grade import latest_tpu_rows

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    from harp_tpu.health import grade as health_grade

    ok, finding = health_grade.model_gate(repo)
    if not ok:
        raise SystemExit(
            "measure_all: --predicted-top REFUSED (fail closed): the "
            "perfmodel is INVALIDATED by committed evidence "
            f"({finding.get('failures')} grade failure(s): "
            f"{finding.get('detail')}). Re-calibrate the model and "
            "re-check with `python -m harp_tpu predict --grade` before "
            "pruning a sprint with it.")
    bench = latest_tpu_rows(os.path.join(repo, "BENCH_local.jsonl"))
    ranked, unpriced = candidate_ranking(_topology(topology), bench)
    selected = gate_closure(c for c, _ in ranked[:top_n])
    only = [c for c in SPRINT_ORDER if c in selected]
    return only, ranked, unpriced


def run_all(smoke: bool, only, watchdog=None, skip=None):
    import jax

    from bench_common import SMOKE
    from harp_tpu.models import (kmeans, kmeans_stream, lda, mfsgd, mlp, rf,
                                 subgraph, svm, wdamds)
    from harp_tpu.serve import bench as serve_bench

    # (name, callable) — each returns the model module's benchmark dict
    configs = {
        "kmeans": lambda: kmeans.benchmark(
            **(SMOKE["kmeans"] if smoke else
               {"n": 1_000_000, "d": 300, "k": 100, "iters": 100})),
        # use_pallas=False pins the XLA incumbent arm: the user-facing
        # auto default is the fused kernel since the 2026-08-01 flip,
        # and the A/B identity must not follow it
        "kmeans_int8": lambda: kmeans.benchmark(
            quantize="int8", use_pallas=False,
            **(SMOKE["kmeans"] if smoke else
               {"n": 1_000_000, "d": 300, "k": 100, "iters": 100})),
        # round 3: the FUSED int8 kernel (ops/kmeans_kernel.py) — the XLA
        # int8 path's wall is the ~2 GB/iter [n, k] intermediates it
        # materializes; the kernel never writes them (single HBM pass)
        "kmeans_int8_fused": lambda: kmeans.benchmark(
            quantize="int8", use_pallas=True,
            **(SMOKE["kmeans"] if smoke else
               {"n": 1_000_000, "d": 300, "k": 100, "iters": 100})),
        # PR 11: the planner's hierarchical two-stage psum on the graded
        # kmeans shape (collective.allreduce_hier; Plan rows name this
        # config).  On one chip/host it should read ~1.0x — the win
        # condition is a multi-host mesh — so the verdict doubles as the
        # cost model's honesty check: flip only where topology says to.
        "kmeans_hier_psum": lambda: kmeans.benchmark(
            psum_schedule="hier",
            **(SMOKE["kmeans_hier_psum"] if smoke else
               {"n": 1_000_000, "d": 300, "k": 100, "iters": 100})),
        # north-star shape (SURVEY.md §1): blocked-epoch streaming at
        # 100M×300 k=1000 (full 1B runs via --n on the app CLI)
        "kmeans_stream": lambda: kmeans_stream.benchmark_streaming(
            **(SMOKE["kmeans_stream"] if smoke else
               # calibrate_gen: one extra compile+run isolating the RNG
               # scaffolding a real ingest wouldn't pay (ex-gen rate)
               {"n": 100_000_000, "d": 300, "k": 1000, "iters": 2,
                "chunk_points": 262_144, "calibrate_gen": True})),
        # round 3: the same compute formulation on the int8 MXU (2× the
        # bf16 rate on v5e) — device-quantized chunks, static 5σ scale
        "kmeans_stream_int8": lambda: kmeans_stream.benchmark_streaming(
            quantize="int8",
            **(SMOKE["kmeans_stream"] if smoke else
               {"n": 100_000_000, "d": 300, "k": 1000, "iters": 2,
                "chunk_points": 262_144, "calibrate_gen": True})),
        "mfsgd": lambda: mfsgd.benchmark(
            **(SMOKE["mfsgd"]
               if smoke else {})),
        "mfsgd_scatter": lambda: mfsgd.benchmark(
            algo="scatter",
            **(SMOKE["mfsgd_scatter"] if smoke else {})),
        # round 4: W tile carried across its tou-run (the LDA carry_db
        # lever applied to the dense MF-SGD path); bit-identical chain
        "mfsgd_carry": lambda: mfsgd.benchmark(
            carry_w=True,
            **(SMOKE["mfsgd"] if smoke else {})),
        # round 3: the dense update fused into one VMEM Pallas kernel
        # (ops/mfsgd_kernel.py) — candidate new default if it wins on TPU
        "mfsgd_pallas": lambda: mfsgd.benchmark(
            algo="pallas",
            # smoke tiles must pass the kernel's TPU gate (128-multiples)
            **(SMOKE["mfsgd_pallas"] if smoke else {})),
        # PR 2: the chunked double-buffered rotator at 4 chunks/worker on
        # the flipped pallas stack — finer overlap granularity (quarter
        # slices in flight) than the incumbent 2-chunk schedule; may flip
        # MFSGDConfig.rotate_chunks=4 via flip_decision (quality gate:
        # rmse_final — the visit order changes, the math does not)
        "mfsgd_chunked_rotate": lambda: mfsgd.benchmark(
            algo="pallas", rotate_chunks=4,
            **(SMOKE["mfsgd_pallas"] if smoke else {})),
        "lda": lambda: lda.benchmark(
            **(SMOKE["lda"] if smoke else
               {"pack_cache": BENCH_DATA})),
        # round 4: doc-tile carried across its od-run (one flush/load per
        # run instead of per entry) — the VERDICT r3 item 2 Db-carry, now
        # a flag; bit-identical chain (tested), TPU verdict pending
        "lda_carry": lambda: lda.benchmark(
            carry_db=True,
            **(SMOKE["lda"] if smoke else
               {"pack_cache": BENCH_DATA})),
        # round 3: exponential-race topic draw (identical distribution,
        # ~5× fewer VPU transcendentals) — candidate default if it wins
        "lda_exprace": lambda: lda.benchmark(
            sampler="exprace",
            **(SMOKE["lda"] if smoke else
               {"pack_cache": BENCH_DATA})),
        # round 3: exprace + hardware RNG together — the candidate new
        # default sampling stack; vs lda/lda_exprace it attributes the
        # win between sampler math and bit generation
        "lda_fast": lambda: lda.benchmark(
            sampler="exprace", rng_impl="rbg",
            **(SMOKE["lda"] if smoke else
               {"pack_cache": BENCH_DATA})),
        # round 3: the whole entry fused into one VMEM kernel
        # (ops/lda_kernel.py) — candidate new default if it wins on TPU.
        # round 4: gathers are EXACT by default (base-256 digit planes)
        "lda_pallas": lambda: lda.benchmark(
            algo="pallas",
            **(SMOKE["lda_pallas"] if smoke else
               {"pack_cache": BENCH_DATA})),
        # round 4: the single-dot bf16 gather variant (counts > 256 round
        # ~0.4% in the posterior) — may flip pallas_exact_gathers=False
        # only if ≥10% faster at equal chain likelihood (flip_decision)
        "lda_pallas_approx": lambda: lda.benchmark(
            algo="pallas", pallas_exact_gathers=False,
            **(SMOKE["lda_pallas"] if smoke else
               {"pack_cache": BENCH_DATA})),
        # VERDICT r4 item 7: the exact-vs-approx gather A/B at a shape
        # whose counts EXCEED 256 from initialization (avg Nwk cell =
        # 4M tok / (256 vocab × 32 topics) ≈ 488) — at the default sweep
        # shape counts stay double-digit, so bf16 rounding physically
        # cannot show in the LL and the quality gate would pass vacuously.
        # pallas_exact_gathers=False may flip only if BOTH the
        # default-shape speed gate and THIS LL gate pass (flip_decision).
        "lda_pallas_hot": lambda: lda.benchmark(
            algo="pallas",
            **(SMOKE["lda_pallas"] if smoke else
               {"n_docs": 20_000, "vocab_size": 256, "n_topics": 32,
                "tokens_per_doc": 200, "d_tile": 128, "w_tile": 128,
                "pack_cache": BENCH_DATA})),
        "lda_pallas_approx_hot": lambda: lda.benchmark(
            algo="pallas", pallas_exact_gathers=False,
            **(SMOKE["lda_pallas"] if smoke else
               {"n_docs": 20_000, "vocab_size": 256, "n_topics": 32,
                "tokens_per_doc": 200, "d_tile": 128, "w_tile": 128,
                "pack_cache": BENCH_DATA})),
        # round 4: fused kernel + carried doc tile — the two HBM levers
        # stacked (entry VMEM-residency from the kernel, od-run tile
        # amortization from the carry)
        "lda_pallas_carry": lambda: lda.benchmark(
            algo="pallas", carry_db=True,
            **(SMOKE["lda_pallas"] if smoke else
               {"pack_cache": BENCH_DATA})),
        # PR 2: int8 rotate wire on the flipped default stack — quarter
        # the ring bytes per word-slice hop (collective.rotate_quantized;
        # one rounding per hop, but counts dequantize lossily so the
        # chain samples against perturbed word-topic counts — the LL
        # flip gate decides whether quality holds).  Shares the 2-chunk
        # pack cache with lda_pallas_carry (wire is not layout)
        "lda_rotate_int8": lambda: lda.benchmark(
            algo="pallas", carry_db=True, rotate_wire="int8",
            **(SMOKE["lda_pallas"] if smoke else
               {"pack_cache": BENCH_DATA})),
        # PR 11: the planner's bf16 reshard wire on the flipped default
        # stack — half the ring bytes at ONE rounding per hop (better
        # conditioned than int8's lossy count dequant), the middle rung
        # the Plan row prices between exact and int8.  EXCLUSIVE with
        # lda_rotate_int8 in flip_decision: rotate_wire is one knob.
        "lda_planner_wire": lambda: lda.benchmark(
            algo="pallas", carry_db=True, rotate_wire="bf16",
            **(SMOKE["lda_planner_wire"] if smoke else
               {"pack_cache": BENCH_DATA})),
        "lda_scatter": lambda: lda.benchmark(
            algo="scatter",
            **(SMOKE["lda_scatter"] if smoke
               else {"pack_cache": BENCH_DATA})),
        # PR 6: steady-state serving — synthetic state at the graded
        # shapes (kmeans k=100/d=300 centroids; ML-20M-sized factors),
        # single-row requests in bursts: the latency ladder the "serve
        # heavy traffic" north-star leg is graded on.  Self-contained
        # (no checkpoint on the relay host); AOT cache in a temp dir so
        # each run measures a true cold start + warm steady state.
        "serve_kmeans": lambda: serve_bench.benchmark(
            app="kmeans",
            **(SMOKE["serve_kmeans"] if smoke else
               {"n_requests": 2048, "rows_per_request": 1,
                "state_shape": {"k": 100, "d": 300}})),
        "serve_mfsgd_topk": lambda: serve_bench.benchmark(
            app="mfsgd", topk=10,
            **(SMOKE["serve_mfsgd_topk"] if smoke else
               {"n_requests": 2048, "rows_per_request": 1,
                "state_shape": {"n_users": 138_493, "n_items": 26_744,
                                "rank": 64}})),
        # PR 7: sustained-load A/B at the same graded state shapes —
        # single-row requests on one seeded trace offered at 2× the
        # calibrated burst capacity (both planes saturated, so policy
        # not arrival luck decides), 4096 requests so the backlog can
        # fill 512-rungs (see the bench_common smoke comment)
        "serve_kmeans_sustained": lambda: serve_bench.benchmark_sustained(
            app="kmeans",
            **(SMOKE["serve_kmeans_sustained"] if smoke else
               {"n_requests": 4096, "rows_per_request": 1,
                "state_shape": {"k": 100, "d": 300}})),
        "serve_mfsgd_sustained": lambda: serve_bench.benchmark_sustained(
            app="mfsgd", topk=10,
            **(SMOKE["serve_mfsgd_sustained"] if smoke else
               {"n_requests": 4096, "rows_per_request": 1,
                "state_shape": {"n_users": 138_493, "n_items": 26_744,
                                "rank": 64}})),
        # ladder configs AFTER the default-shape flip pairs: the
        # relay can die mid-sweep, and the round-4 priority is the
        # candidates table (a dead relay at minute 40 should have
        # already measured every gated pair)
        # graded-scale ladder (VERDICT r1 item 5): 500k docs × 1k topics
        # with the int16 doc-topic table (2 GB instead of 4 GB at 1M docs)
        "lda_scale": lambda: lda.benchmark(
            **({"n_docs": 512, "vocab_size": 128, "n_topics": 8,
                "tokens_per_doc": 16, "epochs": 1, "d_tile": 16,
                "w_tile": 16, "entry_cap": 64, "ndk_dtype": "int16"}
               if smoke else
               {"n_docs": 500_000, "vocab_size": 50_000, "n_topics": 1000,
                "tokens_per_doc": 100, "epochs": 1, "ndk_dtype": "int16",
                "pack_cache": BENCH_DATA})),
        # TRUE graded shapes (enwiki-1M: 1M docs × 1k topics, 100M tokens,
        # int16 Ndk — fits one chip: 2 GB Ndk + 0.23 GB Nwk; the program
        # is lowering-proven in tests/test_lda_scale.py, this EXECUTES it
        "lda_scale_1m": lambda: lda.benchmark(
            **({"n_docs": 1024, "vocab_size": 128, "n_topics": 8,
                "tokens_per_doc": 16, "epochs": 1, "d_tile": 16,
                "w_tile": 16, "entry_cap": 64, "ndk_dtype": "int16"}
               if smoke else
               {"n_docs": 1_000_000, "vocab_size": 50_000,
                "n_topics": 1000, "tokens_per_doc": 100, "epochs": 1,
                "ndk_dtype": "int16", "pack_cache": BENCH_DATA})),
        # the FLIPPED default stack (pallas+exprace+rbg+carry_db,
        # 2026-08-01) at the true graded shape — the dense arm above
        # measured 5.88M tok/s there; this row is the framework's
        # graded-#3 headline after the flip
        "lda_scale_1m_pallas": lambda: lda.benchmark(
            algo="pallas", carry_db=True,
            **({"n_docs": 1024, "vocab_size": 128, "n_topics": 8,
                "tokens_per_doc": 16, "epochs": 1, "d_tile": 16,
                "w_tile": 16, "entry_cap": 64, "ndk_dtype": "int16"}
               if smoke else
               {"n_docs": 1_000_000, "vocab_size": 50_000,
                "n_topics": 1000, "tokens_per_doc": 100, "epochs": 1,
                "ndk_dtype": "int16", "pack_cache": BENCH_DATA})),
        "mlp": lambda: mlp.benchmark(
            **(SMOKE["mlp"] if smoke else {})),
        # PR 8: the quantized-gradient-wire candidates — same shapes as
        # the incumbent "mlp" row, only the allreduce wire differs, so
        # the A/B isolates wire bytes vs train_acc (flip_decision gate)
        "mlp_grad_bf16": lambda: mlp.benchmark(
            cfg=mlp.MLPConfig(grad_wire="bf16"),
            **(SMOKE["mlp"] if smoke else {})),
        "mlp_grad_int8": lambda: mlp.benchmark(
            cfg=mlp.MLPConfig(grad_wire="int8"),
            **(SMOKE["mlp"] if smoke else {})),
        # PR 12: svm/wdamds incumbents + wire candidates (same shapes as
        # their incumbent so the A/B isolates wire bytes vs quality —
        # train_acc for svm, final_stress for wdamds; EXCLUSIVE pairs
        # in flip_decision, one wire slot per knob).  Full shapes are
        # the apps' graded defaults (svm 500k×128, wdamds n=4096).
        "svm": lambda: svm.benchmark(
            **(SMOKE["svm"] if smoke else {})),
        "svm_sv_bf16": lambda: svm.benchmark(
            sv_wire="bf16", **(SMOKE["svm"] if smoke else {})),
        "svm_sv_int8": lambda: svm.benchmark(
            sv_wire="int8", **(SMOKE["svm"] if smoke else {})),
        # PR 16: bf16-staged X (half the H2D bytes on the staging-bound
        # committed wall; dots promote to f32 so only the stored feature
        # precision changes — train_acc gates the flip)
        "svm_x_bf16": lambda: svm.benchmark(
            x_dtype="bf16", **(SMOKE["svm_x_bf16"] if smoke else {})),
        # PR 17: the fused Pegasos kernel arm (ops/svm_kernel.py) —
        # same shapes as the incumbent "svm" row, only the inner-solve
        # schedule differs (one feature pass per step instead of two;
        # train_acc gates the flip)
        "svm_kernel_pallas": lambda: svm.benchmark(
            algo="pallas",
            **(SMOKE["svm_kernel_pallas"] if smoke else {})),
        "wdamds": lambda: wdamds.benchmark(
            **(SMOKE["wdamds"] if smoke else {})),
        "wdamds_coord_bf16": lambda: wdamds.benchmark(
            coord_wire="bf16", **(SMOKE["wdamds"] if smoke else {})),
        "wdamds_coord_int8": lambda: wdamds.benchmark(
            coord_wire="int8", **(SMOKE["wdamds"] if smoke else {})),
        # PR 16: bf16-staged dissimilarity matrix (the n² delta is the
        # dominant staged buffer; final_stress gates the flip)
        "wdamds_delta_bf16": lambda: wdamds.benchmark(
            delta_dtype="bf16",
            **(SMOKE["wdamds_delta_bf16"] if smoke else {})),
        # PR 17: the fused SMACOF kernel arm (ops/wdamds_kernel.py) —
        # same shapes as the incumbent "wdamds" row, only the Guttman
        # step schedule differs (D/ratio stay in VMEM; final_stress
        # gates the flip)
        "wdamds_dist_pallas": lambda: wdamds.benchmark(
            algo="pallas",
            **(SMOKE["wdamds_dist_pallas"] if smoke else {})),
        "subgraph": lambda: subgraph.benchmark(
            **(SMOKE["subgraph"] if smoke else {})),
        # PR 16: half-width padded CSR on the graded uniform graph — the
        # staged adjacency halves, the clipped tail rides the exact
        # overflow segment path (estimate equality gates the flip)
        "subgraph_csr32": lambda: subgraph.benchmark(
            max_degree=32,
            **(SMOKE["subgraph_csr32"] if smoke else {})),
        # overflow-tail A/B pair (r2 verdict item 7): POWERLAW graph so
        # the tail carries real mass (the uniform graded config's
        # ~Poisson(16) degrees never exceed max_degree=64 — segment vs
        # onehot would execute identical work and the A/B would read
        # 1.0x at any truth); identical counts by construction —
        # flip_decision compares the rates and asserts the estimates
        # match to 1e-6 before overflow_algo may change default
        "subgraph_pl": lambda: subgraph.benchmark(
            graph="powerlaw", max_degree=16,
            **(SMOKE["subgraph"] if smoke else {})),
        "subgraph_onehot": lambda: subgraph.benchmark(
            graph="powerlaw", max_degree=16, overflow_algo="onehot",
            **(SMOKE["subgraph"] if smoke else {})),
        # the graded template at graded scale (VERDICT r2 item 4): u5-tree
        # on a 1M-vertex power-law graph — hub mass rides the exact
        # overflow segment-sum path (overflow_share reported; 0 dropped)
        "subgraph_1m": lambda: subgraph.benchmark(
            graph="powerlaw",
            **({**SMOKE["subgraph"], "max_degree": 8}
               if smoke else
               {"n_vertices": 1_000_000, "avg_degree": 8,
                "max_degree": 16, "template": "u5-tree"})),
        "subgraph_1m_onehot": lambda: subgraph.benchmark(
            graph="powerlaw", overflow_algo="onehot",
            **({**SMOKE["subgraph"], "max_degree": 8}
               if smoke else
               {"n_vertices": 1_000_000, "avg_degree": 8,
                "max_degree": 16, "template": "u5-tree"})),
        "rf": lambda: rf.benchmark(
            **({**SMOKE["rf"], "n_trees": 2 * jax.device_count()}
               if smoke else {})),
        # PR 16: the histogram-formulation A/B the profile pass priced —
        # dense one-hot MXU (the incumbent default's mechanism) vs the
        # 25 GB/s scatter wall; counts are bit-identical int32, so
        # train_acc gates only against harness drift
        "rf_dense_hist": lambda: rf.benchmark(
            hist_algo="dense",
            **({**SMOKE["rf_dense_hist"], "n_trees": 2 * jax.device_count()}
               if smoke else {})),
        "rf_scatter_hist": lambda: rf.benchmark(
            hist_algo="scatter",
            **({**SMOKE["rf_scatter_hist"],
                "n_trees": 2 * jax.device_count()}
               if smoke else {})),
        # PR 17: the on-chip histogram kernel arm (ops/rf_kernel.py) —
        # bit-identical counts to the dense arm (tests assert it), only
        # the memory schedule differs; CONDITIONAL on rf_dense_hist in
        # flip_decision
        "rf_hist_pallas": lambda: rf.benchmark(
            hist_algo="pallas",
            **({**SMOKE["rf_hist_pallas"],
                "n_trees": 2 * jax.device_count()}
               if smoke else {})),
        # the REAL-ingest half of the north-star (disk npy memmap through
        # fit_streaming; VERDICT r2 item 2) — full mode keeps a 12 GB
        # float16 file in .bench_data/ for reuse; the honest 100M-row run
        # is scripts/bench_ingest.py directly (60 GB, host-bound).
        # LAST deliberately: generating the file on this 1-core host took
        # 864 s of the 1200 s watchdog window on 2026-07-31 and the
        # watchdog exit then skipped every config after it — a slow
        # ingest can only cost itself here (and measure_on_relay.sh
        # pre-generates outside any watchdog)
        "kmeans_ingest": lambda: _bench_ingest(smoke),
        "kmeans_ingest_int8": lambda: _bench_ingest(smoke,
                                                    quantize="int8"),
    }
    assert set(SPRINT_ORDER) == set(configs), (
        set(SPRINT_ORDER) ^ set(configs))  # config added to one list only
    configs = {name: configs[name] for name in SPRINT_ORDER}
    env = {
        "date": datetime.date.today().isoformat(),
        "backend": jax.default_backend(),
        "n_devices": jax.device_count(),
        "jax": jax.__version__,
        "smoke": smoke,
        # the r2 verdict's stale-claims weakness was ATTRIBUTION: a rate
        # means little without the code it measured
        "commit": _git_commit(),
    }
    for name, fn in configs.items():
        if only and name not in only:
            continue
        if skip and name in skip:
            continue
        if watchdog is not None:
            watchdog.arm(name)  # restart the hang clock per config
        try:
            result = fn()
        except Exception as e:  # keep measuring the rest
            yield {"config": name, "error": f"{type(e).__name__}: {e}", **env}
            continue
        from harp_tpu.utils.roofline import annotate

        result = annotate(name, result)  # % of v5e peak, where modeled
        yield {"config": name,
               **{k: (round(v, 4) if isinstance(v, float) else v)
                  for k, v in result.items()}, **env}
    if watchdog is not None:
        watchdog.cancel()


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None, help="append JSONL records here")
    p.add_argument("--smoke", action="store_true")
    # one list for --only AND --skip: a typo in either is an argparse
    # error, never a silent empty sweep or a silently-unskipped config
    # derived from SPRINT_ORDER so a config added there is immediately
    # addressable here (a hand-copied list drifted in round 5: the hot
    # LL-gate pair was briefly un-skippable)
    config_names = sorted(SPRINT_ORDER)
    p.add_argument("--only", nargs="+", default=None, metavar="CONFIG",
                   choices=config_names,
                   help="subset of configs to run (typo → argparse error, "
                        "not a silent empty sweep)")
    p.add_argument("--skip", nargs="+", default=None, metavar="CONFIG",
                   choices=config_names,
                   help="configs to exclude (the relay sprint skips the "
                        "pallas configs when kernel_equiv_check.py fails "
                        "on silicon — ADVICE r3: no pallas row may be "
                        "recorded before the equivalence check passes; a "
                        "typo'd skip must error, not silently record an "
                        "unverified row)")
    p.add_argument("--platform", choices=["cpu"], default=None,
                   help="force the CPU backend (the axon site pin would "
                        "otherwise send even --smoke runs to the TPU "
                        "relay, which can hang — CLAUDE.md)")
    # PR 13: perfmodel sprint pruning — the model's candidate ranking
    # mapped onto the --only machinery; gate partners are ALWAYS pulled
    # in (gate_closure), so a pruned sprint can still produce verdicts
    p.add_argument("--predicted-top", type=int, default=None, metavar="N",
                   help="run only the perfmodel's top-N predicted flip "
                        "candidates (plus their JOINT/EXCLUSIVE "
                        "partners and CONDITIONAL anchors — "
                        "flip_decision's gates stay authoritative); "
                        "mutually exclusive with --only")
    p.add_argument("--topology",
                   choices=("auto", "single_chip", "sim_ring_8", "v4_32"),
                   default="v4_32",
                   help="topology the --predicted-top ranking prices "
                        "wire terms against (default: the north-star "
                        "v4_32 slice)")
    p.add_argument("--dry-run", action="store_true",
                   help="print the selected config list and exit "
                        "without benchmarking anything (CPU-only; the "
                        "drive_check/CI hook for --predicted-top)")
    args = p.parse_args(argv)
    if args.predicted_top is not None:
        if args.only:
            p.error("--predicted-top computes its own --only list; "
                    "pass one or the other")
        only, ranked, unpriced = predicted_only(args.predicted_top,
                                                args.topology)
        print(json.dumps({"predicted_top": args.predicted_top,
                          "topology": args.topology,
                          "ranking": ranked, "unpriced": unpriced,
                          "only": only}), file=sys.stderr, flush=True)
        args.only = only
    if args.dry_run:
        sel = [c for c in SPRINT_ORDER
               if (not args.only or c in args.only)
               and not (args.skip and c in args.skip)]
        print(json.dumps({"dry_run": True, "would_run": sel}))
        return
    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    sink = open(args.out, "a") if args.out else None

    def emit(rec):
        line = json.dumps(rec)
        print(line, flush=True)
        if sink:
            sink.write(line + "\n")
            sink.flush()

    # A relay hang is uninterruptible from Python (CLAUDE.md), so recovery
    # within the process is impossible: the watchdog names the hung config
    # in a final error record (prior records are already flushed) and exits.
    from harp_tpu.utils.timing import HangWatchdog

    watchdog = HangWatchdog(
        on_fire=lambda what: emit(
            {"config": what,
             "error": f"hang: no result after {watchdog.timeout_s:.0f}s "
                      "(TPU relay suspected)"}))
    # Armed before run_all's `import jax`: the relay hang strikes at first
    # backend use, which happens while building the env dict.
    watchdog.arm("backend init")
    try:
        for rec in run_all(args.smoke, args.only, watchdog, args.skip):
            emit(rec)
    finally:
        watchdog.cancel()
        if sink:
            sink.close()


if __name__ == "__main__":
    main()
