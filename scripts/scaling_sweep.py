#!/usr/bin/env python
"""1→N simulated-worker scaling curves for the graded apps.

VERDICT r4 item 5: the framework targets a v4-32 pod but had no scaling
evidence at all.  This script produces the half that needs no relay:
weak- and strong-scaling sweeps of every graded app over 1/2/4/8
simulated CPU workers, with the collective share of each run measured
from an XLA trace (`utils.profiling.op_breakdown` self-times, classified
by op name).  One JSON row per (app, mode, n_workers) → SCALING_local.jsonl.
Each row also carries per-worker SKEW columns (skew_work / skew_max_mean /
skew_wasted_frac, from the utils/skew.py ledger the instrumented drivers
feed during the telemetry-enabled warmup run), so
`scripts/project_scaling.py` can attribute efficiency loss to load
imbalance separately from collective overhead.

The device count is baked into XLA at backend init, so the parent spawns
one child subprocess per worker count (`--child`), each with its own
``--xla_force_host_platform_device_count=N``; children force the CPU
backend in-process (the axon site pin overrides the env var, CLAUDE.md).

Reading the rows (CPU-sim caveat, recorded in every row): absolute CPU
rates are non-predictive of TPU (BASELINE.md's onehot 7.8× CPU
inversion).  What transfers is (a) the SHAPE of the weak/strong curves —
how collective overhead grows with worker count under a fixed-bandwidth
memory system — and (b) the measured collective-op share, which bounds
the comm-byte models `scripts/project_scaling.py` feeds with measured
TPU compute rates + ICI bandwidth to produce the v4-32 projection
(BASELINE.md scaling section).

Usage:
  python scripts/scaling_sweep.py [--out SCALING_local.jsonl]
      [--workers 1 2 4 8] [--apps kmeans ...] [--modes strong weak]
"""

import argparse
import datetime
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

APPS = ("kmeans", "mfsgd", "lda", "mlp", "subgraph", "rf")

#: substrings identifying collective ops in XLA span names (CPU and TPU
#: use the same HLO names: all-reduce.3, collective-permute.1, ...)
COMM_MARKERS = ("all-reduce", "all-gather", "all-to-all",
                "collective-permute", "reduce-scatter", "collective")

#: headline rate key per app (mirrors bench.py UNITS); *_per_chip keys
#: are multiplied by N for the total-rate scaling curves
RATE_KEYS = {
    "kmeans": "iters_per_sec",
    "mfsgd": "updates_per_sec_per_chip",
    "lda": "tokens_per_sec_per_chip",
    "mlp": "samples_per_sec",
    "subgraph": "vertices_per_sec",
    "rf": "trees_per_sec",
}


def shapes(app: str, mode: str, n: int) -> dict:
    """Benchmark kwargs for one (app, mode, n_workers) cell.

    strong: total problem fixed (divisible by 8) — speedup curve.
    weak: per-worker work fixed — efficiency curve.  Shapes are sized so
    the slowest cell stays tens of seconds on this 1-core CPU host.
    """
    w = n if mode == "weak" else 8  # weak grows with n; strong is fixed
    if app == "kmeans":
        return {"n": 16384 * w, "d": 64, "k": 64, "iters": 5}
    if app == "mfsgd":
        # rotation app: users+ratings shard; item factors rotate
        return {"n_users": 256 * w, "n_items": 512, "nnz": 32768 * w,
                "rank": 16, "epochs": 1, "u_tile": 32, "i_tile": 32,
                "entry_cap": 256}
    if app == "lda":
        # rotation+pushpull app: docs shard; word-topic slices rotate
        return {"n_docs": 256 * w, "vocab_size": 512, "n_topics": 16,
                "tokens_per_doc": 32, "epochs": 1, "d_tile": 32,
                "w_tile": 32, "entry_cap": 128}
    if app == "mlp":
        return {"n": 1024 * w, "batch": 128 * w, "steps": 10}
    if app == "subgraph":
        return {"n_vertices": 2048 * w, "avg_degree": 8}
    if app == "rf":
        return {"n": 2048 * w, "f": 32, "max_depth": 4, "n_trees": 8}
    raise ValueError(app)


def skew_columns():
    """Per-worker skew columns for a sweep row, from the SkewLedger the
    instrumented drivers fed during the (telemetry-enabled) warmup run.
    Picks the heaviest EXECUTION phase — the superstep the app's barrier
    actually waits on; apps without instrumented drivers yield the
    ingest view instead, and apps recording nothing yield one null
    marker so downstream readers see "not measured", not "balanced"."""
    from harp_tpu.utils import skew

    s = skew.ledger.summary()
    execs = {k: v for k, v in s.items() if v["source"] == "execution"} \
        or {k: v for k, v in s.items() if v["source"] == "ingest"}
    if not execs:
        return {"skew_max_mean": None}
    phase = max(execs, key=lambda k: execs[k]["total"])
    v = execs[phase]
    return {"skew_phase": phase, "skew_unit": v["unit"],
            "skew_work": v["work"],
            "skew_max_mean": v["max_mean_ratio"],
            "skew_wasted_frac": v["wasted_frac"]}


def child(app: str, mode: str, n: int, emit=print) -> None:
    """Run one cell in THIS process (device count fixed at init)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import tempfile
    import time

    from harp_tpu.models import kmeans, lda, mfsgd, mlp, rf, subgraph
    from harp_tpu.utils import skew, telemetry
    from harp_tpu.utils.profiling import op_breakdown, trace

    mod = {"kmeans": kmeans, "mfsgd": mfsgd, "lda": lda, "mlp": mlp,
           "subgraph": subgraph, "rf": rf}[app]
    kw = shapes(app, mode, n)
    assert jax.device_count() == n, (jax.device_count(), n)
    # warmup/compile OUTSIDE the trace; telemetry on for THIS run only,
    # so the drivers feed the skew ledger while the traced (timed) run
    # stays instrumentation-free — the host-phase stamp per subprocess
    # plus per-worker device counters, zero cost in the timed region
    telemetry.enable(True)
    t_warm = time.perf_counter()
    mod.benchmark(**kw)
    skew.record_host(f"{app}.child", 0, time.perf_counter() - t_warm,
                     n_workers=1)
    skew_cols = skew_columns()
    telemetry.enable(False)
    logdir = tempfile.mkdtemp(prefix=f"harp_scale_{app}_{n}_")
    t0 = time.perf_counter()
    with trace(logdir):
        result = mod.benchmark(**kw)
    wall = time.perf_counter() - t0
    ops = op_breakdown(logdir, top=10 ** 6)  # every span, self-time
    traced = sum(t for _, t in ops)
    comm = sum(t for name, t in ops
               if any(m in name.lower() for m in COMM_MARKERS))
    rate_key = RATE_KEYS[app]
    rate = float(result[rate_key])
    total = rate * n if rate_key.endswith("_per_chip") else rate
    emit(json.dumps({
        "app": app, "mode": mode, "n_workers": n,
        "rate": round(rate, 4), "rate_key": rate_key,
        "total_rate": round(total, 4),
        "wall_sec": round(wall, 4),
        "traced_sec": round(traced, 5),
        "comm_sec": round(comm, 5),
        "comm_fraction": round(comm / traced, 4) if traced else None,
        **skew_cols,
        "backend": "cpu", "cpu_sim": True,
        "date": datetime.date.today().isoformat(),
    }), flush=True)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=os.path.join(REPO, "SCALING_local.jsonl"))
    p.add_argument("--workers", nargs="+", type=int, default=[1, 2, 4, 8])
    p.add_argument("--apps", nargs="+", choices=APPS, default=list(APPS))
    p.add_argument("--modes", nargs="+", choices=["strong", "weak"],
                   default=["strong", "weak"])
    p.add_argument("--child", nargs=3, metavar=("APP", "MODE", "N"),
                   default=None, help="internal: run one cell in-process")
    args = p.parse_args(argv)
    if args.child:
        child(args.child[0], args.child[1], int(args.child[2]))
        return 0
    sink = open(args.out, "a")
    failures = 0
    for app in args.apps:
        for mode in args.modes:
            for n in args.workers:
                env = dict(os.environ)
                env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                                    + f" --xla_force_host_platform_device"
                                      f"_count={n}")
                row = None
                try:
                    r = subprocess.run(
                        [sys.executable, os.path.abspath(__file__),
                         "--child", app, mode, str(n)],
                        capture_output=True, text=True, env=env, cwd=REPO,
                        timeout=1800)
                except subprocess.TimeoutExpired:
                    # a hung cell must cost only itself, like the
                    # returncode path below (review finding, round 5)
                    r = None
                    err = "timeout after 1800s (hung cell)"
                else:
                    for line in reversed(r.stdout.strip().splitlines()):
                        if line.startswith("{"):
                            row = line
                            break
                    err = (r.stderr.strip().splitlines() or ["?"])[-1]
                if r is None or r.returncode != 0 or row is None:
                    failures += 1
                    row = json.dumps({
                        "app": app, "mode": mode, "n_workers": n,
                        "error": err,
                        "backend": "cpu", "cpu_sim": True})
                print(row, flush=True)
                sink.write(row + "\n")
                sink.flush()
    sink.close()
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
