#!/usr/bin/env python
"""Re-annotate committed bench records with the CURRENT roofline model.

Why this exists (VERDICT round 3, weak #2 / next #5): `roofline.annotate`
is pure — every BENCH_local.jsonl row stores its raw measured fields, so
when the work model is corrected (e.g. the 2026-07-31 bf16-default peak
fix, roofline.py:27-33) the committed records of record can be refreshed
without hardware.  Stale annotations otherwise contradict the current
annotator (the pre-fix kmeans row claimed 97.28% of an f32 peak the
matmuls never run against; kmeans_stream claimed an impossible 128.95%).

Usage: python scripts/reannotate.py [path ...]
Defaults to BENCH_local.jsonl at the repo root.  Rows are rewritten in
place; rows without a work model or without their metric field pass
through unchanged (annotate()'s own contract).  A `reannotated` date
stamp is added to any row whose annotation changed, so a reader can tell
a refreshed row from an original one.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROOF_KEYS = ("achieved_tflops", "achieved_gbs", "pct_peak_flops",
             "pct_peak_bw", "roofline_peak", "bound")


def reannotate_file(path: str) -> int:
    from harp_tpu.utils.roofline import annotate

    changed = 0
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rows.append(json.loads(line))
    for i, row in enumerate(rows):
        config = row.get("config")
        if not config:
            continue
        stripped = {k: v for k, v in row.items() if k not in ROOF_KEYS}
        fresh = annotate(config, stripped)
        if any(fresh.get(k) != row.get(k) for k in ROOF_KEYS):
            import datetime

            fresh["reannotated"] = datetime.date.today().isoformat()
            rows[i] = fresh
            changed += 1
    if changed:
        with open(path, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
    return changed


def main():
    paths = sys.argv[1:] or [os.path.join(REPO, "BENCH_local.jsonl")]
    for path in paths:
        n = reannotate_file(path)
        print(f"{path}: {n} row(s) re-annotated")


if __name__ == "__main__":
    main()
