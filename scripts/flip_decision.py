#!/usr/bin/env python
"""Encode BASELINE.md's default-flip rule: ≥10% faster AT EQUAL QUALITY.

VERDICT r3 weak #5 / next #6: the decision rule existed only as prose —
a fast-but-degraded candidate kernel could become a default with nobody
noticing, because nothing in code compared the candidate's quality field
against the incumbent's.  This module is that comparison.

Each candidate config in CANDIDATES names its incumbent, its throughput
metric, its quality field, the direction quality improves, and the
tolerance inside which the two count as "equal quality".  ``decide``
takes the two measured rows and returns a verdict dict; the CLI reads
BENCH_local.jsonl (last non-error full-shape row per config wins),
prints one verdict JSON line per candidate, and exits 1 if any verdict
could not be computed (missing rows must block the flip, not pass it).

A flip verdict here authorizes the one-line default change listed in
BASELINE.md's candidates table (MFSGDConfig.algo, LDAConfig.sampler/
rng_impl/algo, KMeansConfig.use_pallas, SubgraphConfig.overflow_algo);
the BASELINE.md row and bench.py BASELINES update in the same commit.

Tolerances (stated, per VERDICT "within a stated tolerance"):
- rmse_final (lower better, rel 2%): the pallas kernel replays the dense
  update order, so real parity is ~bit-level; 2% allows accumulation-
  order noise only.
- log_likelihood (higher better, abs 0.05 nats/token): exprace/rbg draw
  from the identical distribution with a different stream; 2-epoch mean
  per-token LL jitters ~0.01 across seeds, while a biased sampler (e.g.
  the bf16-count rounding ADVICE r3 flags) shows up well above 0.05.
- inertia (lower better, rel 1%): int8 quantization measured 1.2e-4 rel
  on the graded shape (BENCH_local 2026-07-31); 1% is ~100× that.
- estimate (equal, rel 1e-6): segment/onehot are the same exact counts —
  BASELINE.md says "identical to 7 digits".
- train_acc (higher better, abs 0.005).
"""

import argparse
import json
import os
import sys

# candidate → how to judge it (see module doc for tolerance rationale)
CANDIDATES = {
    "mfsgd_pallas": {
        "incumbent": "mfsgd", "metric": "updates_per_sec_per_chip",
        "quality": "rmse_final", "sense": "lower", "rel_tol": 0.02,
        "flips": "MFSGDConfig.algo='pallas'"},
    "mfsgd_carry": {
        "incumbent": "mfsgd", "metric": "updates_per_sec_per_chip",
        "quality": "rmse_final", "sense": "lower", "rel_tol": 0.02,
        "flips": "MFSGDConfig.carry_w=True"},
    "lda_exprace": {
        "incumbent": "lda", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.sampler='exprace'"},
    "lda_fast": {
        "incumbent": "lda", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.sampler='exprace', rng_impl='rbg'"},
    "lda_pallas": {
        "incumbent": "lda", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.algo='pallas'"},
    # the ADVICE-r3 likelihood A/B in gate form: approx (single-dot bf16)
    # gathers may become the kernel default only by beating the exact
    # kernel ≥10% at equal chain likelihood
    "lda_pallas_approx": {
        "incumbent": "lda_pallas", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.pallas_exact_gathers=False (ALSO requires the "
                 "lda_pallas_approx_hot LL gate)"},
    # VERDICT r4 item 7: the same knob gated at a >256-count shape where
    # bf16 gather rounding CAN show in the LL (default sweep counts are
    # double-digit — there the quality gate passes vacuously).  The knob
    # flips only if BOTH this and lda_pallas_approx say flip.
    "lda_pallas_approx_hot": {
        "incumbent": "lda_pallas_hot", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.pallas_exact_gathers=False (hot-count LL gate; "
                 "flip only together with lda_pallas_approx)"},
    # VERDICT r3 item 2's Db-carry, bit-identical chain by construction
    # (same tile cores, tested) — the gate still demands the quality
    # field so a broken carry can't slip through on speed alone
    "lda_carry": {
        "incumbent": "lda", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.carry_db=True"},
    "lda_pallas_carry": {
        "incumbent": "lda_pallas", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.carry_db=True (pallas stack)"},
    "kmeans_int8_fused": {
        "incumbent": "kmeans_int8", "metric": "iters_per_sec",
        "quality": "inertia", "sense": "lower", "rel_tol": 0.01,
        "flips": "KMeansConfig.use_pallas=True (int8 path)"},
    "kmeans_stream_int8": {
        "incumbent": "kmeans_stream",
        # prefer the ex-gen rate when present (same rule as roofline.py:
        # synthetic chunk generation is scaffolding outside the work model)
        "metric": "iters_per_sec_ex_gen", "metric_fallback": "iters_per_sec",
        "quality": "inertia", "sense": "lower", "rel_tol": 0.01,
        "flips": "kmeans_stream default quantize='int8'"},
    # incumbent is the POWERLAW segment twin (subgraph_pl), not the
    # uniform graded config — the uniform graph's overflow share is ~0,
    # so comparing against it would read 1.0x at any truth
    "subgraph_onehot": {
        "incumbent": "subgraph_pl", "metric": "vertices_per_sec",
        "quality": "estimate", "sense": "equal", "rel_tol": 1e-6,
        "flips": "SubgraphConfig.overflow_algo='onehot'"},
    "subgraph_1m_onehot": {
        "incumbent": "subgraph_1m", "metric": "vertices_per_sec",
        "quality": "estimate", "sense": "equal", "rel_tol": 1e-6,
        "flips": "SubgraphConfig.overflow_algo='onehot' (graded scale)"},
}

WIN_THRESHOLD = 1.10  # "wins >=10%" half of the rule

# candidate groups flipping the SAME knob: all must flip or none does
# (main() enforces this after per-candidate verdicts)
JOINT_GATES = [("lda_pallas_approx", "lda_pallas_approx_hot")]


def _metric_key(candidate_row, incumbent_row, spec):
    """Pick ONE metric key valid for BOTH rows, or None.

    The fallback applies only when BOTH rows lack the primary metric —
    dividing an ex-gen rate by an end-to-end rate (mixed basis) would
    overstate the speedup the gate authorizes (ADVICE r4), so a mixed
    pair refuses like the missing-quality path does.
    """
    primary = spec["metric"]
    has_c = candidate_row.get(primary) is not None
    has_i = incumbent_row.get(primary) is not None
    if has_c and has_i:
        return primary
    fb = spec.get("metric_fallback")
    if fb and not has_c and not has_i:
        return fb
    return None


def decide(candidate_row: dict, incumbent_row: dict, spec: dict) -> dict:
    """Apply the ≥10%-at-equal-quality rule to one candidate/incumbent pair.

    Returns {"flip": bool, "speedup": float|None, "quality_ok": bool|None,
    "reason": str, ...}.  Missing rows, error rows, or a missing quality
    field REFUSE the flip — the gate fails closed.
    """
    out = {"flip": False, "speedup": None, "quality_ok": None}
    for which, row in (("candidate", candidate_row),
                       ("incumbent", incumbent_row)):
        if row is None:
            out["reason"] = f"no measured row for {which} — refusing flip"
            return out
        if "error" in row:
            out["reason"] = f"{which} row is an error record — refusing flip"
            return out
    key = _metric_key(candidate_row, incumbent_row, spec)
    if key is None:
        out["reason"] = (f"metric {spec['metric']} missing or on mixed "
                         "basis across the pair — refusing flip")
        return out
    cv, iv = candidate_row.get(key), incumbent_row.get(key)
    if not cv or not iv:
        out["reason"] = f"metric {key} missing — refusing flip"
        return out
    out["speedup"] = round(float(cv) / float(iv), 4)
    cq, iq = candidate_row.get(spec["quality"]), incumbent_row.get(
        spec["quality"])
    if cq is None or iq is None:
        out["reason"] = (f"quality field {spec['quality']!r} missing — "
                         "refusing flip (gate fails closed)")
        return out
    cq, iq = float(cq), float(iq)
    sense = spec["sense"]
    if sense == "lower":
        ok = cq <= iq * (1.0 + spec["rel_tol"])
    elif sense == "higher":
        ok = cq >= iq - spec["abs_tol"]
    elif sense == "equal":
        ok = abs(cq - iq) <= spec["rel_tol"] * max(abs(iq), 1e-30)
    else:  # pragma: no cover — spec typo
        raise ValueError(f"unknown sense {sense!r}")
    out["quality_ok"] = bool(ok)
    out["quality_candidate"] = cq
    out["quality_incumbent"] = iq
    if not ok:
        out["reason"] = (f"QUALITY DEGRADED: {spec['quality']} "
                         f"{cq:.6g} vs incumbent {iq:.6g} — refusing flip "
                         f"regardless of {out['speedup']:.2f}x speed")
        return out
    if out["speedup"] >= WIN_THRESHOLD:
        out["flip"] = True
        out["reason"] = (f"FLIP: {out['speedup']:.2f}x at equal quality — "
                         f"apply {spec['flips']}")
    else:
        out["reason"] = (f"keep incumbent: {out['speedup']:.2f}x < "
                         f"{WIN_THRESHOLD:.2f}x threshold")
    return out


def latest_rows(path: str) -> dict:
    """config → last full-shape non-error TPU row (later lines win).

    CPU-sim rows are skipped like bench.py's ``_last_measured`` does:
    relative CPU speeds are explicitly non-predictive of TPU here
    (BASELINE.md's onehot-vs-segment 7.8× CPU inversion), so they must
    never authorize a flip.
    """
    rows = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue  # sprint tee'd a non-JSON line; skip
                cfg = row.get("config")
                if (not cfg or row.get("smoke") or "error" in row
                        or row.get("backend") == "cpu"):
                    continue
                rows[cfg] = row
    except OSError:
        pass
    return rows


def main(argv=None):
    p = argparse.ArgumentParser()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p.add_argument("--bench", default=os.path.join(repo, "BENCH_local.jsonl"))
    p.add_argument("--only", nargs="+", choices=sorted(CANDIDATES),
                   default=None)
    args = p.parse_args(argv)
    rows = latest_rows(args.bench)
    undecidable = 0
    verdicts = {}
    for name, spec in CANDIDATES.items():
        if args.only and name not in args.only:
            continue
        verdicts[name] = decide(rows.get(name), rows.get(spec["incumbent"]),
                                spec)
    # joint gates IN CODE, not prose: candidates flipping the same knob
    # must ALL say flip, or none does ("apply the FLIP lines above" must
    # stay safe to follow mechanically — review finding, round 5)
    for group in JOINT_GATES:
        present = [n for n in group if n in verdicts]
        if len(present) < 2:
            continue  # --only selected one half; its line stands alone
        if not all(verdicts[n]["flip"] for n in present):
            for n in present:
                if verdicts[n]["flip"]:
                    verdicts[n]["flip"] = False
                    # the veto reason must NOT contain the literal
                    # "FLIP:" marker — an operator grepping for it to
                    # apply flips mechanically must not match a vetoed
                    # line (review finding, round 5)
                    verdicts[n]["reason"] = (
                        "VETOED by joint gate: this half passed "
                        f"({verdicts[n]['speedup']:.2f}x at equal "
                        "quality) but partner gate(s) "
                        f"{[m for m in present if m != n]} refused; "
                        "the knob flips only if every gate flips")
    for name, verdict in verdicts.items():
        if verdict["speedup"] is None or verdict["quality_ok"] is None:
            undecidable += 1
        print(json.dumps({"flip_decision": name,
                          "incumbent": CANDIDATES[name]["incumbent"],
                          **verdict}))
    return 1 if undecidable else 0


if __name__ == "__main__":
    sys.exit(main())
