#!/usr/bin/env python
"""Encode BASELINE.md's default-flip rule: ≥10% faster AT EQUAL QUALITY.

VERDICT r3 weak #5 / next #6: the decision rule existed only as prose —
a fast-but-degraded candidate kernel could become a default with nobody
noticing, because nothing in code compared the candidate's quality field
against the incumbent's.  This module is that comparison.

Each candidate config in CANDIDATES names its incumbent, its throughput
metric, its quality field, the direction quality improves, and the
tolerance inside which the two count as "equal quality".  ``decide``
takes the two measured rows and returns a verdict dict; the CLI reads
BENCH_local.jsonl (last non-error full-shape row per config wins),
prints one verdict JSON line per candidate, and exits 1 if any verdict
could not be computed (missing rows must block the flip, not pass it).

A flip verdict here authorizes the one-line default change listed in
BASELINE.md's candidates table (MFSGDConfig.algo, LDAConfig.sampler/
rng_impl/algo, KMeansConfig.use_pallas, SubgraphConfig.overflow_algo);
the BASELINE.md row and bench.py BASELINES update in the same commit.

Tolerances (stated, per VERDICT "within a stated tolerance"):
- rmse_final (lower better, rel 2%): the pallas kernel replays the dense
  update order, so real parity is ~bit-level; 2% allows accumulation-
  order noise only.
- log_likelihood (higher better, abs 0.05 nats/token): exprace/rbg draw
  from the identical distribution with a different stream; 2-epoch mean
  per-token LL jitters ~0.01 across seeds, while a biased sampler (e.g.
  the bf16-count rounding ADVICE r3 flags) shows up well above 0.05.
- inertia (lower better, rel 1%): int8 quantization measured 1.2e-4 rel
  on the graded shape (BENCH_local 2026-07-31); 1% is ~100× that.
- estimate (equal, rel 1e-3): segment/onehot reformulate the SAME sum
  over the SAME seed-0 coloring, but in f32 — and at the measured
  shapes the counts (1e16–1e18) are far beyond f32's 2^24 exact range,
  so the two summation ORDERS legitimately round differently (measured
  2026-08-01: 1.3e-4 rel at the powerlaw A/B shape, 3.7e-4 at graded
  1M, opposite signs).  1e-3 is ~3× the worst measured order-drift
  while a real counting bug (dropped overflow edges, wrong tail) moves
  the estimate by percents.  The original 1e-6 ("identical to 7
  digits") was calibrated on small exact-range shapes and can never
  pass at scale — it refused the round-5 A/B on rounding noise.
- train_acc (higher better, abs 0.005).
"""

import argparse
import json
import os
import sys

# candidate → how to judge it (see module doc for tolerance rationale)
CANDIDATES = {
    "mfsgd_pallas": {
        "incumbent": "mfsgd", "metric": "updates_per_sec_per_chip",
        "quality": "rmse_final", "sense": "lower", "rel_tol": 0.02,
        "flips": "MFSGDConfig.algo='pallas'"},
    "mfsgd_carry": {
        "incumbent": "mfsgd", "metric": "updates_per_sec_per_chip",
        "quality": "rmse_final", "sense": "lower", "rel_tol": 0.02,
        "flips": "MFSGDConfig.carry_w=True"},
    # PR 2: the chunked rotator at 4 chunks vs the incumbent 2-chunk
    # schedule, both on the flipped pallas stack.  The visit ORDER
    # changes (4n shorter steps instead of 2n), so rmse_final gates a
    # genuinely different-but-equal chain, not a bit-identical one.
    "mfsgd_chunked_rotate": {
        "incumbent": "mfsgd_pallas", "metric": "updates_per_sec_per_chip",
        "quality": "rmse_final", "sense": "lower", "rel_tol": 0.02,
        "flips": "MFSGDConfig.rotate_chunks=4"},
    "lda_exprace": {
        "incumbent": "lda", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.sampler='exprace'"},
    "lda_fast": {
        "incumbent": "lda", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.sampler='exprace', rng_impl='rbg'"},
    "lda_pallas": {
        "incumbent": "lda", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.algo='pallas'"},
    # the ADVICE-r3 likelihood A/B in gate form: approx (single-dot bf16)
    # gathers may become the kernel default only by beating the exact
    # kernel ≥10% at equal chain likelihood
    "lda_pallas_approx": {
        "incumbent": "lda_pallas", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.pallas_exact_gathers=False (ALSO requires the "
                 "lda_pallas_approx_hot LL gate)"},
    # VERDICT r4 item 7: the same knob gated at a >256-count shape where
    # bf16 gather rounding CAN show in the LL (default sweep counts are
    # double-digit — there the quality gate passes vacuously).  The knob
    # flips only if BOTH this and lda_pallas_approx say flip.
    "lda_pallas_approx_hot": {
        "incumbent": "lda_pallas_hot", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.pallas_exact_gathers=False (hot-count LL gate; "
                 "flip only together with lda_pallas_approx)"},
    # VERDICT r3 item 2's Db-carry, bit-identical chain by construction
    # (same tile cores, tested) — the gate still demands the quality
    # field so a broken carry can't slip through on speed alone
    "lda_carry": {
        "incumbent": "lda", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.carry_db=True"},
    "lda_pallas_carry": {
        "incumbent": "lda_pallas", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.carry_db=True (pallas stack)"},
    # PR 2: int8 rotate wire vs the exact wire on the SAME default stack
    # (pallas+carry).  The narrow wire perturbs the word-topic counts a
    # chunk carries (≤ global_max/254 per element per hop), so the LL
    # gate is load-bearing here, not a formality — a degraded chain must
    # refuse the flip no matter the wire-byte saving.
    "lda_rotate_int8": {
        "incumbent": "lda_pallas_carry", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.rotate_wire='int8'"},
    # PR 11: the planner-named bf16 reshard wire — same incumbent and
    # gate as the int8 twin (rotate_wire is ONE knob: the pair is
    # EXCLUSIVE below), half the ring bytes at one bf16 rounding per
    # hop.  The Plan row (python -m harp_tpu plan) prices this site;
    # only this gate can flip it.
    "lda_planner_wire": {
        "incumbent": "lda_pallas_carry", "metric": "tokens_per_sec_per_chip",
        "quality": "log_likelihood", "sense": "higher", "abs_tol": 0.05,
        "flips": "LDAConfig.rotate_wire='bf16'"},
    # PR 11: the planner's hierarchical two-stage psum on the graded
    # kmeans shape.  Quality gates on inertia at the int8 candidates'
    # tolerance: the two-stage reduce only reassociates float sums —
    # orders of magnitude below 1% — so a miss here means a broken
    # schedule, not noise.  A flat-ring measurement SHOULD read ~1.0x
    # and refuse; the flip is expected only from a multi-host window.
    "kmeans_hier_psum": {
        "incumbent": "kmeans", "metric": "iters_per_sec",
        "quality": "inertia", "sense": "lower", "rel_tol": 0.01,
        "flips": "KMeansConfig.psum_schedule='hier'"},
    # PR 8: the quantized gradient wire (ROADMAP decision-machinery
    # item; EQuARX-style bf16/int8 allreduce).  train_acc gates per the
    # module-doc tolerance (abs 0.005): a wire that degrades training
    # must refuse no matter the byte saving.  The pair is EXCLUSIVE
    # below — grad_wire has one default slot.
    "mlp_grad_bf16": {
        "incumbent": "mlp", "metric": "samples_per_sec",
        "quality": "train_acc", "sense": "higher", "abs_tol": 0.005,
        "flips": "MLPConfig.grad_wire='bf16'"},
    "mlp_grad_int8": {
        "incumbent": "mlp", "metric": "samples_per_sec",
        "quality": "train_acc", "sense": "higher", "abs_tol": 0.005,
        "flips": "MLPConfig.grad_wire='int8'"},
    # PR 12: the last per-app wires (planner-named; see
    # plan.planner.FLIP_CANDIDATE_CONFIGS).  svm gates on train_acc at
    # the mlp grad-wire tolerance — a quantized SV exchange that
    # degrades the ensemble must refuse.  wdamds gates on final_stress
    # (lower better) at the kernels' 2% band: SMACOF is a contraction,
    # so surviving wire noise shows as a small stress offset while a
    # broken exchange moves it by large factors.  Both pairs EXCLUSIVE
    # below (one wire slot per knob).
    "svm_sv_bf16": {
        "incumbent": "svm", "metric": "samples_per_sec",
        "quality": "train_acc", "sense": "higher", "abs_tol": 0.005,
        "flips": "SVMConfig.sv_wire='bf16'"},
    "svm_sv_int8": {
        "incumbent": "svm", "metric": "samples_per_sec",
        "quality": "train_acc", "sense": "higher", "abs_tol": 0.005,
        "flips": "SVMConfig.sv_wire='int8'"},
    "wdamds_coord_bf16": {
        "incumbent": "wdamds", "metric": "iters_per_sec",
        "quality": "final_stress", "sense": "lower", "rel_tol": 0.02,
        "flips": "MDSConfig.coord_wire='bf16'"},
    "wdamds_coord_int8": {
        "incumbent": "wdamds", "metric": "iters_per_sec",
        "quality": "final_stress", "sense": "lower", "rel_tol": 0.02,
        "flips": "MDSConfig.coord_wire='int8'"},
    "kmeans_int8_fused": {
        "incumbent": "kmeans_int8", "metric": "iters_per_sec",
        "quality": "inertia", "sense": "lower", "rel_tol": 0.01,
        "flips": "KMeansConfig.use_pallas=True (int8 path)"},
    "kmeans_stream_int8": {
        "incumbent": "kmeans_stream",
        # prefer the ex-gen rate when present (same rule as roofline.py:
        # synthetic chunk generation is scaffolding outside the work model)
        "metric": "iters_per_sec_ex_gen", "metric_fallback": "iters_per_sec",
        "quality": "inertia", "sense": "lower", "rel_tol": 0.01,
        "flips": "kmeans_stream default quantize='int8'"},
    # incumbent is the POWERLAW segment twin (subgraph_pl), not the
    # uniform graded config — the uniform graph's overflow share is ~0,
    # so comparing against it would read 1.0x at any truth
    "subgraph_onehot": {
        "incumbent": "subgraph_pl", "metric": "vertices_per_sec",
        "quality": "estimate", "sense": "equal", "rel_tol": 1e-3,
        "flips": "SubgraphConfig.overflow_algo='onehot'"},
    "subgraph_1m_onehot": {
        "incumbent": "subgraph_1m", "metric": "vertices_per_sec",
        "quality": "estimate", "sense": "equal", "rel_tol": 1e-3,
        "flips": "SubgraphConfig.overflow_algo='onehot' (graded scale)"},
    # PR 16: one flip candidate per app the attribution observatory
    # newly priced.  rf's pair makes CLAUDE.md's 25 GB/s scatter-wall
    # claim a measured verdict on THIS app (the dense one-hot MXU
    # histogram vs the scatter arm — same counts bit-identically, so
    # train_acc gates a genuinely equal chain); the svm/wdamds dtype
    # knobs halve the H2D staging the profile pass named as their
    # walls; subgraph_csr32 halves the padded-CSR ship on the graded
    # uniform shape (Poisson(16) degrees rarely exceed 32 — the
    # overflow path absorbs the tail, so estimate must hold).
    "rf_dense_hist": {
        "incumbent": "rf_scatter_hist", "metric": "trees_per_sec",
        "quality": "train_acc", "sense": "higher", "abs_tol": 0.005,
        "flips": "RFConfig.hist_algo='dense' (confirms the one-hot MXU "
                 "default against the scatter arm)"},
    "svm_x_bf16": {
        "incumbent": "svm", "metric": "samples_per_sec",
        "quality": "train_acc", "sense": "higher", "abs_tol": 0.005,
        "flips": "SVMConfig.x_dtype='bf16'"},
    "wdamds_delta_bf16": {
        "incumbent": "wdamds", "metric": "iters_per_sec",
        "quality": "final_stress", "sense": "lower", "rel_tol": 0.02,
        "flips": "MDSConfig.delta_dtype='bf16'"},
    "subgraph_csr32": {
        "incumbent": "subgraph", "metric": "vertices_per_sec",
        "quality": "estimate", "sense": "equal", "rel_tol": 1e-3,
        "flips": "subgraph benchmark default max_degree=32 (padded-CSR "
                 "width; the overflow path absorbs the tail)"},
    # PR 17: the kernelized arms of the newly priced half (presized
    # offline, Mosaic-proven via HL201 — no silicon rows yet).  svm
    # gates on train_acc at the wire-knob tolerance: the fused kernel
    # replays the same Pegasos sums, so a miss means a broken fusion.
    # wdamds gates on final_stress at the kernels' 2% band (the fused
    # D/ratio block reassociates float sums only).  rf's kernel is
    # bit-identical to the dense arm by construction (tests assert it),
    # so its incumbent is rf_dense_hist — the arm that HOLDS the
    # hist_algo slot — and the pair is EXCLUSIVE below.
    "svm_kernel_pallas": {
        "incumbent": "svm", "metric": "samples_per_sec",
        "quality": "train_acc", "sense": "higher", "abs_tol": 0.005,
        "flips": "SVMConfig.algo='pallas'"},
    "wdamds_dist_pallas": {
        "incumbent": "wdamds", "metric": "iters_per_sec",
        "quality": "final_stress", "sense": "lower", "rel_tol": 0.02,
        "flips": "MDSConfig.algo='pallas'"},
    "rf_hist_pallas": {
        "incumbent": "rf_dense_hist", "metric": "trees_per_sec",
        "quality": "train_acc", "sense": "higher", "abs_tol": 0.005,
        "flips": "RFConfig.hist_algo='pallas'"},
}

WIN_THRESHOLD = 1.10  # "wins >=10%" half of the rule

# candidate groups flipping the SAME knob: all must flip or none does
# (main() enforces this after per-candidate verdicts).  The subgraph
# pair gates overflow_algo at BOTH the controlled powerlaw A/B shape
# and the graded 1M scale — a knob that wins only off-scale must not
# print a FLIP line (round 5).
JOINT_GATES = [("lda_pallas_approx", "lda_pallas_approx_hot"),
               ("subgraph_onehot", "subgraph_1m_onehot")]

# alternatives for the same default slot: MFSGDConfig rejects
# carry_w=True with algo != "dense" (mfsgd.py __post_init__), so both
# FLIP lines applied together would crash the default config — if both
# pass, only the faster prints a FLIP line.  The grad-wire pair (PR 8)
# is the same shape: MLPConfig.grad_wire is one knob, bf16 and int8
# cannot both be its default.
EXCLUSIVE_GATES = [("mfsgd_pallas", "mfsgd_carry"),
                   ("mlp_grad_bf16", "mlp_grad_int8"),
                   # PR 11: LDAConfig.rotate_wire is one default slot —
                   # the int8 and planner-bf16 wires cannot both hold it
                   ("lda_rotate_int8", "lda_planner_wire"),
                   # PR 12: one wire slot per exchange knob
                   ("svm_sv_bf16", "svm_sv_int8"),
                   ("wdamds_coord_bf16", "wdamds_coord_int8")]

# stack-conditional: carry_db=True is one knob, but the evidence row
# that authorizes it depends on which algo the verdicts make default
CONDITIONAL_GATES = {
    "lda_pallas_carry": ("requires", "lda_pallas"),
    "lda_carry": ("requires_not", "lda_pallas"),
    # PR 17: the rf kernel's evidence row measures pallas against the
    # DENSE arm — it authorizes hist_algo='pallas' only on the stack
    # where dense itself held the slot against scatter (an EXCLUSIVE
    # gate would compare the two speedups raw, but they have different
    # incumbents — dense-vs-scatter would veto a winning pallas flip)
    "rf_hist_pallas": ("requires", "rf_dense_hist"),
}


def _metric_key(candidate_row, incumbent_row, spec):
    """Pick ONE metric key valid for BOTH rows, or None.

    The fallback applies only when BOTH rows lack the primary metric —
    dividing an ex-gen rate by an end-to-end rate (mixed basis) would
    overstate the speedup the gate authorizes (ADVICE r4), so a mixed
    pair refuses like the missing-quality path does.
    """
    primary = spec["metric"]
    has_c = candidate_row.get(primary) is not None
    has_i = incumbent_row.get(primary) is not None
    if has_c and has_i:
        return primary
    fb = spec.get("metric_fallback")
    if fb and not has_c and not has_i:
        return fb
    return None


def decide(candidate_row: dict, incumbent_row: dict, spec: dict) -> dict:
    """Apply the ≥10%-at-equal-quality rule to one candidate/incumbent pair.

    Returns {"flip": bool, "speedup": float|None, "quality_ok": bool|None,
    "reason": str, ...}.  Missing rows, error rows, or a missing quality
    field REFUSE the flip — the gate fails closed.
    """
    out = {"flip": False, "speedup": None, "quality_ok": None}
    for which, row in (("candidate", candidate_row),
                       ("incumbent", incumbent_row)):
        if row is None:
            out["reason"] = f"no measured row for {which} — refusing flip"
            return out
        if "error" in row:
            out["reason"] = f"{which} row is an error record — refusing flip"
            return out
    key = _metric_key(candidate_row, incumbent_row, spec)
    if key is None:
        out["reason"] = (f"metric {spec['metric']} missing or on mixed "
                         "basis across the pair — refusing flip")
        return out
    cv, iv = candidate_row.get(key), incumbent_row.get(key)
    if not cv or not iv:
        out["reason"] = f"metric {key} missing — refusing flip"
        return out
    out["speedup"] = round(float(cv) / float(iv), 4)
    cq, iq = candidate_row.get(spec["quality"]), incumbent_row.get(
        spec["quality"])
    if cq is None or iq is None:
        out["reason"] = (f"quality field {spec['quality']!r} missing — "
                         "refusing flip (gate fails closed)")
        return out
    cq, iq = float(cq), float(iq)
    sense = spec["sense"]
    if sense == "lower":
        ok = cq <= iq * (1.0 + spec["rel_tol"])
    elif sense == "higher":
        ok = cq >= iq - spec["abs_tol"]
    elif sense == "equal":
        ok = abs(cq - iq) <= spec["rel_tol"] * max(abs(iq), 1e-30)
    else:  # pragma: no cover — spec typo
        raise ValueError(f"unknown sense {sense!r}")
    out["quality_ok"] = bool(ok)
    out["quality_candidate"] = cq
    out["quality_incumbent"] = iq
    if not ok:
        out["reason"] = (f"QUALITY DEGRADED: {spec['quality']} "
                         f"{cq:.6g} vs incumbent {iq:.6g} — refusing flip "
                         f"regardless of {out['speedup']:.2f}x speed")
        return out
    if out["speedup"] >= WIN_THRESHOLD:
        out["flip"] = True
        out["reason"] = (f"FLIP: {out['speedup']:.2f}x at equal quality — "
                         f"apply {spec['flips']}")
    else:
        out["reason"] = (f"keep incumbent: {out['speedup']:.2f}x < "
                         f"{WIN_THRESHOLD:.2f}x threshold")
    return out


def latest_rows(path: str) -> dict:
    """config → last full-shape non-error TPU row (later lines win).

    CPU-sim rows are skipped like bench.py's ``_last_measured`` does:
    relative CPU speeds are explicitly non-predictive of TPU here
    (BASELINE.md's onehot-vs-segment 7.8× CPU inversion), so they must
    never authorize a flip.
    """
    rows = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue  # sprint tee'd a non-JSON line; skip
                cfg = row.get("config")
                if (not cfg or row.get("smoke") or "error" in row
                        or row.get("backend") == "cpu"):
                    continue
                rows[cfg] = row
    except OSError:
        pass
    return rows


def main(argv=None):
    p = argparse.ArgumentParser()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p.add_argument("--bench", default=os.path.join(repo, "BENCH_local.jsonl"))
    p.add_argument("--only", nargs="+", choices=sorted(CANDIDATES),
                   default=None)
    args = p.parse_args(argv)
    rows = latest_rows(args.bench)
    # evaluate every selected candidate PLUS every gate partner/anchor a
    # selected one depends on — "--only subgraph_onehot" must not bypass
    # the graded-scale half of its joint gate (fail open); partners are
    # evaluated but only selected names print (review finding, round 5)
    selected = set(args.only) if args.only else set(CANDIDATES)
    needed = set(selected)
    for group in JOINT_GATES + EXCLUSIVE_GATES:
        if needed & set(group):
            needed |= set(group)
    for name, (_, anchor) in CONDITIONAL_GATES.items():
        if name in needed:
            needed.add(anchor)
    verdicts = {}
    for name, spec in CANDIDATES.items():
        if name not in needed:
            continue
        verdicts[name] = decide(rows.get(name), rows.get(spec["incumbent"]),
                                spec)
    # gates IN CODE, not prose: "apply the FLIP lines above" must stay
    # safe to follow mechanically (round 5).  Veto reasons must NOT
    # contain the literal "FLIP:" marker — an operator grepping for it
    # must never match a vetoed line.
    # 1. joint: same knob, every gate must flip or none does (an
    #    unevaluated partner counts as refused — fail closed)
    blocked_by_unmeasured = False  # a partner's MISSING rows vetoed a
    #                                selected winner -> exit 1 (rerun)

    def _undecided(v):
        return v["speedup"] is None or v["quality_ok"] is None

    for group in JOINT_GATES:
        present = [n for n in group if n in verdicts]
        if not present:
            continue
        if not all(verdicts[n]["flip"] for n in present):
            for n in present:
                if verdicts[n]["flip"]:
                    verdicts[n]["flip"] = False
                    verdicts[n]["reason"] = (
                        "VETOED by joint gate: this half passed "
                        f"({verdicts[n]['speedup']:.2f}x at equal "
                        "quality) but partner gate(s) "
                        f"{[m for m in present if m != n]} refused; "
                        "the knob flips only if every gate flips")
                    if n in selected and any(
                            _undecided(verdicts[m]) for m in present
                            if m != n):
                        blocked_by_unmeasured = True
    # 2. exclusive: alternatives for the same default slot (applying
    #    both would violate the config's own validation) — keep the
    #    faster, veto the rest
    for group in EXCLUSIVE_GATES:
        flipping = sorted(
            (n for n in group if n in verdicts and verdicts[n]["flip"]),
            key=lambda n: -verdicts[n]["speedup"])
        for n in flipping[1:]:
            verdicts[n]["flip"] = False
            verdicts[n]["reason"] = (
                f"VETOED by exclusive gate: {flipping[0]} also flips and "
                f"is faster ({verdicts[flipping[0]]['speedup']:.2f}x vs "
                f"{verdicts[n]['speedup']:.2f}x); the two knobs cannot "
                "both be defaults")
    # 3. conditional: valid only on the stack the anchor verdict selects.
    #    An UNMEASURED anchor is not a verdict at all — both modes veto
    #    and signal exit 1, else requires_not would fail open (apply
    #    carry on the dense stack, then a later sprint flips the algo
    #    and the applied flip is exactly the off-stack evidence this
    #    gate exists to block — review finding, round 5)
    for name, (mode, anchor) in CONDITIONAL_GATES.items():
        if name not in verdicts or not verdicts[name]["flip"]:
            continue
        av = verdicts.get(anchor)
        if av is None or _undecided(av):
            verdicts[name]["flip"] = False
            verdicts[name]["reason"] = (
                "VETOED by conditional gate: this half passed "
                f"({verdicts[name]['speedup']:.2f}x) but its anchor "
                f"{anchor} is UNMEASURED — measure it, then re-decide")
            if name in selected:
                blocked_by_unmeasured = True
            continue
        if (av["flip"] if mode == "requires" else not av["flip"]):
            continue
        verdicts[name]["flip"] = False
        verdicts[name]["reason"] = (
            "VETOED by conditional gate: this half passed "
            f"({verdicts[name]['speedup']:.2f}x) but applies only when "
            f"{anchor} {'flips' if mode == 'requires' else 'does not flip'}"
            " — which is not the verdict")
    # exit 1 is the "rerun the benches" signal: any SELECTED verdict
    # that could not be computed, or a selected winner vetoed because a
    # gate partner's rows are MISSING (not because the partner measured
    # and refused — that is a genuine, fully-decided refusal).  An
    # unmeasured EXCLUSIVE partner never blocks, so it never signals.
    undecidable = 0
    for name, verdict in verdicts.items():
        if name not in selected:
            continue  # evaluated only as a gate partner
        if _undecided(verdict):
            undecidable += 1
        print(json.dumps({"flip_decision": name,
                          "incumbent": CANDIDATES[name]["incumbent"],
                          **verdict}))
    return 1 if (undecidable or blocked_by_unmeasured) else 0


if __name__ == "__main__":
    sys.exit(main())
