#!/bin/bash
# One-shot TPU measurement sweep — run the moment the relay answers.
#
# Probes the relay (bounded, per CLAUDE.md: never block on it), then runs
# the full measurement checklist from BASELINE.md's outage list:
#   1. scripts/measure_all.py  → BENCH_local.jsonl (candidates FIRST —
#      the sweep order prices relay scarcity, VERDICT r4 weak #3 — then
#      incumbent re-measures; roofline annotations; per-config watchdog)
#   2. scripts/flip_decision.py → FLIP_DECISIONS.jsonl (run right after
#      the sweep AND again at the end: a relay death in a later step
#      must not cost the sprint its verdicts)
#   3. bench.py                → one driver-contract JSON line
# Each step is watchdogged (HARP_BENCH_TIMEOUT, default 1200 s/config), so
# a relay that dies mid-sweep still leaves parseable partial records.
# After it finishes: update BASELINE.md rows from BENCH_local.jsonl and
# commit immediately (the relay can die again).
#
# --rehearse: run the WHOLE protocol end-to-end on the CPU backend with
# smoke shapes (VERDICT r4 weak #2: the integrated pipeline must have run
# once before a scarce relay window pays for it).  Sweep records go to
# BENCH_rehearsal.jsonl (never BENCH_local.jsonl); flip decisions run
# against the real committed BENCH_local.jsonl rows, so the rehearsal
# produces a genuine FLIP_DECISIONS.jsonl from existing TPU data.
# Relay-only steps (H2D probe, prewarm, 1B run, wire sweep) print an
# explicit skip line so the rehearsal log shows the full sequence; the
# trace pass DOES run (one smoke config, ~1 min) and failing it fails
# the rehearsal.

set -u
cd "$(dirname "$0")/.."

REHEARSE=""
if [ "${1:-}" = "--rehearse" ]; then
  REHEARSE=1
  OUT=BENCH_rehearsal.jsonl
  SWEEP_FLAGS="--smoke --platform cpu"
  EQUIV_ARGS="cpu8"
  # required new-record count scales with the gate below
  MIN_NEW=5
  echo "== REHEARSAL: CPU backend, smoke shapes, out=${OUT} =="
else
  OUT=BENCH_local.jsonl
  SWEEP_FLAGS=""
  EQUIV_ARGS=""
  MIN_NEW=5
fi

# Relay-watcher arming check (CLAUDE.md round-5 note): the watcher is
# NOT self-starting after environment resets, and a forgotten arm loses
# the next window.  Warn loudly; never fail the sprint over it (when the
# watcher itself fired this script, pgrep finds the parent).
if ! pgrep -f relay_watch >/dev/null 2>&1; then
  echo "WARNING: relay_watch.sh is NOT armed (pgrep -f relay_watch found" >&2
  echo "nothing). It is not self-starting after resets — relaunch it" >&2
  echo "detached (see its header) or the next relay window will be missed." >&2
fi

# NB: grep -vc prints the 0 AND exits 1 on zero matches — no `|| echo 0`
# (that would yield "0\n0" and break the arithmetic below)
start_ok=$(grep -vc '"error"' "$OUT" 2>/dev/null)
start_ok=${start_ok:-0}

# harplint preflight: a sprint must never launch with a known
# relay-burner in the tree (copy traps, per-seed recompiles, >2-word
# prng_seed kernels, cross-thread jax ownership breaks — the silicon
# failures the linter encodes).  All FIVE layers run (AST, jaxpr,
# Mosaic, CommGraph, threads — HL0xx..HL4xx) on the CPU backend in a
# couple of seconds; in rehearsal it HARD-FAILS
# (certifying a dirty tree defeats the rehearsal), in a live window it
# warns and continues — the scarce relay must still be measured, and the
# lint verdict is in the log for the post-sprint commit to act on.
echo "== harplint preflight (python -m harp_tpu lint --json) =="
if ! python -m harp_tpu lint --json; then
  if [ -n "$REHEARSE" ]; then
    echo "[rehearse] harplint FAILED — rehearsal NOT certified" >&2
    exit 1
  fi
  echo "WARNING: harplint FAILED — sprint continues, but fix the" >&2
  echo "violations (or allowlist with justification) before committing" >&2
fi

if [ -z "$REHEARSE" ]; then
  echo "== probing relay (45 s bound) =="
  if ! timeout 45 python -c "import jax; print(jax.devices())"; then
    echo "relay not answering — try again later (poll, don't block)" >&2
    exit 1
  fi

  echo "== raw H2D/D2H bandwidth over the relay (kmeans_ingest diagnosis) =="
  timeout 600 python scripts/probe_h2d.py | tee -a "$OUT"

  echo "== prewarm host-side caches OUTSIDE any watchdog =="
  # 12 GB ingest npy took 864 s and the enwiki-1M LDA pack ~675 s on this
  # 1-core host (2026-07-31) — the sweep configs must only pay device
  # time.  Idempotent: instant when scripts/prewarm_bench_cache.py was
  # already run during the outage (recommended).
  python scripts/prewarm_bench_cache.py
else
  echo "== [rehearse] relay probe skipped (CPU backend) =="
  echo "== [rehearse] H2D probe skipped (relay-only) =="
  echo "== [rehearse] prewarm skipped (smoke shapes need no packs) =="
fi

echo "== kernel equivalence BEFORE any pallas row (ADVICE r3) =="
# interpret mode + Mosaic lowering can't prove compiled-mode buffer
# revisions; execute pallas==dense/XLA on the chip first, and refuse to
# record pallas rows if it fails
if timeout 900 python scripts/kernel_equiv_check.py ${EQUIV_ARGS}; then
  SKIP_PALLAS=""
else
  # EVERY config gated on the equivalence check: all Pallas-kernel
  # configs (the approx/carry/hot LDA variants run the same unverified
  # kernel) AND lda_carry (the check also proves carry_db == baseline
  # on this backend; a divergent carry must not record either)
  SKIP_PALLAS="--skip mfsgd_pallas mfsgd_carry lda_pallas lda_pallas_approx lda_pallas_hot lda_pallas_approx_hot lda_pallas_carry lda_carry kmeans_int8_fused"
  echo "kernel_equiv_check FAILED — gated configs skipped this sprint" >&2
fi

echo "== full graded sweep → ${OUT} (candidates FIRST) =="
# measure_all's internal order prices scarcity (VERDICT r4 weak #3):
# unmeasured candidates, then incumbent re-measures, then ladder shapes
python scripts/measure_all.py --out "$OUT" ${SWEEP_FLAGS} ${SKIP_PALLAS}

echo "== default-flip decisions, first pass (before anything else can die) =="
# a relay death in any LATER step must not cost the sprint its verdicts;
# re-run at the end with full data — this file is overwritten then.
# Always reads the committed BENCH_local.jsonl: in rehearsal that makes
# the verdicts REAL (existing TPU rows), and smoke/CPU rows can never
# authorize a flip anyway (latest_rows skips them).
python scripts/flip_decision.py | tee FLIP_DECISIONS.jsonl || true

if [ -z "$REHEARSE" ]; then
  echo "== driver bench line =="
  python bench.py | tee -a "$OUT"

  echo "== 1B-point formulation (2 epochs, ~minutes) =="
  python -m harp_tpu kmeans-stream --n 1000000000 --iters 2 \
    | tee -a "$OUT"

  # subgraph overflow-tail A/B (r2 item 7) runs INSIDE the sweep as
  # subgraph_onehot / subgraph_1m_onehot — proper config-named JSONL rows
  # that flip_decision.py can compare (the old CLI tee wrote dict-reprs)

  echo "== per-config op-breakdown traces (self-time; fast configs only) =="
  timeout 2400 python scripts/profile_on_relay.py --out PROFILE_local.jsonl \
    || echo "profile pass died (relay?) — partial PROFILE_local.jsonl kept"

  echo "== sparse pull/push capacity-vs-skew table (TPU wire timings) =="
  python -m harp_tpu bench --sparse-capacity-sweep --reps 5 \
    | tee -a "$OUT"
else
  echo "== [rehearse] driver bench line (smoke, CPU) =="
  python bench.py --smoke --cpu | tee -a "$OUT"
  echo "== [rehearse] op-breakdown trace pass (one config, smoke, CPU) =="
  # the only sprint step the first rehearsal skipped; one config proves
  # the trace->parse->record plumbing without relay time.  Fresh file:
  # profile_on_relay APPENDS and a stale top_ops line from a previous
  # rehearsal must not certify a now-broken pass
  rm -f PROFILE_rehearsal.jsonl
  # unlike the real sprint (partial results deliberately kept), a broken
  # trace pipeline must FAIL the rehearsal — certifying it as rehearsed
  # and discovering the break inside a relay window defeats the point
  if ! timeout 600 python scripts/profile_on_relay.py --smoke \
      --platform cpu --only kmeans --out PROFILE_rehearsal.jsonl; then
    echo "[rehearse] profile pass FAILED — rehearsal NOT certified" >&2
    exit 1
  fi
  grep -q '"top_ops"' PROFILE_rehearsal.jsonl || {
    echo "[rehearse] profile pass wrote no op table" >&2; exit 1; }
  echo "== [rehearse] 1B run / wire sweep skipped (relay-only) =="
fi

# Success = the sweep actually produced records AND the relay still
# answers (per-config watchdogs os._exit the python steps on a hang but
# this shell keeps going — without these checks a mid-sprint hang would
# report success with an empty BENCH_local.jsonl, and relay_watch.sh
# would stop watching).
# count only REAL measurements: watchdogged steps append {"error": ...}
# records, which must not satisfy the success gate
total_ok=$(grep -vc '"error"' "$OUT" 2>/dev/null)
total_ok=${total_ok:-0}
new_ok=$(( total_ok - start_ok ))
if [ "$new_ok" -lt "$MIN_NEW" ]; then
  echo "sprint FAILED: only ${new_ok} new error-free records in ${OUT}" >&2
  exit 1
fi
if [ -z "$REHEARSE" ]; then
  if ! timeout 45 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "sprint DEGRADED: relay stopped answering before the end" >&2
    exit 1
  fi
fi

echo "== default-flip decisions, final (>=10% at equal quality, in code) =="
# prints one verdict JSON line per candidate; exit 1 (undecidable rows)
# is informational here — the sprint itself still succeeded
python scripts/flip_decision.py | tee FLIP_DECISIONS.jsonl || true

echo "== perfmodel self-grade vs the fresh rows (fail-closed pruning gate) =="
# ROADMAP autotuning item (3), closed by PR 14: a sprint that just landed
# new silicon rows re-checks the cost model IN the sprint.  The one
# kind:"health" row (verdict confirmed / model_invalidated, invariant 13)
# is committed evidence in ${OUT}; on model_invalidated the next
# `measure_all.py --predicted-top` REFUSES to prune (the gate re-runs
# this same grade live) until the model is re-calibrated.  CPU-only —
# never touches the relay — and never fails the sprint itself.
python -m harp_tpu health --grade-model | tee -a "$OUT" || {
  echo "WARNING: perfmodel INVALIDATED by fresh evidence — the next" >&2
  echo "--predicted-top pruning will refuse until the model is" >&2
  echo "re-calibrated (python -m harp_tpu predict --grade for the" >&2
  echo "term breakdowns)" >&2
}

echo "done — apply the FLIP lines above (one-line config flips +"
echo "BASELINE.md + bench.py BASELINES in the same commit), then COMMIT NOW"
