#!/bin/bash
# Re-run the equiv-gated configs after a mid-sprint kernel fix.
#
# Round-5 situation this exists for: the sprint's silicon
# kernel_equiv_check FAILED on the LDA kernel (prng_seed with 3 words —
# the real TPU compiler takes at most 2; the CPU Mosaic lowering pass
# does not enforce that), so measure_on_relay.sh correctly --skip'ped
# every pallas/carry config.  After fixing the kernel, this script:
#   1. waits for the given sprint PID to exit (ONE process on the chip
#      at a time — concurrent runs would corrupt each other's timings),
#   2. probes the relay bounded (never block on it, CLAUDE.md),
#   3. re-runs kernel_equiv_check on silicon,
#   4. measures exactly the configs the failed check gated,
#   5. re-runs flip_decision over the now-complete BENCH_local.jsonl.
#
# Usage: measure_gated_retry.sh <sprint_pid>   (detach with setsid)

set -u
cd "$(dirname "$0")/.."

PID=${1:?usage: measure_gated_retry.sh <sprint_pid>}
# a mistyped or recycled PID must not let the retry share the chip with
# a live sprint (or wait forever on an unrelated long-lived process):
# if the PID is alive it must BE the sprint; already-gone is fine
if kill -0 "$PID" 2>/dev/null; then
  if ! tr '\0' ' ' < "/proc/$PID/cmdline" 2>/dev/null \
      | grep -q measure_on_relay; then
    echo "pid ${PID} is alive but not measure_on_relay — refusing" >&2
    exit 1
  fi
fi
while kill -0 "$PID" 2>/dev/null; do sleep 60; done
echo "== sprint pid ${PID} exited; probing relay (45 s bound) =="
if ! timeout 45 python -c "import jax; print(jax.devices())"; then
  echo "relay not answering — retry later" >&2
  exit 1
fi

# the same gate the sprint applies: no pallas row without silicon
# equivalence (ADVICE r3), and lda_carry rides the same check
echo "== kernel equivalence with the fixed kernel =="
if ! timeout 900 python scripts/kernel_equiv_check.py; then
  echo "kernel_equiv_check STILL failing — no gated rows recorded" >&2
  exit 1
fi

echo "== measuring the gated configs =="
# same success discipline as measure_on_relay.sh: watchdogged configs
# append {"error": ...} rows, which must not count as measurements
start_ok=$(grep -vc '"error"' BENCH_local.jsonl 2>/dev/null)
start_ok=${start_ok:-0}
python scripts/measure_all.py --out BENCH_local.jsonl --only \
  mfsgd_pallas mfsgd_carry \
  lda_pallas lda_pallas_approx lda_pallas_hot lda_pallas_approx_hot \
  lda_pallas_carry lda_carry kmeans_int8_fused
total_ok=$(grep -vc '"error"' BENCH_local.jsonl 2>/dev/null)
total_ok=${total_ok:-0}
RETRY_OK=$(( total_ok - start_ok ))

echo "== default-flip decisions over the complete row set =="
# pipefail so flip_decision's exit-1 "verdicts incomplete — rerun"
# signal survives the tee (review finding, round 5): this script exists
# to complete the verdict set, so reporting success on an incomplete one
# is exactly the failure it fixes
set -o pipefail
if python scripts/flip_decision.py | tee FLIP_DECISIONS.jsonl; then
  FLIP_RC=0
else
  FLIP_RC=1
fi

# preserve the window's evidence immediately, like relay_watch.sh does —
# this runs detached and the relay history says windows die in minutes;
# an environment reset must not lose the round's silicon rows.  -f:
# FLIP_DECISIONS is gitignored as scratch but a completed run's copy is
# a record.  Default flips still go through a human reading the FLIP
# lines (the gate only AUTHORIZES them).
git add -f BENCH_local.jsonl FLIP_DECISIONS.jsonl 2>/dev/null
git commit -m "Record the gated-config retry measurements" \
  || echo "[gated_retry] nothing new to commit"

if [ "$RETRY_OK" -lt 5 ]; then
  echo "retry DEGRADED: only ${RETRY_OK}/9 gated configs measured —" >&2
  echo "re-run when the relay answers; evidence so far is committed" >&2
  exit 1
fi
if [ "$FLIP_RC" -ne 0 ]; then
  echo "verdicts INCOMPLETE (missing rows) — re-run after the relay" >&2
  echo "answers again; evidence so far is committed" >&2
  exit 1
fi
echo "done — apply the FLIP lines (config flips + BASELINE.md +"
echo "bench.py BASELINES in one commit), then COMMIT NOW"
