#!/usr/bin/env python
"""Pallas-kernel ≡ reference equivalence on the CURRENT backend — the
gate the relay sprint runs BEFORE recording any pallas numbers.

ADVICE r3 (ops/mfsgd_kernel.py:101): kernel correctness on real TPU
hinges on Mosaic buffer-revision behavior that interpret mode + lowering
cannot prove — so the first thing a relay window must do is execute the
equivalence checks on silicon, and only then let measure_all.py record
mfsgd_pallas / lda_pallas / kmeans_int8_fused rows.  measure_on_relay.sh
runs this with a bounded timeout and SKIPS the pallas configs if it
fails.

Unlike scripts/drive_check.py (the full 19-section public-API drive,
minutes of relay compiles), this is the three kernel checks only —
small shapes, TPU-legal tiles, ~1 min of relay time.

Exit 0 = all kernels equivalent; nonzero = do not record pallas rows.

Usage: python scripts/kernel_equiv_check.py [cpu8]
``cpu8`` forces the 8-device CPU sim (local validation; the axon site
pin would otherwise send this to the TPU relay — CLAUDE.md gotchas).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    if "cpu8" in sys.argv[1:]:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   " --xla_force_host_platform_device_count=8")
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from harp_tpu import WorkerMesh
    from harp_tpu.models.kmeans import fit as kfit
    from harp_tpu.models.lda import LDA, LDAConfig, synthetic_corpus
    from harp_tpu.models.mfsgd import MFSGD, MFSGDConfig, synthetic_ratings
    from harp_tpu.parallel.mesh import set_mesh

    mesh = WorkerMesh()
    set_mesh(mesh)
    on_tpu = jax.default_backend() != "cpu"
    tile = 128 if on_tpu else 8  # kernels gate 128-multiples on TPU
    rng = np.random.default_rng(0)

    if not on_tpu:
        # TPU shape pre-pass (round 5, review finding): rehearsal runs
        # with CPU tiles, so the kernels' TPU-only validation branches
        # (n_topics/tile multiple-of rules) never execute — the
        # n_topics=4 hot-count shape burned part of a live window that
        # way.  Trace-lower each LDA pallas config THIS SCRIPT runs on
        # TPU, at the TPU-mode tiles, through the same Mosaic pin the
        # kernel tests use (CLAUDE.md: catches relay-burners hardware-
        # free).  Any future shape edit here fails the rehearsal, not
        # the window.
        import harp_tpu.models.lda as Lm

        os.environ["HARP_PALLAS_FORCE_MOSAIC"] = "1"
        try:
            for n_topics, n_docs, vocab, n_tok, exact in (
                    (8, 64, 32, 64 * 40, True),        # check 2's config
                    (8, 64, 128, 64 * 320, True),      # check 5, exact
                    (8, 64, 128, 64 * 320, False)):    # check 5, approx
                pcfg = Lm.LDAConfig(
                    n_topics=n_topics, algo="pallas", d_tile=128,
                    w_tile=128, entry_cap=64, alpha=0.5, beta=0.1,
                    sampler="exprace", rng_impl="rbg",
                    pallas_exact_gathers=exact)
                shapes = Lm.epoch_arg_shapes(mesh.num_workers, n_docs,
                                             vocab, pcfg, n_tokens=n_tok)
                sds = [jax.ShapeDtypeStruct(
                    shape, dt,
                    sharding=(mesh.replicated() if i == 2
                              else mesh.sharding(mesh.spec(0))))
                    for i, (shape, dt) in enumerate(shapes)]
                fn = Lm.make_multi_epoch_fn(mesh, pcfg, vocab, epochs=1)
                text = fn.trace(*sds).lower(
                    lowering_platforms=("tpu",)).as_text()
                assert "tpu_custom_call" in text
        finally:
            del os.environ["HARP_PALLAS_FORCE_MOSAIC"]
        print("tpu shape pre-pass: every TPU-mode LDA config "
              "traces + Mosaic-lowers")

    # 1. MF-SGD: pallas kernel replays dense's exact update order
    u, i, v = synthetic_ratings(96, 64, 3000, rank=4, noise=0.05, seed=2)
    factors = {}
    for algo in ("dense", "pallas"):
        cfg = MFSGDConfig(rank=8, algo=algo, u_tile=tile, i_tile=tile,
                          entry_cap=32, compute_dtype=jnp.float32,
                          lr=0.03, reg=0.01)
        m = MFSGD(96, 64, cfg, mesh, seed=4)
        m.set_ratings(u, i, v)
        rm = [m.train_epoch() for _ in range(2)]
        factors[algo] = (m.factors(), rm)
    np.testing.assert_allclose(factors["pallas"][0][0],
                               factors["dense"][0][0], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(factors["pallas"][0][1],
                               factors["dense"][0][1], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(factors["pallas"][1], factors["dense"][1],
                               rtol=1e-5)
    print(f"mfsgd pallas == dense (rmse {factors['pallas'][1][-1]:.4f})")

    # 2. LDA: pallas chain ascends, counts exact, quality matches gumbel
    d, w = synthetic_corpus(n_docs=64, vocab_size=32, n_topics_true=4,
                            tokens_per_doc=40, seed=3)
    lt = 128 if on_tpu else 16
    lls = {}
    for algo in ("dense", "pallas"):
        # the pallas kernel fuses the exprace draw over hardware bits —
        # its required sampler stack; dense keeps the gumbel default so
        # this doubles as the sampler-stack quality A/B
        extra = ({"sampler": "exprace", "rng_impl": "rbg"}
                 if algo == "pallas" else {})
        lcfg = LDAConfig(n_topics=8, algo=algo, d_tile=lt, w_tile=lt,
                         entry_cap=64, alpha=0.5, beta=0.1, **extra)
        lm = LDA(64, 32, lcfg, mesh, seed=1)
        lm.set_tokens(d, w)
        for _ in range(6):
            lm.sample_epoch()
        ndk, nwk = np.asarray(lm.Ndk), np.asarray(lm.Nwk)
        assert ndk.sum() == lm.n_tokens and (ndk >= 0).all()
        assert (nwk == np.round(nwk)).all(), "counts must stay integers"
        lls[algo] = lm.log_likelihood()
    # different streams on a tiny corpus: ~10% spread; gate with margin
    assert abs(lls["pallas"] - lls["dense"]) / abs(lls["dense"]) < 0.25, lls
    print(f"lda pallas chain quality == dense ({lls})")

    # 3. KMeans: fused int8 kernel == XLA int8 formulation
    pts = rng.normal(size=(1024, 16)).astype(np.float32) * 3
    # use_pallas=False EXPLICIT: since the int8 auto default flipped to
    # the kernel (2026-08-01), an unset arm would make this check
    # kernel-vs-kernel — vacuously green (review finding, round 5)
    ca, ia = kfit(pts, k=4, iters=4, mesh=mesh, seed=5, quantize="int8",
                  use_pallas=False)
    cb, ib = kfit(pts, k=4, iters=4, mesh=mesh, seed=5, quantize="int8",
                  use_pallas=True)
    np.testing.assert_allclose(ca, cb, rtol=1e-5, atol=1e-5)
    print(f"kmeans fused int8 == XLA int8 (inertia {ib:.1f})")

    # 4. carry variants: the run-carried tiles must be bit-identical to
    # the slice-per-entry chains ON THIS BACKEND (the cond+DUS-on-carry
    # interaction is exactly where an XLA:TPU buffer decision could
    # diverge from the CPU sim — gate it before lda_carry / mfsgd_carry
    # rows record)
    chains = {}
    for carry in (False, True):
        cm = LDA(64, 32, LDAConfig(n_topics=8, algo="dense", d_tile=lt,
                                   w_tile=lt, entry_cap=64, alpha=0.5,
                                   beta=0.1, carry_db=carry), mesh, seed=3)
        cm.set_tokens(d, w)
        for _ in range(3):
            cm.sample_epoch()
        chains[carry] = (np.asarray(cm.Ndk), np.asarray(cm.Nwk),
                         np.asarray(cm.z_grid))
    for a, b in zip(chains[False], chains[True]):
        np.testing.assert_array_equal(a, b)
    print("lda carry_db == slice-per-entry (bit-identical)")

    mf_chains = {}
    for carry in (False, True):
        mc = MFSGD(96, 64, MFSGDConfig(rank=8, algo="dense", u_tile=tile,
                                       i_tile=tile, entry_cap=32,
                                       compute_dtype=jnp.float32, lr=0.03,
                                       reg=0.01, carry_w=carry),
                   mesh, seed=4)
        mc.set_ratings(u, i, v)
        rm = [mc.train_epoch() for _ in range(2)]
        mf_chains[carry] = (mc.factors(), rm)
    np.testing.assert_array_equal(mf_chains[True][0][0],
                                  mf_chains[False][0][0])
    np.testing.assert_array_equal(mf_chains[True][0][1],
                                  mf_chains[False][0][1])
    np.testing.assert_array_equal(mf_chains[True][1], mf_chains[False][1])
    print("mfsgd carry_w == slice-per-entry (bit-identical)")

    # 5. hot counts (round 5): the lda_pallas_hot/_approx_hot sweep pair
    # runs where per-cell counts exceed 256, engaging the SECOND base-256
    # digit plane in the exact gathers — a plane-count bug on silicon
    # would only show here, so gate it before those rows record.  Corpus:
    # 20480 tokens over 8 distinct words (count bound 2560 >> 256), and
    # n_topics=8 is the kernel's TPU minimum (the first in-window run
    # failed the kernel's own multiple-of-8 check at n_topics=4, which
    # interpret-mode rehearsal cannot catch); max(Nwk) >= 2560/8 = 320
    # keeps the >256 hot condition true by construction.
    dh = np.repeat(np.arange(64, dtype=np.int32), 320)
    wh = (np.arange(64 * 320, dtype=np.int32) % 8)
    hot_lls = {}
    for algo, exact in (("dense", None), ("pallas", True),
                        ("pallas", False)):
        extra = ({"sampler": "exprace", "rng_impl": "rbg",
                  "pallas_exact_gathers": exact}
                 if algo == "pallas" else {})
        hm = LDA(64, 128, LDAConfig(n_topics=8, algo=algo, d_tile=lt,
                                    w_tile=lt, entry_cap=64, alpha=0.5,
                                    beta=0.1, **extra), mesh, seed=7)
        hm.set_tokens(dh, wh)
        for _ in range(3):
            hm.sample_epoch()
        ndk = np.asarray(hm.Ndk)
        assert ndk.sum() == hm.n_tokens and (ndk >= 0).all()
        nwk = np.asarray(hm.Nwk)
        assert (nwk == np.round(nwk)).all(), (algo, exact,
                                              "counts must stay integers")
        assert nwk.max() > 256, "shape failed to reach hot counts"
        hot_lls[(algo, exact)] = hm.log_likelihood()
    ref = hot_lls[("dense", None)]
    assert abs(hot_lls[("pallas", True)] - ref) / abs(ref) < 0.25, hot_lls
    # the approx variant gets only a GARBAGE bound (2x the exact
    # tolerance): its fine-grained quality question is exactly what the
    # sprint's LL A/B measures and flip_decision judges — but a gather
    # path that zeroes (not rounds) the high plane must not burn the
    # window recording junk rows
    assert abs(hot_lls[("pallas", False)] - ref) / abs(ref) < 0.5, hot_lls
    print(f"lda pallas hot-count (>256) exact gathers == dense ({hot_lls})")

    print(f"KERNEL EQUIV OK ({jax.default_backend()})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
