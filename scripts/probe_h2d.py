#!/usr/bin/env python
"""Measure raw host->device transfer bandwidth over the current backend.

Diagnostic for the kmeans_ingest hang (2026-07-31): the axon relay
tunnels H2D over HTTP, so `jax.device_put` of streaming chunks may run
orders of magnitude below a real TPU-VM's PCIe/DMA path.  This probe
times device_put (H2D) and np.asarray readback (D2H) at a few sizes and
prints one JSON line.  Run bounded (`timeout 300 ...`) — the relay can
hang (CLAUDE.md gotchas).
"""

import json
import sys
import time

import numpy as np


def main():
    import jax

    dev = jax.devices()[0]
    # config key: the sprint tees this line into BENCH_local.jsonl, and
    # bench_ingest reads it back to size its streaming chunks
    out = {"config": "probe_h2d", "device": str(dev), "probes": []}
    for mb in (1, 16, 64, 157):
        arr = np.random.default_rng(0).standard_normal(
            (mb * 1 << 20) // 2).astype(np.float16)
        # warm one tiny transfer to exclude connection setup from the 1st row
        jax.device_put(np.ones(8, np.float16), dev).block_until_ready()
        t0 = time.perf_counter()
        x = jax.device_put(arr, dev)
        x.block_until_ready()
        h2d = time.perf_counter() - t0
        t0 = time.perf_counter()
        back = np.asarray(x)
        d2h = time.perf_counter() - t0
        assert back[0] == arr[0]
        out["probes"].append({"mb": mb, "h2d_s": round(h2d, 3),
                              "h2d_mb_s": round(mb / h2d, 1),
                              "d2h_s": round(d2h, 3),
                              "d2h_mb_s": round(mb / d2h, 1)})
        print(json.dumps(out["probes"][-1]), file=sys.stderr, flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
