#!/usr/bin/env python
"""Real-ingest benchmark for streaming KMeans — the disk-resident half of
the 1B-point north-star (SURVEY.md §1, §4.2 "load points shard").

``benchmark_streaming`` proves the compute formulation; THIS measures the
ingest-bound reality: a .npy memmap (or CSV via the native streaming
parser) on local disk, streamed through ``fit_streaming`` with device
compute double-buffered behind the host read/parse/transfer pipeline.
Prints one JSON line (same fields as
``kmeans_stream.benchmark_ingest``).

Usage:
    python scripts/bench_ingest.py                       # 100M×300 f16 npy
    python scripts/bench_ingest.py --format csv --rows 2000000
    python scripts/bench_ingest.py --smoke --platform cpu
    python scripts/bench_ingest.py --rows 1000000000 ... # if disk allows

Dataset notes (measured constraints, 2026-07-30, this host):
- 100M×300 f32 = 120 GB > the 79 GB free on /; the default disk dtype is
  float16 (60 GB) so the TRUE 100M-row count runs — GB/s is computed on
  actual on-disk bytes, so the rate is honest for the format streamed.
  Pass ``--disk-dtype float32 --rows 40000000`` for a pure-f32 run.
- CSV text is ~2.4 GB per 1M rows at 300 cols; the CSV default is 2M
  rows (parse rate is row-width-independent enough to project).
- The file lands in ``.bench_data/`` (gitignored) and is DELETED after
  the run unless ``--keep`` — it is most of the disk.
- With 125 GB RAM the OS page cache holds the whole default file after
  generation, so ``host_gb_per_sec`` measures the warm-cache pipeline
  (parse+pad+dispatch), not cold spindle reads; ``--drop-caches`` echoes
  3 > /proc/sys/vm/drop_caches first (needs root) for the cold number.
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DATA_DIR = os.path.join(REPO, ".bench_data")


def gen_points_npy(path: str, rows: int, cols: int, dtype="float16",
                   seed=0, chunk_rows=1 << 20) -> None:
    """Write a [rows, cols] standard-normal .npy in bounded memory."""
    import numpy as np

    os.makedirs(os.path.dirname(path), exist_ok=True)
    out = np.lib.format.open_memmap(path, mode="w+", dtype=np.dtype(dtype),
                                    shape=(rows, cols))
    rng = np.random.default_rng(seed)
    for lo in range(0, rows, chunk_rows):
        hi = min(lo + chunk_rows, rows)
        out[lo:hi] = rng.standard_normal((hi - lo, cols),
                                         dtype=np.float32).astype(out.dtype)
    out.flush()
    del out


def gen_points_csv(path: str, rows: int, cols: int, seed=0,
                   chunk_rows=1 << 16) -> None:
    """Write a [rows, cols] CSV in bounded memory (%.4f ≈ 7 B/value)."""
    import numpy as np

    os.makedirs(os.path.dirname(path), exist_ok=True)
    rng = np.random.default_rng(seed)
    with open(path, "w") as f:
        for lo in range(0, rows, chunk_rows):
            hi = min(lo + chunk_rows, rows)
            blk = rng.standard_normal((hi - lo, cols), dtype=np.float32)
            np.savetxt(f, blk, fmt="%.4f", delimiter=",")


def ensure_dataset(fmt: str, rows: int, cols: int, disk_dtype: str,
                   verbose=True) -> tuple[str, bool]:
    """Generate (or reuse) the benchmark file → (path, generated_now).

    ``generated_now`` lets run() clean up only files THIS invocation
    created — a cached file another run kept (bench.py/measure_all's
    reusable 12 GB dataset) must survive a no-``--keep`` run that merely
    reused it."""
    name = (f"pts_{rows}x{cols}_{disk_dtype}.npy" if fmt == "npy"
            else f"pts_{rows}x{cols}.csv")
    path = os.path.join(DATA_DIR, name)
    if os.path.exists(path):
        return path, False
    t0 = time.perf_counter()
    if verbose:
        print(f"generating {path} ...", file=sys.stderr, flush=True)
    if fmt == "npy":
        gen_points_npy(path, rows, cols, disk_dtype)
    else:
        gen_points_csv(path, rows, cols)
    if verbose:
        gb = os.path.getsize(path) / 1e9
        print(f"  {gb:.1f} GB in {time.perf_counter() - t0:.0f}s",
              file=sys.stderr, flush=True)
    return path, True


def run(fmt="npy", rows=100_000_000, cols=300, disk_dtype="float16",
        k=1000, iters=2, chunk_points=262_144, keep=False,
        compare_synthetic=False, drop_caches=False, verbose=True,
        quantize=None, prefetch=2) -> dict:
    import numpy as np

    from harp_tpu.models.kmeans_stream import benchmark_ingest

    path, generated = ensure_dataset(fmt, rows, cols, disk_dtype,
                                     verbose=verbose)
    cold = False
    try:
        if drop_caches:
            # record cold_cache only if the drop actually happened — a
            # non-root failure must not label a warm-cache rate as cold
            cold = os.system(
                "sync; echo 3 > /proc/sys/vm/drop_caches") == 0
            if not cold:
                print("drop_caches failed (need root) — measuring warm "
                      "cache", file=sys.stderr)
        if fmt == "npy":
            pts = np.load(path, mmap_mode="r")
        else:
            from harp_tpu.native.datasource import CSVPoints

            pts = CSVPoints(path, chunk_rows=chunk_points)
        res = benchmark_ingest(pts, k=k, iters=iters,
                               chunk_points=chunk_points,
                               disk_bytes=os.path.getsize(path),
                               compare_synthetic=compare_synthetic,
                               quantize=quantize, prefetch=prefetch)
        res.update({"format": fmt, "disk_dtype":
                    (disk_dtype if fmt == "npy" else "text"),
                    "cold_cache": cold})
        return res
    finally:
        # delete only what this run created: a cached file another run
        # kept must survive a no-keep rerun that merely reused it
        if not keep and generated and os.path.exists(path):
            os.remove(path)


# the A/B smoke shape: big enough that the host chain, not thread/jit
# overhead, dominates (51 MB f16 over 25 chunks × 4 epochs) yet seconds
# on the CPU sim; the tiny run_smoke shape (2.6 MB) reads ~1.0x at any
# truth.  f16 disk + the auto f16 wire is the north-star disk format,
# and the shape where the staged chain's work elimination (memmap view
# straight into device_put, masks shipped once instead of per chunk) is
# cleanly measurable.  prefetch=1 deliberately: the staged chain is
# bit-exact at every depth, but on a 1-core host the thread-prefetch
# modes only add scheduler preemption noise to the measurement (depth-2
# reruns spread 0.94-1.35x while depth-1 repeats at ~1.9x, measured
# 2026-08-04 CPU host) — CPU-bound stages cannot overlap on one core
# (see harp_tpu/ingest.py module doc), so the A/B grades the chain, and
# the relay sprint's multi-core kmeans_ingest config grades the depth
AB_SMOKE = dict(fmt="npy", rows=400_000, cols=64, disk_dtype="float16",
                k=8, iters=4, chunk_points=16_384, prefetch=1)


def run_ab(fmt="npy", rows=200_000, cols=64, disk_dtype="float32",
           k=16, iters=2, chunk_points=32_768, keep=True, quantize=None,
           prefetch=2, verbose=True) -> dict:
    """The pipelined-vs-serial host-path A/B at ONE config (PR 8
    acceptance row): arm A is ``prefetch=0`` — the pre-pipeline serial
    chain kept verbatim in ``kmeans_stream._legacy_put_chunk`` — arm B
    the prefetch pipeline.  Both arms stream the same (page-cache-warm)
    file, so ``pipeline_speedup`` is host-chain work, not disk luck.
    Emits ONE merged ``kind:"ingest"`` dict (checked by check_jsonl
    invariant 8): pipelined fields canonical, serial arm suffixed."""
    import numpy as np

    path, generated = ensure_dataset(fmt, rows, cols, disk_dtype,
                                     verbose=verbose)
    common = dict(fmt=fmt, rows=rows, cols=cols, disk_dtype=disk_dtype,
                  k=k, iters=iters, chunk_points=chunk_points, keep=True,
                  quantize=quantize, verbose=verbose)
    try:
        if fmt == "npy":
            # warm the page cache for BOTH arms: a freshly generated
            # file's dirty pages flush during arm A otherwise, charging
            # writeback to whichever arm runs first
            float(np.asarray(np.load(path, mmap_mode="r")).max())
        serial = run(prefetch=0, **common)
        piped = run(prefetch=prefetch, **common)
    finally:
        # both arms ran keep=True so arm B reuses arm A's (warm) file;
        # clean up here instead, only what THIS call generated
        if not keep and generated and os.path.exists(path):
            os.remove(path)
    piped.update({
        "mode": "ab",
        "host_gb_per_sec_serial": serial["host_gb_per_sec"],
        "host_sec_per_epoch_serial": serial["host_sec_per_epoch"],
        "points_per_sec_serial": serial["points_per_sec"],
        "pipeline_speedup": (piped["host_gb_per_sec"]
                             / serial["host_gb_per_sec"]),
    })
    return piped


def run_smoke(quantize=None) -> dict:
    """The ONE smoke preset shared by bench.py and measure_all — tiny
    npy, CPU-safe, regenerated per run."""
    return run("npy", 20_000, 32, "float32", k=16, iters=2,
               chunk_points=4096, verbose=False, quantize=quantize)


def relay_sized_chunk(cols=300, dtype_bytes=2, default=262_144,
                      target_s=2.0, bench_path=None) -> int:
    """Streaming chunk rows sized so ONE H2D dispatch takes ~``target_s``
    at the MEASURED relay bandwidth (VERDICT r3 item 4: "size
    kmeans_ingest chunks from the measured relay H2D rate").

    Reads the last ``probe_h2d`` record the sprint teed into
    BENCH_local.jsonl (largest-probe h2d_mb_s — the sustained rate).
    The r3 hang was 12 GB of 157 MB chunks through an unmeasured
    tunnel; a measured-slow relay now gets proportionally smaller
    dispatches instead of multi-minute ones.  No probe on record →
    ``default`` (the tuned real-TPU-VM chunk).  Clamped to
    [16384, default], rounded down to a 8192 multiple.
    """
    import json

    path = bench_path or os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_local.jsonl")
    rate_mb_s = None
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if row.get("config") == "probe_h2d" and row.get("probes"):
                    rate_mb_s = row["probes"][-1]["h2d_mb_s"]
    except OSError:
        pass
    if not rate_mb_s:
        return default
    rows = int(rate_mb_s * target_s * 1e6 / (cols * dtype_bytes))
    rows = max(16_384, min(default, rows))
    return (rows // 8192) * 8192


def run_full(compare_synthetic: bool = False, quantize=None) -> dict:
    """The ONE full preset shared by bench.py and measure_all: 20M×300
    float16 (12 GB), kept in .bench_data/ for reuse across runs.
    ``compare_synthetic`` adds the device-regenerated compute twin (a
    second full-scale compile + timed run) — measure_all opts in; the
    driver's bench.py skips it to stay well inside its per-config
    watchdog.  Chunk size follows the measured relay H2D rate when a
    probe is on record (:func:`relay_sized_chunk`)."""
    return run("npy", 20_000_000, 300, "float16", k=1000, iters=2,
               chunk_points=relay_sized_chunk(), keep=True,
               compare_synthetic=compare_synthetic, quantize=quantize)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--format", choices=["npy", "csv"], default="npy")
    p.add_argument("--rows", type=int, default=None,
                   help="default: 100M npy / 2M csv (smoke: 20k)")
    p.add_argument("--cols", type=int, default=300)
    p.add_argument("--disk-dtype", choices=["float16", "float32"],
                   default="float16",
                   help="npy on-disk dtype (f16 default: 100M×300 must "
                        "fit the 79 GB free on this host)")
    p.add_argument("--k", type=int, default=1000)
    p.add_argument("--iters", type=int, default=2)
    p.add_argument("--chunk", type=int, default=262_144)
    p.add_argument("--keep", action="store_true",
                   help="keep the generated file (it is most of the disk)")
    p.add_argument("--compare-synthetic", action="store_true",
                   help="also time the device-regenerated formulation at "
                        "the same shapes (second compile + run)")
    p.add_argument("--drop-caches", action="store_true")
    p.add_argument("--prefetch", type=int, default=2,
                   help="ingest pipeline work-ahead depth (0 = the "
                        "pre-pipeline serial loop, the A/B incumbent)")
    p.add_argument("--ensure-only", action="store_true",
                   help="generate (or reuse) the dataset file and exit — "
                        "run this OUTSIDE any benchmark watchdog: on this "
                        "1-core host generation alone can eat most of a "
                        "1200 s window (12 GB took 864 s on 2026-07-31)")
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--platform", default=None, choices=["cpu"],
                   help="force the CPU backend (the axon relay can hang; "
                        "host-side rates are chip-independent)")
    args = p.parse_args(argv)

    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.smoke:
        # --smoke IS the pipelined-vs-serial A/B (PR 8 acceptance): one
        # provenance-stamped kind:"ingest" line, ready to tee into
        # BENCH_local.jsonl and graded by check_jsonl invariant 8
        from harp_tpu.utils.metrics import benchmark_json

        res = run_ab(keep=False, **AB_SMOKE)
        print(benchmark_json("kmeans_ingest_ab_smoke", res))
        return
    rows = args.rows or (100_000_000 if args.format == "npy"
                         else 2_000_000)
    cols, k, chunk = args.cols, args.k, args.chunk
    if args.ensure_only:
        path, generated = ensure_dataset(args.format, rows, cols,
                                         args.disk_dtype)
        print(json.dumps({"ensured": path, "generated_now": generated}))
        return
    res = run(args.format, rows, cols, args.disk_dtype, k, args.iters,
              chunk, keep=args.keep,
              compare_synthetic=args.compare_synthetic,
              drop_caches=args.drop_caches, prefetch=args.prefetch)
    print(json.dumps({k2: (round(v, 4) if isinstance(v, float) else v)
                      for k2, v in res.items()}))


if __name__ == "__main__":
    main()
