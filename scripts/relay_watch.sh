#!/bin/bash
# Background relay watcher: probe bounded every POLL_S seconds; the moment
# the relay answers, run the full measurement sprint (measure_on_relay.sh)
# exactly once and exit.  Detach it with:
#     nohup scripts/relay_watch.sh > relay_watch.log 2>&1 & disown
# then check relay_watch.log / BENCH_local.jsonl periodically.  The sweep
# itself stays watchdogged per config, so a relay that dies mid-sprint
# still leaves parseable partial records to commit.

set -u
cd "$(dirname "$0")/.."
POLL_S="${POLL_S:-600}"

while true; do
  if timeout 45 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[relay_watch] relay ANSWERED at $(date -u +%FT%TZ) — sprinting"
    if ./scripts/measure_on_relay.sh; then
      echo "[relay_watch] sprint done at $(date -u +%FT%TZ) — COMMIT the results"
      exit 0
    fi
    # the documented flapping mode: answered the probe, hung again before
    # the sprint — keep watching, partial records (if any) are appended
    echo "[relay_watch] sprint FAILED at $(date -u +%FT%TZ) — still watching"
  else
    echo "[relay_watch] $(date -u +%FT%TZ) relay still hung; sleeping ${POLL_S}s"
  fi
  sleep "$POLL_S"
done
