#!/bin/bash
# Background relay watcher: probe bounded every POLL_S seconds; the moment
# the relay answers, run the full measurement sprint (measure_on_relay.sh)
# exactly once and exit.  Detach it with:
#     nohup scripts/relay_watch.sh > relay_watch.log 2>&1 & disown
# then check relay_watch.log / BENCH_local.jsonl periodically.  The sweep
# itself stays watchdogged per config, so a relay that dies mid-sprint
# still leaves parseable partial records to commit.

set -u
cd "$(dirname "$0")/.."
POLL_S="${POLL_S:-240}"  # r5: 240s default — a 45s-bounded probe is
                         # cheap and a shorter poll loses less of a
                         # short relay window (round-3's lasted ~2.5h)

while true; do
  if timeout 45 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "[relay_watch] relay ANSWERED at $(date -u +%FT%TZ) — sprinting"
    if ./scripts/measure_on_relay.sh; then
      # preserve the window's evidence immediately — the sprint may fire
      # unattended and the relay history says it can die minutes later.
      # -f: PROFILE/FLIP artifacts are gitignored as scratch but a
      # completed sprint's copies are records.  Default flips still go
      # through a human reading FLIP_DECISIONS + BASELINE.md (the gate
      # only AUTHORIZES them).
      git add -f BENCH_local.jsonl FLIP_DECISIONS.jsonl \
        PROFILE_local.jsonl 2>/dev/null
      git commit -m "Record the relay-window measurement sprint" \
        || echo "[relay_watch] nothing new to commit"
      echo "[relay_watch] sprint done at $(date -u +%FT%TZ) — apply FLIP verdicts + update BASELINE.md"
      exit 0
    fi
    # the documented flapping mode: answered the probe, hung again before
    # the sprint — keep watching, partial records (if any) are appended
    echo "[relay_watch] sprint FAILED at $(date -u +%FT%TZ) — still watching"
  else
    echo "[relay_watch] $(date -u +%FT%TZ) relay still hung; sleeping ${POLL_S}s"
  fi
  sleep "$POLL_S"
done
