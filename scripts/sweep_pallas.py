#!/usr/bin/env python
"""Tile-size sweep for the fused Pallas kernels — relay-sprint tooling.

The dense-algo tiling was tuned on TPU (512×512 best, see
MFSGDConfig.u_tile); the fused kernels change the cost model (one-hots
never leave VMEM), so their best tiles may differ.  Sweeps
algo="pallas" over tile sizes for MF-SGD and LDA at the graded shapes,
one JSON line each; run AFTER measure_on_relay.sh's main sweep commits
(each point is a full-scale benchmark, minutes of prep on this host).

Usage: python scripts/sweep_pallas.py [--model mfsgd lda] [--smoke]
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))  # bench_common


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", nargs="+", default=["mfsgd", "lda"],
                   choices=["mfsgd", "lda"])
    p.add_argument("--tiles", nargs="+", type=int, default=[256, 512, 1024])
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--platform", choices=["cpu"], default=None)
    p.add_argument("--out", default="SWEEP_pallas.jsonl")
    args = p.parse_args(argv)
    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    from harp_tpu.utils.timing import HangWatchdog

    sink = open(args.out, "a")
    watchdog = HangWatchdog(on_fire=lambda what: (
        sink.write(json.dumps({"sweep": what, "error": "hang"}) + "\n"),
        sink.flush()))
    for model in args.model:
        for t in args.tiles:
            what = f"{model} pallas {t}x{t}"
            watchdog.arm(what)
            try:
                from bench_common import SMOKE

                if model == "mfsgd":
                    from harp_tpu.models import mfsgd

                    kw = {k: v for k, v in SMOKE["mfsgd_pallas"].items()
                          if not k.endswith("_tile")} if args.smoke else {}
                    r = mfsgd.benchmark(algo="pallas", u_tile=t, i_tile=t,
                                        **kw)
                else:
                    from harp_tpu.models import lda

                    from measure_all import BENCH_DATA

                    # per-tile packs cache too (tiling is in the key), so
                    # re-running a sweep point skips the host packing
                    kw = ({k: v for k, v in SMOKE["lda_pallas"].items()
                           if not k.endswith("_tile")} if args.smoke
                          else {"pack_cache": BENCH_DATA})
                    r = lda.benchmark(algo="pallas", d_tile=t, w_tile=t,
                                      **kw)
                rec = {"sweep": what, "tile": t, **{
                    k: (round(v, 4) if isinstance(v, float) else v)
                    for k, v in r.items()}}
            except Exception as e:  # a bad tile must not kill the sweep
                rec = {"sweep": what, "tile": t,
                       "error": f"{type(e).__name__}: {e}"}
            line = json.dumps(rec)
            print(line, flush=True)
            sink.write(line + "\n")
            sink.flush()
    watchdog.cancel()
    sink.close()


if __name__ == "__main__":
    main()
