#!/usr/bin/env python
"""v4-32 scaling projection from measured 1-chip rates + comm-byte models.

VERDICT r4 item 5, second half: the defensible multi-chip projection.
Inputs, per graded app:
  * the MEASURED 1-chip TPU rate (BENCH_local.jsonl committed rows via
    bench.py's `_last_measured`, dated 2026-07-31 unless a newer sprint
    has landed);
  * an ANALYTIC per-sync-quantum collective byte model at the graded
    shape — the same collective patterns the CPU-sim sweep traced
    (SCALING_local.jsonl), whose measured collective-op fractions grow
    with worker count the way these byte models predict;
  * stated ICI assumptions (below).

Per app the model defines one SYNC QUANTUM (an iteration, an epoch, a
step, a tree) and computes, at N workers:
  t_comp = per-chip compute time for the quantum at the measured rate;
  t_comm = wire_bytes/ICI_BW + hops·LAT for the quantum's collectives;
  - synchronous allreduce patterns:  eff = t_comp / (t_comp + t_comm)
  - double-buffered rotation rings (parallel/rotate.py; the reference's
    dymoro makes the identical bet, SURVEY.md §3.5): comm hides under
    compute until one slice hop outruns one compute step,
    eff = step_comp / max(step_comp, step_comm).

ICI assumptions (conservative, stated once here and in BASELINE.md):
  * ICI_BW_GBS = 90  — a 1-D ring uses 2 of a v4 chip's 6 links; public
    v4 figures put a link around 45 GB/s/direction; 2 × 45 = 90 GB/s of
    ring bandwidth per chip.
  * LAT_US = 1 per hop.
  * v4-32 = 32 workers (north star: "one Harp worker per chip via a
    pjit mesh"; if the slice name counts TensorCores, read the N=16
    row instead — both are emitted).

No relay needed; run anytime:  python scripts/project_scaling.py
One JSON line per (app, N); pipe into BASELINE.md's scaling section.
"""

import datetime
import importlib.util
import json
import math
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ICI_BW_GBS = 90.0
LAT_US = 1.0


def measured_skew(path=None):
    """Latest measured per-app load skew from SCALING_local.jsonl's skew
    columns (scripts/scaling_sweep.py; utils/skew.py ledger): app →
    max/mean work ratio at the HIGHEST worker count that recorded one.
    The projection multiplies its comm-model efficiency by the measured
    ``1/ratio`` — a barrier superstep ends when the max-loaded worker
    does, so imbalance stacks multiplicatively with collective overhead
    — and emits both, so BASELINE.md's scaling section can state how
    much efficiency loss is attributable to skew vs the wire."""
    path = path or os.path.join(REPO, "SCALING_local.jsonl")
    best: dict = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                r = row.get("skew_max_mean")
                app, n = row.get("app"), row.get("n_workers")
                if r and app and isinstance(n, int):
                    cur = best.get(app)
                    if cur is None or n >= cur[0]:
                        best[app] = (n, float(r))
    except OSError:
        pass
    return {app: ratio for app, (n, ratio) in best.items()}


def ring_bytes(payload_bytes, n):
    """Wire bytes per chip for a ring ALLREDUCE of `payload` bytes
    (reduce-scatter + allgather: 2(n-1)/n of the payload)."""
    return 2.0 * (n - 1) / n * payload_bytes


def allgather_bytes(shard_bytes, n):
    """Wire bytes per chip for a ring ALLGATHER of per-chip shards:
    each chip forwards every other chip's shard once — (n-1)·S, NOT the
    allreduce 2(n-1)/n formula (review finding, round 5)."""
    return (n - 1.0) * shard_bytes


def ring_hops(n):
    """Sequential neighbor steps in a ring allreduce: reduce-scatter is
    n-1 hops, allgather another n-1 (review finding, round 5)."""
    return 2 * (n - 1)


def t_wire(nbytes, hops):
    return nbytes / (ICI_BW_GBS * 1e9) + hops * LAT_US * 1e-6


def sync_eff(t_comp, t_comm):
    """Synchronous collective after each quantum (allreduce patterns)."""
    return t_comp / (t_comp + t_comm) if t_comp else 0.0


def rotate_eff(t_comp_quantum, slice_bytes, n):
    """Double-buffered ring: N steps/quantum, one slice hop per step."""
    if n == 1:
        return 1.0
    step_comp = t_comp_quantum / n
    step_comm = t_wire(slice_bytes, 1)
    return step_comp / max(step_comp, step_comm) if step_comp else 0.0


def project(n_workers=(4, 8, 16, 32)):
    """Emit rows for every graded app at each worker count.

    Shapes mirror measure_all.py's full-mode configs; `per_chip` marks
    rates already divided by chip count (their projected value is the
    per-chip rate × efficiency; aggregate = × N).
    """
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py"))
    b = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(b)
    lm = b._last_measured()
    skew_by_app = measured_skew()

    rows = []

    def emit(app, rate_key, n, eff, t_comp, wire, pattern, quantum,
             per_chip, note, projected=None):
        rate1 = lm[rate_key]["value"]
        if projected is None:
            projected = rate1 * eff if per_chip else rate1 * n * eff
        # measured load skew stacks multiplicatively on the comm model:
        # the straggler sets the superstep, the wire sets the rest
        sk = skew_by_app.get(app)
        skew_cols = {}
        if sk:
            skew_cols = {
                "skew_max_mean": round(sk, 4),
                "eff_skew": round(1.0 / sk, 4),
                "efficiency_with_skew": round(eff / sk, 4),
                "projected_with_skew": round(projected / sk, 2),
            }
        rows.append({
            "app": app, "n_workers": n, "pattern": pattern,
            "quantum": quantum,
            "measured_rate_1chip": rate1,
            "measured_unit": lm[rate_key]["unit"],
            "measured_date": lm[rate_key]["date"],
            "wire_bytes_per_chip": round(wire),
            "compute_sec_per_chip_per_quantum": round(t_comp, 9),
            "efficiency": round(eff, 4),
            "projected": round(projected, 2),
            "projected_unit": (lm[rate_key]["unit"] if per_chip else
                               lm[rate_key]["unit"] + " aggregate"),
            "note": note,
            **skew_cols,
            "assumptions": f"ICI {ICI_BW_GBS:.0f} GB/s ring, "
                           f"{LAT_US:.0f}us/hop",
        })

    for n in n_workers:
        # kmeans 1M×300 k=100 f32: data shards, one psum of [k, d+1]/iter
        t_comp = 1.0 / (lm["kmeans"]["value"] * n)
        wire = ring_bytes(4 * 100 * 301, n)
        emit("kmeans", "kmeans", n,
             sync_eff(t_comp, t_wire(wire, ring_hops(n))), t_comp, wire,
             "allreduce", "iteration", False,
             "graded 1M points shard across chips; projected = iters/s "
             "on the SAME 1M-point problem")

        # north star: kmeans 1B pts k=1000 — measured rate is iter/s at
        # 100M on one chip, so per-chip work scales by (1e9/N)/1e8
        r = lm["kmeans_stream"]["value"]
        t_comp = (1e9 / n) / 1e8 / r   # measured rate is iter/s at 100M
        wire = ring_bytes(4 * 1000 * 301, n)
        t_comm = t_wire(wire, ring_hops(n))
        emit("kmeans_stream_1b", "kmeans_stream", n,
             sync_eff(t_comp, t_comm), t_comp, wire,
             "allreduce", "iteration(1B pts)", False,
             "north-star 1B×300 k=1k iter/s, e2e basis incl. the "
             "measured host-gen floor; the 10x-more-work-than-measured "
             "shape means projected is ABSOLUTE, not rate1-scaled",
             projected=1.0 / (t_comp + t_comm))

        # MF-SGD MovieLens-20M: epoch = 20M updates; H [26744, 64] f32
        # rotates in N double-buffered slices.  Rate = the DEFAULT stack
        # (fused kernel since the 2026-08-01 flip): ~3× the dense rate
        # shrinks the compute window the ring hides under — the honest
        # projection must use the shipped default, not the slower arm
        r = lm["mfsgd_pallas"]["value"]  # updates/s/chip
        t_comp = 20e6 / n / r
        slice_b = 4 * 26_744 * 64 / n
        emit("mfsgd", "mfsgd_pallas", n, rotate_eff(t_comp, slice_b, n),
             t_comp, slice_b * n, "rotate", "epoch", True,
             "projected updates/s/chip (fused-kernel default); rotation "
             "comm double-buffers under compute")

        # LDA enwiki-1M: epoch = 100M tokens; Nwk [50k, 1000] f32 rotates.
        # Rate = the default stack (kernel + exprace + rbg + Db-carry)
        r = lm["lda_pallas_carry"]["value"]  # tokens/s/chip
        t_comp = 100e6 / n / r
        slice_b = 4 * 50_000 * 1000 / n
        emit("lda", "lda_pallas_carry", n, rotate_eff(t_comp, slice_b, n),
             t_comp, slice_b * n, "rotate", "epoch", True,
             "projected tokens/s/chip (default stack); the 200 MB Nwk "
             "ring is the heaviest wire in the suite")

        # MLP MNIST: DP step at per-chip batch 8192; grads psum
        r = lm["mlp"]["value"]  # samples/s (1 chip)
        params = 784 * 512 + 512 * 256 + 256 * 10 + 512 + 256 + 10
        t_comp = 8192 / r
        wire = ring_bytes(4 * params, n)
        emit("mlp", "mlp", n, sync_eff(t_comp, t_wire(wire, ring_hops(n))),
             t_comp, wire, "allreduce", "step(batch 8192/chip)", False,
             "weak-scaled batch; projected = aggregate samples/s")

        # Subgraph u5-tree @1M powerlaw: per color-coding trial, one
        # allgather of the child's COMPACT table [V/N, cols] per template
        # edge (subgraph.py:199; u5-tree: 4 edges, compact cols avg ~4)
        r = lm["subgraph"]["value"]  # vertices/s
        t_comp = 1e6 / n / r
        wire = 4 * allgather_bytes(4 * (1e6 / n) * 4, n)
        emit("subgraph", "subgraph", n,
             sync_eff(t_comp, t_wire(wire, 4 * (n - 1))), t_comp, wire,
             "allgather", "color-coding trial", False,
             "4 compact-table allgathers per trial ((n-1)·shard wire "
             "each); projected = aggregate vertices/s, same 1M graph")

        # RF 32 trees depth 6 on 200k×64: per level, one-hot histogram
        # [nodes≤2^l, feat, bins, classes] psum; Σ_l 2^l ≈ 2^7
        r = lm["rf"]["value"]  # trees/s
        t_comp = 1.0 / r
        wire = ring_bytes(4 * (2 ** 7) * 64 * 32 * 2, n)
        emit("rf", "rf", n, sync_eff(t_comp, t_wire(wire, ring_hops(n))),
             t_comp, wire, "allreduce", "tree", False,
             "per-tree histogram psums; projected = aggregate trees/s "
             "with data sharded")
    return rows


def main():
    for row in project():
        print(json.dumps({**row,
                          "date": datetime.date.today().isoformat()}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
