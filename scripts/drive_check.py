"""End-to-end drive of the PUBLIC harp_tpu API, checked against numpy.

The standing verification recipe (see .claude/skills/verify/SKILL.md):
imports only the package surface, runs every major subsystem — the
collective verbs with edge-case shifts/dtypes, Zipf LDA pushpull with
exact capacity sizing, the real-ingest harness, the sparse capacity
sweep, power-law subgraph with both overflow tails, the enwiki-1M and
million-token lowering pins, sharded/file-split/int8 ingest — and
checks results against straight-line numpy.  Grows a section per round;
every "DRIVE OK round-N" line must print.

Usage: python scripts/drive_check.py [cpu8|tpu]
  cpu8 — 8 simulated CPU workers (no hardware needed; the default)
  tpu  — whatever backend the axon site pin provides (probe the relay
         with a 45 s timeout first; it can hang — CLAUDE.md)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

mode = sys.argv[1] if len(sys.argv) > 1 else "cpu8"
if mode == "cpu8":
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax

if mode == "cpu8":
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp

from harp_tpu import WorkerMesh
from harp_tpu.parallel import collective as C
from harp_tpu.parallel.mesh import set_mesh

mesh = WorkerMesh()
set_mesh(mesh)
nw = mesh.num_workers
print(f"mode={mode} backend={jax.default_backend()} workers={nw}")

# 1. iterative program through shard_map + verbs vs numpy straight-line
x = np.arange(nw * 4, dtype=np.float32).reshape(nw, 4)
op = C.host_op(mesh, C.allreduce, in_dim=0, out_dim=0)
got = np.asarray(op(x))
np.testing.assert_allclose(got, np.tile(x.sum(0), (nw, 1)))

# rotate shift 0 / negative / > nw
for shift in (0, -1, nw + 1):
    rot = C.host_op(mesh, lambda t, s=shift, **kw: C.rotate(t, s, **kw),
                    in_dim=0, out_dim=0)
    np.testing.assert_allclose(np.asarray(rot(x)),
                               np.roll(x, shift % nw, axis=0),
                               err_msg=f"shift={shift}")

# bool through broadcast/reduce (psum promotes bool)
b = np.zeros(nw, bool)
b[0] = True
bc = C.host_op(mesh, C.broadcast, in_dim=0, out_dim=0)
assert np.asarray(bc(b)).any()

# regroup divisibility: rows % nw != 0 must raise, not corrupt
try:
    bad = C.host_op(mesh, C.regroup, in_dim=0, out_dim=0)
    bad(np.zeros((nw, 3), np.float32)) if nw > 3 else None
    if nw > 3:
        raise SystemExit("regroup divisibility did not raise")
except ValueError:
    pass
except Exception as e:  # XLA's own divisibility error is fine too
    assert "divisible" in str(e) or "divide" in str(e), e

# 2. round-3: LDA pushpull dedup + exact cap sizing on a Zipf corpus
from harp_tpu.models.lda import LDA, LDAConfig

rng = np.random.default_rng(0)
n_docs, vocab, tpd = 8 * nw, 128, 16
d_ids = np.repeat(np.arange(n_docs, dtype=np.int32), tpd)
w_ids = ((rng.zipf(1.1, size=n_docs * tpd) - 1) % vocab).astype(np.int32)
model = LDA(n_docs, vocab, LDAConfig(n_topics=4, algo="pushpull", chunk=32),
            mesh, seed=0)
model.set_tokens(d_ids, w_ids)
cap = model.suggest_pull_cap(apply=True)
assert 1 <= cap <= 32, cap
model.sample_epoch()
assert model.last_dropped == 0, model.last_dropped
assert np.asarray(model.Ndk).sum() == model.n_tokens
print(f"lda pushpull dedup: cap={cap}, 0 drops, counts exact")

# 3. round-3: real-ingest harness on a disk npy
import tempfile

from harp_tpu.models.kmeans_stream import benchmark_ingest

tmp = tempfile.mkdtemp()
pts = rng.normal(size=(4096, 16)).astype(np.float16)
np.save(os.path.join(tmp, "p.npy"), pts)
mm = np.load(os.path.join(tmp, "p.npy"), mmap_mode="r")
r = benchmark_ingest(mm, k=8, iters=2, chunk_points=1024, mesh=mesh,
                     disk_bytes=os.path.getsize(os.path.join(tmp, "p.npy")))
assert r["points_per_sec"] > 0 and 0 < r["overlap_efficiency"] <= 1
assert r["host_sec_per_epoch"] <= r["epoch_sec"]
print(f"ingest: {r['points_per_sec']:.0f} pts/s, "
      f"host {r['host_gb_per_sec']:.2f} GB/s, "
      f"overlap {r['overlap_efficiency']:.2f}")

# 4. round-3: capacity sweep contract under skew
from harp_tpu import benchmark as B

recs = list(B.sweep_sparse_capacity(mesh, m=256, d=8, reps=1,
                                    caps=(1 / 4, 1.0)))
by = {}
for rec in recs:
    by.setdefault(rec["dist"], []).append(rec)
assert by["zipf_dedup"][0]["drop_rate"] <= by["zipf"][0]["drop_rate"]
assert all(rows[-1]["drop_rate"] == 0.0 for rows in by.values())
print("sparse capacity sweep: dedup<=raw, full cap never drops")

# 5. round-3: subgraph power-law graph, exact overflow
from harp_tpu.models.subgraph import benchmark as sg_bench

sg = sg_bench(n_vertices=1000, avg_degree=4, template="u3-path",
              max_degree=4, graph="powerlaw", mesh=mesh)
assert sg["dropped_edges"] == 0 and sg["overflow_share"] > 0
print(f"subgraph powerlaw: overflow {sg['overflow_share']:.0%}, 0 dropped")

# 6. round-3: enwiki shape model + lowering of the true-shape program
from harp_tpu.models import lda as L

cfg = L.LDAConfig(n_topics=64, algo="pushpull", ndk_dtype="int16")
shapes = L.epoch_arg_shapes(nw, 10_000, 2_000, cfg, n_tokens=200_000)
sds = [jax.ShapeDtypeStruct(s, dt, sharding=(mesh.replicated() if i == 2
                                             else mesh.sharding(mesh.spec(0))))
       for i, (s, dt) in enumerate(shapes)]
text = L.make_multi_epoch_fn(mesh, cfg, 2_000, epochs=2).lower(*sds).as_text()
assert "while" in text and "xi16" in text
print("epoch_arg_shapes lowering: ok")

print(f"DRIVE OK ({mode})")

# 7. public dedup verbs: one slot per distinct id, contract parity
from harp_tpu.table import pull_rows_sparse_dedup, push_rows_sparse_dedup

tb = np.arange(nw * 4 * 2, dtype=np.float32).reshape(nw * 4, 2)
hot = np.zeros(nw * 6, np.int32)  # every worker: 6 copies of row 0

def ddprog(t, i):
    rows, ok, drop = pull_rows_sparse_dedup(t, i, capacity=1)
    t2, pdrop = push_rows_sparse_dedup(
        t, i, jnp.ones((i.shape[0], 2), jnp.float32), capacity=1)
    return rows, ok, drop, t2, pdrop

dd = jax.jit(mesh.shard_map(
    ddprog, in_specs=(mesh.spec(0),) * 2,
    out_specs=(mesh.spec(0), mesh.spec(0), None, mesh.spec(0), None)))
try:
    rows, ok, drop, t2, pdrop = dd(tb, hot)
except Exception:
    from jax.sharding import PartitionSpec as PS
    dd = jax.jit(mesh.shard_map(
        ddprog, in_specs=(mesh.spec(0),) * 2,
        out_specs=(mesh.spec(0), mesh.spec(0), PS(), mesh.spec(0), PS())))
    rows, ok, drop, t2, pdrop = dd(tb, hot)
assert int(drop) == 0 and int(pdrop) == 0 and np.asarray(ok).all()
np.testing.assert_allclose(np.asarray(rows), np.tile(tb[0], (nw * 6, 1)))
exp = tb.copy(); exp[0] += 6 * nw  # 6 dups pre-summed, pushed by nw workers
np.testing.assert_allclose(np.asarray(t2), exp)
print("dedup verbs: cap=1 serves the hot row, push pre-sum exact")
print(f"DRIVE OK round-2 ({mode})")

# 8. sharded ingest: fit_streaming_local ≡ fit_streaming (explicit init)
from harp_tpu.models.kmeans_stream import fit_streaming, fit_streaming_local

pl = rng.normal(size=(3000, 12)).astype(np.float32) \
    + (np.arange(3000)[:, None] % 3) * 6
c0 = pl[:6].copy()
cg, ig = fit_streaming(pl, k=6, iters=4, chunk_points=400, mesh=mesh, init=c0)
cl_, il_ = fit_streaming_local(pl, k=6, iters=4, chunk_points=400,
                               mesh=mesh, init=c0)
# the two paths sum partial stats in different orders, so f32 roundoff
# can flip one boundary point's assignment (moves a centroid by
# ~point_scale/cluster_size; seen at 0.009 on jax 0.4.37) — the invariant
# is inertia parity plus boundary-flip-sized centroid agreement
assert abs(ig - il_) / max(abs(ig), 1.0) < 1e-3, (ig, il_)
assert np.allclose(cg, cl_, rtol=1e-4, atol=0.05)
print(f"sharded ingest: local≡global, inertia {ig:.1f} vs {il_:.1f}")
print(f"DRIVE OK round-3 ({mode})")

# 9. file-split ingest: directory of splits, per-worker file streams
import glob as _glob

from harp_tpu.models.kmeans_stream import fit_streaming_files

sdir = tempfile.mkdtemp()
fpts = rng.normal(size=(900, 10)).astype(np.float32) \
    + (np.arange(900)[:, None] % 3) * 7
for i in range(4):
    np.savetxt(os.path.join(sdir, f"part_{i}.csv"),
               fpts[i * 225:(i + 1) * 225], fmt="%.5f", delimiter=",")
c0f = fpts[:5].copy()
cf, inf = fit_streaming_files(sorted(_glob.glob(os.path.join(sdir, "*.csv"))),
                              k=5, iters=3, chunk_points=200, mesh=mesh,
                              init=c0f)
cg2, ig2 = fit_streaming(fpts, k=5, iters=3, chunk_points=200, mesh=mesh,
                         init=c0f)
assert np.allclose(cf, cg2, rtol=1e-3, atol=1e-3)
print(f"file-split ingest: 4 csv splits ≡ single source ({inf:.1f})")
print(f"DRIVE OK round-4 ({mode})")

# 10. subgraph overflow: both exact tails agree through the public API
from harp_tpu.models import subgraph as SG

hub_edges = [(0, i) for i in range(1, 48)] + \
    [(int(a), int(b)) for a, b in zip(rng.integers(0, 48, 80),
                                      rng.integers(0, 48, 80))]
trials = {}
for algo in ("segment", "onehot"):
    cfgs = SG.SubgraphConfig(template="u3-path", n_trials=3, seed=2,
                             max_degree=4, overflow_algo=algo,
                             overflow_row_tile=8, overflow_entry_tile=16)
    est, tr, ovf = SG.count_template(hub_edges, 48, cfgs, mesh)
    assert ovf > 0
    trials[algo] = tr
np.testing.assert_allclose(trials["onehot"], trials["segment"], rtol=1e-5)
print("subgraph overflow: onehot ≡ segment on a hub graph")
print(f"DRIVE OK round-5 ({mode})")

# 11. int8 sharded ingest + million-token attention lowering
from harp_tpu.models.kmeans_stream import fit_streaming_local as fsl

cq, iq = fsl(pl, k=6, iters=3, chunk_points=400, mesh=mesh, init=c0,
             quantize="int8")
assert np.isfinite(iq)
from harp_tpu.ops.ring_attention import make_ring_attention_fn as mra

sh_att = mesh.sharding(mesh.spec(1, ndim=4))
sds_att = [jax.ShapeDtypeStruct((1, 1_048_576, 8, 128), jnp.bfloat16,
                                sharding=sh_att) for _ in range(3)]
t_att = mra(mesh, causal=True).lower(*sds_att).as_text()
assert "collective_permute" in t_att and "131072" in t_att
print("int8 sharded ingest + 1M-token ring attention lowering: ok")
print(f"DRIVE OK round-6 ({mode})")

# 12. int8 file-split ingest through the CLI surface
from harp_tpu.models.kmeans_stream import fit_streaming_files as fsf

cq2, iq2 = fsf(sorted(_glob.glob(os.path.join(sdir, "*.csv"))), k=5,
               iters=2, chunk_points=200, mesh=mesh, init=c0f,
               quantize="int8")
assert np.isfinite(iq2)
print(f"int8 file-split ingest: ok ({iq2:.1f})")
print(f"DRIVE OK round-7 ({mode})")

# 13. roofline annotation math (this session: default-precision bf16 peak
# + fused-kernel kmeans byte model, driven against hand-computed numpy)
from harp_tpu.utils.roofline import V5E_PEAKS, annotate

rec = {"n": 1_000_000, "d": 300, "k": 100, "iters_per_sec": 400.0,
       "quantize": None, "num_workers": 1}
ann = annotate("kmeans", rec)
flops_s = 4.0 * rec["n"] * rec["d"] * rec["k"] * rec["iters_per_sec"]
bytes_s = (rec["n"] * rec["d"] * 4 + 4.0 * rec["n"]) * rec["iters_per_sec"]
np.testing.assert_allclose(ann["achieved_tflops"], round(flops_s / 1e12, 3))
np.testing.assert_allclose(ann["achieved_gbs"], round(bytes_s / 1e9, 2))
assert ann["roofline_peak"] == "bf16_flops"  # default-precision matmuls
np.testing.assert_allclose(
    ann["pct_peak_flops"],
    round(100.0 * flops_s / V5E_PEAKS["bf16_flops"], 2))
# the silicon fact that forced the fix: 131 TF/s measured ex-gen on
# kmeans_stream must be REPRESENTABLE (< 100% of the chosen peak)
fast = annotate("kmeans_stream", {"n": 99_876_864, "d": 300, "k": 1000,
                                  "iters_per_sec": 0.53,
                                  "iters_per_sec_ex_gen": 1.0934,
                                  "quantize": None, "num_workers": 1})
assert fast["pct_peak_flops"] < 100.0, fast
assert fast["bound"] == "compute"
print("roofline: bf16 peak + fused byte model vs numpy: ok")
print(f"DRIVE OK round-8 ({mode})")

# 14. wire-dtype streaming + fused int8 kernel (this session)
import tempfile as _tf

_wd = _tf.mkdtemp(prefix="drive_wire_")
_pts16 = (rng.normal(size=(1500, 16)).astype(np.float32) * 3).astype(np.float16)
_npy = os.path.join(_wd, "pts16.npy")
np.save(_npy, _pts16)
_mm = np.load(_npy, mmap_mode="r")
from harp_tpu.models.kmeans_stream import fit_streaming as _fstr

_c_auto, _i_auto = _fstr(_mm, k=6, iters=3, chunk_points=512, mesh=mesh,
                         seed=11)
_c_leg, _i_leg = _fstr(_mm, k=6, iters=3, chunk_points=512, mesh=mesh,
                       seed=11, wire_dtype=None)
np.testing.assert_array_equal(_c_auto, _c_leg)  # f16 wire is exact
from harp_tpu.models.kmeans import fit as _kfit

_pts_i8 = np.asarray(_pts16, np.float32)[:1024]
_ca, _ia = _kfit(_pts_i8, k=4, iters=4, mesh=mesh, seed=5, quantize="int8")
_cb, _ib = _kfit(_pts_i8, k=4, iters=4, mesh=mesh, seed=5, quantize="int8",
                 use_pallas=True)
np.testing.assert_allclose(_ca, _cb, rtol=1e-5, atol=1e-5)
print(f"wire dtype exact + fused int8 kernel ≡ XLA int8 ({_ib:.1f})")
print(f"DRIVE OK round-9 ({mode})")

# 15. fused Pallas MF-SGD (this session): algo="pallas" through the public
# MFSGD driver must reproduce algo="dense" (same entries, same order) and
# leave ratings-free W blocks untouched.
from harp_tpu.models.mfsgd import MFSGD, MFSGDConfig, synthetic_ratings

_u, _i, _v = synthetic_ratings(96, 64, 3000, rank=4, noise=0.05, seed=2)
_factors = {}
_mt = 128 if mode == "tpu" else 8  # kernel gates 128-multiples on TPU
for _algo in ("dense", "pallas"):
    _cfg = MFSGDConfig(rank=8, algo=_algo, u_tile=_mt, i_tile=_mt,
                       entry_cap=32, compute_dtype=jnp.float32,
                       lr=0.03, reg=0.01)
    _m = MFSGD(96, 64, _cfg, mesh, seed=4)
    _m.set_ratings(_u, _i, _v)
    _rm = [_m.train_epoch() for _ in range(2)]
    _factors[_algo] = (_m.factors(), _rm)
np.testing.assert_allclose(_factors["pallas"][0][0], _factors["dense"][0][0],
                           rtol=1e-4, atol=1e-5)
np.testing.assert_allclose(_factors["pallas"][0][1], _factors["dense"][0][1],
                           rtol=1e-4, atol=1e-5)
np.testing.assert_allclose(_factors["pallas"][1], _factors["dense"][1],
                           rtol=1e-5)
assert _factors["pallas"][1][1] < _factors["pallas"][1][0]  # converging
print(f"pallas MF-SGD ≡ dense through public driver "
      f"(rmse {_factors['pallas'][1][-1]:.4f})")
print(f"DRIVE OK round-10 ({mode})")

# 16. self-time op_breakdown (this session): trace a real jitted run and
# check the table is flame-graph-consistent — parent/aggregate spans must
# not outweigh the whole capture (they triple-counted before the fix).
import tempfile as _tf2

from harp_tpu.utils.profiling import op_breakdown, trace

_x = jnp.ones((256, 256))
_g = jax.jit(lambda a: (a @ a).sum())
float(_g(_x))  # compile outside
with trace(_tf2.mkdtemp(prefix="drive_prof_")) as _td:
    float(_g(_x))
_rows = op_breakdown(_td, top=50)
assert _rows and all(s >= 0 for _, s in _rows)
_raw = op_breakdown(_td, top=50, self_time=False)
# self-time never exceeds raw for any op, and the self-time total is ≤ raw
assert sum(s for _, s in _rows) <= sum(s for _, s in _raw) + 1e-9
print(f"self-time op_breakdown: {len(_rows)} ops, "
      f"{sum(s for _, s in _rows) * 1e3:.2f} ms traced")
print(f"DRIVE OK round-11 ({mode})")

# 17. exprace topic sampler (this session): the exponential-race draw
# through the public LDA driver — frequencies must match the posterior
# (identical distribution to gumbel, ~5× fewer transcendentals).
from harp_tpu.models.lda import LDA, LDAConfig, synthetic_corpus

_d, _w = synthetic_corpus(n_docs=64, vocab_size=32, n_topics_true=4,
                          tokens_per_doc=40, seed=3)
_lls = {}
for _sm, _ri in (("gumbel", "threefry"), ("exprace", "threefry"),
                 ("exprace", "rbg")):
    _lcfg = LDAConfig(n_topics=8, algo="dense", d_tile=16, w_tile=16,
                      entry_cap=64, alpha=0.5, beta=0.1, sampler=_sm,
                      rng_impl=_ri)
    _lm = LDA(64, 32, _lcfg, mesh, seed=1)
    _lm.set_tokens(_d, _w)
    for _ in range(8):
        _lm.sample_epoch()
    _lls[f"{_sm}/{_ri}"] = _lm.log_likelihood()
    _ndk = np.asarray(_lm.Ndk)
    assert _ndk.sum() == _lm.n_tokens and (_ndk >= 0).all()
# both chains must reach the same likelihood ballpark on this corpus
# (different random streams on a tiny corpus: ~10% run-to-run spread,
# so the gate needs real margin over it)
_base = _lls["gumbel/threefry"]
for _k, _v in _lls.items():
    assert abs(_v - _base) / abs(_base) < 0.25, _lls
print(f"sampler/rng variants ≡ gumbel chain quality ({_lls})")
print(f"DRIVE OK round-12 ({mode})")

# 18. fused Pallas LDA entry resample (this session): algo="pallas"
# through the public driver — chain ascends, counts stay exact integers.
# TPU-legal tiles when driving real hardware (the kernel gates 128-
# multiples there); the CPU sim keeps the fast small-tile shapes
_pt = 128 if mode == "tpu" else 16
_pcfg = LDAConfig(n_topics=8, algo="pallas", d_tile=_pt, w_tile=_pt,
                  entry_cap=64, alpha=0.5, beta=0.1,
                  sampler="exprace", rng_impl="rbg")
_pm = LDA(64, 32, _pcfg, mesh, seed=1)
_pm.set_tokens(_d, _w)
_pll0 = _pm.log_likelihood()
for _ in range(6):
    _pm.sample_epoch()
_pndk = np.asarray(_pm.Ndk)
_pnwk = np.asarray(_pm.Nwk)
assert _pndk.sum() == _pm.n_tokens and (_pndk >= 0).all()
assert (_pnwk == np.round(_pnwk)).all()  # integer counts survive bf16 gathers
np.testing.assert_allclose(_pnwk.sum(0), np.asarray(_pm.Nk))
assert _pm.log_likelihood() > _pll0
_pbase = _lls["gumbel/threefry"]
assert abs(_pm.log_likelihood() - _pbase) / abs(_pbase) < 0.25
print(f"pallas LDA chain ok (ll {_pll0:.2f} -> {_pm.log_likelihood():.2f})")
print(f"DRIVE OK round-13 ({mode})")

# 19. int8 synthetic streaming formulation (this session): the north-star
# compute twin on the int8 MXU — same keys as f32, inertia within the
# quantization tolerance and descending.
from harp_tpu.models.kmeans_stream import benchmark_streaming as _bstr

_bkw = dict(n=32768, d=16, k=8, chunk_points=4096, mesh=mesh, warmup=1)
_bf = _bstr(iters=2, **_bkw)
_bq = _bstr(iters=2, quantize="int8", **_bkw)
assert _bq["quantize"] == "int8"
assert abs(_bq["inertia"] - _bf["inertia"]) / _bf["inertia"] < 0.05
print(f"int8 streaming formulation ≡ f32 within tolerance "
      f"({_bq['inertia']:.0f} vs {_bf['inertia']:.0f})")
print(f"DRIVE OK round-14 ({mode})")

# 20. ZeRO-1 sharded optimizer (this session): the optax update through
# push/pull must equal the replicated step, and the state must actually
# shard.
from harp_tpu.models.mlp import MLPConfig, MLPTrainer, synthetic_mnist

_zx, _zy = synthetic_mnist(n=256, d=32, classes=4, seed=0)
_zout = {}
for _z in (False, True):
    _zt = MLPTrainer(MLPConfig(sizes=(32, 48, 4), optimizer="adam",
                               zero1=_z), mesh, seed=0)
    _zl = [_zt.train_batch(_zx, _zy)[0] for _ in range(3)]
    _zout[_z] = (_zl, np.concatenate(
        [np.asarray(p).ravel() for p in jax.tree.leaves(_zt.params)]))
np.testing.assert_allclose(_zout[True][0], _zout[False][0], rtol=1e-5)
np.testing.assert_allclose(_zout[True][1], _zout[False][1],
                           rtol=2e-5, atol=2e-6)
print(f"zero1 ≡ replicated adam over 3 steps (loss {_zout[True][0][-1]:.4f})")
print(f"DRIVE OK round-15 ({mode})")

# 21. round 4 (this session): carry_db through the public LDA driver —
# the od-run-carried doc tile must be BIT-identical to the
# slice-per-entry chain on both tiled algos; the exact-gather kernel
# default keeps integer tables; and the flip gate refuses a degraded
# candidate.
from harp_tpu.models.lda import LDA as _R4L
from harp_tpu.models.lda import LDAConfig as _R4C
from harp_tpu.models.lda import synthetic_corpus as _r4corpus

_r4d, _r4w = _r4corpus(n_docs=48, vocab_size=24, n_topics_true=3,
                       tokens_per_doc=24, seed=9)
for _r4algo in ("dense", "pallas"):
    _r4extra = ({"sampler": "exprace", "rng_impl": "rbg"}
                if _r4algo == "pallas" else {})
    _r4chains = {}
    for _r4carry in (False, True):
        _r4m = _R4L(48, 24, _R4C(n_topics=4, algo=_r4algo, d_tile=8,
                                 w_tile=8, entry_cap=32,
                                 carry_db=_r4carry, **_r4extra),
                    mesh, seed=2)
        _r4m.set_tokens(_r4d, _r4w)
        for _ in range(3):
            _r4m.sample_epoch()
        _r4chains[_r4carry] = (np.asarray(_r4m.Ndk), np.asarray(_r4m.Nwk),
                               np.asarray(_r4m.z_grid))
    for _a, _b in zip(_r4chains[False], _r4chains[True]):
        np.testing.assert_array_equal(_a, _b)
    print(f"carry_db ≡ slice-per-entry ({_r4algo}, bit-identical)")

# exact plane gathers: a pallas chain at hot counts (tiny vocab) keeps
# integer tables and tracks dense likelihood
import importlib.util as _r4ilu
import os as _r4os

_r4spec = _r4ilu.spec_from_file_location(
    "flip_decision", _r4os.path.join(
        _r4os.path.dirname(_r4os.path.abspath(__file__)),
        "flip_decision.py"))
_r4fd = _r4ilu.module_from_spec(_r4spec)
_r4spec.loader.exec_module(_r4fd)
_r4v = _r4fd.decide(
    {"tokens_per_sec_per_chip": 9e6, "log_likelihood": -9.5},
    {"tokens_per_sec_per_chip": 6e6, "log_likelihood": -9.1},
    _r4fd.CANDIDATES["lda_pallas"])
assert not _r4v["flip"] and _r4v["quality_ok"] is False  # degraded → refused
_r4v2 = _r4fd.decide(
    {"tokens_per_sec_per_chip": 9e6, "log_likelihood": -9.11},
    {"tokens_per_sec_per_chip": 6e6, "log_likelihood": -9.1},
    _r4fd.CANDIDATES["lda_pallas"])
assert _r4v2["flip"]  # 1.5x at equal quality → flips
print("flip gate: degraded refused, equal-quality 1.5x flips")
print(f"DRIVE OK round-16 ({mode})")

# 22. round 5 (this session): ADVICE r4 fixes through the public surface.
# (a) the shared carry_tile_switch stays exact for OVERLAPPING
# (non-tile-aligned) offsets — carry vs slice-per-entry bit-identical on
# a hand-built block whose u-runs overlap (0 -> 4 -> 0 with u_tile=8);
from harp_tpu.models import mfsgd as _R5M

_r5rng = np.random.default_rng(11)
_r5blk = (jnp.asarray(_r5rng.integers(0, 8, (5, 4)).astype(np.int32)),
          jnp.asarray(_r5rng.integers(0, 8, (5, 4)).astype(np.int32)),
          jnp.asarray(_r5rng.normal(size=(5, 4)).astype(np.float32)),
          jnp.asarray(np.array([0, 0, 4, 4, 0], np.int32)),
          jnp.asarray(np.array([0, 8, 0, 8, 0], np.int32)))
_r5W0 = _r5rng.normal(size=(24, 3)).astype(np.float32)
_r5H0 = _r5rng.normal(size=(16, 3)).astype(np.float32)
_r5out = {}
for _r5c in (False, True):
    _r5cfg = _R5M.MFSGDConfig(rank=3, algo="dense", u_tile=8, i_tile=8,
                              entry_cap=4, compute_dtype=jnp.float32,
                              lr=0.05, reg=0.01, carry_w=_r5c)
    _r5out[_r5c] = jax.jit(
        lambda W, H, b, c=_r5cfg: _R5M._tile_block_update(W, H, b, c))(
        jnp.asarray(_r5W0), jnp.asarray(_r5H0), _r5blk)
for _a, _b in zip(_r5out[False], _r5out[True]):
    np.testing.assert_array_equal(np.asarray(_a), np.asarray(_b))
print("carry_tile_switch exact for overlapping offsets (bit-identical)")

# (b) the flip gate refuses a MIXED metric basis (ex-gen vs end-to-end);
_r5spec = _r4fd.CANDIDATES["kmeans_stream_int8"]
_r5v = _r4fd.decide(
    {"iters_per_sec": 0.9, "iters_per_sec_ex_gen": 2.2, "inertia": 1e10},
    {"iters_per_sec": 0.53, "inertia": 1e10}, _r5spec)
assert not _r5v["flip"] and _r5v["speedup"] is None
assert "mixed" in _r5v["reason"]
print("flip gate: mixed metric basis refused")

# (c) _save_pack sweeps dead writers' tmp orphans, survives a racing
# live-pid tmp, and round-trips the pack;
import subprocess as _r5sp
import tempfile as _r5tf

from harp_tpu.models.lda import _load_pack as _r5load
from harp_tpu.models.lda import _save_pack as _r5save

with _r5tf.TemporaryDirectory() as _r5d:
    _r5p = _r4os.path.join(_r5d, "pack.npz")
    # a guaranteed-dead pid: a reaped child (999999 could be live under
    # a large kernel.pid_max)
    _r5dead = _r5sp.Popen(["true"])
    _r5dead.wait()
    open(f"{_r5p}.{_r5dead.pid}.tmp.npz", "w").close()  # dead pid: swept
    open(_r5p + ".tmp.npz", "w").close()              # legacy name: swept
    # a LIVE foreign writer (sleeping child): its tmp must survive
    _r5alive = _r5sp.Popen(["sleep", "30"])
    _r5live = f"{_r5p}.{_r5alive.pid}.tmp.npz"
    open(_r5live, "w").close()
    _r5pack = {"tokens": (np.arange(6, dtype=np.int32),),
               "z_grid": np.zeros((2, 3), np.int32),
               "Ndk": np.ones((2, 2), np.int32),
               "Nwk": np.ones((2, 2), np.int32),
               "Nk": np.ones((2,), np.int32), "n_tokens": 6}
    _r5save(_r5p, _r5pack)
    assert not _r4os.path.exists(f"{_r5p}.{_r5dead.pid}.tmp.npz")
    assert not _r4os.path.exists(_r5p + ".tmp.npz")
    assert _r4os.path.exists(_r5live)                 # live writer kept
    _r5alive.kill()
    _r5alive.wait()
    _r5back = _r5load(_r5p)
    assert _r5back["n_tokens"] == 6
    np.testing.assert_array_equal(_r5back["tokens"][0], _r5pack["tokens"][0])
print("_save_pack: dead-writer tmp swept, pack round-trips")

# (d) the mlp fit CLI emits one parseable JSON line (ADVICE r4 #5).
import contextlib as _r5ctx
import io as _r5io
import json as _r5json

from harp_tpu.models import mlp as _R5mlp

_r5buf = _r5io.StringIO()
with _r5ctx.redirect_stdout(_r5buf):
    _R5mlp.main(["--train", "--batch", "256"])
_r5rows = [_r5json.loads(ln) for ln in _r5buf.getvalue().splitlines()
           if ln.strip()]
assert any(r.get("config") == "mlp_fit_cli" and "train_acc" in r
           for r in _r5rows)
print("mlp --train CLI emits parseable mlp_fit_cli JSON")
print(f"DRIVE OK round-17 ({mode})")

# 23. round 5 (this session): scaling-evidence CLIs drive end to end.
# project_scaling emits a complete dated (app x N) grid whose BASELINE.md
# table derives from it; every row cites a measured rate date and the
# rotation rows show the double-buffered ring hiding under compute.
import subprocess as _r5sp2

_r5proj = _r5sp2.run([sys.executable, "scripts/project_scaling.py"],
                     capture_output=True, text=True, timeout=300,
                     cwd=_r4os.path.dirname(_r4os.path.dirname(
                         _r4os.path.abspath(__file__))))
assert _r5proj.returncode == 0, _r5proj.stderr[-500:]
_r5rows = [_r5json.loads(ln) for ln in _r5proj.stdout.splitlines()
           if ln.strip()]
assert {r["app"] for r in _r5rows} == {
    "kmeans", "kmeans_stream_1b", "mfsgd", "lda", "mlp", "subgraph", "rf"}
assert all(0.0 < r["efficiency"] <= 1.0 and r["measured_date"]
           for r in _r5rows)
assert all(r["efficiency"] == 1.0 for r in _r5rows
           if r["pattern"] == "rotate")
print(f"project_scaling: {len(_r5rows)}-row grid, rotation comm hidden")
print(f"DRIVE OK round-18 ({mode})")

# 24. round 5 session 2: the two-word prng_seed invariant.  The real TPU
# compiler rejects pltpu.prng_seed with >2 seed words ("Setting seed
# with more than 2 values is not supported" — silicon 2026-08-01, which
# cost the sprint its pallas rows until the mid-window fix); the local
# Mosaic lowering pass does NOT enforce it and the kernel MLIR is
# serialized inside the lowered module (not text-greppable), so the pin
# records the call arity AT TRACE TIME: wrap pltpu.prng_seed, lower the
# noise-free (compiled-mode) kernel for TPU, assert every call passed
# <= 2 words.
import functools as _r5f2

from harp_tpu.ops import lda_kernel as _r5lk

_r5arities = []
_r5orig_seed = _r5lk.pltpu.prng_seed


def _r5rec_seed(*a):
    # count seed WORDS, not positional args — prng_seed accepts array
    # args, so a [3]-shaped single argument is still 3 words to the
    # compiler (review finding, round 5)
    _r5arities.append(sum(int(np.size(x)) for x in a))
    return _r5orig_seed(*a)


_r5lk.pltpu.prng_seed = _r5rec_seed
try:
    _r5kf = _r5f2.partial(_r5lk.cgs_entry_update,
                          alpha=0.1, beta=0.01, vbeta=1.28)
    _r5kargs = (jnp.zeros((128, 128), jnp.float32),
                jnp.zeros((128, 128), jnp.float32),
                jnp.zeros((128,), jnp.float32),
                jnp.zeros((256,), jnp.int32), jnp.zeros((256,), jnp.int32),
                jnp.zeros((256,), jnp.int32), jnp.zeros((2,), jnp.int32))
    jax.jit(_r5kf).trace(*_r5kargs).lower(lowering_platforms=("tpu",))
finally:
    _r5lk.pltpu.prng_seed = _r5orig_seed
assert _r5arities, "noise-free kernel never seeded the PRNG"
assert max(_r5arities) <= 2, (
    f"prng_seed called with {max(_r5arities)} words — the real TPU "
    "compiler takes at most 2 (silicon 2026-08-01)")
print(f"prng_seed arity <= 2 across {len(_r5arities)} trace-time calls")
print(f"DRIVE OK round-19 ({mode})")

# 25. round 6 (this session): the telemetry spine through the public
# surface.  (a) CommLedger counts per EXECUTION, not per trace: a jitted
# allreduce invoked 3 times (1 trace) must report 3x the hand-computed
# per-shard sheet; (b) kmeans.fit's allreduce row is exactly
# (k*d*4 + k*4 + 4) per iteration; (c) spans nest and export; (d) the
# report CLI round-trips the exported JSONL; (e) disabled telemetry
# records nothing.
from harp_tpu.utils import telemetry as _r6T
from harp_tpu.parallel import collective as _r6C

_r6T.ledger.reset(); _r6T.tracer.reset()
_r6op = _r6C.host_op(mesh, _r6C.allreduce)
_r6x = np.ones((nw * 8, 128), np.float32)
with _r6T.scope():
    for _ in range(3):
        with _r6T.ledger.run("drive.ar", steps=1):
            _r6op(_r6x)
    _r6per = 8 * 128 * 4  # per-shard: [8, 128] f32
    assert _r6T.ledger.bytes_per_execution("drive.ar") == _r6per
    assert _r6T.ledger.volume("drive.ar") == 3 * _r6per

    from harp_tpu.models import kmeans as _r6KM
    _r6k, _r6d, _r6it = 8, 16, 3
    _r6pts = np.random.default_rng(6).normal(
        size=(nw * 32, _r6d)).astype(np.float32)
    _r6KM.fit(_r6pts, k=_r6k, iters=_r6it, mesh=mesh)
    _r6tag = _r6T.ledger.summary()["kmeans.fit"]
    _r6sheet = _r6k * _r6d * 4 + _r6k * 4 + 4  # sums + counts + inertia
    assert _r6tag["bytes_per_execution"] == _r6sheet, _r6tag
    assert _r6tag["executions"] == _r6it
    assert _r6tag["total_bytes"] == _r6sheet * _r6it

    with _r6T.span("drive.outer"):
        with _r6T.span("drive.inner"):
            pass
    _r6recs = {r["span"]: r for r in _r6T.tracer.records}
    assert _r6recs["drive.inner"]["path"] == "drive.outer/drive.inner"

    _r6path = os.path.join(tempfile.mkdtemp(), "run.jsonl")
    _r6T.export(_r6path)

import json as _r6json
import subprocess as _r6sp

_r6rep = _r6sp.run(
    [sys.executable, "-m", "harp_tpu", "report", "--telemetry", _r6path],
    capture_output=True, text=True, timeout=300,
    cwd=_r4os.path.dirname(_r4os.path.dirname(_r4os.path.abspath(__file__))))
assert _r6rep.returncode == 0, _r6rep.stderr[-500:]
assert "== harp-tpu run report ==" in _r6rep.stdout
_r6row = _r6json.loads(_r6rep.stdout.strip().splitlines()[-1])
assert _r6row["comm_tags"]["kmeans.fit"]["total_bytes"] == _r6sheet * _r6it
assert all(f in _r6row for f in ("backend", "date", "commit"))

# disabled => zero records (the stay-on-for-sprints guarantee)
assert not _r6T.enabled()
_r6T.ledger.reset(); _r6T.tracer.reset()
with _r6T.ledger.run("off", steps=1):
    _r6op(np.ones((nw, 128), np.float32))
with _r6T.span("off"):
    pass
assert _r6T.ledger.summary() == {} and _r6T.tracer.records == []
print(f"telemetry: exec-counted ledger, kmeans sheet {_r6sheet} B/iter, "
      "report round-trip, zero-cost off")
print(f"DRIVE OK round-20 ({mode})")

# 25. PR 2 (this session): overlap-first rotation through the public
# surface.  (a) the chunked pipeline at n_chunks=4: the resident-chunk
# index formula, coverage, and home-placement against a numpy model of
# the queue schedule;
from harp_tpu.parallel import resident_chunk_index, rotate_pipeline
from jax.sharding import PartitionSpec as _P2

_p2nc = 4
_p2rows = 8  # per worker, divisible by 4
_p2ids = np.repeat(np.arange(nw * _p2nc, dtype=np.float32),
                   _p2rows // _p2nc)[:, None]


def _p2prog(s):
    def step(st, cur, t):
        err, acc = st
        want = resident_chunk_index(t, _p2nc).astype(jnp.float32)
        return (err + jnp.abs(cur - want).sum(), acc + cur.sum()), cur

    (err, acc), out = rotate_pipeline(
        step, (jnp.float32(0.0), jnp.float32(0.0)), s, n_chunks=_p2nc)
    return jnp.concatenate([err[None, None], acc[None, None], out], 0)


_p2out = np.asarray(jax.jit(mesh.shard_map(
    _p2prog, in_specs=(mesh.spec(0),), out_specs=mesh.spec(0)))(_p2ids))
_p2out = _p2out.reshape(nw, _p2rows + 2)
assert (_p2out[:, 0] == 0).all()          # schedule == index formula
np.testing.assert_allclose(                # every worker saw every chunk
    _p2out[:, 1], np.full(nw, _p2ids.sum()))
np.testing.assert_array_equal(             # chunks land home
    _p2out[:, 2:].reshape(-1), _p2ids.reshape(-1))
print(f"chunked rotate_pipeline(n_chunks={_p2nc}): schedule, coverage, home")

# (b) quantized data movement: one rounding against the worker-shared
# scale (vs numpy roll / exact regroup), int leaves exact
_p2x = np.random.default_rng(21).normal(size=(nw * 4, 16)).astype(np.float32)
_p2rot = C.host_op(mesh, C.rotate_quantized, in_dim=0, out_dim=0,
                   wire_dtype=jnp.int8)
_p2got = np.asarray(_p2rot(_p2x)).reshape(nw, 4, 16)
_p2exp = np.roll(_p2x.reshape(nw, 4, 16), 1, axis=0)
assert np.abs(_p2got - _p2exp).max() <= np.abs(_p2x).max() / 254 + 1e-6
_p2xi = np.arange(nw * nw, dtype=np.int32).reshape(nw * nw, 1)
_p2rg = C.host_op(mesh, C.regroup_quantized, in_dim=0, out_dim=0,
                  wire_dtype=jnp.int8)
_p2rge = C.host_op(mesh, C.regroup, in_dim=0, out_dim=0)
np.testing.assert_array_equal(np.asarray(_p2rg(_p2xi)),
                              np.asarray(_p2rge(_p2xi)))
print("rotate/regroup_quantized int8: single-rounding bound, int exact")

# (c) MF-SGD at rotate_chunks=4 through the public driver vs the numpy
# replica of the generalized schedule
from harp_tpu.models import mfsgd as _P2M

_p2rng = np.random.default_rng(23)
_p2u = _p2rng.integers(0, 8 * nw, 400).astype(np.int32)
_p2i = _p2rng.integers(0, 6 * nw, 400).astype(np.int32)
_p2v = _p2rng.normal(size=400).astype(np.float32)
_p2cfg = _P2M.MFSGDConfig(rank=4, chunk=16, lr=0.02, reg=0.01,
                          algo="scatter", rotate_chunks=4)
_p2m = _P2M.MFSGD(8 * nw, 6 * nw, _p2cfg, mesh, seed=3)
_p2W0, _p2H0 = np.asarray(_p2m.W).copy(), np.asarray(_p2m.H).copy()
_p2m.set_ratings(_p2u, _p2i, _p2v)
_p2m.train_epoch()
_p2bu, _p2bi, _p2bv, _p2bm, _p2ub, _p2ib = _P2M.partition_ratings(
    _p2u, _p2i, _p2v, 8 * nw, 6 * nw, nw, 16, n_slices=4 * nw)
_p2ns = 4 * nw
_p2W, _p2H = _p2W0.copy(), _p2H0.copy()
_p2bu2 = _p2bu.reshape(nw, _p2ns, -1)
_p2bi2 = _p2bi.reshape(nw, _p2ns, -1)
_p2bv2 = _p2bv.reshape(nw, _p2ns, -1)
_p2bm2 = _p2bm.reshape(nw, _p2ns, -1)
for _t in range(_p2ns):
    for _w in range(nw):
        _r = _t % 4
        _s = 4 * ((_w - _t // 4 - (1 if _r == 3 else 0)) % nw) + _r
        _Wv = _p2W[_w * _p2ub:(_w + 1) * _p2ub]
        _Hv = _p2H[_s * _p2ib:(_s + 1) * _p2ib]
        _B = _p2bu2.shape[-1]
        for _lo in range(0, _B, 16):
            _sl = slice(_lo, _lo + 16)
            _uu, _ii, _vv, _mm = (_p2bu2[_w, _s, _sl], _p2bi2[_w, _s, _sl],
                                  _p2bv2[_w, _s, _sl], _p2bm2[_w, _s, _sl])
            _wu, _hi = _Wv[_uu], _Hv[_ii]
            _err = _mm * (_vv - (_wu * _hi).sum(-1))
            _gw = _err[:, None] * _hi - 0.01 * _mm[:, None] * _wu
            _gh = _err[:, None] * _wu - 0.01 * _mm[:, None] * _hi
            np.add.at(_Wv, _uu, 0.02 * _gw)
            np.add.at(_Hv, _ii, 0.02 * _gh)
np.testing.assert_allclose(np.asarray(_p2m.W), _p2W, rtol=2e-4, atol=2e-5)
np.testing.assert_allclose(np.asarray(_p2m.H), _p2H, rtol=2e-4, atol=2e-5)
print("mfsgd rotate_chunks=4 epoch == numpy generalized schedule")

# (d) LDA at rotate_chunks=4: Gibbs count invariants survive the
# generalized schedule; the CommLedger accounts the int8 rotate wire at
# exactly 1/4 of the f32 baseline (the report's bytes-on-wire claim)
from harp_tpu.models.lda import LDA as _P2L
from harp_tpu.models.lda import LDAConfig as _P2LC
from harp_tpu.models.lda import synthetic_corpus as _p2corpus
from harp_tpu.utils import telemetry as _P2T

_p2d, _p2w = _p2corpus(6 * nw, 64, 3, 16, seed=5)
_p2lm = _P2L(6 * nw, 64, _P2LC(n_topics=6, algo="dense", d_tile=8,
                               w_tile=8, entry_cap=32, rotate_chunks=4),
             mesh, seed=0)
_p2lm.set_tokens(_p2d, _p2w)
for _ in range(2):
    _p2lm.sample_epoch()
assert _p2lm.doc_topic_table().sum() == len(_p2d)
assert _p2lm.word_topic_table().sum() == len(_p2d)
np.testing.assert_allclose(_p2lm.word_topic_table().sum(0),
                           np.asarray(_p2lm.Nk))
assert np.isfinite(_p2lm.log_likelihood())


def _p2rot_bytes(wire):
    with _P2T.scope(True):
        _m = _P2M.MFSGD(64, 64, _P2M.MFSGDConfig(
            rank=8, algo="scatter", chunk=64, rotate_wire=wire), mesh,
            seed=0)
        _m.set_ratings(*_P2M.synthetic_ratings(64, 64, 500, seed=0))
        with _P2T.ledger.run("probe", steps=0):
            _m._epoch_fn.lower(_m.W, _m.H, *_m._blocks)
        return sum(s["payload_bytes"]
                   for s in _P2T.ledger.summary()["probe"]["sites"]
                   # PR 11: the ring hop is the reshard shim now
                   if s["verb"] in ("rotate", "rotate_quantized",
                                    "reshard"))


assert _p2rot_bytes("exact") == 4 * _p2rot_bytes("int8") > 0
print("lda rotate_chunks=4 invariants; ledger: int8 rotate = 1/4 f32 bytes")

# (e) the new flip candidates fail closed without rows and flip at
# equal quality >= 1.10x
for _p2name in ("mfsgd_chunked_rotate", "lda_rotate_int8"):
    _p2spec = _r4fd.CANDIDATES[_p2name]
    assert not _r4fd.decide(None, None, _p2spec)["flip"]
_p2v = _r4fd.decide(
    {"updates_per_sec_per_chip": 12e6, "rmse_final": 0.366},
    {"updates_per_sec_per_chip": 10e6, "rmse_final": 0.366},
    _r4fd.CANDIDATES["mfsgd_chunked_rotate"])
assert _p2v["flip"]
print("flip gate: chunked-rotate candidates fail closed / flip at 1.2x")
print(f"DRIVE OK round-21 ({mode})")

# --- round 22: execution flight recorder ----------------------------------
# CompileWatch counts real XLA backend compiles with span attribution, the
# TransferLedger's counters reproduce hand-computed byte sheets for a real
# kmeans fit, the budget guard catches the documented relay traps and the
# shipped loop passes its pinned budget, report + export + checker round-trip.
import json as _fr_json
import tempfile as _fr_tmp

from harp_tpu import report as _FRrep
from harp_tpu.models import kmeans as _FRKM
from harp_tpu.utils import flightrec as _FR
from harp_tpu.utils import prng as _FRprng
from harp_tpu.utils import telemetry as _FRT

assert _FR.COMPILE_EVENTS_AVAILABLE  # this jax has the monitoring hook

# (a) collectors against hand-computed values on a real fit
_fr_pts = np.random.default_rng(0).normal(size=(32 * nw, 8)).astype(np.float32)
_FRKM.fit(_fr_pts, k=4, iters=3, mesh=mesh, seed=0)  # warm shared ops
with _FRT.scope(True):
    with _FRT.span("fit"):
        with _FR.budget(compiles=1, dispatches=1, readbacks=2,
                        h2d_bytes=_fr_pts.nbytes, tag="drive.kmeans"):
            _fr_c, _fr_inertia = _FRKM.fit(_fr_pts, k=4, iters=3, mesh=mesh,
                                           seed=0)
    _fr_row, _fr_spans = _FRrep.live_report()
    assert _FR.transfers.h2d_bytes == _fr_pts.nbytes      # points, ONCE
    assert _FR.transfers.dispatches == 1                  # one tracked fit
    assert _FR.transfers.readbacks == 2                   # stats + centroids
    # PR 4: the inertia readback became the [nw, 2] per-worker stats
    # array (rows + inertia — the skew counter rides the same fetch)
    assert _FR.transfers.d2h_bytes == 2 * 4 * nw + _fr_c.nbytes
    assert _FR.compile_watch.count == 1                   # one fresh seed jit
    assert _FR.compile_watch.summary()["by_span"] == {
        "fit/kmeans.fit": {"count": 1,
                           "total_s": _FR.compile_watch.summary()["total_s"]}}
    assert np.isfinite(_fr_inertia)
    # the report row carries the same numbers
    assert _fr_row["compile"]["count"] == 1
    assert _fr_row["transfer"]["h2d_bytes"] == _fr_pts.nbytes
    _fr_text = _FRrep.render(_fr_row, _fr_spans)
    assert "compiles (XLA backend): 1" in _fr_text
    assert "transfers (host<->device):" in _fr_text
    # (b) export -> CLI report -> checker, all from one file
    with _fr_tmp.NamedTemporaryFile("r", suffix=".jsonl") as _fr_fh:
        _FRT.export(_fr_fh.name)
        _fr_kinds = _FRT.load_rows(_fr_fh.name)
        assert _fr_kinds["compile"] and _fr_kinds["transfer"]
        for _fr_r in _fr_kinds["compile"] + _fr_kinds["transfer"]:
            assert {"backend", "date", "commit"} <= set(_fr_r)
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__))))
        import check_jsonl as _fr_cj

        assert _fr_cj.check_file(_fr_fh.name) == []
        _fr_row2 = _FRrep.build_row(
            _FRrep.comm_summary_from_rows(_fr_kinds["comm"]),
            _FRrep.span_summary_from_rows(_fr_kinds["span"]),
            compile_info=_FRrep.compile_summary_from_rows(
                _fr_kinds["compile"]),
            transfer_info=_FRrep.transfer_summary_from_rows(
                _fr_kinds["transfer"]))
        assert _fr_row2["compile"]["count"] == _fr_row["compile"]["count"]
        assert _fr_row2["transfer"]["h2d_bytes"] == _fr_pts.nbytes

# (c) the budget guard CATCHES the relay traps (raise mode)
with _FRT.scope(True):
    _fr_f = jax.jit(lambda x: x * 1.01)
    _fr_x = _fr_f(jnp.ones(8))
    from harp_tpu.utils.timing import device_sync as _fr_sync
    try:
        with _FR.budget(readbacks=1, tag="trap"):
            for _ in range(3):
                _fr_x = _fr_f(_fr_x)
                _fr_sync(_fr_x)  # per-epoch readback loop
        raise AssertionError("readbacks budget failed to trip")
    except _FR.BudgetExceeded as _fr_e:
        assert "readbacks used 3 > budget 1" in str(_fr_e)

# (d) prng.key_bits: bit-exact vs PRNGKey and compile-free across seeds
for _fr_seed in (0, 7, -3, 2**40 + 1):
    assert np.array_equal(_FRprng.key_bits(_fr_seed),
                          np.asarray(jax.random.PRNGKey(_fr_seed)))
with _FRT.scope(True):
    _FRprng.split_keys(1, nw)  # warm the shape-keyed split program
    _fr_n = _FR.compile_watch.count
    for _fr_seed in range(50, 60):
        _FRprng.split_keys(_fr_seed, nw)
    assert _FR.compile_watch.count == _fr_n  # zero per-seed compiles

# (e) zero-cost when off: no counter moves, result identical
with _FRT.scope(False):
    _fr_c2, _fr_i2 = _FRKM.fit(_fr_pts, k=4, iters=3, mesh=mesh, seed=0)
    assert _FR.compile_watch.count == 0 and _FR.transfers.dispatches == 0
    assert _FR.transfers.h2d_bytes == 0 and _FR.transfers.readbacks == 0
np.testing.assert_array_equal(_fr_c2, _fr_c)
print("flight recorder: counters == hand sheet, budget trips trap, "
      "export/report/checker round-trip, prng compile-free, zero-cost off")
print(f"DRIVE OK round-22 ({mode})")

# --- round 23: superstep skew profiler -------------------------------------
# SkewLedger per-worker counts == numpy bincount by the partitioners'
# ownership rule, the execution counters ride the EXISTING stacked
# readbacks (flagship budgets hold), the imbalance model and roofline
# composition match hand math, suggest_rebalance closes the loop through
# schedule.apply_rebalance on REAL files, and export rows pass checker
# invariant 5 (while a forged bad row fails it).
import tempfile as _sk_tmp

from harp_tpu import schedule as _SKsched
from harp_tpu.fileformat import multi_file_splits as _sk_splits
from harp_tpu.models import lda as _SKL
from harp_tpu.models import mfsgd as _SKMF
from harp_tpu.utils import skew as _SK
from harp_tpu.utils import telemetry as _SKT

# (a) skewed LDA: ingest == execution == numpy bincount; budget holds
_sk_d = np.concatenate([np.repeat(np.arange(8), 40),
                        np.repeat(np.arange(8, 64), 4)]).astype(np.int32)
_sk_w = np.random.default_rng(0).integers(0, 48, len(_sk_d)).astype(np.int32)
with _SKT.scope(True):
    _sk_lda = _SKL.LDA(64, 48, _SKL.LDAConfig(
        n_topics=8, algo="dense", d_tile=16, w_tile=16, entry_cap=64),
        mesh, seed=0)
    _sk_lda.set_tokens(_sk_d, _sk_w)
    _sk_lda.sample_epoch()  # warmup compile
    _sk_lda.compile_epochs(2)
    with _FR.budget(compiles=0, dispatches=1, readbacks=1,
                    h2d_bytes=nw * 8, tag="drive.skew.lda"):
        _sk_lda.sample_epochs(2)
    _sk_expect = np.bincount(_sk_d // _sk_lda.d_own, minlength=nw)
    for _sk_phase in ("lda.partition", "lda.epochs"):
        _sk_s = _SK.ledger.summary()[_sk_phase]
        np.testing.assert_allclose(_sk_s["work"], _sk_expect)
        assert _sk_s["total"] == len(_sk_d)
    assert _sk_s["max_mean_ratio"] == round(
        float(_sk_expect.max() / _sk_expect.mean()), 4)
    assert _sk_s["wasted_chip_s"] > 0  # wall measured, waste priced
    # report section renders with per-worker bars and sums
    _sk_row, _sk_spans = _FRrep.live_report()
    _sk_text = _FRrep.render(_sk_row, _sk_spans)
    assert "skew (per-worker load" in _sk_text and "max/mean" in _sk_text
    assert sum(_sk_row["skew"]["lda.epochs"]["work"]) == \
        _sk_row["skew"]["lda.epochs"]["total"]
    # (b) export -> checker invariant 5: real rows clean, forged row loud
    with _sk_tmp.NamedTemporaryFile("r+", suffix=".jsonl") as _sk_fh:
        _SKT.export(_sk_fh.name)
        assert len(_SKT.load_rows(_sk_fh.name)["skew"]) == 2
        assert _fr_cj.check_file(_sk_fh.name) == []
        _sk_fh.seek(0, 2)
        _sk_fh.write(_fr_json.dumps(
            {"kind": "skew", "phase": "forged", "work": [2, 2],
             "total": 5, "padding_frac": 1.5, "backend": "cpu",
             "date": "2026-08-04", "commit": "x"}) + "\n")
        _sk_fh.flush()
        _sk_errs = _fr_cj.check_file(_sk_fh.name)
        assert len(_sk_errs) == 2  # bad sum AND bad padding_frac
        assert any("sum" in e for e in _sk_errs)
        assert any("padding_frac" in e for e in _sk_errs)

# (c) mfsgd execution counter rides the stacked readback, == bincount
_sk_u = np.concatenate([np.random.default_rng(1).integers(0, 8, 700),
                        np.random.default_rng(2).integers(8, 64, 300)]
                       ).astype(np.int32)
_sk_i = np.random.default_rng(3).integers(0, 48, 1000).astype(np.int32)
_sk_v = np.random.default_rng(4).normal(size=1000).astype(np.float32)
with _SKT.scope(True):
    _sk_m = _SKMF.MFSGD(64, 48, _SKMF.MFSGDConfig(
        rank=4, algo="dense", u_tile=8, i_tile=8, entry_cap=32), mesh, 0)
    _sk_m.set_ratings(_sk_u, _sk_i, _sk_v)
    _sk_m.train_epoch()
    with _FR.budget(dispatches=1, readbacks=1, tag="drive.skew.mf"):
        _sk_m.train_epochs(2)
    np.testing.assert_allclose(
        _SK.ledger.summary()["mfsgd.epochs"]["work"],
        np.bincount(_sk_u // _sk_m.u_own, minlength=nw))

# (d) imbalance model + roofline composition, hand math
with _SKT.scope(True):
    _SK.record_execution("p", [10, 2, 2, 2], unit="u", wall_s=2.0)
    _sk_p = _SK.ledger.summary()["p"]
    assert (_sk_p["max_mean_ratio"], _sk_p["wasted_frac"]) == (2.5, 0.6)
    assert abs(_sk_p["wasted_chip_s"] - 4.8) < 1e-9  # 4 chips x 2 s x 0.6
    _sk_pct = _SK.wasted_pct_of_peak(
        "lda", {"n_topics": 100, "tokens_per_sec_per_chip": 1e9}, "p")
    # 1e9 tok/s x 1400 flop/tok / 197e12 peak = 0.7107 %-of-peak, 60% lost
    assert abs(_sk_pct - round(100 * 1e9 * 1400 / 197e12 * 0.6, 3)) < 2e-3
    # (e) rebalance loop on REAL files: measured loads -> whole-file plan
    with _sk_tmp.TemporaryDirectory() as _sk_dir:
        _sk_paths = []
        for _sk_j, _sk_kb in enumerate((48, 40, 2, 1, 1, 1)):
            _sk_p2 = os.path.join(_sk_dir, f"f{_sk_j}.csv")
            open(_sk_p2, "wb").write(b"x" * (_sk_kb * 1024))
            _sk_paths.append(_sk_p2)
        _sk_sp = _sk_splits(_sk_paths, 2)  # records units + byte loads
        _sk_plan = _SK.suggest_rebalance("fileformat.multi_file_splits")
        assert _sk_plan["ratio_after"] <= _sk_plan["ratio_before"]
        _sk_new = _SKsched.apply_rebalance(_sk_sp, _sk_plan)
        _sk_loads = [sum(os.path.getsize(p) for p in s) for s in _sk_new]
        np.testing.assert_allclose(_sk_loads, _sk_plan["work_after"])

# (f) zero-cost off: ledger untouched, LDA chain identical on/off
with _SKT.scope(False):
    _SK.record_execution("off", [1, 2], unit="u")
    assert _SK.ledger.summary() == {}
print("skew: ingest==execution==bincount, budgets hold, waste priced, "
      "roofline composed, file rebalance loop closed, invariant 5 loud")
print(f"DRIVE OK round-23 ({mode})")

# ===========================================================================
# Round 24 — harplint: static relay-burner analysis (PR 5).
# Drives the linter as a CONSUMER: seeded violations in every layer must
# exit non-zero, the repo at HEAD must be clean, the rerouted table verbs
# must match numpy AND become visible to the CommLedger (the point of
# HL001), and the flash_attention is_finite fix must keep numerics.
# ===========================================================================
import json as _hl_json
import tempfile as _hl_tmp

from harp_tpu.analysis import cli as _HLC
from harp_tpu.analysis import rule_ids as _hl_rule_ids
from harp_tpu.analysis.astlints import lint_source as _hl_lint
from harp_tpu.analysis.jaxpr_checks import find_scan_copy_traps as _hl_scan
from harp_tpu.analysis.mosaic_audit import (audit_registry as _hl_audit,
                                            check_kernel_jaxpr as _hl_kchk)
from jax import lax as _hl_lax

# (a) one seeded Layer-1 violation per rule id, via the public lint_source
for _hl_src, _hl_want in (
        ("from jax import lax\ndef f(x): return lax.psum(x, 'w')\n",
         "HL001"),
        ("import jax\ndef f(s): return jax.random.PRNGKey(s)\n", "HL002"),
        ("import jax.numpy as jnp, numpy as np\n"
         "def f(x): return jnp.asarray(np.asarray(x))\n", "HL003"),
        ("import jax\ndef f():\n    s = jax.jit(lambda x: x)\n"
         "    return s\n", "HL004"),
        ('def f():\n    """Hits 9.9M tok/s."""\n', "HL005")):
    _hl_got = {v.rule for v in _hl_lint("harp_tpu/models/fake.py", _hl_src)}
    assert _hl_got == {_hl_want}, (_hl_want, _hl_got)

# (b) the pre-fix LDA copy trap flags; the tile-local fixed form is clean
def _hl_bad(tbl, i, u):
    def body(t, x):
        vals = jnp.take(t, x[0], axis=0)
        return _hl_lax.dynamic_update_slice(t, x[1], (x[0][0], 0)), vals.sum()
    return _hl_lax.scan(body, tbl, (i, u))

def _hl_good(tbl, i, u):
    def body(t, x):
        tile = _hl_lax.dynamic_slice(t, (0, 0), (4, t.shape[1]))
        vals = jnp.take(tile, x[0] % 4, axis=0)
        return _hl_lax.dynamic_update_slice(t, x[1], (x[0][0], 0)), vals.sum()
    return _hl_lax.scan(body, tbl, (i, u))

_hl_args = (jnp.zeros((16, 8)), jnp.zeros((3, 2), jnp.int32),
            jnp.zeros((3, 1, 8)))
assert [v.rule for v in _hl_scan(
    jax.jit(_hl_bad).trace(*_hl_args).jaxpr, "d")] == ["HL101"]
assert _hl_scan(jax.jit(_hl_good).trace(*_hl_args).jaxpr, "d") == []

# (c) Mosaic: the 2026-08-01 3-seed-word silicon failure flags from the
# jaxpr alone (no hardware), and the whole ops/ registry audits clean —
# including flash_attention, whose is_finite this audit caught
from jax.experimental import pallas as _hl_pl
from jax.experimental.pallas import tpu as _hl_pltpu

def _hl_seed3(seed):
    def kern(seed_ref, o_ref):
        _hl_pltpu.prng_seed(seed_ref[0], seed_ref[1], seed_ref[2])
        bits = _hl_pltpu.prng_random_bits(o_ref.shape)
        o_ref[...] = _hl_lax.shift_right_logical(bits, 8).astype(jnp.float32)
    return _hl_pl.pallas_call(
        kern, in_specs=[_hl_pl.BlockSpec(memory_space=_hl_pltpu.SMEM)],
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32))(seed)

_hl_vs = _hl_kchk(jax.jit(_hl_seed3).trace(jnp.zeros(3, jnp.int32)).jaxpr,
                  "toy")
assert "HL202" in {v.rule for v in _hl_vs}
assert _hl_audit() == []

# flash_attention numerics after the > -inf fix: == reference, causal+window
from harp_tpu.ops.flash_attention import (flash_attention as _hl_fa,
                                          reference_attention as _hl_ref)
_hl_rng = np.random.default_rng(24)
_hl_q, _hl_k, _hl_v = (jnp.asarray(
    _hl_rng.normal(size=(2, 64, 16)).astype(np.float32)) for _ in range(3))
for _hl_kw in ({"causal": True}, {"causal": True, "window": 8}):
    np.testing.assert_allclose(
        np.asarray(_hl_fa(_hl_q, _hl_k, _hl_v, block_q=32, block_k=32,
                          interpret=True, **_hl_kw)),
        np.asarray(_hl_ref(_hl_q, _hl_k, _hl_v, **_hl_kw)),
        rtol=2e-5, atol=2e-5)

# (d) rerouted table verbs: == numpy golden AND now on the CommLedger
from harp_tpu import table as _hl_table
from harp_tpu.utils import telemetry as _HLT

_hl_shard = _hl_rng.normal(size=(16, 4)).astype(np.float32)   # 2 rows/worker
_hl_ids = np.array([0, 5, 11, 3], np.int32)
_hl_deltas = _hl_rng.normal(size=(4, 4)).astype(np.float32)
with _HLT.scope(True):
    _hl_pull = jax.jit(mesh.shard_map(
        lambda g: _hl_table.pull_rows(g, jnp.asarray(_hl_ids)),
        in_specs=(mesh.spec(0),), out_specs=mesh.spec(0)))
    _hl_got = np.asarray(_hl_pull(mesh.shard_array(_hl_shard, 0)))
    np.testing.assert_allclose(_hl_got[:4], _hl_shard[_hl_ids], rtol=1e-6)
    _hl_push = jax.jit(mesh.shard_map(
        lambda g, d: _hl_table.push_rows(g, jnp.asarray(_hl_ids), d),
        in_specs=(mesh.spec(0), None), out_specs=mesh.spec(0)))
    _hl_after = np.asarray(_hl_push(mesh.shard_array(_hl_shard, 0),
                                    jax.device_put(_hl_deltas)))
    _hl_gold = _hl_shard.copy()
    np.add.at(_hl_gold, _hl_ids, _hl_deltas)   # every worker pushes once...
    _hl_gold = _hl_shard + (_hl_gold - _hl_shard) * mesh.num_workers
    np.testing.assert_allclose(_hl_after, _hl_gold, rtol=1e-5)
    _hl_verbs = {s["verb"] for t in _HLT.ledger.summary().values()
                 for s in t["sites"]}
    # HL001's whole point: the row exchange is on the ledger (PR 11:
    # pull_rows's replication rides the reshard shim)
    assert {"reshard", "push"} <= _hl_verbs, _hl_verbs

# (e) the lint CLI at HEAD: exit 0, clean, stamped line that satisfies
# check_jsonl invariant 6; a seeded file exits 1
import io as _hl_io
import contextlib as _hl_ctx
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import check_jsonl as _hl_cj

_hl_buf = _hl_io.StringIO()
with _hl_ctx.redirect_stdout(_hl_buf):
    _hl_rc = _HLC.main(["--json"])
_hl_row = _hl_json.loads(_hl_buf.getvalue().strip().splitlines()[-1])
assert _hl_rc == 0 and _hl_row["clean"] is True
assert _hl_cj._check_lint_row("drive", 1, _hl_row) == []
assert tuple(_hl_rule_ids()) == _hl_cj.KNOWN_LINT_RULES
with _hl_tmp.TemporaryDirectory() as _hl_dir:
    _hl_bad_py = os.path.join(_hl_dir, "bad.py")
    open(_hl_bad_py, "w").write(
        "import jax\ndef f(s): return jax.random.PRNGKey(s)\n")
    with _hl_ctx.redirect_stdout(_hl_io.StringIO()):
        assert _HLC.main([_hl_bad_py, "--json"]) == 1

print("harplint: 5 AST rules seeded+tripped, copy trap pinned both ways, "
      "3-word prng_seed flagged sans hardware, registry+repo clean at "
      "HEAD, rerouted pull/push == numpy and on the ledger, CLI exit "
      "codes + invariant 6 round-trip")
print(f"DRIVE OK round-24 ({mode})")

# ---------------------------------------------------------------------------
# Round 25 — harp serve: persistent-mesh inference (PR 6)
# Drives the PUBLIC serve surface end to end: checkpoint →
# restore_latest → Server startup (AOT cache cold, then warm with ZERO
# compiles after jax.clear_caches), ladder batching, the steady-state
# budget's exact dispatch/readback accounting, kmeans/mfsgd answers vs
# straight-line numpy, the stdio JSONL protocol, and a bench row through
# check_jsonl invariant 7.
# ---------------------------------------------------------------------------
import tempfile as _sv_tmp
import io as _sv_io
import json as _sv_json

from harp_tpu.serve import Server as _SvServer
from harp_tpu.serve.bench import benchmark as _sv_bench
from harp_tpu.utils import flightrec as _sv_fr, telemetry as _sv_tel
from harp_tpu.utils.checkpoint import CheckpointManager as _SvCkpt
from harp_tpu.utils.metrics import benchmark_json as _sv_bjson
import check_jsonl as _sv_cj

_sv_rng = np.random.default_rng(25)
with _sv_tmp.TemporaryDirectory() as _sv_dir:
    # checkpoint → newest step wins through restore_latest
    _sv_mgr = _SvCkpt(os.path.join(_sv_dir, "ckpt"))
    _sv_c_old = _sv_rng.normal(size=(6, 12)).astype(np.float32)
    _sv_c = _sv_rng.normal(size=(6, 12)).astype(np.float32)
    _sv_mgr.save(1, {"centroids": _sv_c_old})
    _sv_mgr.save(4, {"centroids": _sv_c})
    assert _sv_mgr.restore_latest()[0] == 4

    _sv_cache = os.path.join(_sv_dir, "aot")
    with _sv_tel.scope(True):
        _sv_srv = _SvServer("kmeans", ckpt=os.path.join(_sv_dir, "ckpt"),
                            mesh=mesh, ladder=(1, 8, 32),
                            cache_dir=_sv_cache)
        _sv_cold = _sv_srv.startup()
        assert _sv_cold["cache_misses"] == 3 and _sv_cold["compiles"] >= 3
        # steady state: 70 rows over a (1,8,32) ladder → 32+32+8-pad
        _sv_x = _sv_rng.normal(size=(70, 12)).astype(np.float32)
        _sv_base = _sv_fr.snapshot()
        (_sv_resp,) = _sv_srv.process([{"id": 0, "x": _sv_x.tolist()}])
        _sv_spent = _sv_fr.delta_since(_sv_base)
        assert _sv_srv.steady.batches == 3 and _sv_srv.steady.violations == 0
        assert (_sv_spent["compiles"], _sv_spent["dispatches"],
                _sv_spent["readbacks"]) == (0, 3, 3)
        _sv_ref = np.argmin(
            ((_sv_x[:, None, :] - _sv_c[None]) ** 2).sum(-1), axis=1)
        assert _sv_resp["result"] == _sv_ref.tolist()

    # warm restart: in-memory jit caches dropped, disk cache must serve
    jax.clear_caches()
    with _sv_tel.scope(True):
        _sv_srv2 = _SvServer("kmeans", ckpt=os.path.join(_sv_dir, "ckpt"),
                             mesh=mesh, ladder=(1, 8, 32),
                             cache_dir=_sv_cache)
        _sv_warm = _sv_srv2.startup()
        assert _sv_warm == {"rungs": [1, 8, 32], "cache_hits": 3,
                            "cache_misses": 0, "compiles": 0}, _sv_warm
        # stdio protocol round trip on the warm server
        _sv_in = _sv_io.StringIO(
            _sv_json.dumps({"id": "q", "x": _sv_x[:3].tolist()}) + "\n"
            + _sv_json.dumps({"cmd": "quit"}) + "\n")
        _sv_out = _sv_io.StringIO()
        _sv_srv2.serve_stdio(_sv_in, _sv_out)
        (_sv_line,) = _sv_out.getvalue().splitlines()
        assert _sv_json.loads(_sv_line)["result"] == _sv_ref[:3].tolist()
        assert _sv_fr.compile_watch.count == 0  # still zero post-serve

# mfsgd top-k: sharded H + pull merge == numpy argsort (49 items ⇒ the
# worker padding must not leak phantom items)
from harp_tpu.serve.engines import ENGINES as _SvEngines
_sv_st = _SvEngines["mfsgd"].synthetic_state(_sv_rng, n_users=40,
                                             n_items=49, rank=8)
with _sv_tmp.TemporaryDirectory() as _sv_dir2:
    _sv_m = _SvServer("mfsgd", state=_sv_st, mesh=mesh, ladder=(1, 8),
                      cache_dir=_sv_dir2, engine_opts={"topk": 5})
    _sv_m.startup()
    (_sv_r,) = _sv_m.process([{"id": 1, "users": [0, 17, 39]}])
    for _sv_row, _sv_u in zip(_sv_r["result"], [0, 17, 39]):
        _sv_sc = _sv_st["W"][_sv_u] @ _sv_st["H"].T
        assert _sv_row["items"] == np.argsort(-_sv_sc)[:5].tolist()

# bench row → provenance stamp → invariant 7 clean
_sv_res = _sv_bench(app="kmeans", n_requests=12, rows_per_request=1,
                    burst=4, ladder=(1, 8), mesh=mesh,
                    state_shape={"k": 4, "d": 8})
assert _sv_res["steady_compiles"] == 0 and _sv_res["qps"] > 0
_sv_rowd = _sv_json.loads(_sv_bjson("serve_kmeans", _sv_res))
assert _sv_cj._check_serve_row("drive", 1, _sv_rowd) == []
# and the checker is LOUD on a row that compiled in steady state
assert _sv_cj._check_serve_row("drive", 1,
                               {**_sv_rowd, "steady_compiles": 2})

print("serve: restore_latest → cold AOT cache → warm restart 0 compiles, "
      "steady batches exact (0 compiles / 1 dispatch / 1 readback each), "
      "kmeans+sharded-topk == numpy, stdio round trip, bench row through "
      "invariant 7 both ways")
print(f"DRIVE OK round-25 ({mode})")

# ---------------------------------------------------------------------------
# Round 26 — serve review fixes (PR 6 follow-up): option-keyed AOT cache,
# any-exception cache fallback, parallel sources in the fingerprint, and
# raw-fd burst reads that see past TextIOWrapper buffering.
# ---------------------------------------------------------------------------
import hashlib as _rv_hash
import warnings as _rv_warn

from harp_tpu.serve.cache import code_fingerprint as _rv_fp

# (a) engine options are program constants, not avals: a restart with a
# different --topk must MISS and answer with the new k (numpy-checked)
with _sv_tmp.TemporaryDirectory() as _rv_dir:
    _rv_st = _SvEngines["mfsgd"].synthetic_state(_sv_rng, n_users=40,
                                                 n_items=49, rank=8)
    _rv_a = _SvServer("mfsgd", state=_rv_st, mesh=mesh, ladder=(4,),
                      cache_dir=_rv_dir, engine_opts={"topk": 5})
    _rv_a.startup()
    _rv_b = _SvServer("mfsgd", state=_rv_st, mesh=mesh, ladder=(4,),
                      cache_dir=_rv_dir, engine_opts={"topk": 7})
    _rv_info = _rv_b.startup()
    assert (_rv_info["cache_hits"], _rv_info["cache_misses"]) == (0, 1)
    (_rv_r7,) = _rv_b.process([{"id": 0, "users": [3, 21]}])
    for _rv_row, _rv_u in zip(_rv_r7["result"], [3, 21]):
        _rv_sc = _rv_st["W"][_rv_u] @ _rv_st["H"].T
        assert _rv_row["items"] == np.argsort(-_rv_sc)[:7].tolist()
    _rv_c = _SvServer("mfsgd", state=_rv_st, mesh=mesh, ladder=(4,),
                      cache_dir=_rv_dir, engine_opts={"topk": 5})
    assert _rv_c.startup()["cache_hits"] == 1  # tag keys, doesn't disable

    # (b) ANY deserialize exception degrades to a fresh compile
    from jax.experimental import serialize_executable as _rv_se
    _rv_orig = _rv_se.deserialize_and_load

    def _rv_boom(*a, **k):
        raise RuntimeError("xla rejected the payload")

    _rv_se.deserialize_and_load = _rv_boom
    try:
        with _rv_warn.catch_warnings(record=True) as _rv_caught:
            _rv_warn.simplefilter("always")
            _rv_d = _SvServer("mfsgd", state=_rv_st, mesh=mesh,
                              ladder=(4,), cache_dir=_rv_dir,
                              engine_opts={"topk": 5})
            _rv_dinfo = _rv_d.startup()
    finally:
        _rv_se.deserialize_and_load = _rv_orig
    assert _rv_dinfo["cache_misses"] == 1
    assert any("unreadable" in str(w.message) for w in _rv_caught)
print("serve cache: --topk restart misses + answers new k, same-opts "
      "hits, arbitrary deserialize error recompiles")

# (c) the fingerprint hashes the parallel layer too (shard_map +
# collective verbs compile into the mfsgd program) — replicate the sha1
# by hand to prove which sources participate
import harp_tpu.parallel.collective as _rv_coll
import harp_tpu.parallel.mesh as _rv_mesh
import harp_tpu.serve as _rv_pkg

_rv_h = _rv_hash.sha1()
_rv_pdir = os.path.dirname(os.path.abspath(_rv_pkg.__file__))
_rv_paths = [os.path.join(_rv_pdir, f) for f in sorted(os.listdir(_rv_pdir))
             if f.endswith(".py")]
_rv_paths += [_rv_coll.__file__, _rv_mesh.__file__]
for _rv_p in _rv_paths:
    _rv_h.update(open(_rv_p, "rb").read())
assert _rv_fp() == _rv_h.hexdigest()[:16]
print("serve fingerprint covers serve/* + parallel/collective + mesh")

# (d) burst reader: lines a TextIOWrapper would hold internally (fd not
# selectable) land in the CURRENT burst; partial lines carry over
from harp_tpu.serve.server import _BurstReader as _RvBurst

_rv_r, _rv_w = os.pipe()
_rv_stdin = os.fdopen(_rv_r, "r")
try:
    os.write(_rv_w, b'{"id": 1}\n{"id": 2}\n{"id": 3')
    _rv_reader = _RvBurst(_rv_stdin)
    assert [_sv_json.loads(x)["id"]
            for x in _rv_reader.read_burst()] == [1, 2]
    os.write(_rv_w, b'}\n')
    assert [_sv_json.loads(x)["id"]
            for x in _rv_reader.read_burst()] == [3]
    os.close(_rv_w)
    assert _rv_reader.read_burst() == []
finally:
    _rv_stdin.close()
print("burst reader: queued lines in-burst, partial line carries, EOF")
print(f"DRIVE OK round-26 ({mode})")

# ---------------------------------------------------------------------------
# Round 27 — continuous serving (PR 7): the asyncio TCP front end over a
# REAL socket (concurrent connections, per-connection order, interleaved
# clients, stats/quit/shutdown), the admit-while-in-flight scheduler's
# exact steady accounting, and the sustained-load A/B row through the
# extended invariant 7 — all without a relay.
# ---------------------------------------------------------------------------
import socket as _ct_socket
import threading as _ct_threading

from harp_tpu.serve.bench import benchmark_sustained as _ct_sus
from harp_tpu.serve.transport import TCPFrontEnd as _CtFE

_ct_rng = np.random.default_rng(27)
_ct_state = _SvEngines["kmeans"].synthetic_state(_ct_rng, k=8, d=16)
with _sv_tmp.TemporaryDirectory() as _ct_dir:
    _ct_srv = _SvServer("kmeans", state=_ct_state, mesh=mesh,
                        ladder=(1, 8, 32), cache_dir=_ct_dir,
                        budget_action="warn")
    _ct_srv.startup()
    _ct_fe = _CtFE(_ct_srv, port=0,
                   max_queue_delay_s=0.002).start_in_thread()
    _ct_cent = _ct_state["centroids"]

    def _ct_client(nm, out):
        s = _ct_socket.create_connection(("127.0.0.1", _ct_fe.port),
                                         timeout=120)
        f = s.makefile("rw")
        xs = [_ct_rng.normal(size=(1 + i % 4, 16)).astype(np.float32)
              for i in range(16)]
        for i, x in enumerate(xs):  # all 16 in flight at once
            f.write(_sv_json.dumps({"id": f"{nm}-{i}",
                                    "x": x.tolist()}) + "\n")
        f.flush()
        got = [_sv_json.loads(f.readline()) for _ in xs]
        f.write(_sv_json.dumps({"cmd": "stats"}) + "\n")
        f.flush()
        st = _sv_json.loads(f.readline())
        assert st["kind"] == "serve_stats" and "continuous" in st
        f.write(_sv_json.dumps({"cmd": "quit"}) + "\n")
        f.flush()
        assert f.readline() == ""  # server closed after the drain
        s.close()
        out[nm] = (xs, got)

    _ct_out = {}
    _ct_threads = [_ct_threading.Thread(target=_ct_client,
                                        args=(nm, _ct_out))
                   for nm in ("c1", "c2", "c3")]
    for _t in _ct_threads:
        _t.start()
    for _t in _ct_threads:
        _t.join(240)
    assert set(_ct_out) == {"c1", "c2", "c3"}
    for _nm, (_xs, _got) in _ct_out.items():
        assert [r["id"] for r in _got] == [f"{_nm}-{i}"
                                           for i in range(16)]
        for _r, _x in zip(_got, _xs):  # routed to the right conn, exact
            _ref = np.argmin(((_x[:, None, :] - _ct_cent[None]) ** 2
                              ).sum(-1), 1)
            assert _r["result"] == _ref.tolist()
    # runner totals are EXACT: one dispatch + one readback per batch
    _ct_fe.runner.verify_exact()
    _ct_fe.shutdown()
    _ct_fe.join(120)
print("tcp front end: 3 interleaved clients x 16 requests routed + "
      "ordered per connection, stats/quit/shutdown, exact accounting")

# sustained A/B: one seeded trace, both planes, extended invariant 7
_ct_res = _ct_sus(app="kmeans", n_requests=96, rows_per_request=1,
                  burst_admit=8, ladder=(1, 8, 32), mesh=mesh,
                  state_shape={"k": 8, "d": 16})
assert _ct_res["offered_qps"] >= _ct_res["achieved_qps"] > 0
assert _ct_res["steady_compiles"] == 0
assert _ct_res["steady_dispatches"] == _ct_res["batches"] == \
    _ct_res["steady_readbacks"]
_ct_row = _sv_json.loads(_sv_bjson("serve_kmeans_sustained", _ct_res))
assert _sv_cj._check_serve_row("drive", 1, _ct_row) == []
assert _sv_cj._check_serve_row(  # forged: queue evidence stripped
    "drive", 1, {k: v for k, v in _ct_row.items()
                 if k != "qdepth_p95"})
assert _sv_cj._check_serve_row(  # forged: achieved above offered
    "drive", 1, {**_ct_row, "achieved_qps": _ct_row["offered_qps"] + 1})
print(f"sustained A/B: {_ct_res['qps_ratio_vs_burst']}x vs burst at "
      f"p99 {_ct_res['p99_ms']:.1f} vs {_ct_res['burst_p99_ms']:.1f} ms, "
      "row passes extended invariant 7, forgeries loud")
print(f"DRIVE OK round-27 ({mode})")

# ---------------------------------------------------------------------------
# Round 28 — prefetch-pipelined ingest (PR 8): the bench_ingest --smoke A/B
# through a real subprocess (the new staged chain vs the pre-PR serial loop
# on one page-cache-warm file), depth bit-exactness through the public
# fit_streaming surface, and the kind:"ingest" row through invariant 8
# both ways.
# ---------------------------------------------------------------------------
import subprocess as _ig_sp

_ig_run = _ig_sp.run(
    [sys.executable, "scripts/bench_ingest.py", "--smoke",
     "--platform", "cpu"],
    capture_output=True, text=True, timeout=600,
    cwd=_r4os.path.dirname(_r4os.path.dirname(_r4os.path.abspath(__file__))))
assert _ig_run.returncode == 0, _ig_run.stderr[-800:]
_ig_row = _r5json.loads(_ig_run.stdout.strip().splitlines()[-1])
assert _ig_row["kind"] == "ingest" and _ig_row["mode"] == "ab"
assert _ig_row["host_gb_per_sec"] > 0 and _ig_row["points_per_sec"] > 0
assert 0.0 <= _ig_row["overlap_efficiency"] <= 1.0
# a loaded driver box adds scheduler noise, so this smoke pass gates the
# A/B DIRECTION only; the graded >= 1.25x number is the committed
# BENCH_local kmeans_ingest_ab_smoke row (2026-08-04: 1.7-1.9x)
assert _ig_row["pipeline_speedup"] > 1.0, _ig_row["pipeline_speedup"]
assert _ig_row["host_gb_per_sec_serial"] > 0
assert _sv_cj._check_ingest_row("drive", 1, _ig_row) == []
assert _sv_cj._check_ingest_row(  # forged: impossible overlap score
    "drive", 1, {**_ig_row, "overlap_efficiency": 1.7})
assert _sv_cj._check_ingest_row(  # forged: stamp stripped
    "drive", 1, {k: v for k, v in _ig_row.items() if k != "backend"})
assert _sv_cj._check_ingest_row(  # forged: the loop never ran
    "drive", 1, {**_ig_row, "points_per_sec": 0})

# depth is invisible to the math: legacy chain (0) == pipelined (2)
_ig_pts = rng.normal(size=(2000, 12)).astype(np.float32)
_ig_outs = [fit_streaming(_ig_pts, k=5, iters=3, chunk_points=512,
                          mesh=mesh, seed=4, prefetch=_p)
            for _p in (0, 2)]
np.testing.assert_array_equal(_ig_outs[0][0], _ig_outs[1][0])
assert _ig_outs[0][1] == _ig_outs[1][1]
print(f"ingest A/B: pipelined {_ig_row['host_gb_per_sec']:.2f} GB/s = "
      f"{_ig_row['pipeline_speedup']:.2f}x serial "
      f"{_ig_row['host_gb_per_sec_serial']:.2f} GB/s, overlap "
      f"{_ig_row['overlap_efficiency']:.2f}, depths bit-exact, "
      "row through invariant 8 both ways")
print(f"DRIVE OK round-28 ({mode})")

# --- round 29: harplint Layer 4 — CommGraph static communication audit -----
# The static collective schedule extractor cross-checked against the
# CommLedger (HL301/HL302) and numpy byte math, the hoistable-collective
# detector's per-leaf granularity (HL304), the use-after-donate audit
# over the REAL serve ContinuousRunner depth-2 pipeline (HL303, clean)
# and a sabotaged twin (flags), the full registry sweep, and the CLI
# round trip: byte_sheets through check_jsonl invariant 6 both ways.
# ---------------------------------------------------------------------------
import contextlib as _cg_ctx
import json as _cg_json
import subprocess as _cg_sp
import tempfile as _cg_tmp

from jax import lax as _cg_lax
from jax.sharding import PartitionSpec as _cg_P

import harp_tpu.utils.telemetry as _cg_T
from harp_tpu.analysis import cli as _cg_cli
from harp_tpu.analysis import commgraph as _cg
from harp_tpu.analysis.drivers import DRIVERS as _cg_DRIVERS
from harp_tpu.analysis.drivers import PROTOCOLS as _cg_PROTOCOLS
from harp_tpu.utils import flightrec as _cg_fr

_cg_repo = _r4os.path.dirname(_r4os.path.dirname(_r4os.path.abspath(__file__)))

# (a) hand-built iterative program: allreduce of a two-leaf tree inside
# a 3-iter fori.  Static sheet == numpy byte math == ledger payload,
# amplified by the trip count; both leaves depend on the carry -> clean.
_cg_rows, _cg_d, _cg_iters = 2 * nw, 8, 3
_cg_r = _cg_rows // nw  # per-shard rows
_cg_x = jax.ShapeDtypeStruct((_cg_rows, _cg_d), jnp.float32,
                             sharding=mesh.sharding(mesh.spec(0)))


def _cg_clean_epoch(x):
    def body(i, c):
        s, n = C.allreduce((x * c.sum(), x[:, 0] + c[0, 0]))
        return c + s[:1, :1] + n.sum()

    return _cg_lax.fori_loop(0, _cg_iters, body,
                             jnp.zeros((1, 1), jnp.float32))


_cg_fn = jax.jit(mesh.shard_map(_cg_clean_epoch, in_specs=(mesh.spec(0),),
                                out_specs=_cg_P(), check_vma=False))
_cg_vs, _cg_g = _cg.analyze_program("drive.clean", _cg_fn, (_cg_x,))
assert _cg_vs == [], [v.format() for v in _cg_vs]
_cg_expect = _cg_r * _cg_d * 4 + _cg_r * 4  # leaf bytes, per shard
assert _cg_g.bytes_per_trace() == _cg_expect, _cg_g.sheet()
assert _cg_g.amplified_bytes() == _cg_expect * _cg_iters
(_cg_site,) = _cg_g.sites
assert _cg_site.verb == "allreduce" and _cg_site.amplification == _cg_iters
_cg_ledger = sum(r["payload_bytes"] for recs in _cg_g.ledger_sites.values()
                 for r in recs)
assert _cg_ledger == _cg_expect  # static == ledger, to the byte

# (b) per-leaf hoist granularity: make the SECOND leaf loop-invariant
# (drops the carry term) -> exactly one HL304, naming the psum site
def _cg_hoist_epoch(x):
    def body(i, c):
        s, n = C.allreduce((x * c.sum(), x[:, 0]))
        return c + s[:1, :1] + n.sum()

    return _cg_lax.fori_loop(0, _cg_iters, body,
                             jnp.zeros((1, 1), jnp.float32))


_cg_fn = jax.jit(mesh.shard_map(_cg_hoist_epoch, in_specs=(mesh.spec(0),),
                                out_specs=_cg_P(), check_vma=False))
_cg_vs, _ = _cg.analyze_program("drive.hoist", _cg_fn, (_cg_x,))
assert [v.rule for v in _cg_vs] == ["HL304"], [v.format() for v in _cg_vs]
assert "hoist" in _cg_vs[0].message

# (c) untracked wire: the raw-lax twin leaves no ledger record -> HL301
def _cg_raw(x):
    return _cg_lax.psum(x, "workers")


_cg_fn = jax.jit(mesh.shard_map(_cg_raw, in_specs=(mesh.spec(0),),
                                out_specs=_cg_P()))
_cg_vs, _ = _cg.analyze_program("drive.raw", _cg_fn, (_cg_x,))
assert [v.rule for v in _cg_vs] == ["HL301"]

# (d) lying byte sheet: record a scalar, psum the full array (one source
# line, so both sides key the same call site) -> HL302
def _cg_lying(x):
    return _cg_T.record_comm("allreduce", x[0, 0], axis="workers") or _cg_lax.psum(x, "workers")  # noqa: E501


_cg_fn = jax.jit(mesh.shard_map(_cg_lying, in_specs=(mesh.spec(0),),
                                out_specs=_cg_P()))
_cg_vs, _ = _cg.analyze_program("drive.lying", _cg_fn, (_cg_x,))
assert [v.rule for v in _cg_vs] == ["HL302"]

# (e) the full registry sweeps clean, covers >= 10 programs, and the
# serve engines' donated batch buffer is visible in the aliasing info
assert len(_cg_DRIVERS) >= 10
for _cg_name, _cg_build in sorted(_cg_DRIVERS.items()):
    _cg_f, _cg_a = _cg_build()
    _cg_vs, _cg_g = _cg.analyze_program(_cg_name, _cg_f, _cg_a)
    assert _cg_vs == [], (_cg_name, [v.format() for v in _cg_vs])
    if _cg_name.startswith("serve."):
        assert _cg_g.donated_args, _cg_name

# (f) HL303: the REAL ContinuousRunner depth-2 protocol is clean; a
# sabotaged re-read + re-dispatch of a donated buffer flags twice (the
# audit records BEFORE jax's own deletion error, which only this CPU
# path even raises — silicon silently reads garbage, hence the lint)
_cg_vs = _cg.audit_protocol("serve.kmeans_continuous",
                            _cg_PROTOCOLS["serve.kmeans_continuous"]())
assert _cg_vs == [], [v.format() for v in _cg_vs]
_cg_audit = _cg.DonationAudit("protocol:drive-sabotage")
with _cg_audit:
    _cg_exe = _cg_audit.wrap(jax.jit(lambda s, b: s + b,
                                     donate_argnums=(1,)), (1,), "toy")
    _cg_s = jax.device_put(np.ones((4,), np.float32))
    _cg_b = jax.device_put(np.ones((4,), np.float32))
    _cg_exe(_cg_s, _cg_b)
    with _cg_ctx.suppress(RuntimeError):
        _cg_fr.readback(_cg_b)
    with _cg_ctx.suppress(RuntimeError, ValueError):
        _cg_exe(_cg_s, _cg_b)
assert [v.rule for v in _cg_audit.violations] == ["HL303", "HL303"]

# (g) the CLI round trip: one full four-layer run prints a clean row
# whose byte_sheets block carries every registered program, kmeans.fit
# matching the hand-computed sheet exactly; the row passes invariant 6
# and forged sheets fail it
_cg_run = _cg_sp.run([sys.executable, "-m", "harp_tpu", "lint", "--json"],
                     capture_output=True, text=True, timeout=900,
                     cwd=_cg_repo)
assert _cg_run.returncode == 0, _cg_run.stdout[-800:] + _cg_run.stderr[-800:]
_cg_row = _cg_json.loads(_cg_run.stdout.strip().splitlines()[-1])
assert _cg_row["clean"] is True and _cg_row["stale_allowlist"] == 0
assert set(_cg_row["byte_sheets"]) == set(_cg_DRIVERS)
_cg_km = _cg_row["byte_sheets"]["kmeans.fit"]
assert _cg_km["bytes_per_trace"] == 8 * 32 * 4 + 8 * 4 + 4
assert _cg_km["amplified_bytes"] == 2 * _cg_km["bytes_per_trace"]
assert _sv_cj._check_lint_row("drive", 1, _cg_row) == []
assert _sv_cj._check_lint_row(  # forged: unregistered program name
    "drive", 1, {**_cg_row, "byte_sheets": {"madeup.prog": _cg_km}})
assert _sv_cj._check_lint_row(  # forged: negative byte count
    "drive", 1, {**_cg_row, "byte_sheets": {
        "kmeans.fit": {**_cg_km, "bytes_per_trace": -1}}})

# (h) stale allowlist entries hard-fail (AST layer is enough to prove
# the exit-code contract), and --changed draws from the sweep set
with _cg_tmp.TemporaryDirectory() as _cg_dir:
    _cg_toml = _r4os.path.join(_cg_dir, "stale.toml")
    with open(_r4os.path.join(_cg_repo, "harp_tpu", "analysis",
                              "allowlist.toml")) as _cg_fh:
        _cg_committed = _cg_fh.read()
    with open(_cg_toml, "w") as _cg_fh:
        _cg_fh.write(_cg_committed + '\n[[allow]]\nrule = "HL002"\n'
                     'path = "harp_tpu/never.py"\nreason = "stale"\n')
    _cg_run = _cg_sp.run(
        [sys.executable, "-m", "harp_tpu", "lint", "--json",
         "--layer", "ast", "--allowlist", _cg_toml],
        capture_output=True, text=True, timeout=300, cwd=_cg_repo)
    assert _cg_run.returncode == 1, _cg_run.stdout[-400:]
    _cg_row = _cg_json.loads(_cg_run.stdout.strip().splitlines()[-1])
    assert _cg_row["stale_allowlist"] == 1 and _cg_row["clean"] is True
from harp_tpu.analysis.astlints import iter_python_files as _cg_iter

assert set(_cg_cli._changed_paths(_cg_repo)) <= set(_cg_iter(_cg_repo))

print(f"commgraph: clean epoch sheet {_cg_expect} B/shard x{_cg_iters} "
      f"== ledger; HL301/302/303/304 all fire on their fixtures; "
      f"{len(_cg_DRIVERS)} driver sheets clean through the CLI + "
      "invariant 6 both ways")
print(f"DRIVE OK round-29 ({mode})")

# 30. the fault plane (PR 10): deterministic chaos + kill/resume +
# degraded serving, end to end over the public surface
import tempfile as _fp_tmp

from harp_tpu.models import mfsgd as _fp_MF
from harp_tpu.serve.bench import benchmark_sustained as _fp_sustained
from harp_tpu.utils.checkpoint import CheckpointManager as _fp_CM
from harp_tpu.utils.fault import FaultInjector as _fp_FI
from harp_tpu.utils.fault import InjectedFault as _fp_IF

with _fp_tmp.TemporaryDirectory() as _fp_dir:
    _fp_rng = np.random.default_rng(0)
    _fp_u = _fp_rng.integers(0, 32, 400).astype(np.int32)
    _fp_i = _fp_rng.integers(0, 24, 400).astype(np.int32)
    _fp_v = _fp_rng.normal(size=400).astype(np.float32)

    def _fp_model():
        m = _fp_MF.MFSGD(32, 24, _fp_MF.MFSGDConfig(
            rank=4, algo="dense", u_tile=8, i_tile=8, entry_cap=32),
            mesh=mesh)
        m.set_ratings(_fp_u, _fp_i, _fp_v)
        return m

    _fp_clean = _fp_model()
    _fp_clean.fit(6)
    _fp_ck = os.path.join(_fp_dir, "kill")
    _fp_crash = _fp_model()
    _fp_inj = _fp_FI(seed=7, fail={"dispatch": (4,)})
    try:
        with _fp_inj.arm():
            _fp_crash.fit(6, _fp_ck, ckpt_every=2, max_restarts=0)
        raise AssertionError("injector never fired")
    except _fp_IF:
        pass
    assert _fp_CM(_fp_ck).latest_step() == 1
    _fp_res = _fp_model()
    _fp_res.fit(6, _fp_ck, ckpt_every=2)
    np.testing.assert_array_equal(np.asarray(_fp_res.W),
                                  np.asarray(_fp_clean.W))
    np.testing.assert_array_equal(np.asarray(_fp_res.H),
                                  np.asarray(_fp_clean.H))

# degraded sustained serving under seeded ~1% dispatch chaos: books
# balance, row passes invariants 7 + 9 both ways
import check_jsonl as _fp_cj  # scripts/ already on sys.path for round 22

_fp_row = _fp_sustained(
    app="kmeans", n_requests=96, rows_per_request=1, burst_admit=8,
    ladder=(1, 8, 32), state_shape={"k": 8, "d": 16},
    fault_rate=0.01, fault_seed=34, deadline_ms=10_000.0,
    max_queue_rows=4096, max_retries=3)
assert _fp_row["faults_injected"] >= 1 and _fp_row["fault_retries"] >= 1
assert (_fp_row["served_requests"] + _fp_row["shed_requests"]
        + _fp_row["failed_requests"]) == _fp_row["offered_requests"] == 96
assert _fp_row["steady_compiles"] == 0
_fp_stamped = {**_fp_row, "backend": "cpu", "date": "2026-08-04",
               "commit": "drive"}
assert _fp_cj._check_serve_row("drive", 1, _fp_stamped) == []
assert any("exactly one of the three" in e for e in _fp_cj._check_serve_row(
    "drive", 1, {**_fp_stamped,
                 "shed_requests": _fp_stamped["shed_requests"] + 1}))

print(f"fault plane: injector-killed mfsgd resumed bit-identical from "
      f"step 1; degraded sustained row balanced "
      f"({_fp_row['served_requests']} served / {_fp_row['shed_requests']} "
      f"shed / {_fp_row['failed_requests']} failed of 96, "
      f"{_fp_row['fault_retries']} retries) through invariant 9 both ways")
print(f"DRIVE OK round-30 ({mode})")

# --- round 31: the collective planner end-to-end (PR 11) -------------------
# One registered program, subprocess-free: CommGraph byte sheet -> Plan ->
# the executed schedule -> ledger agreement BOTH ways (every planned site
# has a trace-time record; every recorded wire is a planned site), plus
# the reshard verb executing the planner's alternative schedules
# bit-identically to "keep".
from harp_tpu.analysis import commgraph as _plC
from harp_tpu.analysis.drivers import DRIVERS as _plD
from harp_tpu.parallel.collective import ShardSpec as _plS
from harp_tpu.plan import planner as _plP
from harp_tpu.plan import topology as _plT
from harp_tpu.utils import telemetry as _plTel

_pl_topo = _plT.detect(mesh)
assert _pl_topo.name == ("sim_ring_8" if mode == "cpu8" else _pl_topo.name)

# byte sheet -> Plan (fail closed, predictions == sheet, exactly)
_pl_fn, _pl_args = _plD["mfsgd.epoch"]()
_pl_graph = _plC.extract("mfsgd.epoch", _pl_fn, _pl_args)
_pl_plan = _plP.plan_sheet(
    "mfsgd.epoch", {"collectives": [s.row() for s in _pl_graph.sites]},
    _pl_topo)
assert all(d.schedule == "keep" for d in _pl_plan.sites)
assert _pl_plan.predicted_bytes_total() == _pl_graph.amplified_bytes() > 0

# ledger agreement both ways: the extraction traced under the ledger, so
# every static site must have a record (HL301's direction) AND every
# recorded comm site must be a planned site (the planner misses nothing)
_pl_static_sites = {d.site for d in _pl_plan.sites}
_pl_ledger_sites = set(_pl_graph.ledger_sites)
assert _pl_static_sites <= _pl_ledger_sites, (
    _pl_static_sites - _pl_ledger_sites)
assert _pl_ledger_sites <= _pl_static_sites, (
    _pl_ledger_sites - _pl_static_sites)
# and byte-exactness site by site: sheet bytes == ledger payload *
# amplification for every exact-wire site (HL302's direction, from the
# planner's own rows)
_pl_amp = {d.site: d for d in _pl_plan.sites}
for _pl_site, _pl_recs in _pl_graph.ledger_sites.items():
    if all(r["wire_dtype"] is None for r in _pl_recs):
        _pl_led = sum(r["payload_bytes"] for r in _pl_recs)
        _pl_sheet = sum(s.per_shard_bytes for s in _pl_graph.sites
                        if s.site == _pl_site)
        assert _pl_led == _pl_sheet, (_pl_site, _pl_led, _pl_sheet)

# the planner's alternative schedules EXECUTE and agree with "keep":
# chunked pipeline bit-identical, int8 wire within its rounding bound
_pl_x = np.arange(nw * 8 * 4, dtype=np.float32).reshape(nw * 8, 4)


def _pl_prog(a):
    keep = C.reshard(a, _plS.blocked(0), _plS.blocked(0, 1))
    chunked = C.reshard(a, _plS.blocked(0), _plS.blocked(0, 1), n_chunks=4)
    narrow = C.reshard(a, _plS.blocked(0), _plS.blocked(0, 1), wire="int8")
    return keep, chunked, narrow


_pl_keep, _pl_chunk, _pl_n8 = jax.jit(mesh.shard_map(
    _pl_prog, in_specs=(mesh.spec(0),),
    out_specs=(mesh.spec(0),) * 3))(mesh.shard_array(_pl_x, 0))
np.testing.assert_array_equal(np.asarray(_pl_keep), np.asarray(_pl_chunk))
assert np.abs(np.asarray(_pl_n8) - np.asarray(_pl_keep)).max() <= \
    np.abs(_pl_x).max() / 254 + 1e-6

# a topology where the alternatives win names ONLY measurable flip
# candidates and still chooses "keep" everywhere (fail closed under
# temptation); kmeans's hier candidate appears exactly on the
# multi-host price list
_pl_flat = _plP.plan_program("kmeans.fit", _plT.sim_ring(8))
_pl_multi = _plP.plan_program("kmeans.fit", _plT.v4_32())
assert _pl_flat.flip_candidates() == []
assert _pl_multi.flip_candidates() == ["kmeans_hier_psum"]
assert all(d.schedule == "keep" for d in _pl_multi.sites)
print(f"planner: mfsgd.epoch sheet {_pl_plan.predicted_bytes_total()} B "
      "== ledger both ways; alt schedules execute bit-identical; "
      "hier candidate only on v4_32")
print(f"DRIVE OK round-31 ({mode})")

# --- round 32: request-level tracing (PR 12) -------------------------------
# One causal timeline across the serve plane: a continuous run under
# seeded chaos yields complete span trees that reconcile EXACTLY with
# the runner's own counters, the merged timeline passes check_jsonl
# invariant 11 next to its ledger row, the trace CLI and the Perfetto
# exporter both load it, and the new svm/wdamds wire knobs execute
# with their exact arm unchanged.
from harp_tpu.serve.engines import ENGINES as _rtE
from harp_tpu.serve.server import Server as _rtServer
from harp_tpu.utils import reqtrace as _rt
from harp_tpu.utils import telemetry as _rtT
from harp_tpu.utils.fault import FaultInjector as _rtFI

import json as _rt_json
import subprocess as _rt_sp
import tempfile as _rt_tmp

with _rtT.scope(True):
    _rt_rng = np.random.default_rng(32)
    _rt_srv = _rtServer(
        "kmeans", state=_rtE["kmeans"].synthetic_state(_rt_rng, k=4, d=8),
        mesh=mesh, ladder=(1, 8))
    _rt_srv.startup()
    _rt_srv.steady.reset()
    _rt_r = _rt_srv.make_runner(depth=2, max_queue_rows=8, max_retries=1)
    _rt_inj = _rtFI(seed=0, fail={"dispatch": (2,)})
    _rt_t = 0.0
    with _rt_inj.arm():
        for _rt_i in range(8):
            _rt_r.submit(_rt_i, {"id": _rt_i, "x": _rt_rng.normal(
                size=(2, 8)).tolist()}, now=_rt_t)
            _rt_t += 0.001
            _rt_r.step(_rt_t)
        _rt_r.drain(_rt_t + 0.1)
    # chaos fired, the retry absorbed it, and EVERY offered request has
    # exactly one terminated span whose counts match the runner's books
    assert _rt_inj.injected["dispatch"] == 1
    assert _rt_r.fault_retries == 1
    _rt_tr = _rt.tracer
    assert _rt_tr.counts["served"] == _rt_r.completed
    assert _rt_tr.counts["shed"] == _rt_r.shed
    assert _rt_tr.counts["failed"] == _rt_r.failed
    assert sum(_rt_tr.counts.values()) == 8
    assert _rt_tr.summary()["open"] == 0
    assert _rt_tr.batch_event_count("retry") == 1
    assert any(m["source"] == "fault" for m in _rt_tr.marks)
    _rt_r.verify_exact()  # flagship budgets hold with tracing armed
    # streaming window percentiles agree with the exact samples they saw
    _rt_win = _rt_r.win.snapshot(_rt_t + 0.1)
    _rt_lat = sorted(_rt_r.latencies_ms)
    import math as _rt_math
    _rt_exact99 = _rt_lat[max(1, _rt_math.ceil(0.99 * len(_rt_lat))) - 1]
    assert abs(_rt_win["p99_ms"] - _rt_exact99) <= \
        _rt.QUANTILE_REL_ERR * _rt_exact99 + 1e-9
    with _rt_tmp.TemporaryDirectory() as _rt_d:
        _rt_p = os.path.join(_rt_d, "timeline.jsonl")
        _rtT.export_timeline(_rt_p)
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__))))
        import check_jsonl as _rt_cj
        assert _rt_cj.check_file(_rt_p) == []
        _rt_rows = _rtT.load_rows(_rt_p)["trace"]
        _rt_perf = _rt.perfetto(_rt_rows)
        _rt_json.dumps(_rt_perf)
        assert any(e.get("ph") == "X" for e in _rt_perf["traceEvents"])
        # the CLI validates the same file (exit 0, machine row)
        _rt_out = _rt_sp.run(
            [sys.executable, "-m", "harp_tpu", "trace", _rt_p, "--json"],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert _rt_out.returncode == 0, _rt_out.stderr[-500:]
        _rt_row = _rt_json.loads(_rt_out.stdout.strip().splitlines()[-1])
        assert _rt_row["unterminated"] == []
        assert _rt_row["served"] == _rt_tr.counts["served"]
print(f"reqtrace: 8 requests -> {_rt_tr.counts} reconciled, 1 injected "
      "fault absorbed, timeline invariant-11 clean, CLI + Perfetto load")

# svm/wdamds wires: the exact arm still trains/embeds (the reshard shim
# is bit-identical to the old allgather), bf16 stays close, and the
# planner names exactly the new measurable candidates
from harp_tpu.models.svm import SVM as _rtSVM, SVMConfig as _rtSVMC
_rt_x = _rt_rng.normal(size=(128, 8)).astype(np.float32)
_rt_y = np.sign(_rt_x @ _rt_rng.normal(size=8) + 1e-3).astype(np.float32)
_rt_cfg = dict(inner_steps=40, outer_rounds=2, sv_per_worker=8)
_rt_exact = _rtSVM(_rtSVMC(**_rt_cfg), mesh).fit(_rt_x, _rt_y)
_rt_bf16 = _rtSVM(_rtSVMC(sv_wire="bf16", **_rt_cfg), mesh).fit(_rt_x, _rt_y)
assert _rt_exact.accuracy(_rt_x, _rt_y) > 0.9
assert abs(_rt_bf16.accuracy(_rt_x, _rt_y)
           - _rt_exact.accuracy(_rt_x, _rt_y)) < 0.05
from harp_tpu.models.wdamds import MDSConfig as _rtMDSC, mds as _rt_mds
_rt_pts = _rt_rng.normal(size=(64, 4)).astype(np.float32)
_rt_delta = np.sqrt(((_rt_pts[:, None] - _rt_pts[None]) ** 2).sum(-1))
_rt_X, _rt_s = _rt_mds(_rt_delta, _rtMDSC(dim=3, iters=10), mesh, seed=0)
_rt_Xb, _rt_sb = _rt_mds(_rt_delta, _rtMDSC(dim=3, iters=10,
                                            coord_wire="bf16"), mesh,
                         seed=0)
assert np.isfinite(_rt_s) and _rt_s > 0
assert abs(_rt_sb - _rt_s) / _rt_s < 0.05
from harp_tpu.plan import planner as _rt_plan, topology as _rt_topo
assert set(_rt_plan.plan_program(
    "svm.train", _rt_topo.sim_ring(8)).flip_candidates()) == \
    {"svm_sv_bf16", "svm_sv_int8"}
assert set(_rt_plan.plan_program(
    "wdamds.smacof", _rt_topo.sim_ring(8)).flip_candidates()) == \
    {"wdamds_coord_bf16", "wdamds_coord_int8"}
print("svm/wdamds wires: exact arm trains/embeds, bf16 within bounds, "
      "planner names the four new candidates")
print(f"DRIVE OK round-32 ({mode})")

# --- round 33: the predictive performance observatory (PR 13) --------------
# Byte sheets -> model rows -> --predicted-top --only list ->
# flip_decision gates respected, end-to-end through the CLI subprocess,
# CPU-only: (a) the predict CLI prices every byte-sheeted program AND
# every modeled config as invariant-12-clean rows; (b) self-grading
# against the committed evidence exits 0; (c) measure_all's pruned
# selection is gate-closed and flip_decision accepts it without a
# bypassed gate; (d) the shared wire oracle prices the planner's sites
# identically; (e) the pre-sizer reproduces the OOM-calibrated tiles.
import json as _pm_json
import subprocess as _pm_sp
import tempfile as _pm_tmp

from harp_tpu import perfmodel as _pm
from harp_tpu.perfmodel import grade as _pm_g

_pm_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_pm_env = {**os.environ, "JAX_PLATFORMS": "cpu"}

# (a) predict CLI: one row per program with a byte sheet (18+) + one per
# modeled config, every row invariant-12-clean
_pm_out = _pm_sp.run(
    [sys.executable, "-m", "harp_tpu", "predict", "--json",
     "--topology", "v4_32"],
    capture_output=True, text=True, timeout=600, env=_pm_env,
    cwd=_pm_root)
assert _pm_out.returncode == 0, _pm_out.stderr[-800:]
_pm_rows = [_pm_json.loads(ln)
            for ln in _pm_out.stdout.strip().splitlines()]
assert sum(1 for r in _pm_rows if r.get("program")) >= 18
assert sum(1 for r in _pm_rows if r.get("config")) >= 25
import check_jsonl as _pm_cj
with _pm_tmp.TemporaryDirectory() as _pm_d:
    _pm_p = os.path.join(_pm_d, "model.jsonl")
    with open(_pm_p, "w") as _pm_f:
        _pm_f.write(_pm_out.stdout)
    assert _pm_cj.check_file(_pm_p) == []
for _pm_r in _pm_rows:
    assert _pm_r["rates_source"] in ("declared", "probed")
    assert abs(sum(_pm_r["terms"].values()) - _pm_r["predicted_s"]) \
        <= 1e-6 * _pm_r["predicted_s"]

# (b) the honesty gate: the model agrees with every committed verdict
# it can price (exit 1 + term breakdowns on any drift)
_pm_gr = _pm_sp.run(
    [sys.executable, "-m", "harp_tpu", "predict", "--grade",
     "--repo", _pm_root],
    capture_output=True, text=True, timeout=300, env=_pm_env,
    cwd=_pm_root)
assert _pm_gr.returncode == 0, _pm_gr.stderr[-800:]
_pm_grow = _pm_json.loads(_pm_gr.stdout.strip().splitlines()[-1])
assert _pm_grow["ok"] is True
assert sum(1 for e in _pm_grow["pairs"]
           if e["status"] == "agrees") >= 5

# (c) pruning through the CLI subprocess: the --predicted-top list is
# gate-closed, and flip_decision evaluates it without a bypassed gate
# (exit 0/1 only — 2 would be an argparse rejection of the list)
_pm_ma = _pm_sp.run(
    [sys.executable, os.path.join(_pm_root, "scripts", "measure_all.py"),
     "--predicted-top", "3", "--dry-run", "--topology", "v4_32"],
    capture_output=True, text=True, timeout=300, env=_pm_env,
    cwd=_pm_root)
assert _pm_ma.returncode == 0, _pm_ma.stderr[-800:]
_pm_sel = _pm_json.loads(_pm_ma.stdout.strip().splitlines()[-1])
_pm_meta = _pm_json.loads(_pm_ma.stderr.strip().splitlines()[-1])
assert _pm_sel["would_run"] == _pm_meta["only"]
import flip_decision as _pm_fd
for _pm_group in _pm_fd.JOINT_GATES + _pm_fd.EXCLUSIVE_GATES:
    if set(_pm_sel["would_run"]) & set(_pm_group):
        assert set(_pm_group) <= set(_pm_sel["would_run"]), _pm_group
_pm_fd_rc = _pm_sp.run(
    [sys.executable, os.path.join(_pm_root, "scripts",
                                  "flip_decision.py"),
     "--only"] + [c for c in _pm_sel["would_run"]
                  if c in _pm_fd.CANDIDATES],
    capture_output=True, text=True, timeout=300, env=_pm_env,
    cwd=_pm_root)
assert _pm_fd_rc.returncode in (0, 1), _pm_fd_rc.stderr[-500:]
for _pm_ln in _pm_fd_rc.stdout.strip().splitlines():
    _pm_v = _pm_json.loads(_pm_ln)
    assert "flip" in _pm_v  # every selected candidate got a verdict row

# (d) one wire oracle: planner site costs == model wire term, and the
# Plan rows still fail closed after the re-point
from harp_tpu.plan import planner as _pm_plan
_pm_plan_row = _pm_plan.plan_program(
    "kmeans.fit", _rt_topo.v4_32()).row()
assert all(s["schedule"] == "keep" for s in _pm_plan_row["sites"])
for _pm_sched in _pm_plan.SCHEDULES:
    assert _pm_plan._site_cost(_rt_topo.v4_32(), "psum", _pm_sched,
                               4096) == \
        _pm.wire_cost_s(_rt_topo.v4_32(), "psum", _pm_sched, 4096)

# (e) the pre-sizer reproduces the hand-calibrated tiles offline
assert _pm.presize("kmeans.partials_int8",
                   n=1_000_000, d=300, k=100)["tile"] == 8000
assert _pm.presize("mfsgd.sgd_tile_update",
                   rank=64, n_items=26_744)["tile"] == 256

# and the grading harness itself fails closed under sabotage: a model
# whose dense arm prices like the kernel must flip ok to False
_pm_real_price = _pm_g.price
def _pm_sab(config, row=None, topo=None):
    p = _pm_real_price(config, row, topo)
    if config == "mfsgd":
        return _pm.Price(p.config, p.metric, p.compute_s, 1e-12,
                         p.wire_s, p.overhead_s)
    return p
_pm_g.price = _pm_sab
try:
    assert _pm_g.grade(_pm_root)["ok"] is False
finally:
    _pm_g.price = _pm_real_price

print(f"perfmodel: {len(_pm_rows)} model rows invariant-12-clean, "
      f"grade OK ({sum(1 for e in _pm_grow['pairs'] if e['status'] == 'agrees')}"
      f" agreements), predicted-top {_pm_sel['would_run']} gate-closed, "
      "wire oracle shared, pre-sizer == hand-calibrated tiles")
print(f"DRIVE OK round-33 ({mode})")

# --- round 34: the health sentinel (PR 14) ---------------------------------
# The sixth (derived) spine end-to-end, CPU-only: (a) a seeded-ordinal
# chaos sustained serve run fires slo_burn + budget_drift findings whose
# counts reconcile EXACTLY with the row's invariant-9 ledger and the
# ReqTracer outcome counts, and the one exported file (trace + health +
# the stamped bench row) passes check_jsonl invariants 9/11/13 together,
# while the identical healthy control emits zero findings; (b) the skew
# trigger fires only after K consecutive over-threshold supersteps and
# its INLINE plan replays through schedule.apply_rebalance (numpy-checked
# resulting loads); (c) the health CLI summarizes/exits honestly and
# --grade-model emits the invariant-13-clean verdict row the sprint
# script tees; (d) the fail-closed --predicted-top gate is OPEN at HEAD
# (the committed evidence grades confirmed); (e) the driver record is
# bounded under the tail capture in the worst outage case.
import json as _hl_json
import subprocess as _hl_sp
import tempfile as _hl_tmp
import warnings as _hl_w

from harp_tpu import health as _hl
from harp_tpu import schedule as _hl_sched
from harp_tpu.serve.bench import benchmark_sustained as _hl_bs
from harp_tpu.utils import reqtrace as _hl_rt
from harp_tpu.utils import skew as _hl_skew
from harp_tpu.utils import telemetry as _hl_tm
from harp_tpu.utils.metrics import benchmark_json as _hl_bj

_hl_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_hl_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
import check_jsonl as _hl_cj

# (a) chaos run: dispatch event #2 fails (exact ordinal), queue bounded
# at 16 rows under ~flood load -> shedding + one retry-with-restage
with _hl_tm.scope(True):
    with _hl_w.catch_warnings():
        _hl_w.simplefilter("ignore", RuntimeWarning)
        _hl_res = _hl_bs(app="kmeans", n_requests=48, rows_per_request=1,
                         burst_admit=8, ladder=(8,), offered_qps=1e5,
                         state_shape={"k": 4, "d": 8}, max_queue_rows=16,
                         max_retries=2, fault_ordinals=(2,), mesh=mesh)
    assert _hl_res["faults_injected"] == 1
    assert _hl_res["fault_retries"] == 1 and _hl_res["shed_requests"] > 0
    _hl_rows = {r["detector"]: r for r in _hl.monitor.findings()}
    _hl_slo, _hl_bd = _hl_rows["slo_burn"], _hl_rows["budget_drift"]
    for _hl_k, _hl_f in (("offered", "offered_requests"),
                         ("served", "served_requests"),
                         ("shed", "shed_requests"),
                         ("failed", "failed_requests")):
        assert _hl_slo[_hl_k] == _hl_res[_hl_f], (_hl_k, _hl_slo, _hl_res)
    assert _hl_rt.tracer.counts == {"served": _hl_slo["served"],
                                    "shed": _hl_slo["shed"],
                                    "failed": _hl_slo["failed"]}
    assert _hl_bd["violations"] == 1
    assert "h2d_calls used 2 > budget 1" in _hl_bd["worst"]
    assert _hl_res["health_findings"] == 2
    assert _hl_res["health_budget_drift"] == 1
    with _hl_tmp.TemporaryDirectory() as _hl_d:
        _hl_p = os.path.join(_hl_d, "chaos.jsonl")
        _hl_tm.export(_hl_p)
        with open(_hl_p, "a") as _hl_f:
            _hl_f.write(_hl_bj("serve_kmeans_sustained", _hl_res) + "\n")
        assert _hl_cj.check_file(_hl_p, provenance=True) == []
        # (c) the CLI on the same file: actionable findings -> exit 1
        _hl_cli = _hl_sp.run(
            [sys.executable, "-m", "harp_tpu", "health", _hl_p, "--json",
             "--repo", _hl_root],
            capture_output=True, text=True, timeout=300, env=_hl_env,
            cwd=_hl_root)
        assert _hl_cli.returncode == 1, _hl_cli.stderr[-500:]
        _hl_sum = _hl_json.loads(
            _hl_cli.stdout.strip().splitlines()[-1])
        assert _hl_sum["findings"] == 2 and _hl_sum["actionable"] == 2
        assert _hl_sum["worst_severity"] == "page"
# healthy control: same trace shape, degradation knobs off -> clean
with _hl_tm.scope(True):
    _hl_ok = _hl_bs(app="kmeans", n_requests=48, rows_per_request=1,
                    burst_admit=8, ladder=(8,), offered_qps=500.0,
                    state_shape={"k": 4, "d": 8}, mesh=mesh)
    assert _hl_ok["health_findings"] == 0
    assert _hl_ok["health_breaches"] == 0
    assert _hl_ok["health_budget_drift"] == 0
    assert _hl.monitor.findings() == []

# (b) skew trigger -> apply_rebalance, loads numpy-checked
with _hl_tm.scope(True):
    for _hl_i in range(_hl.TRIGGER_SUPERSTEPS):
        _hl_skew.record_partition(
            "files", [10, 1, 0, 1], unit="bytes",
            units=[[("a", 6), ("b", 4)], [("c", 1)], [], [("d", 1)]])
        if _hl_i < _hl.TRIGGER_SUPERSTEPS - 1:
            assert _hl.monitor.findings() == []  # K-1 never fires
    _hl_r = _hl.monitor.findings()[0]
    assert _hl_r["detector"] == "skew_trigger"
    _hl_plan = _hl_r["plan"]
    _hl_new = _hl_sched.apply_rebalance([["a", "b"], ["c"], [], ["d"]],
                                        _hl_plan)
    _hl_sizes = {"a": 6, "b": 4, "c": 1, "d": 1}
    _hl_loads = sorted(sum(_hl_sizes[u] for u in w) for w in _hl_new)
    assert _hl_loads == [1, 1, 4, 6]  # greedy LPT on measured loads
    assert _hl_plan["ratio_after"] < _hl_plan["ratio_before"]

# (c) --grade-model: the one verdict row the sprint tees, checker-clean
_hl_gm = _hl_sp.run(
    [sys.executable, "-m", "harp_tpu", "health", "--grade-model",
     "--repo", _hl_root],
    capture_output=True, text=True, timeout=600, env=_hl_env,
    cwd=_hl_root)
assert _hl_gm.returncode == 0, _hl_gm.stderr[-800:]
_hl_row = _hl_json.loads(_hl_gm.stdout.strip().splitlines()[-1])
assert _hl_row["verdict"] == "confirmed"
assert _hl_cj._check_health_row("t", 1, _hl_row) == []

# (d) the gate is OPEN at HEAD: pruning still selects (round 33 already
# proved the selection machinery; this proves PR 14 did not close it)
_hl_ma = _hl_sp.run(
    [sys.executable, os.path.join(_hl_root, "scripts", "measure_all.py"),
     "--predicted-top", "2", "--dry-run"],
    capture_output=True, text=True, timeout=600, env=_hl_env,
    cwd=_hl_root)
assert _hl_ma.returncode == 0, _hl_ma.stderr[-800:]
assert _hl_json.loads(_hl_ma.stdout.strip().splitlines()[-1])["would_run"]

# (e) the driver record stays under the tail capture in the worst case
import importlib.util as _hl_il
_hl_spec = _hl_il.spec_from_file_location(
    "bench_r34", os.path.join(_hl_root, "bench.py"))
_hl_b = _hl_il.module_from_spec(_hl_spec)
_hl_spec.loader.exec_module(_hl_b)
_hl_rec = {"metric": "kmeans_iters_per_sec_1Mx300_k100", "value": 0.0,
           "unit": "iter/s", "vs_baseline": None,
           "submetrics": {n: {"value": 0.0, "unit": "u",
                              "error": "timeout: config exceeded budget"}
                          for n, _ in _hl_b._CONFIG_KEYS},
           "error": "relay_down: probe timed out",
           "last_measured": _hl_b._last_measured()}
_hl_line = _hl_json.dumps(_hl_b._fit_record(_hl_rec))
assert len(_hl_line) <= _hl_b.RECORD_CAP_BYTES < 2000
assert "kmeans" in _hl_json.loads(_hl_line)["last_measured"]

print(f"health: chaos run {_hl_res['served_requests']}/"
      f"{_hl_res['shed_requests']}/{_hl_res['failed_requests']} "
      "reconciled across ledger+trace+sentinel, control clean, "
      f"skew plan applied (loads {_hl_loads}), grade-model confirmed, "
      f"pruning gate open, driver record {len(_hl_line)} B <= "
      f"{_hl_b.RECORD_CAP_BYTES}")
print(f"DRIVE OK round-34 ({mode})")

# ---------------------------------------------------------------------------
# round 35 — elastic execution (PR 15): the whole loop through the
# PUBLIC surface, numpy-checked.  (a) a skewed corpus fires the PR-14
# trigger, the elastic MF-SGD driver consumes it EXACTLY once and the
# rebalanced per-worker loads match a straight-line numpy LPT over the
# pack grains; (b) the reshard-wire row move equals numpy fancy
# indexing bit-for-bit; (c) an injected permanent worker loss at a
# seeded ordinal shrinks 8 -> 7 and the continued training is
# BIT-identical to a survivors-only run from the same checkpoint;
# (d) the full telemetry export (skew + health + elastic rows) passes
# scripts/check_jsonl.py, and the elastic CLI knob round-trips end to
# end in a subprocess.
# ---------------------------------------------------------------------------
import json as _el_json
import subprocess as _el_sp
import tempfile as _el_tmp

from harp_tpu import health as _el_h
from harp_tpu.elastic import ledger as _el_led
from harp_tpu.elastic.apps import MFSGDElastic as _ElMF
from harp_tpu.elastic.apps import elastic_fit as _el_fit
from harp_tpu.elastic.move import regather_rows as _el_regather
from harp_tpu.elastic.rebalance import wasted_frac as _el_wf
from harp_tpu.models.mfsgd import MFSGDConfig as _ElCfg
from harp_tpu.utils import telemetry as _el_tm
from harp_tpu.utils.checkpoint import CheckpointManager as _ElCkpt
from harp_tpu.utils.fault import FaultInjector as _ElInj

_el_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_el_root, "scripts"))
import check_jsonl as _el_cj  # noqa: E402

_el_rng = np.random.default_rng(0)
_el_users = np.concatenate([_el_rng.integers(0, 2 * (64 // nw), 4000),
                            _el_rng.integers(2 * (64 // nw), 64, 1000)])
_el_rng.shuffle(_el_users)
_el_items = _el_rng.integers(0, 48, _el_users.shape[0])
_el_vals = _el_rng.normal(size=_el_users.shape[0]).astype(np.float32)
_el_cfg = _ElCfg(rank=4, algo="dense", u_tile=8, i_tile=8, entry_cap=64)

with _el_tm.scope(True):
    _el_ad = _ElMF(64, 48, _el_cfg, mesh, 0, users=_el_users,
                   items=_el_items, vals=_el_vals, packs_per_worker=8)
    _el_before = _el_ad.worker_loads().copy()
    assert _el_wf(_el_before) > _el_h.WASTED_FRAC_TRIGGER
    _el_fit(_el_ad, 4)
    # (a) numpy model of the rebalanced loads: greedy LPT (size-desc,
    # argmin-load placement) over the measured pack loads — the exact
    # rule SkewLedger.suggest_rebalance applies
    _el_pl = _el_ad.packs.loads(_el_users)
    _el_lpt = np.zeros(nw)
    for _el_pid in sorted(range(len(_el_pl)),
                          key=lambda p: (-_el_pl[p], p)):
        _el_lpt[int(_el_lpt.argmin())] += _el_pl[_el_pid]
    np.testing.assert_allclose(sorted(_el_ad.worker_loads()),
                               sorted(_el_lpt))
    assert _el_wf(_el_ad.worker_loads()) < _el_h.WASTED_FRAC_TRIGGER
    (_el_reb,) = [r for r in _el_led.ledger.rows
                  if r["event"] == "rebalance"]
    assert _el_reb["wasted_frac_after"] < _el_reb["wasted_frac_before"]
    assert sum(_el_reb["loads_after"]) == sum(_el_reb["loads_before"])
    # the handshake spent the fire: nothing left to consume
    assert _el_h.monitor.consume_skew_trigger(_el_ad.phase) is None

    # (b) reshard-wire row move vs numpy fancy indexing
    _el_x = mesh.shard_array(
        _el_rng.normal(size=(8 * nw, 3)).astype(np.float32), 0)
    _el_rows = _el_rng.integers(-1, 8 * nw, 2 * 8 * nw)
    _el_got = np.asarray(_el_regather(mesh, _el_x, _el_rows))
    _el_ref = np.where((_el_rows >= 0)[:, None],
                       np.asarray(_el_x)[np.maximum(_el_rows, 0)], 0.0)
    np.testing.assert_array_equal(_el_got, _el_ref)

    # (c) permanent loss at seeded dispatch ordinal 2 -> shrink -> the
    # continuation is BIT-identical to survivors-only from the ckpt
    _el_dir = _el_tmp.mkdtemp()
    _el_ck = os.path.join(_el_dir, "ck")
    _el_inj = _ElInj(seed=0, permanent={"dispatch": (2,)},
                     lost_worker=nw - 1)
    _el_ad2 = _ElMF(64, 48, _el_cfg, mesh, 0, users=_el_users,
                    items=_el_items, vals=_el_vals, max_worker_loss=1)
    _el_fit(_el_ad2, 3, _el_ck, ckpt_every=1, fault=_el_inj,
            rebalance=False)
    assert _el_inj.permanent_fired
    assert _el_ad2.mesh.num_workers == nw - 1
    _el_events = [r["event"] for r in _el_led.ledger.rows]
    assert _el_events == ["rebalance", "shrink", "resume"], _el_events
    _el_step, _el_state = _ElCkpt(_el_ck).restore(0)
    _el_surv = mesh.survivors(nw - 1)
    _el_ad3 = _ElMF(64, 48, _el_cfg, _el_surv, 0, users=_el_users,
                    items=_el_items, vals=_el_vals)
    _el_ad3.install(_el_state)
    for _el_i in range(_el_step + 1, 3):
        _el_ad3.train_one()
    np.testing.assert_array_equal(_el_ad2.canonical_state()["W"],
                                  _el_ad3.canonical_state()["W"])
    np.testing.assert_array_equal(_el_ad2.canonical_state()["H"],
                                  _el_ad3.canonical_state()["H"])
    # the comparison adapter's install adds its OWN resume row (it is
    # the same restore path) — the export below carries all four
    assert [r["event"] for r in _el_led.ledger.rows][-1] == "resume"

    # (d) the export passes EVERY checker invariant as one file
    _el_out = os.path.join(_el_dir, "run.jsonl")
    _el_tm.export(_el_out)
_el_errs = _el_cj.check_file(_el_out, provenance=True)
assert _el_errs == [], _el_errs

# CLI round trip in a subprocess (the --elastic knob end to end)
_el_env = dict(os.environ)
_el_env["JAX_PLATFORMS"] = ""
_el_code = (
    "import os\n"
    "os.environ['XLA_FLAGS'] = os.environ.get('XLA_FLAGS','') + "
    "' --xla_force_host_platform_device_count=8'\n"
    "import jax\n"
    "jax.config.update('jax_platforms','cpu')\n"
    "import harp_tpu.__main__ as cli\n"
    "raise SystemExit(cli.main(['kmeans-stream', '--elastic', '--n',"
    " '256', '--d', '4', '--k', '3', '--iters', '2']))\n")
_el_cli = _el_sp.run([sys.executable, "-c", _el_code],
                     capture_output=True, text=True, timeout=600,
                     env=_el_env, cwd=_el_root)
assert _el_cli.returncode == 0, _el_cli.stderr[-800:]
_el_row = _el_json.loads(_el_cli.stdout.strip().splitlines()[-1])
assert _el_row["config"] == "kmeans_stream_elastic_cli"
assert _el_row["worker_losses"] == 0 and np.isfinite(_el_row["inertia"])

print(f"elastic: rebalance {round(_el_wf(_el_before), 3)} -> "
      f"{round(_el_wf(_el_ad.worker_loads()), 4)} (numpy LPT match), "
      f"regather bit-exact, loss at ordinal 2 shrank {nw} -> {nw - 1} "
      "bit-identical to survivors-only, export checker-clean, CLI "
      f"inertia {round(_el_row['inertia'], 1)}")
print(f"DRIVE OK round-35 ({mode})")

# ---------------------------------------------------------------------------
# round-36: wall-attribution observatory (PR 16).  Classifier vs a
# hand-labelled span table, attribute() vs a straight-line numpy model,
# one REAL capture cross-reconciled through check_jsonl invariant 15 and
# the lint's CommGraph byte sheet, profile_drift grading (quiet on
# itself, fires on a forged bound flip), and the newly priced perfmodel
# half (rf/svm/wdamds/subgraph + the serve queueing term).
from harp_tpu.profile import attribution as _pf

# (a) classifier priority: collective names never read as gather/mxu,
# runtime/infra spans land in overhead, the residue is elementwise.
_pf_expect = {
    "all-gather.7": "wire", "all-reduce": "wire",
    "collective-permute.2": "wire",
    "dot_general.1": "mxu", "conv.3": "mxu",
    "convert.9": "elementwise",                # conv(?!ert) guard
    "scatter-add.4": "scatter", "segment_sum": "scatter",
    "gather.5": "gather_dus", "dynamic-update-slice.8": "gather_dus",
    "TfrtCpuExecutable::Execute": "overhead",
    "PjitFunction(fit)": "overhead",
    "fusion.12": "elementwise", "broadcast.2": "elementwise",
}
for _pf_name, _pf_want in _pf_expect.items():
    _pf_got = _pf.classify(_pf_name)
    assert _pf_got == _pf_want, (_pf_name, _pf_got, _pf_want)

# (b) attribute() vs numpy: under-attribution fills overhead exactly;
# over-attribution rescales to the wall and reports the residual;
# device-count normalization divides attributed seconds by N.
_pf_bd = [("dot.1", 0, 0.40), ("fusion.2", 1, 0.20),
          ("all-gather.3", 0, 0.10), ("scatter.4", 1, 0.05),
          ("dynamic-update-slice.5", 0, 0.05)]
_pf_a = _pf.attribute(_pf_bd, 1.0, 1)
assert _pf_a["bound"] == "mxu" and _pf_a["sum_rel_err"] == 0.0
assert abs(sum(_pf_a["terms"].values()) - 1.0) < 1e-5
assert abs(_pf_a["terms"]["overhead_s"] - 0.2) < 1e-5      # 1.0 - 0.8
_pf_o = _pf.attribute(_pf_bd, 0.5, 1)       # 0.8 attributed over 0.5 wall
assert abs(_pf_o["sum_rel_err"] - 0.6) < 1e-6
assert abs(sum(_pf_o["terms"].values()) - 0.5) < 1e-5
_pf_n = _pf.attribute(_pf_bd, 1.0, 2)       # halve per-device seconds
assert abs(sum(_pf_v for _pf_k, _pf_v in _pf_n["terms"].items()
               if _pf_k != "overhead_s") - 0.4) < 1e-5

# (c) one real capture end to end: reconciled, invariant-15 clean, and
# the wire column agrees with an independent CommGraph walk.
_pf_row = _pf.capture("kmeans", reps=2)
assert _pf_row["reconciled"] is True and _pf_row["bound"] in _pf.BUCKETS
import check_jsonl as _pf_cj

_pf_errs = _pf_cj._check_profile_row("drive", 0, _pf_row)
assert _pf_errs == [], _pf_errs
from harp_tpu.analysis import commgraph as _pf_cg
from harp_tpu.analysis.drivers import DRIVERS as _PF_DRV

_pf_fn, _pf_fargs = _PF_DRV["kmeans.fit"]()
assert _pf_row["wire_bytes"] == int(
    _pf_cg.extract("kmeans.fit", _pf_fn, _pf_fargs).amplified_bytes())

# (d) drift grading: the row graded against itself is quiet; moving the
# bound bucket's whole share to another bucket fires a warn finding.
from harp_tpu.health import grade as _pf_hg
from harp_tpu.health import sentinel as _pf_sn

_pf_sn.reset()
_pf_base = {_pf_row["app"]: _pf_row}
assert _pf_hg.grade_profile_row(dict(_pf_row), "/root/repo",
                                committed=_pf_base) is None
_pf_other = "mxu" if _pf_row["bound"] != "mxu" else "wire"
_pf_flip = dict(_pf_row, terms=dict(_pf_row["terms"]),
                bound=_pf_other)
_pf_flip["terms"][_pf_other + "_s"] += \
    _pf_flip["terms"][_pf_row["bound"] + "_s"]
_pf_flip["terms"][_pf_row["bound"] + "_s"] = 0.0
_pf_f = _pf_hg.grade_profile_row(_pf_flip, "/root/repo",
                                 committed=_pf_base)
assert _pf_f is not None and _pf_f["detector"] == "profile_drift"
assert _pf_f["bound_flipped"] is True and _pf_f["severity"] == "warn"
assert _pf_f["share_delta"] > _pf_hg.PROFILE_SHARE_DRIFT
_pf_sn.reset()

# (e) the newly priced half prices: every PR-16 flip candidate plus the
# serve queueing term yields a finite positive predicted wall, and the
# deliberately unpriced kmeans_ingest still raises.
from harp_tpu.perfmodel import model as _pf_pm
from harp_tpu.plan.topology import v4_32 as _pf_v432

_pf_topo = _pf_v432()
for _pf_cfg in ("rf_dense_hist", "svm_x_bf16", "wdamds_delta_bf16",
                "subgraph_csr32", "serve_kmeans_sustained"):
    _pf_price = _pf_pm.price(_pf_cfg, None, _pf_topo)
    _pf_mrow = _pf_pm.model_row(_pf_price, _pf_topo, config=_pf_cfg)
    assert _pf_mrow["predicted_s"] > 0 and np.isfinite(
        _pf_mrow["predicted_s"]), _pf_cfg
try:
    _pf_pm.price("kmeans_ingest", None, _pf_topo)
    raise AssertionError("kmeans_ingest must stay unpriced")
except KeyError:
    pass

print(f"profile: {len(_pf_expect)} span labels classified, attribute() "
      "== numpy (overhead fill / rescale / device split), kmeans "
      f"capture reconciled bound={_pf_row['bound']} "
      f"wire={_pf_row['wire_bytes']} B == CommGraph, drift quiet-on-self "
      f"and fires on flip (delta {_pf_f['share_delta']}), 5 new terms "
      "priced + ingest still refuses")
print(f"DRIVE OK round-36 ({mode})")

# --------------------------------------------------------------- round 37
# PR 17: the kernelized half — drive all three Pallas arms end to end.
# (a) CLI knob -> bench row: the three flip candidates run through the
#     REAL measurement harness (scripts/measure_all.py --smoke on the
#     forced-CPU 8-device sim) and emit non-error rows with a finite
#     metric + quality field and the pallas knob recorded on the row;
# (b) the gates fail closed IN CODE: a forged 2x-faster-but-degraded
#     candidate is refused with the QUALITY DEGRADED reason (never the
#     literal "FLIP:" marker an operator greps for), and a winning
#     rf_hist_pallas whose anchor chain is incomplete (rf_dense_hist
#     measured but ITS incumbent rf_scatter_hist missing) exits 1 with
#     the conditional-gate UNMEASURED veto;
# (c) attribution re-capture: the rf/svm/wdamds profile rows still
#     reconcile (dispatch count, zero in-window compiles, CommLedger
#     match) with the new kernels registered.
import contextlib as _k17_ctx
import io as _k17_io
import json as _k17_json
import subprocess as _k17_sp
import tempfile as _k17_tf

import flip_decision as _k17_fd
from harp_tpu.profile import attribution as _k17_attr

_k17_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_k17_cands = ("svm_kernel_pallas", "wdamds_dist_pallas", "rf_hist_pallas")

# (a) the measurement harness itself, in a subprocess (fresh jax with 8
# forced host devices — the parent's backend choice must not leak in)
_k17_env = dict(os.environ)
_k17_env["XLA_FLAGS"] = (_k17_env.get("XLA_FLAGS", "")
                         + " --xla_force_host_platform_device_count=8")
_k17_proc = _k17_sp.run(
    [sys.executable, os.path.join(_k17_root, "scripts", "measure_all.py"),
     "--smoke", "--platform", "cpu", "--only", *_k17_cands],
    env=_k17_env, capture_output=True, text=True, timeout=1800)
assert _k17_proc.returncode == 0, _k17_proc.stderr[-2000:]
_k17_rows = {}
for _k17_line in _k17_proc.stdout.splitlines():
    _k17_line = _k17_line.strip()
    if not _k17_line.startswith("{"):
        continue
    try:
        _k17_row = _k17_json.loads(_k17_line)
    except ValueError:
        continue
    if _k17_row.get("config") in _k17_cands:
        _k17_rows[_k17_row["config"]] = _k17_row
assert set(_k17_rows) == set(_k17_cands), sorted(_k17_rows)
for _k17_name, _k17_metric, _k17_qual in (
        ("svm_kernel_pallas", "samples_per_sec", "train_acc"),
        ("wdamds_dist_pallas", "iters_per_sec", "final_stress"),
        ("rf_hist_pallas", "trees_per_sec", "train_acc")):
    _k17_row = _k17_rows[_k17_name]
    assert "error" not in _k17_row, _k17_row
    assert _k17_row.get(_k17_metric, 0) > 0 and np.isfinite(
        _k17_row[_k17_metric]), _k17_row
    assert np.isfinite(_k17_row[_k17_qual]), _k17_row
assert _k17_rows["svm_kernel_pallas"]["algo"] == "pallas"
assert _k17_rows["wdamds_dist_pallas"]["algo"] == "pallas"
assert _k17_rows["rf_hist_pallas"]["hist_algo"] == "pallas"

# (b1) quality gate: 2x speed never outruns a degraded quality field
_k17_spec = _k17_fd.CANDIDATES["rf_hist_pallas"]
_k17_bad = _k17_fd.decide(
    {"config": "rf_hist_pallas", "trees_per_sec": 200.0, "train_acc": 0.80},
    {"config": "rf_dense_hist", "trees_per_sec": 100.0, "train_acc": 0.99},
    _k17_spec)
assert _k17_bad["flip"] is False and _k17_bad["quality_ok"] is False
assert "QUALITY DEGRADED" in _k17_bad["reason"], _k17_bad
assert "FLIP:" not in _k17_bad["reason"], _k17_bad

# (b2) conditional gate: a winning pallas row with rf_dense_hist
# measured but the anchor's OWN incumbent (rf_scatter_hist) missing is
# not a verdict — main() must veto AND signal exit 1 (rerun the benches)
with _k17_tf.NamedTemporaryFile(
        "w", suffix=".jsonl", delete=False) as _k17_f:
    for _k17_forged in (
            {"config": "rf_hist_pallas", "backend": "tpu",
             "trees_per_sec": 200.0, "train_acc": 0.99},
            {"config": "rf_dense_hist", "backend": "tpu",
             "trees_per_sec": 100.0, "train_acc": 0.99}):
        _k17_f.write(_k17_json.dumps(_k17_forged) + "\n")
    _k17_bench = _k17_f.name
_k17_out = _k17_io.StringIO()
with _k17_ctx.redirect_stdout(_k17_out):
    _k17_rc = _k17_fd.main(
        ["--bench", _k17_bench, "--only", "rf_hist_pallas"])
os.unlink(_k17_bench)
assert _k17_rc == 1, _k17_out.getvalue()
_k17_verdicts = [_k17_json.loads(ln)
                 for ln in _k17_out.getvalue().splitlines() if ln.strip()]
assert len(_k17_verdicts) == 1, _k17_verdicts
_k17_v = _k17_verdicts[0]
assert _k17_v["flip_decision"] == "rf_hist_pallas"
assert _k17_v["flip"] is False
assert "VETOED by conditional gate" in _k17_v["reason"], _k17_v
assert "UNMEASURED" in _k17_v["reason"], _k17_v
assert "FLIP:" not in _k17_v["reason"], _k17_v

# (c) the newly priced apps still reconcile with the kernels registered
for _k17_app in ("rf", "svm", "wdamds"):
    _k17_prow = _k17_attr.capture(_k17_app, reps=2)
    assert _k17_prow["reconciled"] is True, (
        _k17_app, _k17_prow.get("checks"))
    _k17_errs = _pf_cj._check_profile_row("drive", 0, _k17_prow)
    assert _k17_errs == [], (_k17_app, _k17_errs)

print("kernels: 3 pallas flip candidates measured through the real "
      "harness (svm_kernel_pallas/wdamds_dist_pallas/rf_hist_pallas, "
      "finite metric+quality, knob on the row), quality veto says "
      "QUALITY DEGRADED not FLIP:, conditional gate exits 1 on the "
      "unmeasured anchor chain, rf/svm/wdamds captures reconciled")
print(f"DRIVE OK round-37 ({mode})")

# ---------------------------------------------------------------------------
# round 38 — superstep flightpath (PR 18): one causal training-plane
# timeline across all seven spines, hand-checked.  (a) THE chaos drill
# through the PUBLIC elastic surface — a seeded transient dispatch
# fault, a fired-and-consumed skew rebalance, and a permanent worker
# loss in ONE run — yields a timeline whose span-outcome multiset,
# cause-adjacency (every faulted span's seq carries the injector's own
# mark), elastic mark sequence, and EXACT dispatch-mark==flight-delta
# reconciliation are re-derived by hand from the raw rows; (b) the
# export passes scripts/check_jsonl.py whole-file (invariant 16 on top
# of 13/14), INCLUDING an elastic resume row recorded OUTSIDE any run
# (the round-35 manual-install comparison pattern, on_timeline=False —
# exactly the scenario that caught the first cut of this invariant in
# this drive); (c) the timeline CLI round-trips in a subprocess
# (exit 0, stamped --json row, --perfetto Chrome-Trace JSON with only
# M/X/i phases); (d) zero-cost off: with telemetry disabled the tracer
# stays EMPTY through a full instrumented driver run and kmeans.fit
# returns bit-identical centroids vs the traced run.
# ---------------------------------------------------------------------------
import json as _st_json
import subprocess as _st_sp
import tempfile as _st_tmp

from harp_tpu.elastic import ledger as _st_led
from harp_tpu.elastic.apps import MFSGDElastic as _StMF
from harp_tpu.elastic.apps import elastic_fit as _st_fit
from harp_tpu.models import kmeans as _st_km
from harp_tpu.models.mfsgd import MFSGDConfig as _StCfg
from harp_tpu.utils import steptrace as _st_st
from harp_tpu.utils import telemetry as _st_tm
from harp_tpu.utils.checkpoint import CheckpointManager as _StCkpt
from harp_tpu.utils.fault import FaultInjector as _StInj

_st_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_st_root, "scripts"))
import check_jsonl as _st_cj  # noqa: E402

_st_rng = np.random.default_rng(0)
_st_users = np.concatenate([_st_rng.integers(0, 2 * (64 // nw), 4000),
                            _st_rng.integers(2 * (64 // nw), 64, 1000)])
_st_rng.shuffle(_st_users)
_st_items = _st_rng.integers(0, 48, _st_users.shape[0])
_st_vals = _st_rng.normal(size=_st_users.shape[0]).astype(np.float32)
_st_cfg = _StCfg(rank=4, algo="dense", u_tile=8, i_tile=8, entry_cap=64)
_st_dir = _st_tmp.mkdtemp()
_st_out = os.path.join(_st_dir, "run.jsonl")

with _st_tm.scope(True):
    # (a) transient at dispatch ordinal 5, permanent at 7 — the skewed
    # corpus fires the trigger first, so the narrative is
    # rebalance -> transient+restart -> loss+shrink, one run id
    _st_inj = _StInj(seed=0, fail={"dispatch": (5,)},
                     permanent={"dispatch": (7,)}, lost_worker=nw - 1)
    _st_ad = _StMF(64, 48, _st_cfg, mesh, 0, users=_st_users,
                   items=_st_items, vals=_st_vals, packs_per_worker=8,
                   max_worker_loss=1)
    _st_fit(_st_ad, 6, os.path.join(_st_dir, "ck"), ckpt_every=1,
            fault=_st_inj)
    assert _st_inj.permanent_fired and _st_ad.losses == 1
    _st_ev = [r["event"] for r in _st_led.ledger.rows]
    assert _st_ev == ["rebalance", "resume", "shrink", "resume"], _st_ev
    assert all(r["on_timeline"] for r in _st_led.ledger.rows)
    _st_rows = _st_st.tracer.rows()

    # hand re-derivation from the raw rows: one run, every span
    # terminated, outcome multiset matches the injector script
    (_st_rn,) = [r for r in _st_rows if r["ev"] == "run"]
    _st_sp_rows = [r for r in _st_rows if r["ev"] == "superstep"]
    assert len(_st_sp_rows) == _st_rn["supersteps"]
    _st_oc = {o: sum(1 for s in _st_sp_rows if s["outcome"] == o)
              for o in _st_st.OUTCOMES}
    assert _st_oc == {"completed": 3, "faulted": 2, "rebalanced": 1,
                      "resumed": 2}, _st_oc
    # cause-adjacency: the injector's marks sit on the faulted seqs
    _st_marks = [r for r in _st_rows if r["ev"] == "mark"]
    _st_fm = {m["seq"] for m in _st_marks if m["source"] == "fault"}
    assert _st_fm == {s["seq"] for s in _st_sp_rows
                      if s["outcome"] == "faulted"}
    assert [m["name"] for m in _st_marks if m["source"] == "elastic"] \
        == _st_ev
    assert {"skew_trigger", "consume_skew_trigger"} <= {
        m["name"] for m in _st_marks if m["source"] == "health"}
    # the two-spine dispatch reconciliation, EXACT
    _st_dm = sum(1 for m in _st_marks
                 if (m["source"], m["name"]) == ("flight", "dispatch"))
    assert _st_dm == _st_rn["flight"]["dispatches"]

    # (b) an elastic action OUTSIDE any run: restore the ckpt into a
    # fresh survivors-mesh adapter (the round-35 bit-identity pattern)
    # — its resume row must stamp on_timeline=False and the export must
    # STAY invariant-16 clean
    _st_step, _st_state = _StCkpt(os.path.join(_st_dir, "ck")).restore()
    _st_cmp = _StMF(64, 48, _st_cfg, mesh.survivors(nw - 1), 0,
                    users=_st_users, items=_st_items, vals=_st_vals)
    _st_cmp.install(_st_state)
    assert _st_led.ledger.rows[-1]["event"] == "resume"
    assert _st_led.ledger.rows[-1]["on_timeline"] is False
    _st_tm.export(_st_out)
_st_errs = _st_cj.check_file(_st_out, provenance=True)
assert _st_errs == [], _st_errs

# (c) the CLI in a subprocess: exit 0, stamped JSON row, Perfetto shape
_st_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
_st_pf = os.path.join(_st_dir, "trace.json")
_st_cli = _st_sp.run(
    [sys.executable, "-m", "harp_tpu", "timeline", _st_out, "--json",
     "--perfetto", _st_pf],
    capture_output=True, text=True, timeout=300, env=_st_env,
    cwd=_st_root)
assert _st_cli.returncode == 0, _st_cli.stderr[-800:]
_st_row = _st_json.loads(_st_cli.stdout.strip().splitlines()[-1])
assert _st_row["runs"] == 1 and _st_row["supersteps"] == len(_st_sp_rows)
assert _st_row["unterminated"] == [] and _st_row["dispatch_mismatch"] == []
assert all(k in _st_row for k in ("backend", "date", "commit"))
_st_doc = _st_json.load(open(_st_pf))
assert {e["ph"] for e in _st_doc["traceEvents"]} <= {"M", "X", "i"}
assert any(e["ph"] == "X" and e["dur"] >= 0
           for e in _st_doc["traceEvents"])

# (d) zero-cost off: empty tracer + bit-identical traced/untraced fit
_st_pts = np.random.default_rng(3).normal(size=(32 * nw, 8)) \
    .astype(np.float32)
_st_st.reset()
_st_c0, _st_i0 = _st_km.fit(_st_pts, k=4, iters=3, mesh=mesh, seed=0)
assert _st_st.tracer.rows() == [] and _st_st.tracer._run is None
with _st_tm.scope(True):
    _st_c1, _st_i1 = _st_km.fit(_st_pts, k=4, iters=3, mesh=mesh, seed=0)
    assert _st_st.tracer.rows() != []
np.testing.assert_array_equal(np.asarray(_st_c0), np.asarray(_st_c1))
assert _st_i0 == _st_i1

print(f"steptrace: chaos run {_st_rn['supersteps']} spans {_st_oc} on "
      "one run id, fault marks on the faulted seqs, elastic marks == "
      f"ledger {_st_ev}, dispatch marks == flight ({_st_dm}), "
      "uncovered manual-install resume row exports clean, CLI+Perfetto "
      "round trip, tracer zero-cost off (bit-identical kmeans)")
print(f"DRIVE OK round-38 ({mode})")

# ---------------------------------------------------------------------------
# round 39 — the memory plane (PR 19).  One instrumented scope drives
# every hook through the PUBLIC surface: (a) shard_array staging +
# a donate_argnums-tracked dispatch (the donated buffer must LEAVE the
# live set) + a checkpoint restore + one passing and one REFUSED
# vmem gate, all inside steptrace supersteps so the peak rides the
# timeline as memory marks; the export must be invariant-17 clean and
# the watermark must match a straight-line python replay of the buffer
# rows; (b) the serve AOT cache persists the memory_analysis()
# footprint as a .mem.json sidecar and a warm load reports the SAME
# exec_hbm_bytes without recompiling; (c) the CLI round-trips the
# export (exit 0, stamped --json row, exit 2 on garbage); (d) zero
# cost off: with telemetry disabled no hook records anything.
# ---------------------------------------------------------------------------
import json as _mr_json
import subprocess as _mr_sp
import tempfile as _mr_tmp

from harp_tpu.ops.kmeans_kernel import vmem_bytes_int8 as _mr_vb
from harp_tpu.serve.cache import ExecutableCache as _MrCache
from harp_tpu.utils import flightrec as _mr_fr
from harp_tpu.utils import memrec as _mr
from harp_tpu.utils import steptrace as _mr_stt
from harp_tpu.utils import telemetry as _mr_tm
from harp_tpu.utils.checkpoint import CheckpointManager as _MrCkpt

_mr_dir = _mr_tmp.mkdtemp()
_mr_out = os.path.join(_mr_dir, "run.jsonl")
_mr_x = np.arange(nw * 8 * 4, dtype=np.float32).reshape(nw * 8, 4)
_mr_step = _mr_fr.track(
    jax.jit(lambda a: a.sum(), donate_argnums=(0,)),
    "drive.mem.step", donate_argnums=(0,))
_mr_pred = _mr_vb(8000, 1024, 128)  # the 2026-08-01 relay-OOM shape

with _mr_tm.scope(True):
    with _mr_stt.run("drive.mem"):
        with _mr_stt.superstep("drive.mem", 0):
            _mr_xd = mesh.shard_array(_mr_x)          # staged
            _mr_res = float(np.asarray(_mr_step(_mr_xd)))  # donated
            _mr_ck = _MrCkpt(os.path.join(_mr_dir, "ck"))
            _mr_ck.save(1, {"w": np.float32(_mr_res)})
            _mr_ck.restore(1)                         # restored
            _mr.require_vmem_fit("drive.fit", 1 << 20,
                                 budget=14 << 20)     # fits
        with _mr_stt.superstep("drive.mem", 1):
            try:
                _mr.require_vmem_fit("kmeans.partials_int8", _mr_pred,
                                     budget=14 << 20)
                raise AssertionError("over-VMEM config was not refused")
            except MemoryError as e:
                assert str(_mr_pred) in str(e) and "refused before " \
                    "dispatch" in str(e), str(e)
    _mr_rows = list(_mr.ledger._rows)
    _mr_marks = [r for r in _mr_stt.tracer.rows()
                 if r["ev"] == "mark" and r["source"] == "memory"]
    assert _mr_marks and all(m["name"] == "superstep_peak"
                             for m in _mr_marks)
    _mr_tm.export(_mr_out)

# straight-line replay of the buffer rows == every stamped watermark
_mr_live, _mr_peak, _mr_alive = 0, 0, {}
for _mr_r in [r for r in _mr_rows if r["ev"] == "buffer"]:
    if _mr_r["event"] in ("staged", "output"):
        _mr_alive[_mr_r["buf"]] = _mr_r["bytes"]
    elif _mr_r["event"] in ("freed", "donated"):
        _mr_alive.pop(_mr_r["buf"], None)
    # "restored" is a zero-delta provenance row (ckpt state re-enters
    # through its own device_put, already counted) — live unchanged
    _mr_live = sum(_mr_alive.values())
    _mr_peak = max(_mr_peak, _mr_live)
    assert _mr_r["live_bytes"] == _mr_live
    assert _mr_r["peak_bytes"] == _mr_peak
assert _mr_peak >= _mr_x.nbytes
# the donated input is GONE from the live set (runtime HL303 twin)
(_mr_dn,) = [r for r in _mr_rows if r["ev"] == "dispatch"]
assert _mr_dn["donated_bytes"] == _mr_x.nbytes
assert _mr_x.nbytes not in _mr_alive.values()
assert ("restored",) == tuple({r["event"] for r in _mr_rows
                               if str(r.get("label", "")).startswith("ckpt:")})
_mr_errs = _st_cj.check_file(_mr_out, provenance=True)
assert _mr_errs == [], _mr_errs

# (b) AOT cache sidecar: compile writes it, warm load replays it
_mr_cache = _MrCache(_mr_dir, fingerprint="drive39")
_mr_jit = jax.jit(lambda v: v * 2.0)
_mr_args = (jnp.zeros((8, 8), jnp.float32),)
with _mr_tm.scope(True):
    _mr_cache.get_or_compile("drive.prog", _mr_jit, _mr_args)
    (_mr_c,) = [r for r in _mr.ledger._rows if r["ev"] == "executable"]
    assert _mr_c["source"] == "compile" and _mr_c["exec_hbm_bytes"] > 0
assert [f for f in os.listdir(_mr_dir) if f.endswith(".mem.json")]
_mr_fp = _mr_cache.footprint("drive.prog", _mr_args)
assert _mr_fp["argument_bytes"] == 256
with _mr_tm.scope(True):
    _mr_cache.load("drive.prog", _mr_args)
    (_mr_w,) = [r for r in _mr.ledger._rows if r["ev"] == "executable"]
    assert _mr_w["source"] == "cache"
    assert _mr_w["exec_hbm_bytes"] == _mr_c["exec_hbm_bytes"]

# (c) CLI round trip: exit 0 + stamped row matching the replay; exit 2
_mr_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
_mr_cli = _mr_sp.run(
    [sys.executable, "-m", "harp_tpu", "memory", _mr_out, "--json"],
    capture_output=True, text=True, timeout=300, env=_mr_env,
    cwd=_st_root)
assert _mr_cli.returncode == 0, _mr_cli.stderr[-800:]
_mr_row = _mr_json.loads(_mr_cli.stdout.strip().splitlines()[-1])
assert _mr_row["errors"] == [] and _mr_row["peak_hbm_bytes"] == _mr_peak
assert _mr_row["vmem_refusals"] == 1
assert all(k in _mr_row for k in ("backend", "date", "commit"))
_mr_bad = _mr_sp.run(
    [sys.executable, "-m", "harp_tpu", "memory",
     os.path.join(_mr_dir, "nope.jsonl")],
    capture_output=True, text=True, timeout=300, env=_mr_env,
    cwd=_st_root)
assert _mr_bad.returncode == 2, _mr_bad.returncode

# (d) zero-cost off: no hook records anything with telemetry disabled
_mr.reset()
_ = mesh.shard_array(_mr_x)
_ = _mr_step(mesh.shard_array(_mr_x))
assert _mr.ledger._rows == [] and _mr.snapshot()["events"] == 0

print(f"memrec: lifecycle replay == watermark (peak {_mr_peak} B, "
      f"donated {_mr_dn['donated_bytes']} B gone at dispatch), ckpt "
      "restore labeled, over-VMEM refused pre-dispatch naming "
      f"{_mr_pred} B, export invariant-17 clean with "
      f"{len(_mr_marks)} superstep memory mark(s), cache sidecar "
      "compile==warm-load bytes, CLI exit 0/2, zero-cost off")
print(f"DRIVE OK round-39 ({mode})")

# ---------------------------------------------------------------------------
# round 40 — host-concurrency auditor + thread-ownership twin (PR 20).
# (a) the static layer's ownership map, generated from the thread-root
# graph over the REAL planes, names the watchdog / scheduler workers /
# TCP accept loop as forbidden and leaves the serve dispatcher (the
# designated jax owner) alone; every Layer-5 finding at HEAD is a
# reviewed HL403 allowlist entry and the scoped lint CLI exits 0;
# (b) the runtime twin armed around a REAL socket serve under an
# injected transient dispatch fault: the guard audits live traffic
# (checks > 0), objects to none of it, and the responses still match
# numpy; scheduler workers run under names the static patterns match;
# (c) a thread wearing a forbidden name is caught at a flightrec
# observer site; (d) disarmed, the observer registries and spine
# mutators restore exactly (zero-install contract).
# ---------------------------------------------------------------------------
import fnmatch as _tg_fn
import json as _tg_json
import socket as _tg_sock
import subprocess as _tg_sp
import tempfile as _tg_tmp
import threading as _tg_th

from harp_tpu.analysis import allowlist as _tg_al
from harp_tpu.analysis import threadgraph as _tg
from harp_tpu.schedule import StaticScheduler as _TgSched
from harp_tpu.serve.engines import ENGINES as _TG_ENGINES
from harp_tpu.serve.server import Server as _TgServer
from harp_tpu.serve.transport import TCPFrontEnd as _TgFE
from harp_tpu.utils import flightrec as _tg_fr
from harp_tpu.utils import telemetry as _tg_tm
from harp_tpu.utils import threadguard as _tg_guard
from harp_tpu.utils.fault import FaultInjector as _TgInj

_tg_repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (a) static half: generated map + HEAD findings all reviewed
_tg_omap = _tg.ownership_map(_tg_repo)
_tg_pats = _tg_omap["forbidden_thread_patterns"]
assert "harp-watchdog" in _tg_pats and "harp-serve-tcp" in _tg_pats
assert any(p.startswith("harp-sched-") for p in _tg_pats)
assert not any(_tg_fn.fnmatch("harp-serve-dispatch", p)
               for p in _tg_pats)
assert _tg_omap["spines"]["reqtrace"]["locked"] is True
_tg_vs = _tg.analyze_repo(_tg_repo)
_tg_kept, _tg_sup, _ = _tg_al.apply(_tg_vs, _tg_al.load())
assert _tg_kept == [] and {v.rule for v in _tg_sup} == {"HL403"}
_tg_cli = _tg_sp.run(
    [sys.executable, "-m", "harp_tpu", "lint", "--layer", "threads",
     "--json"], capture_output=True, text=True, cwd=_tg_repo)
assert _tg_cli.returncode == 0, _tg_cli.stdout + _tg_cli.stderr
_tg_row = _tg_json.loads(_tg_cli.stdout.strip().splitlines()[-1])
assert _tg_row["clean"] is True and _tg_row["stale_allowlist"] == 0

# (b) runtime twin armed around a real-socket serve under chaos
_tg_regs = (_tg_fr._READBACK_OBSERVERS, _tg_fr._DISPATCH_OBSERVERS,
            _tg_fr._H2D_OBSERVERS, _tg_fr._CKPT_WRITE_OBSERVERS)
_tg_before = [list(r) for r in _tg_regs]
_tg_orig_h2d = _tg_fr.record_h2d
_tg_rng = np.random.default_rng(40)
with _tg_tm.scope(True):
    _tg_state = _TG_ENGINES["kmeans"].synthetic_state(_tg_rng, k=8, d=16)
    _tg_srv = _TgServer("kmeans", state=_tg_state, mesh=mesh,
                        ladder=(1, 8), cache_dir=_tg_tmp.mkdtemp(),
                        budget_action="warn")
    _tg_srv.startup()
    _tg_inj = _TgInj(seed=0, fail={"dispatch": (2,)})
    with _tg_guard.armed() as _tg_g, _tg_inj.arm():
        _tg_fe = _TgFE(_tg_srv, port=0, max_retries=2).start_in_thread()
        try:
            _tg_s = _tg_sock.create_connection(
                ("127.0.0.1", _tg_fe.port), timeout=60)
            _tg_f = _tg_s.makefile("rw")
            _tg_xs = [_tg_rng.normal(size=(2, 16)).astype(np.float32)
                      for _ in range(6)]
            for _tg_i, _tg_x in enumerate(_tg_xs):
                _tg_f.write(_tg_json.dumps(
                    {"id": _tg_i, "x": _tg_x.tolist()}) + "\n")
            _tg_f.flush()
            _tg_got = [_tg_json.loads(_tg_f.readline()) for _ in range(6)]
            _tg_s.close()
        finally:
            _tg_fe.shutdown()
            _tg_fe.join(60)
        # scheduler workers run under statically-forbidden names
        _tg_names = []
        _TgSched(lambda _x: _tg_names.append(
            _tg_th.current_thread().name), n_threads=2).schedule([1, 2])
        assert all(any(_tg_fn.fnmatch(n, p) for p in _tg_pats)
                   for n in _tg_names)
        # (c) a forbidden name is caught at an observer site
        _tg_box = []

        def _tg_evil():
            try:
                _tg_fr.readback(jnp.zeros(2))
            except _tg_guard.ThreadOwnershipError as e:
                _tg_box.append(e)

        _tg_t = _tg_th.Thread(target=_tg_evil, name="harp-watchdog",
                              daemon=True)
        _tg_t.start()
        _tg_t.join(30)
        assert len(_tg_box) == 1 and "harp-watchdog" in str(_tg_box[0])
    assert _tg_inj.injected["dispatch"] == 1
    assert _tg_fe.runner.fault_retries >= 1
    assert _tg_g.checks > 0
    assert _tg_g.violations == [str(_tg_box[0])]  # ONLY the seeded one
    _tg_cent = _tg_state["centroids"]
    for _tg_r, _tg_x in zip(_tg_got, _tg_xs):
        _tg_ref = np.argmin(((_tg_x[:, None, :] - _tg_cent[None]) ** 2
                             ).sum(-1), 1)
        assert _tg_r["result"] == _tg_ref.tolist()
# (d) zero-install after disarm
assert [list(r) for r in _tg_regs] == _tg_before
assert _tg_fr.record_h2d is _tg_orig_h2d
assert _tg_guard.stats()["active"] is False

print(f"threadguard: map generated ({len(_tg_pats)} forbidden patterns, "
      f"{len(_tg_sup)} reviewed HL403), scoped lint clean, chaos serve "
      f"audited {_tg_g.checks} site crossings with 0 violations "
      f"(retry absorbed {_tg_fe.runner.fault_retries} injected fault), "
      f"forbidden-name readback caught, observers restored exactly")
print(f"DRIVE OK round-40 ({mode})")
