#!/usr/bin/env python
"""Per-config XLA op-breakdown capture — the trace half of the perf story.

VERDICT r2 item 7: the roofline annotations (utils/roofline.py) are
analytic models; this script backs them with traces.  For each graded
config it runs a SHORT benchmark inside ``utils.profiling.trace``, then
records the top device ops by total time next to the benchmark dict and
its roofline fields, one JSON line per config → ``PROFILE_local.jsonl``.

Read the output asking two questions per config:
1. does the op class the roofline model says is the bound (matmul vs
   memory-bound scatter/gather) actually dominate the trace?
2. is there an op eating >10% that the model has no term for?

`./scripts/measure_on_relay.sh` runs this AFTER the sweep (bounded
2400 s; a relay death then costs only the partial PROFILE_local).
Works on CPU too for plumbing checks (--smoke --platform cpu), but CPU
traces have no device track so compile/host events appear in the table
(op_breakdown's device filter only engages on TPU, where each
benchmark's internal compile lands on the host track and the op table
is pure device time).
"""

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))  # bench_common


def profiled_configs(smoke: bool):
    """Short-running variants: one trace needs seconds, not minutes."""
    from bench_common import SMOKE
    from harp_tpu.models import kmeans, lda, mfsgd, mlp, rf, subgraph

    from measure_all import BENCH_DATA

    small = {name: SMOKE[name]
             for name in ("kmeans", "mfsgd", "lda", "mlp", "subgraph", "rf")}
    full = {"kmeans": {"n": 1_000_000, "d": 300, "k": 100, "iters": 10},
            "mfsgd": {"epochs": 2},
            "lda": {"epochs": 1, "pack_cache": BENCH_DATA},
            "mlp": {"steps": 50},
            "subgraph": {},
            "rf": {}}
    mods = {"kmeans": kmeans, "mfsgd": mfsgd, "lda": lda, "mlp": mlp,
            "subgraph": subgraph, "rf": rf}
    kw = small if smoke else full
    configs = {name: (mods[name], kw[name]) for name in mods}
    # candidate variants traced next to their baselines so the op tables
    # ATTRIBUTE the wins (and answer the queued decisions: Db/W-carry,
    # exprace/rbg, fused kernels, overflow-tail formulation)
    configs["mfsgd_pallas"] = (
        mfsgd, {"algo": "pallas",
                **(SMOKE["mfsgd_pallas"] if smoke else kw["mfsgd"])})
    configs["mfsgd_carry"] = (mfsgd, {**kw["mfsgd"], "carry_w": True})
    configs["lda_fast"] = (lda, {**kw["lda"], "sampler": "exprace",
                                 "rng_impl": "rbg"})
    configs["lda_pallas"] = (
        lda, {"algo": "pallas",
              **(SMOKE["lda_pallas"] if smoke else kw["lda"])})
    configs["lda_carry"] = (lda, {**kw["lda"], "carry_db": True})
    configs["lda_pallas_carry"] = (
        lda, {"algo": "pallas", "carry_db": True,
              **(SMOKE["lda_pallas"] if smoke else kw["lda"])})
    # overflow-tail A/B on a graph whose tail carries real mass (the
    # uniform default's tail is empty — the r2-item-7 profile question
    # needs the powerlaw shape)
    pl = ({**SMOKE["subgraph"], "max_degree": 8} if smoke
          else {"max_degree": 16})
    configs["subgraph_pl"] = (subgraph, {**pl, "graph": "powerlaw"})
    configs["subgraph_onehot"] = (
        subgraph, {**pl, "graph": "powerlaw", "overflow_algo": "onehot"})
    return configs


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="PROFILE_local.jsonl")
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--top", type=int, default=15)
    p.add_argument("--only", nargs="+", default=None)
    p.add_argument("--platform", choices=["cpu"], default=None)
    args = p.parse_args(argv)
    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    from harp_tpu.utils.profiling import op_breakdown, trace
    from harp_tpu.utils.roofline import annotate
    from harp_tpu.utils.timing import HangWatchdog

    sink = open(args.out, "a")
    watchdog = HangWatchdog(on_fire=lambda what: (
        sink.write(json.dumps({"config": what, "error": "hang"}) + "\n"),
        sink.flush()))
    watchdog.arm("backend init")
    for name, (mod, kw) in profiled_configs(args.smoke).items():
        if args.only and name not in args.only:
            continue
        watchdog.arm(name)
        logdir = tempfile.mkdtemp(prefix=f"harp_prof_{name}_")
        try:
            mod.benchmark(**kw)  # warmup/compile OUTSIDE the trace
            with trace(logdir):
                result = mod.benchmark(**kw)
            ops = op_breakdown(logdir, top=args.top)
        except Exception as e:
            rec = {"config": name, "error": f"{type(e).__name__}: {e}",
                   "trace_dir": logdir}
        else:
            # an empty op table (relay died mid-trace, all spans filtered)
            # is a per-config error, not a sweep-aborting ZeroDivision
            traced = sum(t for _, t in ops) or 1.0
            raw = op_breakdown(logdir, top=args.top, self_time=False)
            rec = {"config": name,
                   **{k: (round(v, 4) if isinstance(v, float) else v)
                      for k, v in annotate(name, result).items()},
                   # op_breakdown has never parsed a REAL TPU trace; keep
                   # the trace dir + the raw (non-self-time) table so the
                   # window's capture can be re-analyzed from disk if the
                   # self-time parse turns out wrong on device tracks
                   "trace_dir": logdir,
                   "top_ops": [{"op": o, "sec": round(t, 5),
                                "share_of_traced": round(t / traced, 3)}
                               for o, t in ops],
                   "top_ops_raw": [{"op": o, "sec": round(t, 5)}
                                   for o, t in raw]}
        line = json.dumps(rec)
        print(line, flush=True)
        sink.write(line + "\n")
        sink.flush()
    watchdog.cancel()
    sink.close()


if __name__ == "__main__":
    main()
