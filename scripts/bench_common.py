"""Shared benchmark-shape presets for the measurement scripts.

THE smoke shapes, in one place: `measure_all.py`, `profile_on_relay.py`
and `sweep_pallas.py` all shrink the graded configs to these for fast
CPU-safe passes — a shape change must hit all three identically or the
scripts silently measure different programs (review finding, round 3).
Full graded shapes stay in measure_all (they are the specification of
the sweep, not a tuning knob).
"""

#: per-model smoke kwargs (CPU-safe, seconds per config)
SMOKE = {
    "kmeans": {"n": 8192, "d": 32, "k": 16, "iters": 10},
    "kmeans_stream": {"n": 65536, "d": 16, "k": 16, "iters": 2,
                      "chunk_points": 8192},
    "mfsgd": {"n_users": 512, "n_items": 256, "nnz": 20_000, "rank": 8,
              "epochs": 2, "u_tile": 16, "i_tile": 16, "entry_cap": 256},
    # the pallas kernels gate 128-multiple tiles on TPU
    "mfsgd_pallas": {"n_users": 512, "n_items": 256, "nnz": 20_000,
                     "rank": 8, "epochs": 2, "u_tile": 128, "i_tile": 128,
                     "entry_cap": 256},
    "mfsgd_scatter": {"n_users": 512, "n_items": 256, "nnz": 20_000,
                      "rank": 8, "epochs": 2, "chunk": 1024},
    "lda": {"n_docs": 256, "vocab_size": 128, "n_topics": 8,
            "tokens_per_doc": 16, "epochs": 1, "d_tile": 16, "w_tile": 16,
            "entry_cap": 64},
    "lda_pallas": {"n_docs": 256, "vocab_size": 128, "n_topics": 8,
                   "tokens_per_doc": 16, "epochs": 1, "d_tile": 128,
                   "w_tile": 128, "entry_cap": 64},
    "lda_scatter": {"n_docs": 256, "vocab_size": 128, "n_topics": 8,
                    "tokens_per_doc": 16, "epochs": 1, "chunk": 256},
    "mlp": {"n": 4096, "batch": 512, "steps": 5},
    # serving (PR 6): tiny ladder + state, seconds on the CPU sim; the
    # state_shape kwargs feed the engines' synthetic_state
    "serve_kmeans": {"n_requests": 48, "rows_per_request": 2,
                     "burst": 16, "ladder": (1, 8, 32),
                     "state_shape": {"k": 16, "d": 32}},
    "serve_mfsgd_topk": {"n_requests": 48, "rows_per_request": 2,
                         "burst": 16, "ladder": (1, 8, 32),
                         "state_shape": {"n_users": 256, "n_items": 128,
                                         "rank": 8}},
    # sustained continuous-batching A/B (PR 7): n_requests must exceed
    # the max rung or the backlog can never fill a max-rung batch and
    # the A/B reads ~1.0x at any truth (measured: 256 requests on the
    # 512 ladder gave 0.96x; 2048 gave 1.78x) — the smoke ladder tops
    # at 32 so 96 requests keep the same property in seconds
    "serve_kmeans_sustained": {"n_requests": 96, "rows_per_request": 1,
                               "burst_admit": 8, "ladder": (1, 8, 32),
                               "state_shape": {"k": 16, "d": 32}},
    "serve_mfsgd_sustained": {"n_requests": 96, "rows_per_request": 1,
                              "burst_admit": 8, "ladder": (1, 8, 32),
                              "state_shape": {"n_users": 256,
                                              "n_items": 128,
                                              "rank": 8}},
    "subgraph": {"n_vertices": 2000, "avg_degree": 4},
    "rf": {"n": 4096, "f": 16, "max_depth": 3, "n_trees": 2},
    # PR 12: first svm/wdamds sweep rows (incumbents of the new wire
    # candidates) — small enough for seconds on the CPU sim
    "svm": {"n": 4096, "d": 32},
    "wdamds": {"n": 256},
}

# PR 11 planner candidates measure the SAME shapes as their incumbents
# (only the collective schedule differs — an A/B over different shapes
# would attribute shape noise to the schedule): aliases, not copies, so
# an incumbent smoke-shape change can never drift the pair apart.
SMOKE["kmeans_hier_psum"] = SMOKE["kmeans"]
SMOKE["lda_planner_wire"] = SMOKE["lda_pallas"]
# PR 12 wire candidates measure their incumbents' shapes (only the
# exchange wire differs) — aliases so the pairs can never drift apart
SMOKE["svm_sv_bf16"] = SMOKE["svm_sv_int8"] = SMOKE["svm"]
SMOKE["wdamds_coord_bf16"] = SMOKE["wdamds_coord_int8"] = SMOKE["wdamds"]
# PR 16 profile-priced candidates measure their incumbents' shapes (only
# a dtype / histogram formulation / CSR width differs) — aliases again
SMOKE["rf_dense_hist"] = SMOKE["rf_scatter_hist"] = SMOKE["rf"]
SMOKE["svm_x_bf16"] = SMOKE["svm"]
SMOKE["wdamds_delta_bf16"] = SMOKE["wdamds"]
SMOKE["subgraph_csr32"] = SMOKE["subgraph"]
# PR 17 kernelized arms measure their incumbents' shapes (only the
# kernel schedule differs) — aliases again.  The shared shapes keep the
# pallas branches ENGAGED in smoke mode: svm pads d to 128 lanes
# regardless; wdamds n=256 pads to a 128-multiple; rf f=16 × 32 bins
# gives fB = 512 (odd widths would silently fall back to the XLA arms).
SMOKE["svm_kernel_pallas"] = SMOKE["svm"]
SMOKE["wdamds_dist_pallas"] = SMOKE["wdamds"]
SMOKE["rf_hist_pallas"] = SMOKE["rf"]
