#!/usr/bin/env python
"""Validate the committed measurement JSONL files.

Two invariants, enforced as a tier-1 test (tests/test_check_jsonl.py) and
runnable standalone (``python scripts/check_jsonl.py [--repo DIR]``):

1. **Every line parses as JSON.**  The relay sprint tees CLI stdout into
   these files; a Python dict repr or a line truncated by a killed sprint
   is a record every downstream reader silently skips — make it loud.

2. **Bench rows carry the provenance stamp** (``backend``, ``date``,
   ``commit`` — the fields :func:`harp_tpu.utils.metrics._provenance`
   writes).  This is the CPU-inversion guard from metrics.py: a
   config-keyed row WITHOUT ``backend`` can pass downstream TPU-evidence
   filters (``flip_decision.latest_rows``, bench.py ``_last_measured``
   exclude only ``backend == "cpu"``), so an unstamped CPU record reads
   as silicon evidence.  Rows committed before the stamp existed are
   grandfathered BY LINE INDEX (the history is append-only; reannotate.py
   rewrites rows in place), so every row appended after this check landed
   must comply — "my row has no date, so I look legacy" is not a loophole.

PROFILE_local.jsonl and FLIP_DECISIONS.jsonl rows are trace/decision rows,
not bench evidence: they get the parse check only — plus invariants 3/4:

3. **CommLedger rows carry a coherent wire dtype** (any file): a
   ``kind: "comm"`` row for a quantized verb must record ``wire_dtype``
   in {bfloat16, int8}, and an exact rotate/regroup row must not claim
   one — the report's bytes-on-wire claims scale by this field.

4. **Flight-recorder rows are coherent evidence** (any file): a ``kind:
   "compile"`` / ``kind: "transfer"`` row must parse, carry the
   backend/date/commit provenance stamp (a CPU-sim compile count must
   never read as relay evidence — the same inversion guard as check 2),
   and its counters (count/dur/total_s/bytes/calls) must be non-negative
   numbers, with a compile row's cumulative ``count``/``total_s``
   monotone non-decreasing down the file (a decrease means two runs'
   exports were interleaved — every downstream "N compiles this run"
   claim would be wrong).

5. **Skew rows are coherent load evidence** (any file): a ``kind:
   "skew"`` row (the SkewLedger export, :mod:`harp_tpu.utils.skew`) must
   carry the provenance stamp (a CPU-sim load sheet must never read as
   relay evidence), its per-worker ``work`` counts must be non-negative
   numbers that SUM to the row's ``total`` (a mismatch means the
   imbalance ratio describes a different workload than the total
   claims), and ``padding_frac`` — when present — must lie in [0, 1].

6. **Lint rows are coherent analysis evidence** (any file): a ``kind:
   "lint"`` row (``python -m harp_tpu lint``) must carry the provenance
   stamp (a lint verdict is about a specific commit — an unstamped
   "clean" can certify the wrong tree), every rule id it mentions (in
   ``rules`` or as a ``per_rule`` key) must come from the registered set
   (``KNOWN_LINT_RULES`` — kept in sync with
   ``harp_tpu.analysis.rules`` by tests/test_lint.py), and the
   per-file/per-rule violation counts must be non-negative integers.
   CommGraph extension (PR 9): a lint row's per-program ``byte_sheets``
   (the Layer-4 static collective schedule — the planner's future
   input) must name programs from the drivers registry
   (``KNOWN_LINT_PROGRAMS``), primitives/verbs from the frozen wire
   vocabulary (``KNOWN_COMM_PRIMITIVES`` / ``KNOWN_COMM_VERBS``), and
   carry non-negative byte/count fields — a sheet naming an unknown
   program or claiming negative bytes would poison every schedule
   decision built on it.

7. **Serve rows are coherent serving evidence** (any file): a ``kind:
   "serve"`` row (``harp_tpu.serve.bench`` / ``serve <app> --bench``)
   must carry the provenance stamp, its latency percentiles must be
   non-negative and monotone (``p50_ms <= p95_ms <= p99_ms`` — crossed
   percentiles mean the latency sample was mangled), ``qps`` must be a
   positive number, and ``steady_compiles`` must be EXACTLY 0 — the
   serving loop's whole contract is that the steady state never
   recompiles, so a row that measured throughput while silently
   compiling per batch is not serving evidence at all.  SUSTAINED serve
   rows (the continuous-batching A/B, ``serve.bench.benchmark_
   sustained`` — recognizable by ``offered_qps``/``achieved_qps`` or
   ``mode == "sustained"``) additionally must satisfy ``offered_qps >=
   achieved_qps > 0`` (achieved above offered means the latency origin
   was not the arrival trace — the burst-submit dishonesty this mode
   exists to fix) and carry non-negative queue-depth percentiles
   (``qdepth_p50``/``qdepth_p95``/``qdepth_p99``): a sustained row
   without queue evidence cannot support any claim about the
   padding-vs-latency tradeoff its knobs encode.

8. **Ingest rows are coherent streaming evidence** (any file): a ``kind:
   "ingest"`` row (``kmeans_stream.benchmark_ingest`` /
   ``scripts/bench_ingest.py``, PR 8) must carry the provenance stamp
   (a CPU host-chain rate must never read as relay-tunnel evidence),
   its ``overlap_efficiency`` (the host pipeline's stage-overlap score)
   must lie in [0, 1], and its rates must be positive:
   ``host_gb_per_sec > 0`` and ``points_per_sec > 0`` — a zero or
   negative rate means the instrument block never ran, and such a row
   grading the ingest fast path would certify a measurement that did
   not happen.

9. **Degraded-mode serve rows balance their books** (any file): a serve
   row carrying the fault-plane fields (``serve.bench.
   benchmark_sustained`` under shedding/deadlines/chaos, PR 10 —
   recognizable by any of ``shed_frac`` / ``deadline_miss_frac`` /
   ``fault_retries`` / ``shed_requests``) must carry ALL of them
   coherently: ``shed_frac`` and ``deadline_miss_frac`` in [0, 1],
   ``fault_retries`` a non-negative integer, and the request ledger
   exact — ``served_requests + shed_requests + failed_requests ==
   offered_requests`` (every offered request came back as exactly one
   of served / structured-shed / hard-failed; a row where requests
   vanish is not degradation evidence, it is a dead server wearing a
   qps number).

10. **Plan rows are coherent schedule evidence** (any file): a ``kind:
    "plan"`` row (``python -m harp_tpu plan``, PR 11) must carry the
    provenance stamp (a schedule decision is about a specific commit's
    byte sheets), name a registered driver program
    (``KNOWN_LINT_PROGRAMS``) and a frozen topology tag
    (``KNOWN_PLAN_TOPOLOGIES``), choose every site's schedule from the
    frozen vocabulary (``KNOWN_PLAN_SCHEDULES``) — and today that
    chosen schedule must be ``"keep"``: the planner FAILS CLOSED, so a
    committed row claiming any other choice is evidence of a bypassed
    flip gate — with per-site ``predicted_bytes`` equal to the frozen
    schedule scaling of the site's ``sheet_bytes`` (for ``keep``,
    exactly the program's byte sheet: a plan whose predictions drift
    from the sheet is pricing a program this repo does not run).

11. **Trace rows are a complete causal timeline** (any file): a ``kind:
    "trace"`` row (``harp_tpu.utils.reqtrace`` — ``telemetry.export`` /
    ``export_timeline``, PR 12) must carry the provenance stamp (a
    CPU-sim request timeline must never read as relay latency
    evidence), declare a known row shape (``ev`` ∈
    ``KNOWN_TRACE_EVS``), and carry a numeric non-negative ``ts`` that
    is MONOTONE non-decreasing down the file (the exporters sort — a
    decrease means two runs' timelines were interleaved, and a
    "causally ordered" file that is not ordered is not a timeline).
    Every request id seen in an ``ev:"event"`` row must have a
    TERMINATED ``ev:"request"`` row whose ``outcome`` ∈
    ``KNOWN_TRACE_OUTCOMES`` (served / shed / failed — an offered
    request that simply vanishes from its own trace is the exact
    failure mode request tracing exists to make impossible), and when
    the same file carries exactly one invariant-9 degraded-mode serve
    row, the per-outcome request counts must reconcile with that
    ledger EXACTLY (served == served_requests, etc.): a trace and a
    bench row telling different stories about the same run means one
    of them is lying.

12. **Model rows are coherent prediction evidence** (any file): a
    ``kind: "model"`` row (``python -m harp_tpu predict``, PR 13 —
    :mod:`harp_tpu.perfmodel`) must carry the provenance stamp (a
    prediction is about a specific commit's byte sheets and work
    models), name a registered program (``KNOWN_LINT_PROGRAMS``)
    and/or a config from the sprint surface (``KNOWN_MODEL_CONFIGS``
    — frozen against ``measure_all.SPRINT_ORDER``: a model row
    referencing a config the sprint cannot run prunes nothing), stamp
    ``rates_source`` and ``bound`` from the frozen vocabularies
    (``KNOWN_MODEL_RATES_SOURCES`` / ``KNOWN_MODEL_BOUNDS`` —
    sync-pinned against ``harp_tpu.perfmodel`` by
    tests/test_perfmodel.py), predict POSITIVE seconds, carry all four
    per-term entries summing to ``predicted_s`` within float
    tolerance, and name as ``bound`` the largest term — a breakdown
    that does not reconcile with its own total is a wrong prediction
    that cannot even be diagnosed, which is the one thing a model row
    exists to prevent.

13. **Health rows are coherent monitoring evidence** (any file): a
    ``kind: "health"`` row (the PR-14 sentinel — ``harp_tpu.health``,
    exported by ``telemetry.export`` / emitted by ``python -m harp_tpu
    health --grade-model``) must carry the provenance stamp (a CPU-sim
    finding must never read as relay degradation evidence), name a
    registered detector and severity (``KNOWN_HEALTH_DETECTORS`` /
    ``KNOWN_HEALTH_SEVERITIES`` — frozen standalone and sync-pinned
    against ``harp_tpu.health`` by tests), carry non-negative integer
    counts and non-negative burn/ratio numbers, and — per detector —
    an ``evidence_regression`` row MUST carry a ``verdict`` from
    ``KNOWN_HEALTH_VERDICTS`` (``model_invalidated`` is the one that
    fails ``measure_all --predicted-top`` closed), while a
    ``skew_trigger`` row MUST carry a structurally valid inline
    rebalance plan (``schedule.apply_rebalance``'s input shape:
    ``phase``, ``moves`` with non-negative worker ids and work, numeric
    before/after ratios) — the elastic-execution hook is only a hook if
    its payload is replayable.

14. **Elastic rows are coherent elasticity evidence** (any file): a
    ``kind:"elastic"`` row (the PR-15 acting half —
    :mod:`harp_tpu.elastic`, exported by ``telemetry.export``) must
    carry the provenance stamp (a CPU-sim drill must never read as
    relay elasticity evidence), name an event from the frozen
    vocabulary (``KNOWN_ELASTIC_EVENTS``: rebalance / shrink / resume —
    sync-pinned against ``harp_tpu.elastic.EVENTS`` by
    tests/test_check_jsonl.py), carry per-worker load lists of
    non-negative numbers that SUM to the row's ``total``, and per
    event: a ``rebalance`` row must carry ``wasted_frac_before``/
    ``wasted_frac_after`` in [0, 1] with after ≤ before (a "rebalance"
    that made the imbalance worse is not rebalance evidence), and a
    ``shrink`` row must show the survivor count strictly below the
    pre-fault count (``n_workers_after < n_workers_before``) — a
    shrink that lost no worker describes a fault that did not happen.

15. **Profile rows are coherent attribution evidence** (any file): a
    ``kind:"profile"`` row (the PR-16 wall-attribution observatory —
    ``python -m harp_tpu profile``, :mod:`harp_tpu.profile`) must carry
    the provenance stamp (a CPU-sim attribution must never read as
    silicon wall evidence), name an app and driver program from the
    frozen vocabularies (``KNOWN_PROFILE_APPS`` /
    ``KNOWN_LINT_PROGRAMS`` — sync-pinned against
    ``harp_tpu.profile.attribution.PROFILE_APPS`` by
    tests/test_check_jsonl.py), carry exactly the six frozen mechanism
    buckets (``KNOWN_PROFILE_BUCKETS``) as non-negative ``*_s`` terms
    that SUM to the measured ``wall_s`` (the whole contract: every
    wall second is attributed to a mechanism, residual in overhead),
    name as ``bound`` the largest bucket (the wall the row claims),
    keep ``sum_rel_err`` within ``PROFILE_SUM_REL_TOL`` (sync-pinned
    against ``attribution.SUM_REL_TOL``), and reconcile against the
    other spines fail-closed: ``dispatches == reps *
    dispatches_per_rep`` (flight recorder), ``compiles_in_window ==
    0`` (a row that compiled mid-capture timed the compiler),
    ``wire_unmatched == 0`` (every static collective site carries a
    CommLedger verb match), and ``reconciled`` literally true — an
    unreconciled attribution committed as evidence is exactly the
    hand-read-profile ritual this row type replaces.

16. **Steptrace rows are a complete causal training timeline** (any
    file): a ``kind:"steptrace"`` row (the PR-18 superstep flightpath —
    :mod:`harp_tpu.utils.steptrace`, exported by ``telemetry.export`` /
    ``export_timeline``) must carry the provenance stamp (a CPU-sim
    training timeline must never read as relay evidence), declare a
    known row shape (``ev`` ∈ ``KNOWN_STEPTRACE_EVS``), and carry a
    numeric non-negative ``ts`` MONOTONE non-decreasing across the
    file's steptrace rows.  Every superstep span must terminate with an
    outcome from ``KNOWN_STEPTRACE_OUTCOMES`` and attribute exactly the
    frozen flight counters (``KNOWN_STEPTRACE_FLIGHT_KEYS``); every
    run id seen in span/mark/lane rows must close in exactly one
    ``ev:"run"`` row, whose declared ``supersteps`` / per-outcome
    counts / ``span_flight`` sums / ``marks`` / ``lanes`` are
    re-derived from the rows and must match EXACTLY.  Cross-spine,
    fail closed: each run's ``flight.dispatches`` must equal its
    dispatch-mark count (the flightrec observer path vs the
    TransferLedger counters — two independent spines), the file's runs
    cannot attribute more dispatches than its ``kind:"transfer"``
    dispatch rows record, elastic marks must match the file's
    timeline-covered ``kind:"elastic"`` rows (``on_timeline: true``)
    event-for-event (a rebalance on the timeline that the elastic
    ledger never recorded — or vice versa — means one spine is lying;
    rows recorded outside any run are legitimately unmarked), every
    health mark must name a detector
    with a ``kind:"health"`` row, and every ``consume_skew_trigger``
    actuation mark must point at a CONSUMED ``skew_trigger`` finding —
    the exactly-once handshake leaves ledger evidence or it did not
    happen.

17. **Memory rows are a replayable device-memory ledger** (any file): a
    ``kind:"memory"`` row (the PR-19 memory spine —
    :mod:`harp_tpu.utils.memrec`, exported by ``telemetry.export``)
    must carry the provenance stamp (a CPU-sim footprint must never
    read as silicon HBM evidence), declare a known row shape (``ev`` ∈
    ``KNOWN_MEMORY_EVS``; buffer rows additionally ``event`` ∈
    ``KNOWN_MEMORY_EVENTS``) with a strictly increasing ``seq``, and
    the ledger must REPLAY: re-deriving the live set from the buffer
    event stream (staged/output add, freed/donated remove — a
    freed/donated buffer must BE live; ``restored`` is zero-delta by
    design), every row's ``live_bytes``/``peak_bytes`` must equal the
    derived watermark EXACTLY; a ``dispatch`` row's donated buffer ids
    must have left the live set (the runtime twin of the HL303
    donation audit); an ``executable`` row's four footprint components
    must sum to its ``exec_hbm_bytes``; a ``vmem_check`` row's
    ``fits``/``refused`` flags must agree with its own
    predicted-vs-budget bytes; and the export must terminate in
    EXACTLY one ``summary`` row whose staged/freed/donated/peak/live
    totals and ``headroom_frac`` (= 1 − peak/hbm) re-derive from the
    stream — buffer events after the summary, or a peak the events
    cannot reproduce, mean the watermark was asserted, not measured.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# line counts at the commit where this check landed (2026-08-04); rows up
# to these indices predate the provenance stamp and are exempt from check
# 2 (never from check 1).  Bump ONLY when deliberately rewriting history.
GRANDFATHERED = {"BENCH_local.jsonl": 73}

PARSE_ONLY = ("PROFILE_local.jsonl", "FLIP_DECISIONS.jsonl",
              "PROFILE_attrib.jsonl")
PROVENANCE_FIELDS = ("backend", "date", "commit")

# CommLedger rows (telemetry exports, teed into committed JSONL by
# HARP_TELEMETRY runs): the quantized movement/reduce verbs MUST name a
# narrow wire, the exact rotate/regroup twins must NOT claim one — a
# wrong wire_dtype silently mis-scales every bytes-on-wire claim the
# report makes (the whole point of the quantized-rotate telemetry).
QUANT_WIRES = ("bfloat16", "int8")
QUANT_VERBS = ("rotate_quantized", "regroup_quantized",
               "allreduce_quantized", "push_quantized")
EXACT_MOVE_VERBS = ("rotate", "regroup")


def _check_comm_row(name: str, i: int, row: dict) -> list[str]:
    verb = row.get("verb")
    wd = row.get("wire_dtype")
    if verb in QUANT_VERBS and wd not in QUANT_WIRES:
        return [f"{name}:{i}: comm row verb={verb!r} has "
                f"wire_dtype={wd!r} — quantized verbs must record one of "
                f"{QUANT_WIRES}"]
    if verb in EXACT_MOVE_VERBS and wd:
        return [f"{name}:{i}: comm row verb={verb!r} claims "
                f"wire_dtype={wd!r} — the exact verbs have no narrow "
                "wire; use the *_quantized twin (or drop the field)"]
    return []


FLIGHT_COUNTER_FIELDS = ("count", "dur", "total_s", "bytes", "calls")
FLIGHT_MONOTONE_FIELDS = ("count", "total_s")  # cumulative per export


def _check_flight_row(name: str, i: int, row: dict,
                      state: dict) -> list[str]:
    """Invariant 4: compile/transfer rows must be coherent evidence.

    ``state`` carries the previous compile row's cumulative counters so
    monotonicity is checked per file in line order.
    """
    errs: list[str] = []
    kind = row.get("kind")
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: {kind} row missing provenance field(s) "
            f"{missing} — export through telemetry.export / "
            "flightrec.export_jsonl, which stamp them")
    for k in FLIGHT_COUNTER_FIELDS:
        v = row.get(k)
        if v is None:
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)) or v < 0:
            errs.append(f"{name}:{i}: {kind} row counter {k}={v!r} must "
                        "be a non-negative number")
    if kind == "compile":
        for k in FLIGHT_MONOTONE_FIELDS:
            v = row.get(k)
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            last = state.get(k)
            if last is not None and v < last:
                errs.append(
                    f"{name}:{i}: compile row {k}={v} decreased from "
                    f"{last} — cumulative counters must be monotone "
                    "(interleaved exports?)")
            state[k] = v
    return errs


def _check_skew_row(name: str, i: int, row: dict) -> list[str]:
    """Invariant 5: skew rows must be coherent load evidence."""
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: skew row missing provenance field(s) {missing} "
            "— export through telemetry.export / skew.export_jsonl, "
            "which stamp them")
    work = row.get("work")
    total = row.get("total")
    if (isinstance(work, list) and work
            and all(isinstance(x, (int, float)) and not isinstance(x, bool)
                    for x in work)):
        if any(x < 0 for x in work):
            errs.append(f"{name}:{i}: skew row has negative per-worker "
                        "work counts")
        if isinstance(total, (int, float)) and not isinstance(total, bool):
            s = sum(work)
            if abs(s - total) > 1e-6 * max(1.0, abs(total)):
                errs.append(
                    f"{name}:{i}: skew row per-worker work sums to {s} "
                    f"but total claims {total} — counts must sum to the "
                    "global total")
    else:
        errs.append(f"{name}:{i}: skew row work={work!r} must be a "
                    "non-empty list of numbers")
    pf = row.get("padding_frac")
    if pf is not None and (isinstance(pf, bool)
                           or not isinstance(pf, (int, float))
                           or not 0.0 <= pf <= 1.0):
        errs.append(f"{name}:{i}: skew row padding_frac={pf!r} must lie "
                    "in [0, 1]")
    return errs


# the registered harplint rule ids, FROZEN here so this script stays
# standalone (no harp_tpu import); tests/test_lint.py asserts equality
# with harp_tpu.analysis.rules.rule_ids() so drift fails tier-1
KNOWN_LINT_RULES = ("HL000", "HL001", "HL002", "HL003", "HL004", "HL005",
                    "HL101", "HL102", "HL201", "HL202", "HL203", "HL204",
                    "HL205", "HL301", "HL302", "HL303", "HL304",
                    "HL401", "HL402", "HL403", "HL404", "HL405")
LINT_COUNT_FIELDS = ("files_scanned", "violations", "allowlisted",
                     "stale_allowlist")

# the CommGraph byte-sheet vocabulary, FROZEN like the rule ids and
# sync-pinned by tests/test_lint.py: program names must come from the
# drivers registry (harp_tpu.analysis.drivers.DRIVERS), primitives from
# the verbs' wire surface (collective.PRIMITIVE_VERBS), verbs from the
# collective verb table — a sheet naming an unknown program or verb is
# not evidence about THIS repo's communication schedule.
KNOWN_LINT_PROGRAMS = (
    "collective.reshard", "collective.reshard_wire",
    "elastic.regather",
    "ingest.accum_chunk", "ingest.finish_epoch", "kmeans.fit",
    "kmeans.fit_hier", "lda.epoch",
    "mfsgd.epoch", "rf.grow", "rf.grow_pallas", "ring_attention",
    "rotate.pipeline_chunked",
    "serve.kmeans_assign", "serve.lda_infer", "serve.mfsgd_topk",
    "serve.mlp_logits", "serve.rf_vote", "serve.svm_scores",
    "subgraph.count", "svm.train", "svm.train_pallas",
    "wdamds.smacof", "wdamds.smacof_pallas")
KNOWN_COMM_PRIMITIVES = ("all_gather", "all_to_all", "pmax", "pmin",
                         "ppermute", "psum", "reduce_scatter")
KNOWN_COMM_VERBS = ("allgather", "allreduce", "allreduce_hier",
                    "allreduce_quantized",
                    "barrier", "broadcast", "pull", "push",
                    "push_quantized", "reduce", "regroup",
                    "regroup_quantized", "reshard", "rotate",
                    "rotate_quantized")
SHEET_BYTE_FIELDS = ("bytes_per_trace", "amplified_bytes")


def _check_lint_row(name: str, i: int, row: dict) -> list[str]:
    """Invariant 6: lint rows must be coherent analysis evidence."""
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: lint row missing provenance field(s) {missing} "
            "— print it through harp_tpu.analysis.cli (benchmark_json "
            "stamps them)")
    mentioned = list(row.get("rules") or []) + list(row.get("per_rule")
                                                   or {})
    unknown = sorted({r for r in mentioned if r not in KNOWN_LINT_RULES})
    if unknown:
        errs.append(
            f"{name}:{i}: lint row mentions unregistered rule id(s) "
            f"{unknown} — ids must come from harp_tpu.analysis.rules "
            "(update KNOWN_LINT_RULES in the same commit as the "
            "registry)")
    counts = dict(row.get("per_file") or {})
    counts.update(row.get("per_rule") or {})
    counts.update({k: row[k] for k in LINT_COUNT_FIELDS if k in row})
    for key, v in counts.items():
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            errs.append(f"{name}:{i}: lint row count {key}={v!r} must be "
                        "a non-negative integer")
    for prog, sheet in (row.get("byte_sheets") or {}).items():
        errs += _check_byte_sheet(name, i, prog, sheet)
    return errs


def _check_byte_sheet(name: str, i: int, prog, sheet) -> list[str]:
    """Invariant 6, CommGraph extension: a lint row's per-program byte
    sheet (the Layer-4 static comm schedule the planner will consume)
    must name a registered driver program, registered primitives/verbs,
    and non-negative byte counts — a malformed sheet poisons every
    schedule decision built on it."""
    errs: list[str] = []
    if prog not in KNOWN_LINT_PROGRAMS:
        errs.append(
            f"{name}:{i}: byte sheet for unregistered program {prog!r} "
            "— program names must come from "
            "harp_tpu.analysis.drivers.DRIVERS (update "
            "KNOWN_LINT_PROGRAMS in the same commit as the registry)")
    if not isinstance(sheet, dict):
        return errs + [f"{name}:{i}: byte sheet for {prog!r} must be an "
                       "object"]
    for k in SHEET_BYTE_FIELDS:
        v = sheet.get(k)
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            errs.append(f"{name}:{i}: byte sheet {prog!r} {k}={v!r} "
                        "must be a non-negative integer")
    for c in sheet.get("collectives") or []:
        if not isinstance(c, dict):
            errs.append(f"{name}:{i}: byte sheet {prog!r} has a "
                        "non-object collective entry")
            continue
        prim = c.get("primitive")
        if prim not in KNOWN_COMM_PRIMITIVES:
            errs.append(
                f"{name}:{i}: byte sheet {prog!r} names unknown "
                f"primitive {prim!r} (known: {KNOWN_COMM_PRIMITIVES})")
        verb = c.get("verb")
        if verb is not None and verb not in KNOWN_COMM_VERBS:
            errs.append(
                f"{name}:{i}: byte sheet {prog!r} names unknown verb "
                f"{verb!r} (known: {KNOWN_COMM_VERBS})")
        for k in ("per_shard_bytes", "calls_per_trace", "amplification"):
            v = c.get(k)
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                errs.append(
                    f"{name}:{i}: byte sheet {prog!r} collective "
                    f"{k}={v!r} must be a non-negative integer")
    return errs


SERVE_PCTL_FIELDS = ("p50_ms", "p95_ms", "p99_ms")


def _check_serve_row(name: str, i: int, row: dict) -> list[str]:
    """Invariant 7: serve rows must be coherent serving evidence."""
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: serve row missing provenance field(s) "
            f"{missing} — print it through "
            "harp_tpu.utils.metrics.benchmark_json")
    pctls = []
    for k in SERVE_PCTL_FIELDS:
        v = row.get(k)
        if (isinstance(v, bool) or not isinstance(v, (int, float))
                or v < 0):
            errs.append(f"{name}:{i}: serve row {k}={v!r} must be a "
                        "non-negative number")
            pctls = None
            break
        pctls.append(v)
    if pctls is not None and not (pctls[0] <= pctls[1] <= pctls[2]):
        errs.append(
            f"{name}:{i}: serve row percentiles p50={pctls[0]} "
            f"p95={pctls[1]} p99={pctls[2]} are not monotone — the "
            "latency sample was mangled")
    qps = row.get("qps")
    if isinstance(qps, bool) or not isinstance(qps, (int, float)) \
            or qps <= 0:
        errs.append(f"{name}:{i}: serve row qps={qps!r} must be a "
                    "positive number")
    sc = row.get("steady_compiles")
    if isinstance(sc, bool) or not isinstance(sc, int) or sc != 0:
        errs.append(
            f"{name}:{i}: serve row steady_compiles={sc!r} must be "
            "exactly 0 — a serving loop that compiles in steady state "
            "violates its own contract (flightrec.SteadyState)")
    if ("offered_qps" in row or "achieved_qps" in row
            or row.get("mode") == "sustained"):
        errs += _check_sustained_serve_row(name, i, row)
    if any(k in row for k in DEGRADED_TRIGGER_FIELDS):
        errs += _check_degraded_serve_row(name, i, row)
    return errs


SERVE_QDEPTH_FIELDS = ("qdepth_p50", "qdepth_p95", "qdepth_p99")


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _check_sustained_serve_row(name: str, i: int, row: dict) -> list[str]:
    """Invariant 7, sustained extension (continuous-batching rows)."""
    errs: list[str] = []
    off, ach = row.get("offered_qps"), row.get("achieved_qps")
    if not _num(off) or not _num(ach) or ach <= 0 or off < ach:
        errs.append(
            f"{name}:{i}: sustained serve row needs offered_qps >= "
            f"achieved_qps > 0, got offered={off!r} achieved={ach!r} — "
            "achieved above offered means latency was not measured "
            "from the arrival trace")
    for k in SERVE_QDEPTH_FIELDS:
        v = row.get(k)
        if not _num(v) or v < 0:
            errs.append(
                f"{name}:{i}: sustained serve row {k}={v!r} must be a "
                "non-negative number — queue-depth evidence is what "
                "grades the padding-vs-latency knobs")
    return errs


DEGRADED_TRIGGER_FIELDS = ("shed_frac", "deadline_miss_frac",
                           "fault_retries", "shed_requests")
DEGRADED_FRAC_FIELDS = ("shed_frac", "deadline_miss_frac")
DEGRADED_COUNT_FIELDS = ("offered_requests", "served_requests",
                         "shed_requests", "failed_requests",
                         "fault_retries")


def _check_degraded_serve_row(name: str, i: int, row: dict) -> list[str]:
    """Invariant 9: fault-plane serve rows must balance their books."""
    errs: list[str] = []
    for k in DEGRADED_FRAC_FIELDS:
        v = row.get(k)
        if not _num(v) or not 0.0 <= v <= 1.0:
            errs.append(
                f"{name}:{i}: degraded serve row {k}={v!r} must lie in "
                "[0, 1] — it is a fraction of offered requests")
    counts = {}
    for k in DEGRADED_COUNT_FIELDS:
        v = row.get(k)
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            errs.append(
                f"{name}:{i}: degraded serve row {k}={v!r} must be a "
                "non-negative integer")
        else:
            counts[k] = v
    if all(k in counts for k in ("offered_requests", "served_requests",
                                 "shed_requests", "failed_requests")):
        total = (counts["served_requests"] + counts["shed_requests"]
                 + counts["failed_requests"])
        if total != counts["offered_requests"]:
            errs.append(
                f"{name}:{i}: degraded serve row served "
                f"{counts['served_requests']} + shed "
                f"{counts['shed_requests']} + failed "
                f"{counts['failed_requests']} = {total} != offered "
                f"{counts['offered_requests']} — every offered request "
                "must come back as exactly one of the three")
    return errs


# the plan-row vocabularies (invariant 10), FROZEN standalone like the
# lint rule ids and sync-pinned by tests/test_plan.py against
# harp_tpu.plan (topology.TOPOLOGY_NAMES / planner.SCHEDULES /
# planner.predicted_bytes)
KNOWN_PLAN_TOPOLOGIES = ("single_chip", "sim_ring_8", "v4_32")
KNOWN_PLAN_SCHEDULES = ("keep", "hier_psum", "chunked_pipeline",
                        "wire_bf16", "wire_int8")


def _plan_predicted_bytes(schedule: str, sheet_bytes: int) -> int:
    """The frozen schedule→bytes scaling (mirror of
    harp_tpu.plan.planner.predicted_bytes; drift fails tests)."""
    if schedule in ("keep", "chunked_pipeline"):
        return int(sheet_bytes)
    if schedule == "hier_psum":
        return 2 * int(sheet_bytes)
    if schedule == "wire_bf16":
        return (int(sheet_bytes) + 1) // 2
    return (int(sheet_bytes) + 3) // 4  # wire_int8


def _check_plan_row(name: str, i: int, row: dict) -> list[str]:
    """Invariant 10: plan rows must be coherent schedule evidence."""
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: plan row missing provenance field(s) {missing} "
            "— print it through harp_tpu.plan.cli (benchmark_json stamps "
            "them)")
    prog = row.get("program")
    if prog not in KNOWN_LINT_PROGRAMS:
        errs.append(
            f"{name}:{i}: plan row for unregistered program {prog!r} — "
            "programs must come from harp_tpu.analysis.drivers.DRIVERS "
            "(update KNOWN_LINT_PROGRAMS in the same commit as the "
            "registry)")
    topo = row.get("topology")
    if topo not in KNOWN_PLAN_TOPOLOGIES:
        errs.append(
            f"{name}:{i}: plan row names unknown topology {topo!r} "
            f"(known: {KNOWN_PLAN_TOPOLOGIES})")
    for s in row.get("sites") or []:
        if not isinstance(s, dict):
            errs.append(f"{name}:{i}: plan row has a non-object site "
                        "entry")
            continue
        sched = s.get("schedule")
        if sched not in KNOWN_PLAN_SCHEDULES:
            errs.append(
                f"{name}:{i}: plan site {s.get('site')!r} chose unknown "
                f"schedule {sched!r} (known: {KNOWN_PLAN_SCHEDULES})")
            continue
        if sched != "keep":
            errs.append(
                f"{name}:{i}: plan site {s.get('site')!r} chose "
                f"{sched!r} — the planner fails closed (schedule is "
                "always 'keep'; alternatives ride flip candidates, "
                "never the chosen slot)")
        sb, pb = s.get("sheet_bytes"), s.get("predicted_bytes")
        for k, v in (("sheet_bytes", sb), ("predicted_bytes", pb)):
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                errs.append(f"{name}:{i}: plan site {k}={v!r} must be a "
                            "non-negative integer")
        if (isinstance(sb, int) and isinstance(pb, int)
                and not isinstance(sb, bool) and not isinstance(pb, bool)
                and pb != _plan_predicted_bytes(sched, sb)):
            errs.append(
                f"{name}:{i}: plan site {s.get('site')!r} predicts "
                f"{pb} B under {sched!r} but the sheet says {sb} B — "
                f"expected {_plan_predicted_bytes(sched, sb)}; the "
                "prediction must equal the frozen scaling of the "
                "program's byte sheet")
    return errs


# the trace-row vocabularies (invariant 11), FROZEN standalone like the
# lint rule ids and sync-pinned by tests/test_reqtrace.py against
# harp_tpu.utils.reqtrace.OUTCOMES
KNOWN_TRACE_OUTCOMES = ("served", "shed", "failed")
KNOWN_TRACE_EVS = ("event", "request", "batch", "mark", "summary")


def _check_trace_row(name: str, i: int, row: dict,
                     state: dict) -> list[str]:
    """Invariant 11, per-row half: stamp, row shape, monotone ts.

    ``state`` accumulates the file-level evidence the end-of-file half
    (:func:`_finish_trace_checks`) reconciles: request ids seen in
    event rows, terminated request rows with their outcomes, and the
    previous row's timestamp for monotonicity.
    """
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: trace row missing provenance field(s) "
            f"{missing} — export through telemetry.export / "
            "telemetry.export_timeline, which stamp them")
    ev = row.get("ev")
    if ev not in KNOWN_TRACE_EVS:
        errs.append(f"{name}:{i}: trace row ev={ev!r} not in "
                    f"{KNOWN_TRACE_EVS}")
    ts = row.get("ts")
    if not _num(ts) or ts < 0:
        errs.append(f"{name}:{i}: trace row ts={ts!r} must be a "
                    "non-negative number — a timeline row without a "
                    "timestamp cannot be causally ordered")
    else:
        last = state.get("last_ts")
        if last is not None and ts < last:
            errs.append(
                f"{name}:{i}: trace row ts={ts} decreased from {last} — "
                "timeline rows must be monotone (interleaved exports?)")
        state["last_ts"] = ts
    if ev == "event" and "req" in row:
        state.setdefault("seen", set()).add(row["req"])
    if ev == "request":
        outcome = row.get("outcome")
        if outcome not in KNOWN_TRACE_OUTCOMES:
            errs.append(
                f"{name}:{i}: trace request row req={row.get('req')!r} "
                f"has outcome={outcome!r} — every request span must "
                f"terminate with one of {KNOWN_TRACE_OUTCOMES}")
        else:
            counts = state.setdefault(
                "outcomes", {o: 0 for o in KNOWN_TRACE_OUTCOMES})
            counts[outcome] += 1
        state.setdefault("terminated", set()).add(row.get("req"))
    return errs


def _finish_trace_checks(name: str, state: dict,
                         degraded: list[tuple[int, dict]]) -> list[str]:
    """Invariant 11, file-level half: span completeness + ledger
    reconciliation (runs after the whole file was scanned)."""
    errs: list[str] = []
    unterminated = sorted(state.get("seen", set())
                          - state.get("terminated", set()))
    if unterminated:
        errs.append(
            f"{name}: trace has {len(unterminated)} request span(s) with "
            f"events but no terminated outcome row: {unterminated[:8]} — "
            "every offered request must end served/shed/failed")
    counts = state.get("outcomes")
    if counts is not None and len(degraded) == 1:
        _, row = degraded[0]
        ledger = {"served": row.get("served_requests"),
                  "shed": row.get("shed_requests"),
                  "failed": row.get("failed_requests")}
        if all(isinstance(v, int) and not isinstance(v, bool)
               for v in ledger.values()) and counts != ledger:
            errs.append(
                f"{name}: trace outcome counts {counts} do not "
                f"reconcile with the file's invariant-9 serve ledger "
                f"{ledger} — the timeline and the bench row describe "
                "different runs")
    return errs


# the model-row vocabularies (invariant 12), FROZEN standalone like the
# plan vocabularies and sync-pinned by tests/test_perfmodel.py against
# harp_tpu.perfmodel (BOUNDS / RATES_SOURCES) and scripts/measure_all.py
# (SPRINT_ORDER)
KNOWN_MODEL_BOUNDS = ("compute", "memory", "wire", "overhead")
KNOWN_MODEL_RATES_SOURCES = ("declared", "probed")
KNOWN_MODEL_CONFIGS = (
    "kmeans", "kmeans_hier_psum", "kmeans_ingest", "kmeans_ingest_int8",
    "kmeans_int8", "kmeans_int8_fused", "kmeans_stream",
    "kmeans_stream_int8", "lda", "lda_carry", "lda_exprace", "lda_fast",
    "lda_pallas", "lda_pallas_approx", "lda_pallas_approx_hot",
    "lda_pallas_carry", "lda_pallas_hot", "lda_planner_wire",
    "lda_rotate_int8", "lda_scale", "lda_scale_1m", "lda_scale_1m_pallas",
    "lda_scatter", "mfsgd", "mfsgd_carry", "mfsgd_chunked_rotate",
    "mfsgd_pallas", "mfsgd_scatter", "mlp", "mlp_grad_bf16",
    "mlp_grad_int8", "rf", "rf_dense_hist", "rf_hist_pallas",
    "rf_scatter_hist", "serve_kmeans", "serve_kmeans_sustained",
    "serve_mfsgd_sustained", "serve_mfsgd_topk", "subgraph",
    "subgraph_1m", "subgraph_1m_onehot", "subgraph_csr32",
    "subgraph_onehot", "subgraph_pl",
    "svm", "svm_kernel_pallas", "svm_sv_bf16",
    "svm_sv_int8", "svm_x_bf16", "wdamds",
    "wdamds_coord_bf16", "wdamds_coord_int8", "wdamds_delta_bf16",
    "wdamds_dist_pallas")
MODEL_TERM_FIELDS = ("compute_s", "memory_s", "wire_s", "overhead_s")


def _check_model_row(name: str, i: int, row: dict) -> list[str]:
    """Invariant 12: model rows must be coherent prediction evidence."""
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: model row missing provenance field(s) "
            f"{missing} — print it through harp_tpu.perfmodel.cli, "
            "which stamps them")
    prog, cfg = row.get("program"), row.get("config")
    if prog is None and cfg is None:
        errs.append(f"{name}:{i}: model row names neither a program nor "
                    "a config — a prediction about nothing prices "
                    "nothing")
    if prog is not None and prog not in KNOWN_LINT_PROGRAMS:
        errs.append(
            f"{name}:{i}: model row for unregistered program {prog!r} — "
            "programs must come from harp_tpu.analysis.drivers.DRIVERS")
    for c in ([cfg] if cfg is not None else []) + list(
            row.get("configs") or []):
        if c not in KNOWN_MODEL_CONFIGS:
            errs.append(
                f"{name}:{i}: model row references config {c!r} not in "
                "the sprint surface (KNOWN_MODEL_CONFIGS — update in "
                "the same commit as measure_all.SPRINT_ORDER)")
    rs = row.get("rates_source")
    if rs not in KNOWN_MODEL_RATES_SOURCES:
        errs.append(f"{name}:{i}: model row rates_source={rs!r} not in "
                    f"{KNOWN_MODEL_RATES_SOURCES} — a declared ranking "
                    "must never masquerade as a measured one")
    bound = row.get("bound")
    if bound not in KNOWN_MODEL_BOUNDS:
        errs.append(f"{name}:{i}: model row bound={bound!r} not in "
                    f"{KNOWN_MODEL_BOUNDS}")
    ps = row.get("predicted_s")
    if not _num(ps) or ps <= 0:
        errs.append(f"{name}:{i}: model row predicted_s={ps!r} must be "
                    "a positive number — zero predicted seconds is not "
                    "a prediction")
    terms = row.get("terms")
    if (not isinstance(terms, dict)
            or sorted(terms) != sorted(MODEL_TERM_FIELDS)
            or not all(_num(terms[k]) and terms[k] >= 0
                       for k in MODEL_TERM_FIELDS)):
        errs.append(
            f"{name}:{i}: model row terms={terms!r} must carry exactly "
            f"{MODEL_TERM_FIELDS} as non-negative numbers — the "
            "breakdown is what makes a wrong prediction diagnosable")
    elif _num(ps) and ps > 0:
        total = sum(terms.values())
        if abs(total - ps) > 1e-6 * max(abs(ps), 1e-12):
            errs.append(
                f"{name}:{i}: model row terms sum to {total} but "
                f"predicted_s claims {ps} — the per-term breakdown "
                "must sum to the total")
        if bound in KNOWN_MODEL_BOUNDS and \
                terms[f"{bound}_s"] < max(terms.values()) - 1e-12:
            errs.append(
                f"{name}:{i}: model row bound={bound!r} is not the "
                "largest term — the bound names the wall the "
                "prediction is against")
    return errs


# the health-row vocabularies (invariant 13), FROZEN standalone like the
# plan/model vocabularies and sync-pinned by tests/test_check_jsonl.py
# against harp_tpu.health (DETECTORS / SEVERITIES / VERDICTS)
KNOWN_HEALTH_DETECTORS = ("slo_burn", "skew_trigger", "budget_drift",
                          "evidence_regression", "profile_drift",
                          "memory_pressure")
KNOWN_HEALTH_SEVERITIES = ("info", "warn", "page")
KNOWN_HEALTH_VERDICTS = ("confirmed", "improved", "regressed",
                         "model_invalidated")
HEALTH_COUNT_FIELDS = ("offered", "served", "shed", "failed",
                       "deadline_missed", "breaches", "violations",
                       "supersteps", "consecutive", "failures")
HEALTH_RATIO_FIELDS = ("fast_burn", "slow_burn", "wasted_frac",
                       "max_mean_ratio", "ratio_vs_incumbent",
                       "model_factor", "error_budget")


def _check_health_row(name: str, i: int, row: dict) -> list[str]:
    """Invariant 13: health rows must be coherent monitoring evidence."""
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: health row missing provenance field(s) "
            f"{missing} — export through telemetry.export / the health "
            "CLI, which stamp them")
    det = row.get("detector")
    if det not in KNOWN_HEALTH_DETECTORS:
        errs.append(f"{name}:{i}: health row detector={det!r} not in "
                    f"{KNOWN_HEALTH_DETECTORS}")
    sev = row.get("severity")
    if sev not in KNOWN_HEALTH_SEVERITIES:
        errs.append(f"{name}:{i}: health row severity={sev!r} not in "
                    f"{KNOWN_HEALTH_SEVERITIES}")
    for k in HEALTH_COUNT_FIELDS:
        v = row.get(k)
        if v is None:
            continue
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            errs.append(f"{name}:{i}: health row count {k}={v!r} must "
                        "be a non-negative integer")
    for k in HEALTH_RATIO_FIELDS:
        v = row.get(k)
        if v is None:
            continue
        if not _num(v) or v < 0:
            errs.append(f"{name}:{i}: health row {k}={v!r} must be a "
                        "non-negative number")
    verdict = row.get("verdict")
    if det == "evidence_regression":
        if verdict not in KNOWN_HEALTH_VERDICTS:
            errs.append(
                f"{name}:{i}: evidence_regression health row has "
                f"verdict={verdict!r} — every graded row must carry "
                f"one of {KNOWN_HEALTH_VERDICTS}")
    elif verdict is not None and verdict not in KNOWN_HEALTH_VERDICTS:
        errs.append(f"{name}:{i}: health row verdict={verdict!r} not "
                    f"in {KNOWN_HEALTH_VERDICTS}")
    if det == "skew_trigger":
        errs += _check_rebalance_plan(name, i, row.get("plan"))
    elif row.get("plan") is not None:
        errs += _check_rebalance_plan(name, i, row.get("plan"))
    return errs


def _check_rebalance_plan(name: str, i: int, plan) -> list[str]:
    """Invariant 13, skew-trigger extension: the inline plan must be
    apply_rebalance-shaped — the elastic-execution PR will replay it."""
    if not isinstance(plan, dict):
        return [f"{name}:{i}: skew_trigger health row plan={plan!r} "
                "must be a suggest_rebalance object (the inline "
                "elastic-execution payload)"]
    errs: list[str] = []
    if not isinstance(plan.get("phase"), str):
        errs.append(f"{name}:{i}: rebalance plan phase="
                    f"{plan.get('phase')!r} must be a string")
    moves = plan.get("moves")
    if not isinstance(moves, list):
        errs.append(f"{name}:{i}: rebalance plan moves={moves!r} must "
                    "be a list")
        moves = []
    for m in moves:
        if not isinstance(m, dict):
            errs.append(f"{name}:{i}: rebalance plan has a non-object "
                        "move entry")
            continue
        for k in ("from", "to"):
            v = m.get(k)
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                errs.append(f"{name}:{i}: rebalance move {k}={v!r} "
                            "must be a non-negative worker index")
        w = m.get("work")
        if not _num(w) or w < 0:
            errs.append(f"{name}:{i}: rebalance move work={w!r} must "
                        "be a non-negative number")
    for k in ("ratio_before", "ratio_after"):
        v = plan.get(k)
        if v is not None and (not _num(v) or v < 0):
            errs.append(f"{name}:{i}: rebalance plan {k}={v!r} must be "
                        "a non-negative number")
    return errs


# the elastic-row vocabulary (invariant 14), FROZEN standalone like the
# health vocabularies and sync-pinned by tests/test_check_jsonl.py
# against harp_tpu.elastic.EVENTS
KNOWN_ELASTIC_EVENTS = ("rebalance", "shrink", "resume")
ELASTIC_LOAD_FIELDS = ("loads", "loads_before", "loads_after")
ELASTIC_COUNT_FIELDS = ("n_workers", "moves", "lost_worker", "ordinal",
                        "from_step", "trigger_supersteps",
                        "n_workers_before", "n_workers_after")


def _check_elastic_row(name: str, i: int, row: dict) -> list[str]:
    """Invariant 14: elastic rows must be coherent elasticity evidence."""
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: elastic row missing provenance field(s) "
            f"{missing} — export through telemetry.export, which "
            "stamps them")
    ev = row.get("event")
    if ev not in KNOWN_ELASTIC_EVENTS:
        errs.append(f"{name}:{i}: elastic row event={ev!r} not in "
                    f"{KNOWN_ELASTIC_EVENTS}")
    total = row.get("total")
    for k in ELASTIC_LOAD_FIELDS:
        v = row.get(k)
        if v is None:
            continue
        if not (isinstance(v, list) and v
                and all(_num(x) and x >= 0 for x in v)):
            errs.append(
                f"{name}:{i}: elastic row {k}={v!r} must be a non-empty "
                "list of non-negative per-worker loads")
        elif not _num(total):
            errs.append(
                f"{name}:{i}: elastic row carries {k} but "
                f"total={total!r} — per-worker loads must state the "
                "total they sum to")
        elif abs(sum(v) - total) > 1e-4 * max(1.0, abs(total)):
            errs.append(
                f"{name}:{i}: elastic row {k} sums to {sum(v)} but "
                f"total claims {total} — a move must conserve work")
    for k in ELASTIC_COUNT_FIELDS:
        v = row.get(k)
        if v is None:
            continue
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            errs.append(f"{name}:{i}: elastic row count {k}={v!r} must "
                        "be a non-negative integer")
    wb, wa = row.get("wasted_frac_before"), row.get("wasted_frac_after")
    for k, v in (("wasted_frac_before", wb), ("wasted_frac_after", wa),
                 ("wasted_frac", row.get("wasted_frac")),
                 ("capacity_frac", row.get("capacity_frac"))):
        if v is not None and (not _num(v) or not 0.0 <= v <= 1.0):
            errs.append(f"{name}:{i}: elastic row {k}={v!r} must lie "
                        "in [0, 1]")
    if ev == "rebalance":
        if not (_num(wb) and _num(wa)):
            errs.append(
                f"{name}:{i}: rebalance elastic row must carry numeric "
                "wasted_frac_before AND wasted_frac_after — the whole "
                "point is before/after evidence")
        elif wa > wb + 1e-9:
            errs.append(
                f"{name}:{i}: rebalance elastic row wasted_frac_after="
                f"{wa} > before={wb} — a move that made the imbalance "
                "worse must be refused, not committed as evidence")
        for k in ("loads_before", "loads_after"):
            if row.get(k) is None:
                errs.append(f"{name}:{i}: rebalance elastic row "
                            f"missing {k}")
    if ev == "shrink":
        nb, na = row.get("n_workers_before"), row.get("n_workers_after")
        ok = (isinstance(nb, int) and isinstance(na, int)
              and not isinstance(nb, bool) and not isinstance(na, bool)
              and nb >= 1 and na >= 1)
        if not ok or na >= nb:
            errs.append(
                f"{name}:{i}: shrink elastic row needs survivor count "
                f"n_workers_after < n_workers_before (>= 1), got "
                f"{na!r} / {nb!r}")
    return errs


# the profile-row vocabularies (invariant 15), FROZEN standalone like
# the model/health vocabularies and sync-pinned by
# tests/test_check_jsonl.py against harp_tpu.profile.attribution
# (BUCKETS / PROFILE_APPS / SUM_REL_TOL)
KNOWN_PROFILE_BUCKETS = ("mxu", "elementwise", "gather_dus", "scatter",
                         "wire", "overhead")
KNOWN_PROFILE_APPS = ("kmeans", "mfsgd", "lda", "rf", "svm", "wdamds",
                      "subgraph", "serve", "rf_pallas", "svm_pallas",
                      "wdamds_pallas")
PROFILE_SUM_REL_TOL = 0.75
PROFILE_COUNT_FIELDS = ("reps", "n_devices", "wire_bytes", "wire_sites",
                        "wire_unmatched", "dispatches",
                        "dispatches_per_rep", "compiles_in_window")


def _check_profile_row(name: str, i: int, row: dict) -> list[str]:
    """Invariant 15: profile rows must be coherent attribution evidence."""
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: profile row missing provenance field(s) "
            f"{missing} — emit it through harp_tpu.profile.cli / "
            "attribution.capture, which stamp them")
    app = row.get("app")
    if app not in KNOWN_PROFILE_APPS:
        errs.append(f"{name}:{i}: profile row app={app!r} not in "
                    f"{KNOWN_PROFILE_APPS}")
    prog = row.get("program")
    if prog not in KNOWN_LINT_PROGRAMS:
        errs.append(
            f"{name}:{i}: profile row for unregistered program {prog!r} "
            "— programs must come from harp_tpu.analysis.drivers.DRIVERS")
    for k in PROFILE_COUNT_FIELDS:
        v = row.get(k)
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            errs.append(f"{name}:{i}: profile row count {k}={v!r} must "
                        "be a non-negative integer")
    wall = row.get("wall_s")
    if not _num(wall) or wall <= 0:
        errs.append(f"{name}:{i}: profile row wall_s={wall!r} must be a "
                    "positive number — an attribution needs a wall to "
                    "attribute")
    term_keys = tuple(f"{b}_s" for b in KNOWN_PROFILE_BUCKETS)
    terms = row.get("terms")
    if (not isinstance(terms, dict)
            or sorted(terms) != sorted(term_keys)
            or not all(_num(terms[k]) and terms[k] >= 0
                       for k in term_keys)):
        errs.append(
            f"{name}:{i}: profile row terms={terms!r} must carry exactly "
            f"{term_keys} as non-negative numbers — the frozen mechanism "
            "vocabulary is what lets the perfmodel consume the row")
    else:
        if _num(wall) and wall > 0:
            total = sum(terms.values())
            # terms are rounded to 6 decimals per bucket in the exporter
            if abs(total - wall) > 1e-3 * wall + 1e-5:
                errs.append(
                    f"{name}:{i}: profile row buckets sum to {total} but "
                    f"wall_s claims {wall} — every wall second must be "
                    "attributed to a mechanism (residual in overhead)")
        bound = row.get("bound")
        if bound not in KNOWN_PROFILE_BUCKETS:
            errs.append(f"{name}:{i}: profile row bound={bound!r} not in "
                        f"{KNOWN_PROFILE_BUCKETS}")
        elif terms[f"{bound}_s"] < max(terms.values()) - 1e-12:
            errs.append(
                f"{name}:{i}: profile row bound={bound!r} is not the "
                "largest bucket — the bound names the wall the row "
                "claims the app is against")
    sre = row.get("sum_rel_err")
    if not _num(sre) or sre < 0 or sre > PROFILE_SUM_REL_TOL:
        errs.append(
            f"{name}:{i}: profile row sum_rel_err={sre!r} must lie in "
            f"[0, {PROFILE_SUM_REL_TOL}] — beyond the documented "
            "concurrency-blur tolerance the capture is broken, not blurry")
    reps, per = row.get("reps"), row.get("dispatches_per_rep")
    disp = row.get("dispatches")
    if (isinstance(reps, int) and isinstance(per, int)
            and isinstance(disp, int)
            and not any(isinstance(x, bool) for x in (reps, per, disp))
            and disp != reps * per):
        errs.append(
            f"{name}:{i}: profile row dispatches={disp} != reps={reps} * "
            f"dispatches_per_rep={per} — the attribution window "
            "disagrees with the flight recorder about what ran")
    for k in ("compiles_in_window", "wire_unmatched"):
        v = row.get(k)
        if isinstance(v, int) and not isinstance(v, bool) and v != 0:
            errs.append(
                f"{name}:{i}: profile row {k}={v} must be exactly 0 — "
                + ("a capture that compiled mid-window timed the "
                   "compiler, not the program"
                   if k == "compiles_in_window" else
                   "every static collective site must carry a "
                   "CommLedger verb match"))
    if row.get("reconciled") is not True:
        errs.append(
            f"{name}:{i}: profile row reconciled="
            f"{row.get('reconciled')!r} must be literally true — an "
            "unreconciled attribution is a hand-read profile wearing a "
            "row format")
    return errs


# the steptrace vocabularies (invariant 16), FROZEN standalone like the
# trace vocabularies and sync-pinned by tests/test_check_jsonl.py
# against harp_tpu.utils.steptrace (EVS / OUTCOMES / SOURCES /
# FLIGHT_KEYS)
KNOWN_STEPTRACE_EVS = ("run", "superstep", "mark", "lane")
KNOWN_STEPTRACE_OUTCOMES = ("completed", "faulted", "rebalanced",
                            "resumed")
KNOWN_STEPTRACE_SOURCES = ("flight", "wire", "ckpt", "fault", "elastic",
                           "health", "memory")
KNOWN_STEPTRACE_FLIGHT_KEYS = ("dispatches", "readbacks", "h2d_calls",
                               "compiles")


def _steptrace_flight_ok(fl) -> bool:
    """Exactly the frozen counter keys, all non-negative integers."""
    return (isinstance(fl, dict)
            and sorted(fl) == sorted(KNOWN_STEPTRACE_FLIGHT_KEYS)
            and all(isinstance(fl[k], int) and not isinstance(fl[k], bool)
                    and fl[k] >= 0 for k in KNOWN_STEPTRACE_FLIGHT_KEYS))


def _check_steptrace_row(name: str, i: int, row: dict,
                         state: dict) -> list[str]:
    """Invariant 16, per-row half: stamp, row shape, monotone ts.

    ``state`` accumulates the per-run evidence the end-of-file half
    (:func:`_finish_steptrace_checks`) re-derives: span/mark/lane
    counts, outcome tallies, span flight sums, dispatch-mark counts,
    and the elastic/health marks for the cross-spine reconciliation.
    """
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: steptrace row missing provenance field(s) "
            f"{missing} — export through telemetry.export / "
            "telemetry.export_timeline, which stamp them")
    ev = row.get("ev")
    if ev not in KNOWN_STEPTRACE_EVS:
        errs.append(f"{name}:{i}: steptrace row ev={ev!r} not in "
                    f"{KNOWN_STEPTRACE_EVS}")
    ts = row.get("ts")
    if not _num(ts) or ts < 0:
        errs.append(f"{name}:{i}: steptrace row ts={ts!r} must be a "
                    "non-negative number — a timeline row without a "
                    "timestamp cannot be causally ordered")
    else:
        last = state.get("last_ts")
        if last is not None and ts < last:
            errs.append(
                f"{name}:{i}: steptrace row ts={ts} decreased from "
                f"{last} — timeline rows must be monotone (interleaved "
                "exports?)")
        state["last_ts"] = ts
    rid = row.get("run")
    if isinstance(rid, bool) or not isinstance(rid, int) or rid < 1:
        errs.append(f"{name}:{i}: steptrace row run={rid!r} must be a "
                    "positive integer run id")
        return errs
    per = state.setdefault("per", {}).setdefault(rid, {
        "spans": 0,
        "outcomes": {o: 0 for o in KNOWN_STEPTRACE_OUTCOMES},
        "span_flight": {k: 0 for k in KNOWN_STEPTRACE_FLIGHT_KEYS},
        "marks": 0, "lanes": 0, "dispatch_marks": 0,
        "elastic_marks": {}, "health_marks": [], "consume_marks": []})
    if ev == "run":
        runs = state.setdefault("runs", {})
        if rid in runs:
            errs.append(f"{name}:{i}: duplicate steptrace run row for "
                        f"run {rid} — every run terminates exactly once")
        runs[rid] = (i, row)
        outcomes = row.get("outcomes")
        if (not isinstance(outcomes, dict)
                or sorted(outcomes) != sorted(KNOWN_STEPTRACE_OUTCOMES)
                or not all(isinstance(outcomes[o], int)
                           and not isinstance(outcomes[o], bool)
                           and outcomes[o] >= 0
                           for o in KNOWN_STEPTRACE_OUTCOMES)):
            errs.append(
                f"{name}:{i}: steptrace run row outcomes={outcomes!r} "
                f"must carry exactly {KNOWN_STEPTRACE_OUTCOMES} as "
                "non-negative integers")
        for k in ("supersteps", "marks", "lanes"):
            v = row.get(k)
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                errs.append(f"{name}:{i}: steptrace run row {k}={v!r} "
                            "must be a non-negative integer")
        for fname in ("flight", "span_flight"):
            if not _steptrace_flight_ok(row.get(fname)):
                errs.append(
                    f"{name}:{i}: steptrace run row {fname}="
                    f"{row.get(fname)!r} must carry exactly "
                    f"{KNOWN_STEPTRACE_FLIGHT_KEYS} as non-negative "
                    "integers")
        t0 = row.get("t0")
        if not _num(t0) or (_num(ts) and t0 > ts):
            errs.append(f"{name}:{i}: steptrace run row t0={t0!r} must "
                        "be a number not after its close ts")
    elif ev == "superstep":
        per["spans"] += 1
        outcome = row.get("outcome")
        if outcome not in KNOWN_STEPTRACE_OUTCOMES:
            errs.append(
                f"{name}:{i}: steptrace span run={rid} seq="
                f"{row.get('seq')!r} has outcome={outcome!r} — every "
                f"opened superstep must terminate with one of "
                f"{KNOWN_STEPTRACE_OUTCOMES}")
        else:
            per["outcomes"][outcome] += 1
        for k in ("seq", "step"):
            v = row.get(k)
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                errs.append(f"{name}:{i}: steptrace span {k}={v!r} "
                            "must be a non-negative integer")
        t0 = row.get("t0")
        if not _num(t0) or (_num(ts) and t0 > ts):
            errs.append(f"{name}:{i}: steptrace span t0={t0!r} must be "
                        "a number not after its close ts")
        fl = row.get("flight")
        if not _steptrace_flight_ok(fl):
            errs.append(
                f"{name}:{i}: steptrace span flight={fl!r} must carry "
                f"exactly {KNOWN_STEPTRACE_FLIGHT_KEYS} as non-negative "
                "integers")
        else:
            for k in KNOWN_STEPTRACE_FLIGHT_KEYS:
                per["span_flight"][k] += fl[k]
    elif ev == "mark":
        per["marks"] += 1
        src = row.get("source")
        if src not in KNOWN_STEPTRACE_SOURCES:
            errs.append(f"{name}:{i}: steptrace mark source={src!r} not "
                        f"in {KNOWN_STEPTRACE_SOURCES}")
        nm = row.get("name")
        if src == "flight" and nm == "dispatch":
            per["dispatch_marks"] += 1
        elif src == "elastic":
            per["elastic_marks"][nm] = per["elastic_marks"].get(nm, 0) + 1
        elif src == "health":
            if nm == "consume_skew_trigger":
                per["consume_marks"].append((i, row.get("phase")))
            else:
                per["health_marks"].append((i, nm))
    elif ev == "lane":
        per["lanes"] += 1
        work = row.get("work")
        if not (isinstance(work, list) and work
                and all(_num(x) and x >= 0 for x in work)):
            errs.append(
                f"{name}:{i}: steptrace lane work={work!r} must be a "
                "non-empty list of non-negative per-worker loads")
    return errs


def _finish_steptrace_checks(name: str, state: dict,
                             elastic_counts: dict,
                             health_rows: list[dict],
                             transfer_dispatches: int | None
                             ) -> list[str]:
    """Invariant 16, file-level half: run termination, re-derived run
    summaries, and the cross-spine reconciliations (runs after the
    whole file was scanned)."""
    per = state.get("per") or {}
    if not per:
        return []
    errs: list[str] = []
    runs = state.get("runs") or {}
    unterminated = sorted(r for r in per if r not in runs)
    if unterminated:
        errs.append(
            f"{name}: steptrace has {len(unterminated)} run(s) with "
            f"spans/marks but no terminating run row: "
            f"{unterminated[:8]} — every opened run must close")
    total_dispatch = 0
    for rid, (i, rrow) in sorted(runs.items()):
        agg = per[rid]
        ss = rrow.get("supersteps")
        if isinstance(ss, int) and agg["spans"] != ss:
            errs.append(
                f"{name}:{i}: steptrace run {rid} claims {ss} "
                f"superstep(s) but the file carries {agg['spans']} span "
                "row(s)")
        outcomes = rrow.get("outcomes")
        if (isinstance(outcomes, dict)
                and sorted(outcomes) == sorted(KNOWN_STEPTRACE_OUTCOMES)
                and agg["outcomes"] != outcomes):
            errs.append(
                f"{name}:{i}: steptrace run {rid} span outcomes "
                f"{agg['outcomes']} do not match the run row's "
                f"{outcomes}")
        sf, fl = rrow.get("span_flight"), rrow.get("flight")
        if _steptrace_flight_ok(sf) and agg["span_flight"] != sf:
            errs.append(
                f"{name}:{i}: steptrace run {rid} span flight sums "
                f"{agg['span_flight']} do not match the run row's "
                f"span_flight {sf}")
        if _steptrace_flight_ok(sf) and _steptrace_flight_ok(fl):
            over = [k for k in KNOWN_STEPTRACE_FLIGHT_KEYS
                    if sf[k] > fl[k]]
            if over:
                errs.append(
                    f"{name}:{i}: steptrace run {rid} span_flight "
                    f"exceeds the run's flight delta for {over} — spans "
                    "cannot own more ops than the run recorded")
        for k in ("marks", "lanes"):
            v = rrow.get(k)
            if isinstance(v, int) and not isinstance(v, bool) \
                    and v != agg[k]:
                errs.append(
                    f"{name}:{i}: steptrace run {rid} claims {v} "
                    f"{k} but the file carries {agg[k]}")
        if _steptrace_flight_ok(fl):
            total_dispatch += fl["dispatches"]
            if agg["dispatch_marks"] != fl["dispatches"]:
                errs.append(
                    f"{name}:{i}: steptrace run {rid} has "
                    f"{agg['dispatch_marks']} dispatch mark(s) but its "
                    f"flight delta counted {fl['dispatches']} — the "
                    "observer spine and the TransferLedger must agree "
                    "EXACTLY")
    if transfer_dispatches is not None \
            and total_dispatch > transfer_dispatches:
        errs.append(
            f"{name}: steptrace runs attribute {total_dispatch} "
            f"dispatch(es) but the file's transfer rows record only "
            f"{transfer_dispatches} — a timeline cannot own more "
            "dispatches than the flight recorder counted")
    emarks: dict = {}
    for agg in per.values():
        for nm, n in agg["elastic_marks"].items():
            emarks[nm] = emarks.get(nm, 0) + n
    for evn in KNOWN_ELASTIC_EVENTS:
        if emarks.get(evn, 0) != elastic_counts.get(evn, 0):
            errs.append(
                f"{name}: steptrace carries {emarks.get(evn, 0)} "
                f"elastic {evn!r} mark(s) but the file has "
                f"{elastic_counts.get(evn, 0)} timeline-covered "
                f"kind:'elastic' {evn!r} row(s) — the timeline and the "
                "elastic ledger must tell one story")
    detectors = {r.get("detector") for r in health_rows}
    for agg in per.values():
        for i, nm in agg["health_marks"]:
            if nm not in detectors:
                errs.append(
                    f"{name}:{i}: steptrace health mark names detector "
                    f"{nm!r} with no kind:'health' row in the file — a "
                    "finding on the timeline must exist in the "
                    "sentinel export")
        for i, phase in agg["consume_marks"]:
            if not any(r.get("detector") == "skew_trigger"
                       and r.get("phase") == phase
                       and r.get("consumed") is True
                       for r in health_rows):
                errs.append(
                    f"{name}:{i}: steptrace consume_skew_trigger mark "
                    f"for phase {phase!r} has no consumed skew_trigger "
                    "health row — the exactly-once handshake leaves "
                    "ledger evidence or it did not happen")
    return errs


# the memory-row vocabularies (invariant 17), FROZEN standalone like the
# steptrace vocabularies and sync-pinned by tests/test_check_jsonl.py
# against harp_tpu.utils.memrec (EVS / BUFFER_EVENTS)
KNOWN_MEMORY_EVS = ("buffer", "dispatch", "executable", "vmem_check",
                    "summary")
KNOWN_MEMORY_EVENTS = ("staged", "restored", "output", "freed",
                       "donated")
MEMORY_EXEC_COMPONENTS = ("argument_bytes", "output_bytes", "temp_bytes",
                          "generated_code_bytes")
MEMORY_SUMMARY_DERIVED = ("peak_hbm_bytes", "live_hbm_bytes",
                          "staged_bytes", "freed_bytes", "donated_bytes",
                          "vmem_checks", "vmem_refusals")


def _check_memory_row(name: str, i: int, row: dict,
                      state: dict) -> list[str]:
    """Invariant 17, per-row half: stamp, row shape, and the live-set
    replay.

    ``state`` carries the re-derived ledger the end-of-file half
    (:func:`_finish_memory_checks`) closes out: the live set (buf id →
    bytes), running live/peak watermarks, staged/freed/donated totals,
    vmem check/refusal counts, and the summary row once seen — the
    IDENTICAL replay :func:`harp_tpu.utils.memrec.summarize_rows` runs,
    so the CLI and the repo gate cannot disagree about a file.
    """
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: memory row missing provenance field(s) "
            f"{missing} — export through telemetry.export, which stamps "
            "them (a CPU-sim footprint must never read as silicon HBM "
            "evidence)")
    ev = row.get("ev")
    if ev not in KNOWN_MEMORY_EVS:
        errs.append(f"{name}:{i}: memory row ev={ev!r} not in "
                    f"{KNOWN_MEMORY_EVS}")
        return errs
    seq = row.get("seq")
    if isinstance(seq, bool) or not isinstance(seq, int) or seq < 1:
        errs.append(f"{name}:{i}: memory row seq={seq!r} must be a "
                    "positive integer")
    else:
        last = state.get("last_seq", 0)
        if seq <= last:
            errs.append(
                f"{name}:{i}: memory row seq={seq} did not increase "
                f"from {last} — the ledger is an ordered event stream")
        state["last_seq"] = seq
    if state.get("summary") is not None and ev != "summary":
        errs.append(
            f"{name}:{i}: memory {ev} row after the summary row — the "
            "summary terminates the export; a late event means the "
            "watermark was asserted, not measured")
    live = state.setdefault("live", {})
    if ev == "buffer":
        errs += _replay_memory_buffer(name, i, row, state, live)
    elif ev == "dispatch":
        for b in row.get("donated") or []:
            if b in live:
                errs.append(
                    f"{name}:{i}: memory dispatch donated buf {b} is "
                    "still in the live set — a donated buffer must "
                    "leave at dispatch (runtime twin of HL303)")
        if row.get("live_bytes") != state.get("live_bytes", 0):
            errs.append(
                f"{name}:{i}: memory dispatch live_bytes="
                f"{row.get('live_bytes')!r} != derived "
                f"{state.get('live_bytes', 0)}")
    elif ev == "executable":
        parts = []
        for k in MEMORY_EXEC_COMPONENTS:
            v = row.get(k)
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                errs.append(f"{name}:{i}: memory executable row "
                            f"{k}={v!r} must be a non-negative integer")
            else:
                parts.append(v)
        if (len(parts) == len(MEMORY_EXEC_COMPONENTS)
                and row.get("exec_hbm_bytes") != sum(parts)):
            errs.append(
                f"{name}:{i}: memory executable row exec_hbm_bytes="
                f"{row.get('exec_hbm_bytes')!r} != component sum "
                f"{sum(parts)} — the four memory_analysis components "
                "must add up")
        if row.get("source") not in ("compile", "cache"):
            errs.append(
                f"{name}:{i}: memory executable row source="
                f"{row.get('source')!r} must be 'compile' or 'cache'")
    elif ev == "vmem_check":
        pb, bb = row.get("predicted_bytes"), row.get("budget_bytes")
        for k, v in (("predicted_bytes", pb), ("budget_bytes", bb)):
            if isinstance(v, bool) or not isinstance(v, int) or v < 0:
                errs.append(f"{name}:{i}: memory vmem_check row "
                            f"{k}={v!r} must be a non-negative integer")
        if (isinstance(pb, int) and isinstance(bb, int)
                and not isinstance(pb, bool) and not isinstance(bb, bool)):
            fits = pb <= bb
            if bool(row.get("fits")) != fits:
                errs.append(
                    f"{name}:{i}: memory vmem_check fits="
                    f"{row.get('fits')!r} contradicts predicted={pb} "
                    f"vs budget={bb} — the gate's verdict must follow "
                    "its own bytes")
            if bool(row.get("refused")) == bool(row.get("fits")):
                errs.append(
                    f"{name}:{i}: memory vmem_check refused="
                    f"{row.get('refused')!r} must be the negation of "
                    f"fits={row.get('fits')!r}")
        state["vmem_checks"] = state.get("vmem_checks", 0) + 1
        if row.get("refused"):
            state["vmem_refusals"] = state.get("vmem_refusals", 0) + 1
    elif ev == "summary":
        if state.get("summary") is not None:
            errs.append(f"{name}:{i}: second memory summary row — the "
                        "export terminates exactly once")
        state["summary"] = (i, row)
    return errs


def _replay_memory_buffer(name: str, i: int, row: dict, state: dict,
                          live: dict) -> list[str]:
    """Invariant 17, buffer-event half of the live-set replay."""
    errs: list[str] = []
    e = row.get("event")
    if e not in KNOWN_MEMORY_EVENTS:
        errs.append(f"{name}:{i}: memory buffer row event={e!r} not in "
                    f"{KNOWN_MEMORY_EVENTS}")
        return errs
    nb = row.get("bytes")
    if isinstance(nb, bool) or not isinstance(nb, int) or nb < 0:
        errs.append(f"{name}:{i}: memory buffer row bytes={nb!r} must "
                    "be a non-negative integer")
        return errs
    b = row.get("buf")
    if e in ("staged", "output"):
        live[b] = nb
        state["live_bytes"] = state.get("live_bytes", 0) + nb
        state["peak_bytes"] = max(state.get("peak_bytes", 0),
                                  state["live_bytes"])
        if e == "staged":
            state["staged_bytes"] = state.get("staged_bytes", 0) + nb
    elif e in ("freed", "donated"):
        if b not in live:
            errs.append(
                f"{name}:{i}: memory buffer row {e} buf {b!r} is not "
                "in the live set — a buffer must be staged/output "
                "before it can leave")
        else:
            state["live_bytes"] = state.get("live_bytes", 0) - live.pop(b)
        key = "freed_bytes" if e == "freed" else "donated_bytes"
        state[key] = state.get(key, 0) + nb
    # e == "restored" is zero-delta by design (restore lands in host
    # RAM; the H2D that follows is its own staged event)
    if row.get("live_bytes") != state.get("live_bytes", 0):
        errs.append(
            f"{name}:{i}: memory buffer row live_bytes="
            f"{row.get('live_bytes')!r} != derived "
            f"{state.get('live_bytes', 0)} — the watermark must "
            "re-derive from the event stream EXACTLY")
    if row.get("peak_bytes") != state.get("peak_bytes", 0):
        errs.append(
            f"{name}:{i}: memory buffer row peak_bytes="
            f"{row.get('peak_bytes')!r} != derived "
            f"{state.get('peak_bytes', 0)}")
    return errs


def _finish_memory_checks(name: str, state: dict) -> list[str]:
    """Invariant 17, file-level half: exactly one terminating summary
    whose totals re-derive from the stream (runs after the whole file
    was scanned)."""
    if not state:
        return []
    errs: list[str] = []
    if state.get("summary") is None:
        return [f"{name}: memory rows with no terminating summary row — "
                "the export is unterminated (telemetry.export writes "
                "exactly one)"]
    i, row = state["summary"]
    derived = {"peak_hbm_bytes": state.get("peak_bytes", 0),
               "live_hbm_bytes": state.get("live_bytes", 0),
               "staged_bytes": state.get("staged_bytes", 0),
               "freed_bytes": state.get("freed_bytes", 0),
               "donated_bytes": state.get("donated_bytes", 0),
               "vmem_checks": state.get("vmem_checks", 0),
               "vmem_refusals": state.get("vmem_refusals", 0)}
    for k in MEMORY_SUMMARY_DERIVED:
        if row.get(k) != derived[k]:
            errs.append(
                f"{name}:{i}: memory summary {k}={row.get(k)!r} != "
                f"derived {derived[k]} — a peak the events cannot "
                "reproduce was asserted, not measured")
    hbm, peak = row.get("hbm_bytes"), row.get("peak_hbm_bytes")
    hf = row.get("headroom_frac")
    if isinstance(hbm, bool) or not isinstance(hbm, int) or hbm <= 0:
        errs.append(f"{name}:{i}: memory summary hbm_bytes={hbm!r} must "
                    "be a positive integer (the topology's declared "
                    "HBM capacity)")
    elif isinstance(peak, int) and not isinstance(peak, bool):
        want = round(max(0.0, 1.0 - peak / hbm), 6)
        if not _num(hf) or abs(hf - want) > 1e-6:
            errs.append(
                f"{name}:{i}: memory summary headroom_frac={hf!r} != "
                f"1 - peak/hbm = {want} — headroom must be computed, "
                "not asserted")
    return errs


INGEST_RATE_FIELDS = ("host_gb_per_sec", "points_per_sec")


def _check_ingest_row(name: str, i: int, row: dict) -> list[str]:
    """Invariant 8: ingest rows must be coherent streaming evidence."""
    errs: list[str] = []
    missing = [f for f in PROVENANCE_FIELDS if f not in row]
    if missing:
        errs.append(
            f"{name}:{i}: ingest row missing provenance field(s) "
            f"{missing} — print it through "
            "harp_tpu.utils.metrics.benchmark_json")
    oe = row.get("overlap_efficiency")
    if not _num(oe) or not 0.0 <= oe <= 1.0:
        errs.append(
            f"{name}:{i}: ingest row overlap_efficiency={oe!r} must lie "
            "in [0, 1] — it is the host pipeline's stage-overlap score "
            "(harp_tpu.ingest.IngestStats)")
    for k in INGEST_RATE_FIELDS:
        v = row.get(k)
        if not _num(v) or v <= 0:
            errs.append(
                f"{name}:{i}: ingest row {k}={v!r} must be a positive "
                "number — a non-positive rate means the instrumented "
                "epoch loop never ran")
    return errs


def check_file(path: str, grandfathered: int = 0,
               provenance: bool = False) -> list[str]:
    """Return a list of violation messages (empty = clean)."""
    errors: list[str] = []
    name = os.path.basename(path)
    try:
        lines = open(path).read().splitlines()
    except OSError as e:
        return [f"{name}: unreadable: {e}"]
    flight_state: dict = {}
    trace_state: dict = {}
    degraded_rows: list[tuple[int, dict]] = []
    steptrace_state: dict = {}
    elastic_counts: dict = {}
    health_rows: list[dict] = []
    memory_state: dict = {}
    transfer_dispatches: int | None = None
    for i, line in enumerate(lines, 1):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except ValueError as e:
            errors.append(f"{name}:{i}: unparseable JSON ({e})")
            continue
        if isinstance(row, dict) and row.get("kind") == "comm":
            errors += _check_comm_row(name, i, row)
        if isinstance(row, dict) and row.get("kind") in ("compile",
                                                         "transfer"):
            errors += _check_flight_row(name, i, row, flight_state)
            if (row.get("kind") == "transfer"
                    and row.get("op") == "dispatch"
                    and isinstance(row.get("calls"), int)
                    and not isinstance(row.get("calls"), bool)):
                transfer_dispatches = ((transfer_dispatches or 0)
                                       + row["calls"])
        if isinstance(row, dict) and row.get("kind") == "skew":
            errors += _check_skew_row(name, i, row)
        if isinstance(row, dict) and row.get("kind") == "lint":
            errors += _check_lint_row(name, i, row)
        if isinstance(row, dict) and row.get("kind") == "serve":
            errors += _check_serve_row(name, i, row)
            if any(k in row for k in DEGRADED_TRIGGER_FIELDS):
                degraded_rows.append((i, row))
        if isinstance(row, dict) and row.get("kind") == "ingest":
            errors += _check_ingest_row(name, i, row)
        if isinstance(row, dict) and row.get("kind") == "plan":
            errors += _check_plan_row(name, i, row)
        if isinstance(row, dict) and row.get("kind") == "trace":
            errors += _check_trace_row(name, i, row, trace_state)
        if isinstance(row, dict) and row.get("kind") == "model":
            errors += _check_model_row(name, i, row)
        if isinstance(row, dict) and row.get("kind") == "health":
            errors += _check_health_row(name, i, row)
            health_rows.append(row)
        if isinstance(row, dict) and row.get("kind") == "elastic":
            errors += _check_elastic_row(name, i, row)
            # only timeline-covered rows enter the invariant-16 mark
            # reconciliation — a row recorded outside any steptrace run
            # (manual install, pre-PR-18 evidence) is legitimately
            # unmarked
            if row.get("on_timeline") is True:
                evn = row.get("event")
                elastic_counts[evn] = elastic_counts.get(evn, 0) + 1
        if isinstance(row, dict) and row.get("kind") == "profile":
            errors += _check_profile_row(name, i, row)
        if isinstance(row, dict) and row.get("kind") == "steptrace":
            errors += _check_steptrace_row(name, i, row, steptrace_state)
        if isinstance(row, dict) and row.get("kind") == "memory":
            errors += _check_memory_row(name, i, row, memory_state)
        if not provenance or i <= grandfathered:
            continue
        if not isinstance(row, dict) or "config" not in row:
            continue  # not a bench row (e.g. a raw verb-sweep record)
        missing = [f for f in PROVENANCE_FIELDS if f not in row]
        if missing:
            errors.append(
                f"{name}:{i}: bench row config={row.get('config')!r} "
                f"missing provenance field(s) {missing} — print it "
                "through harp_tpu.utils.metrics.benchmark_json")
    errors += _finish_trace_checks(name, trace_state, degraded_rows)
    errors += _finish_steptrace_checks(name, steptrace_state,
                                       elastic_counts, health_rows,
                                       transfer_dispatches)
    errors += _finish_memory_checks(name, memory_state)
    return errors


def check_repo(repo: str) -> list[str]:
    errors: list[str] = []
    for name, legacy in GRANDFATHERED.items():
        p = os.path.join(repo, name)
        if os.path.exists(p):
            errors += check_file(p, grandfathered=legacy, provenance=True)
    for name in PARSE_ONLY:
        p = os.path.join(repo, name)
        if os.path.exists(p):
            errors += check_file(p)
    return errors


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    args = p.parse_args(argv)
    errors = check_repo(args.repo)
    for e in errors:
        print(e, file=sys.stderr)
    if errors:
        print(f"check_jsonl: {len(errors)} violation(s)", file=sys.stderr)
        return 1
    print("check_jsonl: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
